"""Concrete optimizers (ref: python/paddle/optimizer/{sgd,momentum,adam,
adamw,lamb}.py). Update rules are pure jax — reused by both eager step()
and the jit train-step compiler."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._apply_decay(param, grad, group)
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.momentum,
                                               self.use_nesterov)

    def _state_names(self):
        return ["velocity"]

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(
            self._master(p) if self._master(p) is not None else p._data)}

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._apply_decay(param, grad, group)
        v = state.get("velocity")
        if v is None:
            v = jnp.zeros_like(param)
        v = self.momentum * v + grad
        if self.use_nesterov:
            update = grad + self.momentum * v
        else:
            update = v
        return param - lr * update, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, moment_dtype=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        # moment_dtype='bfloat16' stores m/v in bf16 (update math stays
        # f32): 8 bytes/param instead of 4+4 f32 — the HBM lever that lets
        # billion-parameter configs train on one 16GB chip (same trade the
        # reference ships as multi-tensor fp16 moments in
        # paddle/phi/kernels/gpu/adamw_kernel.cu's MP path, inverted for
        # TPU where params stay f32 and moments shrink)
        self.moment_dtype = moment_dtype

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.beta1, self.beta2,
                                               self.epsilon)

    def _state_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _moment_dtype(self, base):
        if self.moment_dtype is None:
            return base.dtype
        from ..core import dtype as dtypes
        return dtypes.to_jnp(self.moment_dtype)

    def _init_state(self, p):
        base = self._master(p) if self._master(p) is not None else p._data
        mdt = self._moment_dtype(base)
        return {
            "moment1": jnp.zeros(base.shape, mdt),
            "moment2": jnp.zeros(base.shape, mdt),
            "beta1_pow": jnp.asarray(1.0, jnp.float32),
            "beta2_pow": jnp.asarray(1.0, jnp.float32),
        }

    def _decayed_grad(self, param, grad, group):
        return self._apply_decay(param, grad, group)

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._decayed_grad(param, grad, group)
        mdt = state["moment1"].dtype
        m = state["moment1"].astype(jnp.float32)
        v = state["moment2"].astype(jnp.float32)
        grad32 = grad.astype(jnp.float32)
        b1p = state["beta1_pow"] * self.beta1
        b2p = state["beta2_pow"] * self.beta2
        m = self.beta1 * m + (1 - self.beta1) * grad32
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(grad32)
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        upd = (lr * m_hat / (jnp.sqrt(v_hat) + self.epsilon)).astype(
            param.dtype)
        new_param = param - upd
        new_param = self._post_update(new_param, param, lr, group)
        return new_param, {"moment1": m.astype(mdt), "moment2": v.astype(mdt),
                           "beta1_pow": b1p, "beta2_pow": b2p}

    def _post_update(self, new_param, param, lr, group):
        return new_param


class AdamW(Adam):
    """Decoupled weight decay (ref: python/paddle/optimizer/adamw.py:40)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, moment_dtype=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         moment_dtype=moment_dtype)
        self.weight_decay = weight_decay or 0.0
        self.apply_decay_param_fun = apply_decay_param_fun
        self._current_param_name = None

    def _decayed_grad(self, param, grad, group):
        return grad  # decoupled: no L2 into grad

    def step(self):
        # track param names for apply_decay_param_fun
        super().step()

    def _update_rule(self, param, grad, state, lr, group):
        new_param, new_state = super()._update_rule(param, grad, state, lr,
                                                    group)
        return new_param, new_state

    def _post_update(self, new_param, param, lr, group):
        wd = group.get("weight_decay", self.weight_decay) or 0.0
        if wd and self._decay_applies():
            new_param = new_param - lr * wd * param
        return new_param

    def _decay_applies(self):
        if self.apply_decay_param_fun is None:
            return True
        if self._current_param_name is None:
            return True
        return self.apply_decay_param_fun(self._current_param_name)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.beta1, self.beta2,
                                               self.epsilon)

    def _state_names(self):
        return ["moment", "inf_norm", "beta1_pow"]

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p._data),
                "inf_norm": jnp.zeros_like(p._data),
                "beta1_pow": jnp.asarray(1.0, jnp.float32)}

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._apply_decay(param, grad, group)
        m = self.beta1 * state["moment"] + (1 - self.beta1) * grad
        u = jnp.maximum(self.beta2 * state["inf_norm"], jnp.abs(grad))
        b1p = state["beta1_pow"] * self.beta1
        new_param = param - lr / (1 - b1p) * m / (u + self.epsilon)
        return new_param, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.epsilon,)

    def _state_names(self):
        return ["moment"]

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data,
                                        self.initial_accumulator_value)}

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._apply_decay(param, grad, group)
        mom = state["moment"] + jnp.square(grad)
        return param - lr * grad / (jnp.sqrt(mom) + self.epsilon), {
            "moment": mom}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.rho, self.epsilon,
                                               self.momentum,
                                               self.centered)

    def _state_names(self):
        return ["mean_square", "mean_grad", "momentum_acc"]

    def _init_state(self, p):
        return {"mean_square": jnp.zeros_like(p._data),
                "mean_grad": jnp.zeros_like(p._data),
                "momentum_acc": jnp.zeros_like(p._data)}

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._apply_decay(param, grad, group)
        ms = self.rho * state["mean_square"] + (1 - self.rho) * jnp.square(grad)
        if self.centered:
            mg = self.rho * state["mean_grad"] + (1 - self.rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum * state["momentum_acc"] + lr * grad / denom
        return param - mom, {"mean_square": ms, "mean_grad": mg,
                             "momentum_acc": mom}


class Lamb(Optimizer):
    """(ref: python/paddle/optimizer/lamb.py; fused native twin
    operators/optimizers/distributed_fused_lamb_op.cu)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self.lamb_weight_decay = lamb_weight_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_fn = exclude_from_weight_decay_fn

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.lamb_weight_decay,
                                               self.beta1, self.beta2,
                                               self.epsilon)

    def _state_names(self):
        return ["moment1", "moment2", "beta1_pow", "beta2_pow"]

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._data),
                "moment2": jnp.zeros_like(p._data),
                "beta1_pow": jnp.asarray(1.0, jnp.float32),
                "beta2_pow": jnp.asarray(1.0, jnp.float32)}

    def _update_rule(self, param, grad, state, lr, group):
        m = self.beta1 * state["moment1"] + (1 - self.beta1) * grad
        v = self.beta2 * state["moment2"] + (1 - self.beta2) * jnp.square(grad)
        b1p = state["beta1_pow"] * self.beta1
        b2p = state["beta2_pow"] * self.beta2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + self.epsilon)
        r = r + self.lamb_weight_decay * param
        w_norm = jnp.linalg.norm(param.reshape(-1))
        r_norm = jnp.linalg.norm(r.reshape(-1))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return param - lr * trust * r, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW8bitStub(AdamW):
    pass


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.epsilon, self.rho = epsilon, rho

    def _hyper_fingerprint(self):
        return super()._hyper_fingerprint() + (self.epsilon, self.rho)

    def _state_names(self):
        return ["avg_squared_grad", "avg_squared_update"]

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._data),
                "avg_squared_update": jnp.zeros_like(p._data)}

    def _update_rule(self, param, grad, state, lr, group):
        grad = self._apply_decay(param, grad, group)
        asg = self.rho * state["avg_squared_grad"] + (
            1 - self.rho) * jnp.square(grad)
        update = -jnp.sqrt(state["avg_squared_update"] + self.epsilon) / \
            jnp.sqrt(asg + self.epsilon) * grad
        asu = self.rho * state["avg_squared_update"] + (
            1 - self.rho) * jnp.square(update)
        return param + lr * update, {"avg_squared_grad": asg,
                                     "avg_squared_update": asu}
