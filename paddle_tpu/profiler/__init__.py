"""Profiler (ref: python/paddle/profiler/profiler.py:346 + C++ host/device
tracers §5.1).

Host spans: RecordEvent context managers into the observability trace
ring (`paddle_tpu.observability.tracing`) — ONE event stream shared
with `observability.span`, so `export_chrome_tracing` here and the
observability exporters produce consistent files whichever API recorded
the span. Device timeline: jax.profiler (XLA/PJRT trace) captured
alongside when a dir is given — TPU kernels, transfers, and host
callbacks land in the same tensorboard-loadable trace."""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, List, Optional

import jax

from ..observability import tracing as _tracing


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3

# --- per-op dispatch spans (ref: eager_gen.py:251 "Dygraph Record
# Event" slot — the reference opens a platform::RecordEvent in every
# generated ad_func; here ops.registry._dispatch_profiled reports into
# this aggregator; the profiler swaps the live dispatch pointer so the
# non-recording path pays nothing). chrome-trace events are NOT emitted
# per op (that would distort the timeline the XLA trace covers).
_op_stats: dict = {}
_op_stats_lock = threading.Lock()


def _record_op(name: str, t0_ns: int, cached: bool) -> None:
    dur = (time.perf_counter_ns() - t0_ns) / 1e6
    with _op_stats_lock:
        st = _op_stats.get(name)
        if st is None:
            st = _op_stats[name] = [0, 0.0, 0.0, 0]  # calls,total,max,hits
        st[0] += 1
        st[1] += dur
        if dur > st[2]:
            st[2] = dur
        if cached:
            st[3] += 1


class RecordEvent:
    """(ref: paddle.profiler.RecordEvent / C++ platform/profiler/
    event_tracing.h:43)

    Idempotent: a second end() (or __exit__ after an explicit end()) is
    a no-op — the span is consumed by the first end. Events land in the
    shared observability trace ring whenever tracing is enabled (by a
    running Profiler or by observability.enable())."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        t0, self._t0 = self._t0, None       # consume: double end no-ops
        if t0 is None or not _tracing.enabled():
            return
        t1 = time.perf_counter_ns()
        _tracing.add_event(self.name, t0 / 1000.0, (t1 - t0) / 1000.0)

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed=0, ready=1, record=4, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        fname = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{fname}.pb.trace.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": prof.events()}, f)
        return path

    return handler


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self._jax_trace_dir = None

    def start(self):
        # one event stream: Profiler sessions record into the shared
        # observability ring. start() clears it (a profiling session is
        # a fresh window); tracing stays enabled afterwards only if
        # observability had it on before this session.
        self._trace_was_enabled = _tracing.enabled()
        _tracing.clear()
        _tracing.enable()
        from ..ops import registry as _registry
        _registry._set_op_profiling(True)
        _op_stats.clear()
        if not self.timer_only:
            self._jax_trace_dir = os.environ.get(
                "PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace")
            try:
                jax.profiler.start_trace(self._jax_trace_dir)
            except Exception:
                self._jax_trace_dir = None
        return self

    def stop(self):
        if not getattr(self, "_trace_was_enabled", False):
            _tracing.disable()
        from ..ops import registry as _registry
        _registry._set_op_profiling(False)
        if self._jax_trace_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_trace_dir = None
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        self.step_num += 1

    def events(self):
        return _tracing.events()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        lines = []
        with _op_stats_lock:
            op_rows = sorted(_op_stats.items(), key=lambda kv: -kv[1][1])
        if op_detail and op_rows:
            # per-op dispatch table (ref: profiler_statistic.py
            # "Operator Summary" — calls / total / avg / max host time
            # + executable-cache hit ratio, this backend's analog of
            # the reference's kernel-launch breakdown)
            lines.append("-------------------  Operator Summary  "
                         "-------------------")
            lines.append(f"{'op':<36} {'calls':>7} {'total_ms':>10} "
                         f"{'avg_ms':>8} {'max_ms':>8} {'cache%':>7}")
            for name, (n, tot, mx, hits) in op_rows:
                lines.append(
                    f"{name:<36} {n:>7} {tot:>10.3f} {tot / n:>8.3f} "
                    f"{mx:>8.3f} {100.0 * hits / n:>6.1f}%")
        evs = self.events()
        agg = {}
        for e in evs:
            a = agg.setdefault(e["name"], [0.0, 0])
            a[0] += e["dur"] / 1000.0
            a[1] += 1
        if agg:
            lines.append("-------------------  UserDefined Summary  "
                         "-----------------")
            lines.append(f"{'name':<50} {'calls':>8} {'total_ms':>12}")
            for name, (tot, n) in sorted(agg.items(),
                                         key=lambda kv: -kv[1][0]):
                lines.append(f"{name:<50} {n:>8} {tot:>12.3f}")
        return "\n".join(lines)

    def op_stats(self):
        """Raw per-op rows: {name: (calls, total_ms, max_ms, cache_hits)}."""
        with _op_stats_lock:
            return {k: tuple(v) for k, v in _op_stats.items()}

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)
