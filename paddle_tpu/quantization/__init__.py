"""paddle_tpu.quantization — QAT / PTQ.

Reference: python/paddle/quantization/ (config.py QuantConfig,
qat.py QAT.quantize -> wrapper.py QuantedLayer with activation+weight
quanters, quanters/abs_max.py FakeQuanterWithAbsMaxObserver with a
moving-average abs-max scale, ptq.py PTQ with observers).

TPU rendering: fake-quant is a jnp round/clip with a straight-through
estimator (custom_vjp identity-through-clip) — inside jit XLA fuses it
into the surrounding matmul's epilogue, so QAT costs one multiply-add
per tensor. int8 deployment itself rides XLA's native int8 dot support
when `convert`ed weights are fed as int8 + scale.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer import Layer


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(x, scale, bit_length):
    qmax = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax


def _fake_quant_fwd(x, scale, bit_length):
    return _fake_quant(x, scale, bit_length), (x, scale)


def _fake_quant_bwd(bit_length, res, g):
    # straight-through estimator: pass-through inside the clip range
    x, scale = res
    inside = (jnp.abs(x) <= jnp.maximum(scale, 1e-9)).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale)


_fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)

from ..ops.registry import register_op  # noqa: E402


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def _fake_quant_op(x, scale, bit_length=8):
    """Tape-recorded fake quant (ref: the fake_quantize_dequantize op
    family) — dispatching through the registry is what lets gradients
    flow THROUGH the quantizer (STE) instead of stopping at it."""
    return _fake_quant(x, scale, bit_length)


def quantize_linear(x, scale, zero_point=0, bit_length=8, axis=None):
    """Functional quantize (ref ops quantize_linear)."""
    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = 2 ** (bit_length - 1) - 1
    s = jnp.maximum(jnp.asarray(scale), 1e-9)
    q = jnp.clip(jnp.round(data / s * qmax) + zero_point, -qmax - 1, qmax)
    return Tensor._wrap(q.astype(jnp.int8 if bit_length <= 8
                                 else jnp.int32))


def dequantize_linear(x, scale, zero_point=0, bit_length=8, axis=None):
    data = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    qmax = 2 ** (bit_length - 1) - 1
    return Tensor._wrap((data.astype(jnp.float32) - zero_point)
                        * jnp.asarray(scale) / qmax)


class BaseQuanter(Layer):
    """ref: base_quanter.py"""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """ref: quanters/abs_max.py:129 — moving-average abs-max scale +
    fake quant with STE."""

    def __init__(self, layer=None, moving_rate=0.9, bit_length=8,
                 dtype="float32", name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        from ..nn.initializer import Constant
        self.scale = self.create_parameter(
            [1], default_initializer=Constant(1e-3), is_bias=False)
        self.scale.stop_gradient = True
        self._accum = 0.0   # bias-corrected moving average: the first
        # observation sets scale = cur exactly (accum 1.0 would pin
        # early scales to the 1e-3 init and starve the STE)

    def forward(self, x):
        t = x if isinstance(x, Tensor) else Tensor(x)
        import jax.core
        if self.training and not isinstance(t._data, jax.core.Tracer):
            # observer calibration is an EAGER side effect; under a
            # trace the update would leak tracers into persistent state
            data = t._data
            cur = jnp.max(jnp.abs(data)).reshape(1)
            r = self._moving_rate
            state = r * self.scale._data * self._accum + (1 - r) * cur
            self._accum = r * self._accum + 1 - r
            self.scale._data = state / self._accum
        return _fake_quant_op(t, self.scale.detach()[0],
                              bit_length=self._bit_length)

    def scales(self):
        return self.scale

    def bit_length(self):
        return self._bit_length


class FakeQuanterWithAbsMaxObserver:
    """ref: quanters/abs_max.py:26 — factory passed to QuantConfig."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        self._kwargs = dict(moving_rate=moving_rate,
                            bit_length=bit_length, dtype=dtype)

    def instance(self, layer=None):
        return FakeQuanterWithAbsMaxObserverLayer(layer, **self._kwargs)


class SingleLayerConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """ref: config.py QuantConfig"""

    def __init__(self, activation=None, weight=None):
        self._global = SingleLayerConfig(activation, weight)
        self._layer_cfg: Dict[int, SingleLayerConfig] = {}
        self._type_cfg: Dict[Type, SingleLayerConfig] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = SingleLayerConfig(activation, weight)

    def config_for(self, layer) -> Optional[SingleLayerConfig]:
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._global.activation or self._global.weight:
            from ..nn.layers.common import Linear
            from ..nn.layers.conv import Conv2D
            if isinstance(layer, (Linear, Conv2D)):
                return self._global
        return None


class QuantedLayer(Layer):
    """ref: wrapper.py — wraps a layer with activation/weight fake
    quanters; forward quantizes inputs and weight, calls the float
    kernel (XLA fuses the dequant into the dot)."""

    def __init__(self, layer, cfg: SingleLayerConfig):
        super().__init__()
        self._inner = layer
        self.activation_quanter = (cfg.activation.instance(layer)
                                   if cfg.activation else None)
        self.weight_quanter = (cfg.weight.instance(layer)
                               if cfg.weight else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self._inner,
                                                       "weight"):
            # substitute the quanted TENSOR (a tape node) as the
            # layer's weight for this call: backward flows through the
            # quanter's STE to the leaf weight — swapping only the
            # buffer would detach the quantizer from autograd
            w = self._inner.weight
            qw = self.weight_quanter(w)
            params = self._inner._parameters
            params["weight"], orig = qw, params["weight"]
            try:
                out = self._inner(x)
            finally:
                params["weight"] = orig
            return out
        return self._inner(x)


class Quantization:
    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False):
        raise NotImplementedError

    def convert(self, model: Layer, inplace=False):
        """Strip quanters; bake weight fake-quant into the weights
        (ref qat.py convert -> ONNX-style QDQ; here: final simulated
        values)."""
        for name, sub in list(model.named_sublayers()):
            if isinstance(sub, QuantedLayer):
                inner = sub._inner
                if sub.weight_quanter is not None and \
                        hasattr(inner, "weight"):
                    inner.weight._data = sub.weight_quanter(
                        inner.weight)._data
                _set_sublayer(model, name, inner)
        return model


class QAT(Quantization):
    """ref: qat.py QAT"""

    def quantize(self, model: Layer, inplace=False):
        for name, sub in list(model.named_sublayers()):
            if isinstance(sub, QuantedLayer):
                continue
            cfg = self._config.config_for(sub)
            if cfg is not None and (cfg.activation or cfg.weight):
                _set_sublayer(model, name, QuantedLayer(sub, cfg))
        return model


class PTQ(Quantization):
    """ref: ptq.py — observer-based post-training quantization; the
    same wrapper in eval mode collects abs-max scales over calibration
    batches."""

    def quantize(self, model: Layer, inplace=False):
        qat = QAT(self._config)
        model = qat.quantize(model, inplace=inplace)
        model.train()  # observers update during calibration
        return model


def _set_sublayer(root: Layer, dotted: str, new: Layer):
    parts = dotted.split(".")
    obj = root
    for p in parts[:-1]:
        obj = obj._sub_layers[p] if p in getattr(obj, "_sub_layers", {}) \
            else getattr(obj, p)
    obj.add_sublayer(parts[-1], new)
