"""paddle_tpu.resilience — fault tolerance layer + chaos-test harness.

What lives here vs. where the behaviors are implemented:

  * `faults` (this package) — the deterministic fault-injection
    registry every resilience path is tested through.
  * LLMEngine hardening (deadlines, poisoned-request isolation,
    load-shedding admission) — `inference/llm_engine.py`, instrumented
    with `engine.*` fault points.
  * Crash-safe checkpoints (atomic tmp+fsync+rename, checksum
    manifest, torn-checkpoint skip) — `distributed/checkpoint` and
    `framework_io`, instrumented with `checkpoint.*` /
    `framework_io.*` fault points. `resume_latest` re-exported here.
  * Self-healing DataLoader (dead-worker restart, guaranteed
    SharedMemory unlink) — `io/`, instrumented with `io.*` points.
  * Training autopilot (closed-loop self-healing: divergence rollback,
    N-1 elastic restart, loss-scale-floor escalation) — `supervisor`
    (this package), instrumented with the `supervisor.act` point.
    `Supervisor` / `TrainControl` / `AutopilotFailure` re-exported
    here.

See README "Fault tolerance & chaos testing" and
tests/test_resilience.py for the contract each path guarantees."""
from . import faults  # noqa: F401
from .faults import fault_point, inject  # noqa: F401


def __getattr__(name):
    # lazy: distributed.checkpoint pulls in jax; keep `import
    # paddle_tpu.resilience.faults` light for spawned workers
    if name in ("resume_latest", "is_complete", "verify_checkpoint"):
        from ..distributed import checkpoint as _ckpt
        val = getattr(_ckpt, name)
        globals()[name] = val
        return val
    if name in ("Supervisor", "TrainControl", "AutopilotFailure",
                "Policy"):
        from . import supervisor as _sv
        val = getattr(_sv, name)
        globals()[name] = val
        return val
    raise AttributeError(
        f"module 'paddle_tpu.resilience' has no attribute {name!r}")
