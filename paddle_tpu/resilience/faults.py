"""Deterministic fault injection for chaos testing.

Reference: the reference framework's fault tolerance stack exercises
elastic restarts end-to-end (distributed/fleet/elastic — SURVEY §L6)
but offers no way to *provoke* the faults it claims to survive; every
resilience path here is instead wired through named **fault points**
so tests (and operators) can inject failures deterministically:

    from paddle_tpu.resilience import faults

    with faults.inject("engine.decode.seq", exc=MemoryError("chaos"),
                       match={"rid": "bad"}):
        engine.generate(...)     # request "bad" fails, others finish

A fault point is a single call at an instrumented site::

    faults.fault_point("checkpoint.before_rename", path=tmp)

and costs one truthiness check on a module-level dict when nothing is
injected — cheap enough to leave in production paths.

Registered fault points (grep `fault_point(` for ground truth):

    engine.prefill.seq        per-sequence, before the prefill executable
                              (ctx: rid)
    engine.decode.seq         per-sequence, before the decode executable
                              (ctx: rid)
    engine.step               once per LLMEngine.step() (ctx: none)
    checkpoint.before_meta    after shard files, before metadata.json
                              (ctx: path)
    checkpoint.before_rename  after the tmp dir/file is complete, before
                              the atomic rename (ctx: path)
    checkpoint.between_renames  overwrite-save only: after the previous
                              checkpoint moved aside, before the new one
                              lands (ctx: path)
    framework_io.before_rename  paddle_tpu.save, between tmp write and
                              rename (ctx: path)
    io.worker.batch           in a spawned DataLoader worker, before
                              producing a batch (ctx: wid, bi)
    supervisor.act            training-autopilot supervisor, before each
                              remediation action commits (ctx: action,
                              kind, process)
    disagg.migrate            prefill->decode handoff, once per shipped
                              KV-page chunk, after export / before
                              import (ctx: request, seq, pages)

Injection specs support:

    exc=...         exception instance or class to raise
    delay=...       seconds to sleep before continuing (composable with
                    exc: sleep then raise)
    exit_code=N     call os._exit(N) — simulates a hard crash /
                    SIGKILL'd process (no exception propagates, no
                    cleanup runs). Used to chaos-test dead-worker
                    detection and torn checkpoints.
    times=N         fire at most N times (None = every hit)
    match={k: v}    fire only when the fault point's context kwargs
                    contain all given key/values (picklable — crosses
                    the spawn boundary into DataLoader workers)
    when=callable   fire only when `when(ctx_dict)` is truthy (not
                    picklable; in-process use only)

`inject` doubles as a context manager that removes the spec on exit;
called plainly it stays active until `clear(name)` / `clear_all()`.
Spawned DataLoader workers receive a `snapshot()` of the picklable
specs and `install()` it after their env guard, so `io.*` faults
reach child processes."""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = ["inject", "clear", "clear_all", "fault_point", "fired",
           "snapshot", "install", "set_on_fire", "FaultSpec"]


# reentrant: fault_point() evaluates user `when=` predicates under the
# lock, and a predicate may legitimately call back into this module
# (e.g. when=lambda ctx: faults.fired("other.point") > 0)
_LOCK = threading.RLock()
# name -> FaultSpec; module-level dict so fault_point's disarmed path is
# one truthiness check
_ACTIVE: Dict[str, "FaultSpec"] = {}
_FIRED: Dict[str, int] = {}
# observer called as cb(name, ctx) right after a fault fires, BEFORE
# its effect (delay/exit/raise) — the flight recorder hooks in here so
# the pre-crash state is on disk even for exit_code faults. Survives
# clear_all(): the observer belongs to whoever installed it, not to
# the armed specs.
_ON_FIRE = None


class FaultSpec:
    """One armed fault. Attribute bag + remaining-fire accounting."""

    __slots__ = ("name", "exc", "delay", "exit_code", "times", "match",
                 "when")

    def __init__(self, name, exc=None, delay=None, exit_code=None,
                 times=None, match=None, when=None):
        if exc is None and delay is None and exit_code is None:
            raise ValueError(
                f"fault {name!r}: give at least one of exc=, delay=, "
                "exit_code=")
        self.name = name
        self.exc = exc
        self.delay = delay
        self.exit_code = exit_code
        self.times = times
        self.match = dict(match) if match else None
        self.when = when

    def _matches(self, ctx: dict) -> bool:
        if self.match is not None:
            for k, v in self.match.items():
                if ctx.get(k) != v:
                    return False
        if self.when is not None and not self.when(ctx):
            return False
        return True

    def _picklable(self) -> bool:
        # `when` callables don't cross the spawn boundary; exceptions
        # and match dicts do
        return self.when is None

    def __getstate__(self):
        return {s: getattr(self, s) for s in self.__slots__}

    def __setstate__(self, state):
        for s in self.__slots__:
            setattr(self, s, state.get(s))


class _Injection:
    """Handle returned by inject(): context manager + .remove()."""

    def __init__(self, name):
        self._name = name

    def remove(self):
        clear(self._name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
        return False


def inject(name: str, exc=None, delay: Optional[float] = None,
           exit_code: Optional[int] = None, times: Optional[int] = None,
           match: Optional[dict] = None, when=None) -> _Injection:
    """Arm fault point `name`. See module docstring for the spec
    semantics. Returns a handle usable as a context manager."""
    spec = FaultSpec(name, exc=exc, delay=delay, exit_code=exit_code,
                     times=times, match=match, when=when)
    with _LOCK:
        _ACTIVE[name] = spec
    return _Injection(name)


def clear(name: str) -> None:
    with _LOCK:
        _ACTIVE.pop(name, None)


def clear_all() -> None:
    with _LOCK:
        _ACTIVE.clear()
        _FIRED.clear()


def fired(name: str) -> int:
    """How many times fault `name` has fired in this process."""
    with _LOCK:
        return _FIRED.get(name, 0)


def fault_point(name: str, **ctx) -> None:
    """Instrumented-site hook. No-op (one dict truthiness check) unless
    a matching fault is armed."""
    if not _ACTIVE:
        return
    with _LOCK:
        spec = _ACTIVE.get(name)
        if spec is None or not spec._matches(ctx):
            return
        if spec.times is not None:
            spec.times -= 1
            if spec.times <= 0:
                _ACTIVE.pop(name, None)
        _FIRED[name] = _FIRED.get(name, 0) + 1
    if _ON_FIRE is not None:
        try:
            _ON_FIRE(name, ctx)
        except Exception:
            pass        # an observer must never mask the fault itself
    if spec.delay:
        time.sleep(spec.delay)
    if spec.exit_code is not None:
        import os
        os._exit(spec.exit_code)
    if spec.exc is not None:
        exc = spec.exc() if isinstance(spec.exc, type) else spec.exc
        raise exc


def set_on_fire(cb) -> None:
    """Install (or with None, remove) the fire observer — cb(name,
    ctx) runs after a spec fires and before its effect. One observer;
    the flight recorder's capture_faults owns it when armed."""
    global _ON_FIRE
    _ON_FIRE = cb


def snapshot() -> list:
    """Picklable list of the currently armed specs — ship this across
    a spawn boundary and `install()` it in the child."""
    with _LOCK:
        return [s for s in _ACTIVE.values() if s._picklable()]


def install(specs) -> None:
    """Arm a snapshot()'d spec list in this (child) process."""
    if not specs:
        return
    with _LOCK:
        for s in specs:
            _ACTIVE[s.name] = s
