"""Training autopilot: closed-loop self-healing at fleet scale (see
README "Training autopilot").

PRs 11-15 built the detection stack — the numerics divergence sentinel
with first-bad-parameter attribution, cross-rank straggler gauges,
heartbeat staleness, crash-safe checkpoints with torn-checkpoint
quarantine — but every signal dead-ended in a dashboard: a NaN'd or
straggler-stalled fleet waited for a human. This module closes the
loop. A `Supervisor`, hosted in the fleet-aggregator process, watches
the plane through the aggregator's post-ingest observer hook and ACTS
on three detector families:

* **NaN / divergence.** A `numerics.divergence` trace event (emitted
  by the sentinel alongside its flight bundle, shipped inside the
  diverging process's next fleet bundle) opens an episode. The
  supervisor commands the training loop — which polls it every step
  through `TrainControl` — to halt, roll back via
  `distributed.checkpoint.resume_latest` (whose return now carries
  the restored step), apply the policy remediation (`skip_batch`:
  replay past the poisoned batch without training on it, or
  `reraise_scale`: pin the AMP loss scale back up via
  `GradScaler.set_loss_scaling`), and resume. Continuation from the
  last good step is bit-exact (pinned by tests/test_autopilot.py).

* **Dead rank / persistent straggler.** Missed heartbeats past the
  policy staleness window, or a `collective_straggler` attribution
  held continuously for `straggler_sustain_s`, evict the rank and
  command the controller process to elastic-restart the fleet at N-1
  — checkpoint load-time resharding (the GSPMD-style mesh-change
  machinery in `distributed.checkpoint`) restores the 8-rank state
  onto the 7-rank mesh at load time.

* **Repeated AMP loss-scale floor.** The first `loss_scale_floor`
  episodes are remediated (rollback + `reraise_scale`); once
  `scale_floor_max` episodes have burned, the supervisor escalates to
  a named, actionable `AutopilotFailure` — the polling trainer raises
  it instead of grinding on as a silent dead run.

Every episode emits exactly ONE `autopilot_remediation` flight bundle
whose detail is the full detection → action → outcome timeline, plus
detection-latency and MTTR (detection → training resumed) readings on
`paddle_tpu_autopilot_*` series. Remediation itself is chaos-testable:
`faults.fault_point("supervisor.act", action=..., kind=..., process=
...)` fires before each action COMMITS, and an action that dies there
leaves the episode's pending-action journal intact — the next
`scan()` pass completes the recovery (checkpoints stay un-torn
throughout; rollback only ever READS them).

Split of responsibility: the supervisor never reaches into a trainer
process — it only answers polls. `TrainControl.poll(step)` (one
hardened RPC per step: bounded timeout + bounded-backoff retries, so
a wedged aggregator cannot hang the step loop any more than a wedged
trainer can hang the supervisor's watch) returns the next command,
and `TrainControl.apply(...)` executes the rollback locally. A clean
run polls, receives `None` forever, and performs zero remediations.

Operator entry point: `tools/autopilot.py` serves an aggregator with
an attached supervisor and prints episode summaries as they close.
"""
from __future__ import annotations

import bisect
import itertools
import threading
import time
from typing import Dict, List, Optional

from . import faults

__all__ = ["AutopilotFailure", "Policy", "Episode", "Supervisor",
           "TrainControl", "attach", "supervisor"]


class AutopilotFailure(RuntimeError):
    """Named, actionable autopilot escalation — raised (trainer side)
    or recorded (supervisor side) when remediation is exhausted, so a
    dead run fails LOUDLY with the episode history attached instead of
    burning accelerator-hours at loss scale 1.0."""

    def __init__(self, message: str, kind: Optional[str] = None,
                 episodes: Optional[List[dict]] = None):
        super().__init__(message)
        self.kind = kind
        self.episodes = list(episodes or ())


class Policy:
    """Remediation policy knobs — one instance per supervisor; the
    defaults match the README policy table."""

    __slots__ = ("nan_policy", "reraise_factor", "max_rollbacks",
                 "heartbeat_stale_s", "straggler_sustain_s",
                 "scale_floor_max")

    def __init__(self, nan_policy: str = "skip_batch",
                 reraise_factor: float = 16.0,
                 max_rollbacks: int = 3,
                 heartbeat_stale_s: float = 10.0,
                 straggler_sustain_s: float = 5.0,
                 scale_floor_max: int = 2):
        if nan_policy not in ("skip_batch", "reraise_scale"):
            raise ValueError(
                "nan_policy must be 'skip_batch' or 'reraise_scale', "
                f"got {nan_policy!r}")
        self.nan_policy = nan_policy
        self.reraise_factor = float(reraise_factor)
        self.max_rollbacks = int(max_rollbacks)
        self.heartbeat_stale_s = float(heartbeat_stale_s)
        self.straggler_sustain_s = float(straggler_sustain_s)
        self.scale_floor_max = int(scale_floor_max)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


_EPISODE_IDS = itertools.count(1)

# episode kinds (detector families)
KIND_NAN = "nan"
KIND_SCALE_FLOOR = "scale_floor"
KIND_DEAD_RANK = "dead_rank"
KIND_STRAGGLER = "straggler"


class Episode:
    """One detected incident and its remediation lifecycle. `pending`
    is the action journal: actions move out of it only AFTER their
    `supervisor.act` fault point + commit succeeded, so a crash inside
    remediation leaves the journal for the next scan() to drain."""

    __slots__ = ("id", "kind", "process", "detail", "timeline",
                 "pending", "state", "detected_t", "detected_mono",
                 "done_t", "outcome", "last_action")

    def __init__(self, kind: str, process: str, detail: dict,
                 pending: List[dict], now: float):
        self.id = next(_EPISODE_IDS)
        self.kind = kind
        self.process = process
        self.detail = dict(detail)
        self.timeline: List[dict] = []
        self.pending = list(pending)
        self.state = "detected"     # -> acting -> awaiting -> done
        self.detected_t = now
        self.detected_mono = time.perf_counter()
        self.done_t: Optional[float] = None
        self.outcome: Optional[dict] = None
        self.last_action: Optional[str] = None

    def note(self, phase: str, **kv) -> None:
        ent = {"t": round(time.time(), 6), "phase": phase}
        ent.update(kv)
        self.timeline.append(ent)

    def snapshot(self) -> dict:
        return {"id": self.id, "kind": self.kind,
                "process": self.process, "state": self.state,
                "detail": dict(self.detail),
                "timeline": [dict(e) for e in self.timeline],
                "pending": [dict(a) for a in self.pending],
                "outcome": dict(self.outcome) if self.outcome else None}


def _hobserve(child, v: float) -> None:
    """Flag-bypassing histogram observe (the `_bump` precedent for
    counters): autopilot self-accounting must record even when the
    hosting process's hot-path flag is off."""
    child._buckets[bisect.bisect_left(child._bounds, v)] += 1
    child._sum += v
    child._count += 1
    if v < child._min:
        child._min = v
    if v > child._max:
        child._max = v


class Supervisor:
    """The watch-and-act loop. Construct with the serving
    `FleetAggregator` (detection attaches to its post-ingest observer
    hook and its merged registry hosts the autopilot series) and the
    checkpoint root rollbacks restore from; call `scan()` on a cadence
    (the CLI does; tests drive it manually). `attach()` additionally
    publishes the instance for the module-level RPC targets, so
    `TrainControl` in trainer processes can poll through the
    aggregator's existing HMAC call server."""

    def __init__(self, agg=None, ckpt_root: Optional[str] = None,
                 policy: Optional[Policy] = None, registry=None,
                 controller: Optional[str] = None):
        from ..observability import metrics as _m
        self.agg = agg
        self.ckpt_root = ckpt_root
        self.policy = policy or Policy()
        self.controller = controller
        self.failure: Optional[AutopilotFailure] = None
        self._lock = threading.RLock()
        self._open: Dict[int, Episode] = {}
        self._done: List[dict] = []
        # completed-episode history per (process, kind) — the repeated
        # scale-floor / repeated-rollback escalation counters
        self._history: Dict[tuple, int] = {}
        self._evicted: set = set()
        self._straggler_since: Dict[str, float] = {}
        self._commands: Dict[str, List[dict]] = {}
        self._pollers: Dict[str, dict] = {}
        r = registry if registry is not None else (
            agg.registry if agg is not None else _m.registry())
        self.registry = r
        self._h = {
            "episodes": r.counter(
                "paddle_tpu_autopilot_episodes_total",
                "closed autopilot episodes by detector family "
                "(kind=nan|scale_floor|dead_rank|straggler) and how "
                "they ended (outcome=remediated|escalated|failed)",
                ("kind", "outcome")),
            "actions": r.counter(
                "paddle_tpu_autopilot_actions_total",
                "remediation actions the supervisor committed (the "
                "supervisor.act fault point fired and the action took "
                "effect), by action name from the README policy table",
                ("action",)),
            "action_failures": r.counter(
                "paddle_tpu_autopilot_action_failures_total",
                "remediation actions that died between the "
                "supervisor.act fault point and their commit (chaos "
                "injection, crash) — the episode's pending-action "
                "journal survives and the next scan() retries",
                ("action",)),
            "last_action": r.gauge(
                "paddle_tpu_autopilot_last_action",
                "one-hot marker on the most recently committed "
                "remediation action (1 on the latest, 0 elsewhere) — "
                "the obs_top autopilot panel's 'last action' readout",
                ("action",)),
            "open": r.gauge(
                "paddle_tpu_autopilot_open_episodes",
                "episodes currently detected-but-not-closed (pending "
                "actions or awaiting the trainer's outcome report)"),
            "detect": r.histogram(
                "paddle_tpu_autopilot_detection_latency_seconds",
                "fault signal emission (the numerics.divergence event "
                "timestamp, trainer clock) to supervisor detection "
                "(aggregator clock; CLOCK_MONOTONIC, cross-process "
                "comparable on one host)"),
            "mttr": r.histogram(
                "paddle_tpu_autopilot_mttr_seconds",
                "mean-time-to-recovery per episode: detection to the "
                "trainer's outcome report (training resumed / fleet "
                "restarted); escalations observe detection-to-"
                "escalation"),
        }
        if agg is not None:
            agg.add_observer(self._on_bundle)

    # -- lifecycle --
    def close(self) -> None:
        global _SUPERVISOR
        if self.agg is not None:
            try:
                self.agg.remove_observer(self._on_bundle)
            except Exception:
                pass
        if _SUPERVISOR is self:
            _SUPERVISOR = None

    # -- detection: fleet-bundle observer --
    def _on_bundle(self, proc: str, bundle: dict) -> None:
        for ev in bundle.get("trace") or ():
            if ev.get("name") != "numerics.divergence":
                continue
            args = ev.get("args") or {}
            reasons = args.get("reasons") or []
            kind = KIND_SCALE_FLOOR if "loss_scale_floor" in reasons \
                else KIND_NAN
            self._detect(kind, proc, {
                "step": args.get("step"), "reasons": list(reasons),
                "first_nonfinite_param":
                    args.get("first_nonfinite_param"),
                "grad_norm": args.get("grad_norm"),
                "loss_scale": args.get("loss_scale"),
                "source": args.get("source"),
            }, emitted_ts_us=ev.get("ts"))

    def _detect(self, kind: str, proc: str, detail: dict,
                emitted_ts_us=None) -> Optional[Episode]:
        with self._lock:
            for ep in self._open.values():
                if ep.process == proc and ep.kind == kind:
                    # same incident still in remediation: fold, don't
                    # double-open (and never double-bundle)
                    ep.note("detection_repeat", **detail)
                    return None
            pending = self._plan(kind, proc, detail)
            ep = Episode(kind, proc, detail, pending, time.time())
            if emitted_ts_us is not None:
                lat = max(0.0,
                          ep.detected_mono - float(emitted_ts_us) / 1e6)
                ep.detail["detection_latency_s"] = round(lat, 6)
                _hobserve(self._h["detect"]._require_default(), lat)
            ep.note("detection", kind=kind, process=proc, **detail)
            self._open[ep.id] = ep
            self._h["open"]._require_default()._value = \
                float(len(self._open))
        # acting happens outside the lock: actions fire fault points
        # and enqueue commands, and a chaos exc must not poison the
        # detection path — scan() retries the journal
        try:
            self._advance(ep)
        except Exception:
            pass
        return ep

    def _plan(self, kind: str, proc: str, detail: dict) -> List[dict]:
        """The policy table: detector family -> action journal."""
        p = self.policy
        burned = self._history.get((proc, kind), 0)
        if kind == KIND_SCALE_FLOOR:
            if burned + 1 >= p.scale_floor_max:
                return [{"action": "escalate",
                         "reason": "repeated AMP loss-scale floor "
                                   f"({burned + 1} episodes, policy "
                                   f"max {p.scale_floor_max})"}]
            return [{"action": "rollback_resume",
                     "policy": "reraise_scale"}]
        if kind == KIND_NAN:
            if burned + 1 > p.max_rollbacks:
                return [{"action": "escalate",
                         "reason": "divergence recurred past the "
                                   f"rollback budget ({burned} "
                                   "rollbacks already spent, policy "
                                   f"max {p.max_rollbacks})"}]
            return [{"action": "rollback_resume",
                     "policy": p.nan_policy}]
        # dead rank / sustained straggler: same elastic path
        return [{"action": "evict_rank"},
                {"action": "elastic_restart"}]

    # -- detection: periodic scan --
    def scan(self, now: Optional[float] = None) -> dict:
        """One watch pass: heartbeat staleness + sustained-straggler
        detection, then drain every open episode's pending-action
        journal (retrying actions a previous pass crashed inside).
        Returns a status summary. Never raises on action failure —
        failures are counted and retried next pass."""
        now = time.time() if now is None else now
        p = self.policy
        if self.agg is not None:
            try:
                controller = self._controller()
            except RuntimeError:
                controller = None
            health = self.agg.health(now)
            for proc, st in health.items():
                # the controller runs the step loop the supervisor
                # commands — it cannot be evicted (a dead controller
                # has no one left to restart the fleet; that is the
                # operator's page, not an autopilot episode)
                if proc in self._evicted or proc == controller:
                    continue
                if not st["up"] and st["age_s"] >= p.heartbeat_stale_s:
                    self._detect(KIND_DEAD_RANK, proc, {
                        "age_s": round(st["age_s"], 3),
                        "role": st["role"], "pid": st["pid"]})
            flagged = set()
            for op, proc in self.agg.stragglers().items():
                flagged.add(proc)
                since = self._straggler_since.setdefault(proc, now)
                if proc in self._evicted:
                    continue
                if now - since >= p.straggler_sustain_s:
                    self._detect(KIND_STRAGGLER, proc, {
                        "op": op,
                        "sustained_s": round(now - since, 3)})
            for proc in list(self._straggler_since):
                if proc not in flagged:
                    del self._straggler_since[proc]
        with self._lock:
            open_eps = list(self._open.values())
        for ep in open_eps:
            try:
                self._advance(ep)
            except Exception:
                pass    # counted in _advance; journal intact
        with self._lock:
            return {"open": len(self._open),
                    "done": len(self._done),
                    "failure": str(self.failure) if self.failure
                    else None}

    # -- acting --
    def _advance(self, ep: Episode) -> None:
        while True:
            with self._lock:
                if not ep.pending:
                    break
                step = ep.pending[0]
            action = step["action"]
            try:
                self.act(action, ep, **{k: v for k, v in step.items()
                                        if k != "action"})
            except Exception:
                from ..observability.fleet import _bump
                _bump(self._h["action_failures"], action=action)
                raise
            with self._lock:
                if ep.pending and ep.pending[0] is step:
                    ep.pending.pop(0)
        with self._lock:
            if ep.state in ("detected", "acting"):
                ep.state = "awaiting"

    def act(self, action: str, ep: Episode, **detail) -> None:
        """Commit ONE remediation action for an episode. The
        `supervisor.act` fault point fires before anything takes
        effect — chaos can kill the supervisor mid-remediation here
        and the episode's journal (still holding this action) lets
        the next scan() complete the recovery."""
        ep.note("action_attempt", action=action, **detail)
        ep.state = "acting"
        faults.fault_point("supervisor.act", action=action,
                           kind=ep.kind, process=ep.process)
        if action == "rollback_resume":
            pol = detail.get("policy", self.policy.nan_policy)
            self._enqueue(ep.process, {
                "cmd": "rollback", "episode": ep.id,
                "policy": pol, "skip_step": ep.detail.get("step"),
                "reraise_factor": self.policy.reraise_factor,
                "ckpt_root": self.ckpt_root})
        elif action == "evict_rank":
            with self._lock:
                self._evicted.add(ep.process)
        elif action == "elastic_restart":
            target = self._controller()
            world = None
            if self.agg is not None:
                live = [pr for pr, st in
                        self.agg.health(time.time()).items()
                        if st["up"] and pr not in self._evicted]
                world = len(live)
            self._enqueue(target, {
                "cmd": "restart", "episode": ep.id,
                "evicted": ep.process, "world": world,
                "ckpt_root": self.ckpt_root})
        elif action == "escalate":
            msg = (f"autopilot escalation ({ep.kind}, process "
                   f"{ep.process}): {detail.get('reason', 'policy')}")
            with self._lock:
                self.failure = AutopilotFailure(
                    msg, kind=ep.kind,
                    episodes=self._done + [ep.snapshot()])
            self._enqueue(self._controller(), {
                "cmd": "stop", "episode": ep.id, "error": msg,
                "kind": ep.kind})
        else:
            raise ValueError(f"unknown autopilot action {action!r}")
        ep.note("action", action=action, **detail)
        ep.last_action = action
        from ..observability.fleet import _bump
        _bump(self._h["actions"], action=action)
        for a in ("rollback_resume", "evict_rank", "elastic_restart",
                  "escalate"):
            self._h["last_action"].labels(action=a)._value = \
                1.0 if a == action else 0.0
        if action == "escalate":
            # nothing will report an outcome for a stopped run — the
            # escalation closes the episode
            self._close(ep, "escalated",
                        {"error": str(self.failure)})

    def _controller(self) -> str:
        if self.controller is not None:
            return self.controller
        with self._lock:
            if self._pollers:
                return max(self._pollers,
                           key=lambda pr: self._pollers[pr]["t"])
        raise RuntimeError(
            "autopilot has no controller process to command: no "
            "TrainControl has polled yet and none was configured "
            "(Supervisor(controller=...))")

    def _enqueue(self, proc: str, cmd: dict) -> None:
        with self._lock:
            self._commands.setdefault(proc, []).append(cmd)

    # -- command channel (RPC-served) --
    def poll(self, process: str, step=None):
        """The trainer's per-step check-in: records liveness/progress
        and returns the next queued command (or None). The most recent
        poller doubles as the default controller for fleet-level
        commands."""
        with self._lock:
            self._pollers[process] = {"t": time.time(), "step": step}
            q = self._commands.get(process)
            return q.pop(0) if q else None

    def report(self, process: str, episode_id: int,
               outcome: dict) -> dict:
        """The trainer's remediation-outcome report: closes the
        episode, observes MTTR, dumps the flight bundle."""
        with self._lock:
            ep = self._open.get(int(episode_id))
        if ep is None:
            return {"ok": False, "unknown_episode": episode_id}
        status = "remediated" if outcome.get("ok", True) else "failed"
        self._close(ep, status, dict(outcome, process=process))
        return {"ok": True, "episode": episode_id, "outcome": status}

    def _close(self, ep: Episode, outcome: str, detail: dict) -> None:
        from ..observability import flight as _fl
        from ..observability.fleet import _bump
        with self._lock:
            if ep.id not in self._open:
                return
            mttr = time.perf_counter() - ep.detected_mono
            ep.note("outcome", outcome=outcome,
                    mttr_s=round(mttr, 6), **detail)
            ep.outcome = dict(detail, outcome=outcome,
                              mttr_s=round(mttr, 6))
            ep.state = "done"
            ep.done_t = time.time()
            del self._open[ep.id]
            self._history[(ep.process, ep.kind)] = \
                self._history.get((ep.process, ep.kind), 0) + 1
            snap = ep.snapshot()
            self._done.append(snap)
            self._h["open"]._require_default()._value = \
                float(len(self._open))
        _bump(self._h["episodes"], kind=ep.kind, outcome=outcome)
        _hobserve(self._h["mttr"]._require_default(), mttr)
        # one bundle per episode, dumped OUTSIDE the lock (flight I/O)
        _fl.trigger("autopilot_remediation", detail={
            "episode": ep.id, "kind": ep.kind, "process": ep.process,
            "outcome": outcome, "mttr_s": round(mttr, 6),
            "detection_latency_s":
                ep.detail.get("detection_latency_s"),
            "policy": self.policy.to_dict(),
            "timeline": snap["timeline"]})

    # -- introspection (CLI / tests) --
    def episodes(self, done: bool = True) -> List[dict]:
        with self._lock:
            out = [ep.snapshot() for ep in self._open.values()]
            if done:
                out = self._done + out
            return out


# ---------------------------------------------------------------------------
# module-level RPC targets (pickle by reference; executed in the
# aggregator/supervisor process by the generic rpc call handler — the
# fleet._ingest_bundle pattern)
# ---------------------------------------------------------------------------
_SUPERVISOR: Optional[Supervisor] = None


def attach(sup: Supervisor) -> Supervisor:
    """Publish `sup` as THE supervisor the RPC targets below route to
    (one per process, like the fleet aggregator singleton)."""
    global _SUPERVISOR
    if _SUPERVISOR is not None and _SUPERVISOR is not sup:
        raise RuntimeError("a supervisor is already attached in this "
                           "process; close() it first")
    _SUPERVISOR = sup
    return sup


def supervisor() -> Optional[Supervisor]:
    return _SUPERVISOR


def _require() -> Supervisor:
    if _SUPERVISOR is None:
        raise RuntimeError("no autopilot supervisor is attached in "
                           "this process (supervisor.attach(...))")
    return _SUPERVISOR


def _sv_poll(process, step=None):
    return _require().poll(process, step=step)


def _sv_report(process, episode_id, outcome):
    return _require().report(process, episode_id, outcome)


# ---------------------------------------------------------------------------
# trainer side
# ---------------------------------------------------------------------------
class TrainControl:
    """The training loop's autopilot client: one `poll(step)` per step
    asks the supervisor for a command over the hardened RPC path
    (bounded per-call timeout + bounded-backoff retries — a wedged
    supervisor delays a step, it cannot hang the run), and
    `apply(...)` executes a rollback command locally. A `stop` command
    raises the supervisor's `AutopilotFailure` in the training
    process."""

    def __init__(self, endpoint, process: str, timeout_s: float = 5.0,
                 retries: int = 2, backoff_s: float = 0.05):
        self.endpoint = endpoint
        self.process = str(process)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)

    def _call(self, fn, *args):
        from ..distributed import rpc as _r
        return _r.call_endpoint(self.endpoint, fn, args=args,
                                timeout=self.timeout_s,
                                retries=self.retries,
                                backoff_s=self.backoff_s)

    def poll(self, step=None) -> Optional[dict]:
        cmd = self._call(_sv_poll, self.process, step)
        if cmd and cmd.get("cmd") == "stop":
            raise AutopilotFailure(cmd.get("error", "autopilot stop"),
                                   kind=cmd.get("kind"))
        return cmd

    def report(self, episode_id: int, **outcome) -> dict:
        return self._call(_sv_report, self.process, episode_id,
                          dict(outcome))

    def apply(self, cmd: dict, state_dict=None, root=None,
              scaler=None) -> dict:
        """Execute a `rollback` command: restore the latest good
        checkpoint into `state_dict` (in place) and apply the policy
        remediation. Returns the outcome dict to `report(...)` —
        `resumed_step` is the restored step (from resume_latest's
        RestoredCheckpoint), `skip_step` echoes the batch the policy
        says to replay past without training. `restart` commands are
        returned unchanged for the caller's mesh rebuild (too
        app-specific to automate here)."""
        if cmd.get("cmd") != "rollback":
            return cmd
        from ..distributed import checkpoint as _ckpt
        root = root if root is not None else cmd.get("ckpt_root")
        res = _ckpt.resume_latest(state_dict, root)
        if res is None:
            raise AutopilotFailure(
                f"rollback commanded but no usable checkpoint under "
                f"{root!r}", kind="nan")
        out = {"action": "rollback_resume", "ok": True,
               "policy": cmd.get("policy"),
               "resumed_step": res.step, "resumed_from": str(res),
               "skip_step": cmd.get("skip_step")}
        if cmd.get("policy") == "reraise_scale" and scaler is not None:
            new_scale = float(scaler.get_loss_scaling()) \
                * float(cmd.get("reraise_factor") or 16.0)
            scaler.set_loss_scaling(new_scale)
            out["loss_scale"] = new_scale
        return out
