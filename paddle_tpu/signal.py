"""paddle_tpu.signal — short-time Fourier analysis.

Reference: python/paddle/signal.py (frame:27, overlap_add:134,
stft:231, istft:384; frame/overlap_add lower to phi kernels, stft/istft
are python composites over them + fft).

TPU rendering: frame is a gather of static window indices (one XLA
gather, MXU-free), overlap_add a segment-sum via scatter-add —
both shapes static under jit. stft/istft compose them with the fft
module exactly like the reference.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .ops.registry import register_op
from . import fft as _fft

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_jnp(x, frame_length, hop_length, axis=-1):
    x = jnp.asarray(x)
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")
    n_time = x.shape[axis]
    if frame_length > n_time:
        raise ValueError(
            f"frame_length {frame_length} > signal length {n_time}")
    n_frames = 1 + (n_time - frame_length) // hop_length
    starts = np.arange(n_frames) * hop_length
    idx = starts[:, None] + np.arange(frame_length)[None, :]  # [n, fl]
    if axis == -1:
        return jnp.take(x, jnp.asarray(idx.T), axis=-1)  # [..., fl, n]
    return jnp.take(x, jnp.asarray(idx), axis=0)         # [n, fl, ...]


def _overlap_add_jnp(x, hop_length, axis=-1):
    x = jnp.asarray(x)
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")
    if axis == -1:
        frame_length, n_frames = x.shape[-2], x.shape[-1]
    else:
        n_frames, frame_length = x.shape[0], x.shape[1]
    out_len = (n_frames - 1) * hop_length + frame_length
    starts = np.arange(n_frames) * hop_length
    if axis == -1:
        idx = (starts[None, :] + np.arange(frame_length)[:, None])
        out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
        # ONE scatter-add over the full [fl, n] index matrix (duplicate
        # indices accumulate) — not n_frames chained updates
        return out.at[..., jnp.asarray(idx)].add(x)
    idx = (starts[:, None] + np.arange(frame_length)[None, :])
    out = jnp.zeros((out_len,) + x.shape[2:], x.dtype)
    return out.at[jnp.asarray(idx)].add(x)


@register_op("signal_frame")
def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames; frame axis is added next to the
    time axis (ref signal.py:27: axis=-1 -> [..., frame_length, n],
    axis=0 -> [n, frame_length, ...])."""
    return _frame_jnp(x, frame_length, hop_length, axis)


@register_op("signal_overlap_add")
def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (ref signal.py:134): frames at stride
    hop_length scatter-add into the output signal."""
    return _overlap_add_jnp(x, hop_length, axis)


def _window_arr(window, win_length, dtype):
    if window is None:
        return jnp.ones((win_length,), dtype)
    w = window._data if hasattr(window, "_data") else jnp.asarray(window)
    if w.shape != (win_length,):
        raise ValueError(
            f"window must have shape ({win_length},), got {w.shape}")
    return w.astype(dtype)


@register_op("signal_stft")
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """ref signal.py:231. x: [batch?, seq]; returns
    [batch?, n_fft//2+1 (or n_fft), n_frames] complex. Registered as
    one composite op so autograd flows through it (jax.vjp over the
    whole jnp composite)."""
    data = jnp.asarray(x)
    if data.ndim not in (1, 2):
        raise ValueError("stft expects a 1D or 2D input")
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if not (0 < win_length <= n_fft):
        raise ValueError("0 < win_length <= n_fft required")
    is_complex = jnp.iscomplexobj(data)
    if onesided and is_complex:
        raise ValueError("onesided is not supported for complex input")
    real_dtype = jnp.zeros((), data.dtype).real.dtype
    w = _window_arr(window, win_length, real_dtype)
    if win_length < n_fft:  # center-pad the window (ref behavior)
        pad_l = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad_l, n_fft - win_length - pad_l))
    if center:
        pad = n_fft // 2
        cfg = [(0, 0)] * (data.ndim - 1) + [(pad, pad)]
        data = jnp.pad(data, cfg, mode=pad_mode)
    frames = _frame_jnp(data, n_fft, hop_length, axis=-1)
    frames = frames * w[:, None]
    frames = jnp.swapaxes(frames, -1, -2)  # [..., n, n_fft]
    if onesided:
        spec = jnp.fft.rfft(frames, axis=-1)
    else:
        spec = jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return jnp.swapaxes(spec, -1, -2)  # [..., n_freq, n_frames]


@register_op("signal_istft")
def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """ref signal.py:384 — inverse STFT with COLA window
    normalization. Registered composite (differentiable, see stft)."""
    spec = jnp.asarray(x)
    if spec.ndim not in (2, 3):
        raise ValueError("istft expects [.., n_freq, n_frames]")
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    n_freq = spec.shape[-2]
    if onesided and n_freq != n_fft // 2 + 1:
        raise ValueError(f"expected {n_fft // 2 + 1} freq bins, "
                         f"got {n_freq}")
    if not onesided and n_freq != n_fft:
        raise ValueError(f"expected {n_fft} freq bins, got {n_freq}")
    spec = jnp.swapaxes(spec, -1, -2)  # [..., n_frames, n_freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, n=n_fft, axis=-1)
        if not return_complex:
            frames = frames.real
    real_dtype = jnp.zeros((), frames.dtype).real.dtype
    w = _window_arr(window, win_length, real_dtype)
    if win_length < n_fft:
        pad_l = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad_l, n_fft - win_length - pad_l))
    frames = frames * w  # analysis-window product
    sig = jnp.swapaxes(frames, -1, -2)       # [..., n_fft, n_frames]
    y = _overlap_add_jnp(sig, hop_length, axis=-1)
    # COLA denominator: overlap-added squared window
    n_frames = frames.shape[-2]
    wsq = jnp.broadcast_to((w * w)[:, None], (n_fft, n_frames))
    denom = _overlap_add_jnp(wsq, hop_length, axis=-1)
    y = y / jnp.where(denom > 1e-11, denom, 1.0)
    if center:
        pad = n_fft // 2
        # with an explicit length, only the left pad is trimmed and the
        # right edge extends into the final frames (torch/paddle
        # semantics); without it both pads are dropped
        if length is not None:
            y = y[..., pad:]
        else:
            y = y[..., pad:y.shape[-1] - pad]
    if length is not None:
        if y.shape[-1] < length:  # zero-pad to the requested length
            cfg = [(0, 0)] * (y.ndim - 1) + [(0, length - y.shape[-1])]
            y = jnp.pad(y, cfg)
        y = y[..., :length]
    return y
