"""paddle_tpu.sparse — COO/CSR sparse tensors.

Reference: python/paddle/sparse/ (creation.py sparse_coo_tensor /
sparse_csr_tensor; unary.py elementwise family; binary.py
matmul/masked_matmul/mv/add/...; multiary.py addmm) over
paddle/phi/kernels/sparse/.

TPU rendering: storage is jax.experimental.sparse BCOO/BCSR, whose
matmuls lower to XLA scatter/gather+dot — sparse compute on TPU is only
profitable at high sparsity, so ops with no sparse XLA lowering
(elementwise on values, reshape/transpose) work on the values buffer
directly and structure-changing ops densify explicitly via to_dense().
The user-facing Tensor methods (is_sparse, to_dense, to_sparse_coo)
bridge to the dense world.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "matmul", "masked_matmul", "mv", "addmm", "add",
    "subtract", "multiply", "divide", "is_same_shape", "transpose",
    "reshape", "coalesce",
]


def _dense_data(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor:
    """ref: phi/core/sparse_coo_tensor.h — indices [sparse_dim, nnz] +
    values [nnz, ...dense dims]."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # ---- paddle Tensor surface ----
    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor._wrap(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor._wrap(self._bcoo.data)

    def to_dense(self):
        # sparse.nn ops attach the tape-recorded dense Tensor they were
        # computed from, so to_dense() keeps autograd connectivity
        # (trainable sparse conv layers)
        dt = getattr(self, "_dense_tensor", None)
        if dt is not None:
            return dt
        return Tensor._wrap(self._bcoo.todense())

    def to_sparse_csr(self):
        d = np.asarray(self._bcoo.todense())
        return _dense_to_csr(d)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # elementwise on stored values only (zeros stay zeros) — the
    # reference's unary family has the same semantics
    def _map_values(self, fn):
        b = self._bcoo
        return SparseCooTensor(
            jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))

    def abs(self):
        return self._map_values(jnp.abs)

    def sin(self):
        return self._map_values(jnp.sin)

    def tanh(self):
        return self._map_values(jnp.tanh)

    def sqrt(self):
        return self._map_values(jnp.sqrt)

    def square(self):
        return self._map_values(jnp.square)

    def neg(self):
        return self._map_values(jnp.negative)

    def astype(self, dtype):
        from ..core.dtype import to_jax_dtype
        return self._map_values(
            lambda v: v.astype(to_jax_dtype(dtype)))

    def relu(self):
        return self._map_values(jax.nn.relu)


class SparseCsrTensor:
    """ref: phi/core/sparse_csr_tensor.h — crows/cols/values."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._bcsr = bcsr

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    def nnz(self):
        return int(self._bcsr.nse)

    def crows(self):
        return Tensor._wrap(self._bcsr.indptr)

    def cols(self):
        return Tensor._wrap(self._bcsr.indices)

    def values(self):
        return Tensor._wrap(self._bcsr.data)

    def to_dense(self):
        return Tensor._wrap(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None):
        d = np.asarray(self._bcsr.todense())
        return _dense_to_coo(d)

    def numpy(self):
        return np.asarray(self._bcsr.todense())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def _dense_to_coo(dense) -> SparseCooTensor:
    return SparseCooTensor(jsparse.BCOO.fromdense(jnp.asarray(dense)))


def _dense_to_csr(dense) -> SparseCsrTensor:
    return SparseCsrTensor(jsparse.BCSR.fromdense(jnp.asarray(dense)))


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """ref: creation.py sparse_coo_tensor — indices [sparse_dim, nnz]."""
    idx = np.asarray(_dense_data(indices)).astype(np.int32)
    vals = _dense_data(values)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype
        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = shape + tuple(vals.shape[1:])
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    """ref: creation.py sparse_csr_tensor."""
    vals = _dense_data(values)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype
        vals = vals.astype(to_jax_dtype(dtype))
    crows = jnp.asarray(_dense_data(crows), jnp.int32)
    cols = jnp.asarray(_dense_data(cols), jnp.int32)
    if len(shape) == 3 and crows.ndim == 1:
        # paddle passes BATCHED CSR ([B, S, S]) as flat crows
        # [B*(S+1)] / cols [B*nnz] (ref creation.py sparse_csr_tensor);
        # jax BCSR wants them per-batch
        b, s = int(shape[0]), int(shape[1])
        crows = crows.reshape(b, s + 1)
        cols = cols.reshape(b, -1)
        vals = jnp.asarray(vals).reshape(b, -1, *np.asarray(vals).shape[2:]) \
            if np.asarray(vals).ndim > 1 else jnp.asarray(vals).reshape(b, -1)
    bcsr = jsparse.BCSR((jnp.asarray(vals), cols, crows),
                        shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def _as_bcoo(sx):
    """BCOO view of either format (jax's BCSR lacks a direct converter
    in this version; go through dense — these call sites densify for
    the structural op anyway)."""
    if isinstance(sx, SparseCooTensor):
        return sx._bcoo
    return jsparse.BCOO.fromdense(sx._bcsr.todense())


def _sp(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return x
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def matmul(x, y, name=None):
    """sparse @ dense (ref binary.py matmul): BCOO/BCSR dot -> dense."""
    sx = _sp(x)
    obj = getattr(sx, "_bcoo", None) or getattr(sx, "_bcsr")
    out = obj @ _dense_data(y)
    return Tensor._wrap(out)


def mv(x, vec, name=None):
    return matmul(x, vec, name=name)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense, sampled at mask's sparsity (ref binary.py
    masked_matmul / SDDMM)."""
    m = _sp(mask)
    dense = _dense_data(x) @ _dense_data(y)
    b = _as_bcoo(m)
    rows, cols = b.indices[:, 0], b.indices[:, 1]
    vals = dense[rows, cols]
    out = jsparse.BCOO((vals, b.indices), shape=b.shape)
    return SparseCooTensor(out)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """ref multiary.py addmm: beta*input + alpha*(x@y), x sparse."""
    prod = matmul(x, y)
    return Tensor._wrap(beta * _dense_data(input)
                        + alpha * prod._data)


def _ewise(x, y, fn):
    sx, sy = _sp(x), _sp(y)
    if sx.shape != sy.shape:
        raise ValueError("shapes must match")
    bx = _as_bcoo(sx)
    by = _as_bcoo(sy)
    out = fn(bx.todense(), by.todense())
    return _dense_to_csr(out) if isinstance(x, SparseCsrTensor) \
        else _dense_to_coo(out)


def add(x, y, name=None):
    return _ewise(x, y, jnp.add)


def subtract(x, y, name=None):
    return _ewise(x, y, jnp.subtract)


def multiply(x, y, name=None):
    return _ewise(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _ewise(x, y, jnp.divide)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _like_input(x, dense):
    """Re-sparsify preserving the input's format (paddle's sparse
    transpose/reshape return the same format)."""
    return _dense_to_csr(dense) if isinstance(x, SparseCsrTensor) \
        else _dense_to_coo(dense)


def transpose(x, perm, name=None):
    sx = _sp(x)
    return _like_input(sx, jnp.transpose(_as_bcoo(sx).todense(), perm))


def reshape(x, shape, name=None):
    sx = _sp(x)
    return _like_input(sx, jnp.reshape(_as_bcoo(sx).todense(), shape))


def coalesce(x, name=None):
    return _sp(x).coalesce()


# ---- sparse.nn subpackage (conv3d/subm_conv3d/pooling/attention;
# ref sparse/nn/) — imported at the bottom to avoid a circular import
# with paddle_tpu.nn
from . import nn  # noqa: E402,F401
