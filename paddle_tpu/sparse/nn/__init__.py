"""paddle.sparse.nn parity — layers over sparse/nn/functional (ref:
/root/reference/python/paddle/sparse/nn/layer/{conv.py:102,208,
pooling.py, activation.py})."""
from __future__ import annotations

import numpy as np

from . import functional  # noqa: F401
from ...core.tensor import Tensor
from ...nn.layer import Layer
from ...nn.initializer import Normal


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, nd=3,
                 bias_attr=None, data_format=None):
        super().__init__()
        ks = ((kernel_size,) * nd if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups, self.subm, self.nd = groups, subm, nd
        w_shape = ks + (in_channels // groups, out_channels)
        self.weight = self.create_parameter(
            w_shape, attr=Normal(std=0.02))
        self.bias = (self.create_parameter((out_channels,), is_bias=True)
                     if bias_attr is not False else None)

    def forward(self, x):
        fn = {
            (3, False): functional.conv3d,
            (3, True): functional.subm_conv3d,
            (2, False): functional.conv2d,
            (2, True): functional.subm_conv2d,
        }[(self.nd, self.subm)]
        return fn(x, self.weight, self.bias, self.stride, self.padding,
                  self.dilation, self.groups)


class Conv3D(_SparseConvNd):
    """ref: sparse/nn/layer/conv.py Conv3D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False, nd=3,
                         bias_attr=bias_attr)


class SubmConv3D(_SparseConvNd):
    """ref: sparse/nn/layer/conv.py SubmConv3D (submanifold)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, nd=3,
                         bias_attr=bias_attr)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False, nd=2,
                         bias_attr=bias_attr)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, nd=2,
                         bias_attr=bias_attr)


class MaxPool3D(Layer):
    """ref: sparse/nn/layer/pooling.py MaxPool3D."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding

    def forward(self, x):
        return functional.max_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)

    def __repr__(self):
        return "sparse.nn.ReLU()"


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        import jax
        from .. import SparseCooTensor
        return x._map_values(
            lambda v: jax.nn.leaky_relu(v, self.negative_slope))


class Softmax(Layer):
    """ref: sparse/nn/layer/activation.py Softmax — softmax over the
    stored values of each row (CSR) / last dense axis."""

    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        from .. import SparseCsrTensor
        import jax.numpy as jnp
        import jax
        if isinstance(x, SparseCsrTensor):
            bcsr = x._bcsr
            s = bcsr.shape[-2]
            crows = np.asarray(bcsr.indptr).reshape(-1, s + 1)
            data = np.asarray(bcsr.data).reshape(crows.shape[0], -1)
            out = np.empty_like(data)
            for b in range(crows.shape[0]):
                for r in range(s):
                    lo, hi = crows[b, r], crows[b, r + 1]
                    seg = data[b, lo:hi]
                    if hi > lo:
                        e = np.exp(seg - seg.max())
                        out[b, lo:hi] = e / e.sum()
            new = SparseCsrTensor.__new__(SparseCsrTensor)
            new._bcsr = bcsr.__class__(
                (jnp.asarray(out.reshape(np.asarray(bcsr.data).shape)),
                 bcsr.indices, bcsr.indptr), shape=bcsr.shape)
            return new
        return x._map_values(lambda v: jax.nn.softmax(v, axis=self.axis))
