"""paddle.sparse.nn.functional parity (ref:
/root/reference/python/paddle/sparse/nn/functional/{conv.py:199,305,417,
pooling.py:22, transformer.py:22}).

TPU stance (documented substitution): at the point-cloud densities these
APIs serve, the TPU MXU has no scatter-gather advantage — the compute is
executed DENSE (XLA conv / matmul on the MXU) while the sparse format is
preserved at the API boundary (inputs are SparseCooTensor, outputs are
re-sparsified with the op's exact site semantics: conv activates every
site its receptive field reaches, subm keeps the input's site pattern,
pooling keeps windows containing at least one active site). The CUDA
reference instead gathers rulebooks (paddle/phi/kernels/sparse/gpu/
conv_kernel.cu) — a GPU-shaped choice, not a semantic one.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor


def _coo(x):
    from .. import SparseCooTensor
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"expected SparseCooTensor, got {type(x).__name__}")
    return x


def _dense_tensor(x):
    """Dense Tensor view of a COO input. When the COO came from a
    tape-recorded op (chained sparse convs), this returns the RECORDED
    tensor, so gradients flow through stacked sparse layers."""
    return _coo(x).to_dense()


def _resparsify(dense_t, site_mask):
    """dense Tensor [N, *spatial, C] + bool site mask [N, *spatial] ->
    SparseCooTensor with sparse_dim = 1 + len(spatial), dense channel.
    The sparse wrapper keeps a reference to the recorded dense Tensor so
    to_dense() stays on the autograd tape (trainable sparse layers)."""
    from .. import sparse_coo_tensor
    idx = np.argwhere(np.asarray(site_mask))           # [nnz, 1+spatial]
    vals = jnp.asarray(np.asarray(dense_t._data)[tuple(idx.T)])  # [nnz, C]
    out = sparse_coo_tensor(idx.T, vals, shape=tuple(dense_t._data.shape))
    out._dense_tensor = dense_t
    return out


def _site_mask(x):
    """Active-site mask [N, *spatial] of a COO input (any channel)."""
    dense = np.asarray(_dense_tensor(x)._data)
    return np.any(dense != 0, axis=-1)


def _norm3(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd,
             subm=False):
    from ... import ops

    dense_t = _dense_tensor(x)                          # [N, *spatial, C]
    dense = dense_t._data
    w = weight if isinstance(weight, Tensor) else Tensor(weight)
    spec = ("NDHWC", "DHWIO", "NDHWC") if nd == 3 else \
        ("NHWC", "HWIO", "NHWC")
    if subm:
        # submanifold contract: output sites == input sites, so output
        # SHAPE must equal input shape — force pad=kernel//2, stride=1
        # like the reference (phi conv_kernel ResetSubmKernelSizeAndStrides)
        pd_list = [(k // 2, (k - 1) - k // 2) for k in w.shape[:nd]]
        st = (1,) * nd
    else:
        st = _norm3(stride)[:nd]
        pd = padding
        if isinstance(pd, int):
            pd_list = [(pd, pd)] * nd
        elif isinstance(pd, (list, tuple)) and pd and isinstance(pd[0], int):
            pd_list = [(p, p) for p in pd]
        else:
            pd_list = [tuple(p) for p in pd]
    dl = _norm3(dilation)[:nd]

    # the dense compute runs through the op registry (recorded on the
    # tape) so weight/bias — and chained sparse layers — are trainable.
    # Sparse weights use the reference's [k..., in/groups, out] layout;
    # the dense conv op takes paddle's [out, in/groups, k...] in EVERY
    # data_format — permute (stays on the tape: ops.transpose)
    perm = (4, 3, 0, 1, 2) if nd == 3 else (3, 2, 0, 1)
    w_dense = ops.transpose(w, perm)
    conv_op = ops.conv3d if nd == 3 else ops.conv2d
    out_t = conv_op(dense_t, w_dense, bias, stride=list(st),
                    padding=pd_list, dilation=list(dl), groups=groups,
                    data_format=spec[0])
    if subm:
        # submanifold: the output site pattern IS the input site pattern
        # (ref: conv.py:305 subm_conv3d / phi sparse subm rulebook)
        mask = _site_mask(x)
    else:
        # standard sparse conv: a site is active when any active input
        # site falls inside its receptive field
        act = jnp.asarray(_site_mask(x), dense.dtype)[..., None]
        ones = jnp.ones(tuple(w._data.shape[:nd]) + (1, 1), dense.dtype)
        dnm = jax.lax.conv_dimension_numbers(act.shape, ones.shape, spec)
        reach = jax.lax.conv_general_dilated(
            act, ones, window_strides=st, padding=pd_list, rhs_dilation=dl,
            dimension_numbers=dnm)
        mask = np.asarray(reach[..., 0]) > 0
    masked_t = out_t * Tensor(jnp.asarray(mask, out_t._data.dtype)[..., None])
    return _resparsify(masked_t, mask)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """ref: sparse/nn/functional/conv.py:199 — x [N,D,H,W,C] COO,
    weight [kd,kh,kw,C/groups,M]."""
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports NDHWC only (ref parity)")
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """ref: sparse/nn/functional/conv.py:305 — submanifold conv: output
    sites == input sites (no dilation of the active set)."""
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d supports NDHWC only")
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    subm=True)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """ref: sparse/nn/functional/conv.py:417."""
    if data_format != "NHWC":
        raise ValueError("sparse conv2d supports NHWC only")
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if data_format != "NHWC":
        raise ValueError("sparse subm_conv2d supports NHWC only")
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """ref: sparse/nn/functional/pooling.py:22 — max over ACTIVE sites in
    each window; a window with no active site yields an inactive site."""
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only")
    dense = _dense_tensor(x)._data
    mask = jnp.asarray(_site_mask(x))
    ks = _norm3(kernel_size)
    st = _norm3(stride if stride is not None else kernel_size)
    pd = padding
    if isinstance(pd, int):
        pd = [(pd, pd)] * 3
    elif isinstance(pd, (list, tuple)) and pd and isinstance(pd[0], int):
        pd = [(p, p) for p in pd]
    window = (1,) + ks + (1,)
    strides = (1,) + st + (1,)
    pads = [(0, 0)] + list(pd) + [(0, 0)]
    neg = jnp.asarray(-np.inf, dense.dtype)
    masked = jnp.where(mask[..., None], dense, neg)
    out = jax.lax.reduce_window(masked, neg, jax.lax.max, window, strides,
                                pads)
    out_mask = jax.lax.reduce_window(
        mask, False, jax.lax.bitwise_or, window[:-1], strides[:-1],
        pads[:-1])
    om = np.asarray(out_mask)
    out = jnp.where(out_mask[..., None], out, 0).astype(dense.dtype)
    return _resparsify(Tensor._wrap(out), om)


def relu(x, name=None):
    return _coo(x).relu()


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """ref: sparse/nn/functional/transformer.py:22 — softmax(QK^T/sqrt(d))V
    with the attention matrix restricted to `sparse_mask`'s CSR layout
    ([batch*heads, seq, seq]). TPU rendering: the restriction is a mask on
    the dense MXU matmul — the CSR pattern supplies WHERE attention may
    flow; scores outside it never contribute."""
    from .. import SparseCsrTensor

    q = query._data if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._data if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._data if isinstance(value, Tensor) else jnp.asarray(value)
    b, h, s, d = q.shape
    if not isinstance(sparse_mask, SparseCsrTensor):
        raise TypeError("sparse_mask must be a SparseCsrTensor")
    bcsr = sparse_mask._bcsr
    # CSR layout -> dense bool [b*h, s, s] (host-side, layout is static)
    crows = np.asarray(bcsr.indptr).reshape(b * h, s + 1)
    cols = np.asarray(bcsr.indices).reshape(b * h, -1)
    allow = np.zeros((b * h, s, s), bool)
    for bh in range(b * h):
        counts = np.diff(crows[bh])
        rows = np.repeat(np.arange(s), counts)
        allow[bh, rows, cols[bh][:rows.shape[0]]] = True
    allow = jnp.asarray(allow.reshape(b, h, s, s))

    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    neg = jnp.asarray(-1e30, scores.dtype)
    scores = jnp.where(allow, scores, neg)
    if attn_mask is not None:
        am = attn_mask._data if isinstance(attn_mask, Tensor) \
            else jnp.asarray(attn_mask)
        scores = scores + am.astype(scores.dtype)
    if key_padding_mask is not None:
        kp = key_padding_mask._data if isinstance(key_padding_mask, Tensor) \
            else jnp.asarray(key_padding_mask)
        scores = scores + kp[:, None, None, :].astype(scores.dtype)
    any_valid = jnp.max(scores, axis=-1, keepdims=True) > neg / 2
    p = jax.nn.softmax(scores, axis=-1)
    p = jnp.where(any_valid, p, 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return Tensor._wrap(out)
