"""Static-graph facade (ref: python/paddle/static/).

TPU-native stance (SURVEY §7.1): the "static graph" IS the jax-traced
program; Program/Executor here are thin shims that capture a traced
function per (feed-spec) and run it as one XLA executable. The full
ProgramDesc/IR surface of the reference is intentionally replaced by
trace-and-compile (see paddle_tpu/jit)."""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..jit import InputSpec  # noqa: F401

_static_mode = False


def _enable_static_mode():
    global _static_mode
    _static_mode = True


def _in_static_mode():
    return _static_mode


class Program:
    """A deferred computation: ops recorded as a python callable pipeline.
    Minimal parity object for Executor-style code paths."""

    def __init__(self):
        self._build_fns = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Executor:
    """(ref: python/paddle/base/executor.py:1151) — minimal shim: run()
    evaluates a python callable pipeline eagerly/jitted."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        raise NotImplementedError(
            "paddle_tpu.static.Executor runs traced callables; build "
            "models with paddle_tpu.jit.to_static instead of Program IR")


def gradients(targets, inputs, target_gradients=None):
    from ..autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kw):
    from .. import jit as _jit
    raise NotImplementedError(
        "use paddle_tpu.jit.save for traced-model persistence")


def load_inference_model(path_prefix, executor=None, **kw):
    raise NotImplementedError(
        "use paddle_tpu.jit.load for traced-model persistence")


def name_scope(prefix=None):
    import contextlib
    return contextlib.nullcontext()


# control-flow sugar (ref: python/paddle/static/nn/control_flow.py)
from . import nn  # noqa: E402,F401
