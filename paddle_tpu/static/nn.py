"""paddle.static.nn control-flow sugar (ref:
python/paddle/static/nn/control_flow.py).

TPU-native rendering: `cond`/`while_loop` ARE `jax.lax.cond` /
`jax.lax.while_loop` over Tensor pytrees — the same primitives
@to_static lowers Python `if`/`while` onto (jit/dy2static.py). Under
eager execution with a concrete predicate, only the taken branch runs
(the reference's semantics for materialized conditions)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda a: Tensor._wrap(a) if isinstance(a, jax.Array) else a,
        tree)


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """Run true_fn or false_fn depending on pred (0-D bool Tensor).
    Concrete pred -> only the taken branch executes; traced pred ->
    lax.cond with both branches traced (outputs must match in
    structure/shape, the reference's select_input contract)."""
    p = _arr(pred)
    if not isinstance(p, jax.core.Tracer):
        taken = true_fn if bool(p) else false_fn
        return taken() if taken is not None else None
    if true_fn is None or false_fn is None:
        raise ValueError(
            "cond under tracing requires both true_fn and false_fn")
    return _wrap_tree(jax.lax.cond(
        p, lambda _: _unwrap_tree(true_fn()),
        lambda _: _unwrap_tree(false_fn()), 0))


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """ref: static/nn/control_flow.py while_loop — loop_vars is a
    list/tuple of Tensors threaded through body."""
    vals = _unwrap_tree(tuple(loop_vars))
    concrete = not any(
        isinstance(v, jax.core.Tracer)
        for v in jax.tree_util.tree_leaves(vals))
    if concrete:
        wrapped = _wrap_tree(vals)
        while bool(_arr(cond_fn(*wrapped))):
            wrapped = tuple(body_fn(*wrapped))
        return wrapped

    def c(carry):
        return _arr(cond_fn(*_wrap_tree(carry)))

    def b(carry):
        return _unwrap_tree(tuple(body_fn(*_wrap_tree(carry))))

    return _wrap_tree(jax.lax.while_loop(c, b, vals))


def case(pred_fn_pairs, default=None, name=None):
    """First matching (pred, fn) wins (ref: control_flow.py case)."""
    for i, (pred, fn) in enumerate(pred_fn_pairs):
        p = _arr(pred)
        if isinstance(p, jax.core.Tracer):
            # nest conds over the remaining pairs
            rest = pred_fn_pairs[i + 1:]
            return cond(pred, fn,
                        (lambda: case(rest, default)) if (rest or default)
                        else None)
        if bool(p):
            return fn()
    if default is not None:
        return default()
    raise ValueError("case: no predicate matched and no default given")


def switch_case(branch_index, branch_fns, default=None, name=None):
    """ref: control_flow.py switch_case — integer-indexed branches."""
    idx = _arr(branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    keys = sorted(fns)
    if not isinstance(idx, jax.core.Tracer):
        fn = fns.get(int(idx), default)
        if fn is None:
            raise ValueError(
                f"switch_case: no branch for index {int(idx)} and no "
                "default")
        return fn()
    if default is None:
        default = fns[keys[-1]]
    span = max(keys) - min(keys) + 1
    if span <= 4 * len(keys) and span <= 256:
        # dense-enough keys: one lax.switch table
        table = [fns.get(k, default) for k in range(min(keys),
                                                    max(keys) + 1)]
        off = min(keys)
        clamped = jnp.clip(idx - off, 0, len(table) - 1)
        in_range = (idx >= off) & (idx <= max(keys))
        out = jax.lax.cond(
            in_range,
            lambda: jax.lax.switch(
                clamped,
                [lambda _=None, f=f: _unwrap_tree(f()) for f in table]),
            lambda: _unwrap_tree(default()))
        return _wrap_tree(out)
    # sparse keys: compact switch over the branch LIST indexed via a
    # device-side key lookup (no dense table blowup)
    karr = jnp.asarray(keys)
    pos = jnp.searchsorted(karr, idx)
    pos_c = jnp.clip(pos, 0, len(keys) - 1)
    matched = karr[pos_c] == idx
    branch = jnp.where(matched, pos_c, len(keys))
    fn_list = [lambda _=None, f=fns[k]: _unwrap_tree(f()) for k in keys]
    fn_list.append(lambda _=None: _unwrap_tree(default()))
    return _wrap_tree(jax.lax.switch(branch, fn_list))
