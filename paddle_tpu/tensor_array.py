"""TensorArray parity (ref: python/paddle/tensor/array.py — create_array,
array_write, array_read, array_length; backed by the C++ TensorArray in
the reference's static graphs).

TPU-native stance: the reference needs TensorArray as a graph-level
dynamic list for while-loop bodies; here dynamic-length collection is a
Python list in eager code, and inside `to_static`-staged loops the
fixed-shape equivalent is a preallocated Tensor carried through
lax.while_loop / lax.scan (see jit/dy2static.py). This module keeps the
four reference APIs working in eager/dygraph code.
"""
from __future__ import annotations

from .core.tensor import Tensor
from . import ops


class TensorArray(list):
    """A list of Tensors with the reference's array semantics."""

    def stack(self, axis=0):
        return ops.stack(list(self), axis=axis)

    def concat(self, axis=0):
        return ops.concat(list(self), axis=axis)


def create_array(dtype="float32", initialized_list=None):
    arr = TensorArray()
    for t in initialized_list or ():
        arr.append(t if isinstance(t, Tensor) else Tensor(t))
    return arr


def _index(i):
    if isinstance(i, Tensor):
        return int(i.numpy())
    return int(i)


def array_write(x, i, array=None):
    """Write x at index i, growing the array as the reference does."""
    if array is None:
        array = create_array()
    i = _index(i)
    if i < len(array):
        array[i] = x
    elif i == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {i} beyond array length {len(array)}")
    return array


def array_read(array, i):
    return array[_index(i)]


def array_length(array):
    return Tensor(len(array))
