"""paddle.text parity (ref: python/paddle/text/).

ViterbiDecoder wraps the lax.scan CRF decode in ops/sequence_ops.py.
The dataset zoo (ref: python/paddle/text/datasets/) parses the same
local archives the reference downloads — see datasets.py (zero-egress:
URLs documented, files staged by the operator)."""
from __future__ import annotations

from ..nn.layer import Layer
from ..ops import viterbi_decode
from . import datasets
from .datasets import Imdb, Imikolov, UCIHousing

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets", "Imdb",
           "Imikolov", "UCIHousing"]


class ViterbiDecoder(Layer):
    """Holds the transition matrix; forward decodes (ref:
    python/paddle/text/viterbi_decode.py:99)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
