"""paddle.text parity (ref: python/paddle/text/viterbi_decode.py).

The dataset zoo (paddle.text.datasets.*) is IO-bound downloader code with
no TPU-relevant compute; it is out of scope (see README "Unsupported
surface"). The compute API — ViterbiDecoder — wraps the lax.scan CRF
decode in ops/sequence_ops.py.
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..ops import viterbi_decode

__all__ = ["viterbi_decode", "ViterbiDecoder"]


class ViterbiDecoder(Layer):
    """Holds the transition matrix; forward decodes (ref:
    python/paddle/text/viterbi_decode.py:99)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
