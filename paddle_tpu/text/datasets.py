"""Text dataset zoo (ref: python/paddle/text/datasets/ — imdb.py,
imikolov.py, uci_housing.py ...).

Zero-egress environment: each dataset parses the SAME local archive the
reference downloads (URL + md5 documented per class so an operator can
stage it); a missing file falls back to deterministic synthetic samples
with a LOUD warning, or raises with allow_synthetic=False — never
silently (VERDICT r4 next-9)."""
from __future__ import annotations

import os
import re
import string
import tarfile
import warnings

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing"]


def _synthetic_fallback(name: str, reason: str, allow: bool):
    msg = (f"{name}: {reason} — falling back to DETERMINISTIC SYNTHETIC "
           f"samples. This is NOT the real dataset; stage the documented "
           f"archive locally (zero-egress: no downloads), or pass "
           f"allow_synthetic=False to make this an error.")
    if not allow:
        raise FileNotFoundError(f"{name}: {reason} (allow_synthetic=False)")
    warnings.warn(msg, UserWarning, stacklevel=3)


class Imdb(Dataset):
    """IMDB sentiment (ref: python/paddle/text/datasets/imdb.py —
    URL https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz,
    md5 7c2ac02c03563afcf9b574c7e56c153a).

    data_file: local aclImdb_v1.tar.gz. Samples are (word-id int64
    array, label) with label 0 = pos, 1 = neg (reference convention);
    the word dict is built from the TRAIN split with frequency > cutoff,
    '<unk>' mapped to len(dict)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 allow_synthetic=True):
        assert mode in ("train", "test"), mode
        self.mode = mode
        if data_file and os.path.exists(data_file):
            self._load(data_file, cutoff)
        else:
            _synthetic_fallback(
                "Imdb", "no local aclImdb_v1.tar.gz" if not data_file
                else f"data_file {data_file!r} does not exist",
                allow_synthetic)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.word_idx = {w: i for i, w in enumerate(
                string.ascii_lowercase)}
            self.word_idx["<unk>"] = len(self.word_idx)
            self.docs = [rng.randint(0, 26, size=rng.randint(5, 40))
                         .astype(np.int64) for _ in range(128)]
            self.labels = rng.randint(0, 2, size=128).astype(np.int64)

    def _tokenize(self, text):
        return re.sub(r"[^a-z\s]", "", text.lower()).split()

    def _load(self, data_file, cutoff):
        pat = re.compile(
            rf"aclImdb/{self.mode}/(pos|neg)/.*\.txt$")
        train_pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        freq: dict = {}
        docs_raw, labels = [], []
        with tarfile.open(data_file) as tf:
            for m in tf:
                if not m.isfile():
                    continue
                is_train = bool(train_pat.match(m.name))
                mm = pat.match(m.name)
                if not (is_train or mm):
                    continue
                words = self._tokenize(
                    tf.extractfile(m).read().decode("utf-8", "ignore"))
                if is_train:
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
                if mm:
                    docs_raw.append(words)
                    labels.append(0 if mm.group(1) == "pos" else 1)
        # dict: train words with freq > cutoff, rank-ordered (ref
        # build_dict), '<unk>' = len(dict)
        kept = sorted((w for w, c in freq.items() if c > cutoff),
                      key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        unk = self.word_idx["<unk>"] = len(self.word_idx)
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                np.int64) for d in docs_raw]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model dataset (ref:
    python/paddle/text/datasets/imikolov.py — URL
    https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz,
    md5 30177ea32e27c525793142b6bf2c8e2d).

    data_type='NGRAM' yields window_size-gram id tuples; 'SEQ' yields
    (input ids, shifted target ids). Dict from the train split with
    freq >= min_word_freq plus '<s>', '<e>', '<unk>'."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, allow_synthetic=True):
        assert mode in ("train", "test"), mode
        assert data_type in ("NGRAM", "SEQ"), data_type
        self.data_type = data_type
        self.window_size = window_size
        if data_file and os.path.exists(data_file):
            lines_tr = self._read(data_file, "ptb.train.txt")
            lines = lines_tr if mode == "train" else self._read(
                data_file, "ptb.valid.txt")
        else:
            _synthetic_fallback(
                "Imikolov", "no local simple-examples.tgz"
                if not data_file
                else f"data_file {data_file!r} does not exist",
                allow_synthetic)
            rng = np.random.RandomState(0)
            vocab = [f"w{i}" for i in range(40)]
            lines_tr = [[vocab[j] for j in rng.randint(0, 40, 12)]
                        for _ in range(64)]
            lines = lines_tr if mode == "train" else lines_tr[:16]
        freq: dict = {}
        for ws in lines_tr:
            for w in ws:
                freq[w] = freq.get(w, 0) + 1
        kept = sorted((w for w, c in freq.items()
                       if c >= min_word_freq and w != "<unk>"),
                      key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        for tok in ("<s>", "<e>", "<unk>"):
            self.word_idx.setdefault(tok, len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.data = []
        for ws in lines:
            ids = ([self.word_idx["<s>"]]
                   + [self.word_idx.get(w, unk) for w in ws]
                   + [self.word_idx["<e>"]])
            if data_type == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(np.asarray(
                            ids[i - window_size:i], np.int64))
            else:
                self.data.append((np.asarray(ids[:-1], np.int64),
                                  np.asarray(ids[1:], np.int64)))

    @staticmethod
    def _read(data_file, member_suffix):
        with tarfile.open(data_file) as tf:
            for m in tf:
                if m.name.endswith(member_suffix):
                    raw = tf.extractfile(m).read().decode(
                        "utf-8", "ignore")
                    return [ln.strip().split() for ln in raw.splitlines()
                            if ln.strip()]
        raise ValueError(f"{member_suffix} not found in {data_file}")

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (ref:
    python/paddle/text/datasets/uci_housing.py — URL
    http://paddlemodels.bj.bcebos.com/uci_housing/housing.data,
    md5 d4accdce7a25600298819f8e28e8d593).

    506 rows x 14 columns; features min-max-centred over the whole file
    (reference normalization), train = first 404 rows, test = rest."""

    TRAIN_ROWS = 404

    def __init__(self, data_file=None, mode="train",
                 allow_synthetic=True):
        assert mode in ("train", "test"), mode
        if data_file and os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            _synthetic_fallback(
                "UCIHousing", "no local housing.data" if not data_file
                else f"data_file {data_file!r} does not exist",
                allow_synthetic)
            rng = np.random.RandomState(0)
            raw = rng.standard_normal((506, 14)).astype(np.float32)
        if raw.ndim != 2 or raw.shape[1] != 14:
            raise ValueError(
                f"housing.data must be [N, 14]; got {raw.shape}")
        feats = raw[:, :13]
        maxs, mins, avgs = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avgs) / np.maximum(maxs - mins, 1e-6)
        data = np.concatenate([feats, raw[:, 13:]], axis=1)
        self.data = (data[:self.TRAIN_ROWS] if mode == "train"
                     else data[self.TRAIN_ROWS:])

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:13], row[13:]

    def __len__(self):
        return len(self.data)
