"""Utilities (ref: python/paddle/utils/)."""
from __future__ import annotations

import contextlib

import numpy as np


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"cannot import {module_name}")


@contextlib.contextmanager
def unique_name_guard(prefix=""):
    yield


def to_dlpack(tensor):
    import jax
    return jax.dlpack.to_dlpack(tensor._data)


def from_dlpack(capsule):
    import jax
    from ..core.tensor import Tensor
    return Tensor._wrap(jax.dlpack.from_dlpack(capsule))


dlpack = type("dlpack", (), {"to_dlpack": staticmethod(to_dlpack),
                             "from_dlpack": staticmethod(from_dlpack)})


def run_check():
    """paddle.utils.run_check analog — verifies the TPU stack end-to-end."""
    import jax
    import jax.numpy as jnp
    from .. import ops, nn, optimizer
    from ..core.tensor import to_tensor
    dev = jax.devices()[0]
    x = to_tensor(np.random.randn(8, 4).astype(np.float32),
                  stop_gradient=False)
    w = to_tensor(np.random.randn(4, 4).astype(np.float32),
                  stop_gradient=False)
    y = ops.matmul(x, w).sum()
    y.backward()
    assert w.grad is not None
    print(f"paddle_tpu is installed successfully! device = {dev}")
    return True


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs counter (ref: python/paddle/utils/flops.py)."""
    from ..nn.layer import Layer
    from .. import nn as _nn
    total = [0]

    def hook(layer, inputs, output):
        import numpy as _np
        if isinstance(layer, _nn.Linear):
            total[0] += 2 * int(_np.prod(inputs[0].shape)) // inputs[0].shape[-1] \
                * layer.weight.shape[0] * layer.weight.shape[1]
        elif isinstance(layer, _nn.Conv2D):
            oshape = output.shape
            kh, kw = layer.kernel_size
            total[0] += (2 * oshape[0] * oshape[1] * oshape[2] * oshape[3]
                         * layer.in_channels // layer.groups * kh * kw)

    handles = [l.register_forward_post_hook(hook)
               for l in net.sublayers(include_self=True)]
    from ..ops import zeros
    x = zeros(input_size)
    net(x)
    for h in handles:
        h.remove()
    return total[0]


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn
