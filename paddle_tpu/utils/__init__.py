"""Utilities (ref: python/paddle/utils/)."""
from __future__ import annotations

import contextlib

import numpy as np


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"cannot import {module_name}")


@contextlib.contextmanager
def unique_name_guard(prefix=""):
    yield


def to_dlpack(tensor):
    import jax
    return jax.dlpack.to_dlpack(tensor._data)


def from_dlpack(capsule):
    import jax
    from ..core.tensor import Tensor
    return Tensor._wrap(jax.dlpack.from_dlpack(capsule))


dlpack = type("dlpack", (), {"to_dlpack": staticmethod(to_dlpack),
                             "from_dlpack": staticmethod(from_dlpack)})


def run_check():
    """paddle.utils.run_check analog — verifies the TPU stack end-to-end."""
    import jax
    import jax.numpy as jnp
    from .. import ops, nn, optimizer
    from ..core.tensor import to_tensor
    dev = jax.devices()[0]
    x = to_tensor(np.random.randn(8, 4).astype(np.float32),
                  stop_gradient=False)
    w = to_tensor(np.random.randn(4, 4).astype(np.float32),
                  stop_gradient=False)
    y = ops.matmul(x, w).sum()
    y.backward()
    assert w.grad is not None
    print(f"paddle_tpu is installed successfully! device = {dev}")
    return True


def _layer_flops(layer, inputs, output):
    """Per-layer FLOP formulas (ref: python/paddle/utils/flops.py:27 /
    hapi/dynamic_flops.py count_* registry). Returns None for layers
    with no registered counter."""
    import numpy as _np
    from .. import nn as _nn

    def prod(s):
        return int(_np.prod(s))

    if isinstance(layer, _nn.Linear):
        rows = prod(inputs[0].shape) // inputs[0].shape[-1]
        return 2 * rows * layer.weight.shape[0] * layer.weight.shape[1]
    if isinstance(layer, (_nn.Conv1D, _nn.Conv2D, _nn.Conv3D)):
        k = prod(layer.kernel_size) if hasattr(layer, "kernel_size") \
            else 1
        return (2 * prod(output.shape)
                * layer.in_channels // layer.groups * k)
    if isinstance(layer, (_nn.BatchNorm1D, _nn.BatchNorm2D,
                          _nn.BatchNorm3D, _nn.BatchNorm,
                          _nn.LayerNorm, _nn.GroupNorm,
                          _nn.InstanceNorm2D)):
        return 2 * prod(output.shape)
    if isinstance(layer, (_nn.ReLU, _nn.GELU, _nn.Sigmoid, _nn.Tanh,
                          _nn.Softmax)):
        return prod(output.shape)
    if isinstance(layer, (_nn.AvgPool2D, _nn.MaxPool2D,
                          _nn.AdaptiveAvgPool2D)):
        return prod(output.shape)
    return None


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs counter over a forward pass (ref:
    python/paddle/utils/flops.py:27 via hapi/dynamic_flops.py paddle.flops).
    custom_ops: {LayerType: fn(layer, inputs, output) -> flops}."""
    total = [0]
    rows = []
    custom_ops = custom_ops or {}

    def hook(layer, inputs, output):
        fn = None
        for cls, f in custom_ops.items():
            if isinstance(layer, cls):
                fn = f
                break
        n = fn(layer, inputs, output) if fn else \
            _layer_flops(layer, inputs, output)
        if n:
            total[0] += int(n)
            rows.append((type(layer).__name__,
                         tuple(getattr(output, "shape", ())), int(n)))

    handles = [l.register_forward_post_hook(hook)
               for l in net.sublayers(include_self=True)]
    from ..ops import zeros
    was_training = net.training
    net.eval()
    try:
        net(zeros(input_size))
    finally:
        if was_training:
            net.train()
        for h in handles:
            h.remove()
    if print_detail:
        print(f"{'layer':<24} {'output shape':<24} {'FLOPs':>16}")
        for name, shape, n in rows:
            print(f"{name:<24} {str(shape):<24} {n:>16,}")
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn
