"""Custom C++ op loading (paddle.utils.cpp_extension parity, C6).

The reference JIT-compiles user C++/CUDA against its PD_BUILD_OP ABI
(/root/reference/python/paddle/utils/cpp_extension/extension_utils.py,
paddle/phi/api/ext/op_meta_info.h). The TPU-native split is:

  * DEVICE custom kernels are Pallas — that IS the plugin ABI for the
    accelerator (kernels/pallas/*), no C++ device path exists on TPU.
  * HOST custom ops (pre/post-processing, tokenizers, CPU math the
    framework lacks) compile here with g++ into a shared object and run
    inside the XLA program via `jax.pure_callback` — the host-callback
    analog of the reference's CPU custom kernels.

C ABI (v1, documented contract):

    extern "C" void <op_name>(
        const void* const* inputs,     // n_inputs data pointers
        const long long*  sizes,       // n_inputs element counts
        int               n_inputs,
        void*             output,      // preallocated
        long long         out_elems);

dtype is carried python-side (all inputs and the output share the first
input's dtype). Gradients: host callbacks are opaque to autograd — wrap
the returned op in `paddle_tpu.autograd.PyLayer` to attach a custom
backward, exactly like the reference's custom-grad story.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["load", "CppExtension", "CUDAExtension", "get_build_directory"]


def get_build_directory():
    d = os.environ.get("PT_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name, sources, extra_cflags, build_directory, verbose,
             ldflags=()):
    build_dir = build_directory or get_build_directory()
    tag = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            tag.update(f.read())
    tag.update(" ".join(list(extra_cflags or []) + list(ldflags)).encode())
    lib_path = os.path.join(build_dir, f"{name}_{tag.hexdigest()[:12]}.so")
    if not os.path.exists(lib_path):
        # -l libraries must FOLLOW the objects that reference them
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
               + list(extra_cflags or []) + list(sources)
               + list(ldflags) + ["-o", lib_path])
        if verbose:
            print("cpp_extension:", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return lib_path


class CustomOpModule:
    """Holds the dlopened library; attribute access returns wrapped ops."""

    def __init__(self, name, lib_path, op_names):
        self._name = name
        self._lib = ctypes.CDLL(lib_path)
        self._ops = {}
        for op in op_names:
            self._ops[op] = self._make_op(op)

    def _make_op(self, op_name):
        cfn = getattr(self._lib, op_name)
        cfn.restype = None
        cfn.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                        ctypes.POINTER(ctypes.c_longlong),
                        ctypes.c_int, ctypes.c_void_p, ctypes.c_longlong]

        def _host_call(shape, dtype):
            def call(*arrays):
                arrays = [np.ascontiguousarray(a) for a in arrays]
                out = np.empty(shape, dtype)
                ptrs = (ctypes.c_void_p * len(arrays))(
                    *[a.ctypes.data_as(ctypes.c_void_p).value
                      for a in arrays])
                sizes = (ctypes.c_longlong * len(arrays))(
                    *[a.size for a in arrays])
                cfn(ptrs, sizes, len(arrays),
                    out.ctypes.data_as(ctypes.c_void_p), out.size)
                return out
            return call

        def op(*tensors, out_shape=None, out_dtype=None):
            datas = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
                     for t in tensors]
            shape = (tuple(out_shape) if out_shape is not None
                     else tuple(datas[0].shape))
            dtype = np.dtype(out_dtype) if out_dtype is not None \
                else np.dtype(str(datas[0].dtype))
            aval = jax.ShapeDtypeStruct(shape, dtype)
            res = jax.pure_callback(_host_call(shape, dtype), aval, *datas,
                                    vmap_method="sequential")
            # host callbacks are opaque to autograd; custom backward goes
            # through PyLayer (see module docstring)
            return Tensor._wrap(res, stop_gradient=True)

        op.__name__ = op_name
        return op

    def __getattr__(self, item):
        ops = object.__getattribute__(self, "_ops")
        if item in ops:
            return ops[item]
        raise AttributeError(
            f"custom-op module {self._name!r} has no op {item!r}; "
            f"loaded ops: {sorted(ops)}")


def _discover_ops(sources):
    """Exported op names: every `extern "C"` function following the v1
    signature, declared with PT_EXPORT_OP(<name>) or parsed from an
    extern "C" void <name>( pattern."""
    import re
    names = []
    pat = re.compile(
        r'(?:PT_EXPORT_OP\s*\(\s*(\w+)\s*\))|'
        r'(?:extern\s+"C"\s+void\s+(\w+)\s*\()')
    for s in sources:
        with open(s) as f:
            for m in pat.finditer(f.read()):
                names.append(m.group(1) or m.group(2))
    return list(dict.fromkeys(names))


def load(name, sources, extra_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None,
         build_directory=None, verbose=False):
    """Compile C++ sources and expose their ops (ref API:
    python/paddle/utils/cpp_extension/cpp_extension.py load)."""
    if extra_cuda_cflags:
        raise RuntimeError(
            "CUDA custom ops are not supported on TPU; write device "
            "kernels in Pallas (paddle_tpu/kernels/pallas) instead")
    cflags = list(extra_cflags or [])
    for inc in extra_include_paths or []:
        cflags.append(f"-I{inc}")
    lib_path = _compile(name, sources, cflags, build_directory, verbose,
                        ldflags=list(extra_ldflags or []))
    op_names = _discover_ops(sources)
    if not op_names:
        raise RuntimeError(
            f"no extern \"C\" v1-ABI ops found in {sources}; see "
            "paddle_tpu.utils.cpp_extension docstring for the contract")
    return CustomOpModule(name, lib_path, op_names)


class CppExtension:
    """setup()-style spec shim; `load` is the supported JIT path."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is not supported on TPU; device kernels are "
        "Pallas (see paddle_tpu/kernels/pallas) and host ops use "
        "CppExtension/load")
