"""Small filesystem durability helpers (jax-free — shared by
framework_io and distributed.checkpoint crash-safe writers)."""
from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """Durably record directory entries (renames/creates) themselves —
    fsyncing the file alone does not persist its directory entry. Best
    effort: silently a no-op on platforms without directory fds."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
