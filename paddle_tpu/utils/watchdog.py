"""Step/collective hang watchdog.

Reference: the comm-hang sanitizers around the reference's process
groups (FLAGS_enable_async_trace / comm task timeouts in
ProcessGroupNCCL::WaitTask — a stuck collective dumps state and aborts
instead of hanging CI silently).

TPU rendering: XLA collectives cannot be interrupted per-op, but the
host CAN observe that a dispatched step never completed. The watchdog
arms a timer around a blocking region (a train step, a checkpoint
write, a collective-heavy eval); if the region does not finish in
time it dumps the stacks of every Python thread to stderr and either
warns or aborts the process (FLAGS_watchdog_abort) so the scheduler /
elastic layer can restart the job. Zero overhead when unarmed.

    from paddle_tpu.utils.watchdog import watchdog
    with watchdog(120, what="train step"):
        loss = step(ids, labels)

or process-wide via flags:
    paddle_tpu.set_flags({"FLAGS_watchdog_timeout_s": 300})
    ... TrainStep arms it around every blocking __call__.
"""
from __future__ import annotations

import faulthandler
import os
import sys
import threading
from contextlib import contextmanager

from ..core.flags import get_flags


def _flag(name):
    # fails loudly on an unknown name (a typo must not silently
    # disarm the watchdog); get_flags returns {name: value}
    return get_flags(name)[name]


class _Watchdog:
    def __init__(self, timeout_s: float, what: str, abort: bool):
        self.timeout_s = timeout_s
        self.what = what
        self.abort = abort
        self._done = threading.Event()
        self._timer = None

    def _fire(self):
        if self._done.is_set():
            return
        sys.stderr.write(
            f"\n[paddle_tpu watchdog] {self.what!r} exceeded "
            f"{self.timeout_s:.0f}s — likely a hung collective or "
            "device deadlock. Thread stacks follow.\n")
        sys.stderr.flush()
        try:
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:
            # replaced stderr (ipykernel/StringIO) has no fileno; the
            # abort path below must still run, so fall back to the
            # pure-Python dump
            import traceback
            for tid, frame in sys._current_frames().items():
                sys.stderr.write(f"Thread {tid:#x}:\n")
                traceback.print_stack(frame, file=sys.stderr)
            sys.stderr.flush()
        if self.abort:
            sys.stderr.write(
                "[paddle_tpu watchdog] aborting (FLAGS_watchdog_abort "
                "set) so the elastic layer can restart this worker\n")
            sys.stderr.flush()
            os._exit(124)

    def __enter__(self):
        if self.timeout_s > 0:
            self._timer = threading.Timer(self.timeout_s, self._fire)
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, *exc):
        self._done.set()
        if self._timer is not None:
            self._timer.cancel()
        return False


@contextmanager
def watchdog(timeout_s: float = None, what: str = "blocking region",
             abort: bool = None):
    """Arm a hang detector around a blocking region. timeout_s=None
    reads FLAGS_watchdog_timeout_s (0 = disarmed); abort=None reads
    FLAGS_watchdog_abort (default: warn only)."""
    if timeout_s is None:
        timeout_s = float(_flag("FLAGS_watchdog_timeout_s") or 0.0)
    if abort is None:
        abort = bool(_flag("FLAGS_watchdog_abort"))
    if not timeout_s:
        yield None
        return
    with _Watchdog(timeout_s, what, abort) as w:
        yield w
