"""Vision datasets (ref: python/paddle/vision/datasets/).

Zero-egress environment: datasets load from local files when present and
fall back to deterministic synthetic data (`mode='synthetic'` or missing
files) so examples/tests run hermetically."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
import warnings

import numpy as np

from ...io import Dataset


def _synthetic_fallback(name: str, reason: str, allow: bool):
    """Dataset honesty (VERDICT r4 weak-6): NEVER silently hand the user
    fake data. Warn loudly on fallback; raise when allow_synthetic=False."""
    msg = (f"{name}: {reason} — falling back to DETERMINISTIC SYNTHETIC "
           f"data (random pixels/labels). This is NOT the real dataset; "
           f"metrics trained on it are meaningless. Pass the local "
           f"dataset files (zero-egress environment: no downloads), or "
           f"allow_synthetic=False to make this an error.")
    if not allow:
        raise FileNotFoundError(f"{name}: {reason} (allow_synthetic=False)")
    warnings.warn(msg, UserWarning, stacklevel=3)


class _SyntheticImages(Dataset):
    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        self.n = n
        self.shape = shape
        self.num_classes = num_classes
        self.transform = transform
        self.rng = np.random.RandomState(seed)
        self.images = self.rng.randint(
            0, 256, size=(n,) + shape, dtype=np.uint8)
        self.labels = self.rng.randint(0, num_classes, size=(n,),
                                       dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


class MNIST(Dataset):
    """(ref: python/paddle/dataset/mnist.py) — local idx files or synthetic."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 allow_synthetic=True):
        self.transform = transform
        if (image_path and os.path.exists(image_path)
                and label_path and os.path.exists(label_path)):
            with gzip.open(image_path, "rb") as f:
                _, n, h, w = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, h, w)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8).astype(
                    np.int64)
        else:
            _synthetic_fallback(
                type(self).__name__,
                "no local idx files" if not (image_path and label_path)
                else f"image_path/label_path ({image_path!r}, "
                     f"{label_path!r}) not both present",
                allow_synthetic)
            synth = _SyntheticImages(1024 if mode == "train" else 256,
                                     (28, 28), 10)
            self.images, self.labels = synth.images, synth.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, allow_synthetic=True):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            images, labels = [], []
            with tarfile.open(data_file) as tf:
                names = [n for n in tf.getnames()
                         if ("data_batch" in n if mode == "train"
                             else "test_batch" in n)]
                for n in sorted(names):
                    d = pickle.load(tf.extractfile(n), encoding="bytes")
                    images.append(d[b"data"].reshape(-1, 3, 32, 32))
                    labels.extend(d[b"labels"])
            self.images = np.concatenate(images).transpose(0, 2, 3, 1)
            self.labels = np.asarray(labels, np.int64)
        else:
            _synthetic_fallback(
                type(self).__name__,
                "no local data_file" if not data_file
                else f"data_file {data_file!r} does not exist",
                allow_synthetic)
            synth = _SyntheticImages(1024 if mode == "train" else 256,
                                     (32, 32, 3), 10)
            self.images, self.labels = synth.images, synth.labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    pass


class Flowers(_SyntheticImages):
    """Flowers-102. Real files (102flowers.tgz jpgs) need a jpg decoder
    per image; supply them via DatasetFolder + ops.decode_jpeg. This
    class is synthetic-shape-only and SAYS so (VERDICT r4 weak-6)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None, allow_synthetic=True):
        _synthetic_fallback(
            "Flowers", "jpg-archive parsing is not implemented "
            "(use DatasetFolder + ops.decode_jpeg for the real files)",
            allow_synthetic)
        super().__init__(512, (224, 224, 3), 102, transform)


class DatasetFolder(Dataset):
    """(ref: python/paddle/vision/datasets/folder.py) — directory-of-class
    -subdirs image dataset; requires a local image decoder."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        exts = extensions or (".png", ".jpg", ".jpeg", ".npy")
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(tuple(exts)):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        raise RuntimeError(
            "no image decoder baked in; supply loader= or use .npy files")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder
