"""Vision models (ref: python/paddle/vision/models/)."""
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50,
    resnet101, resnet152, wide_resnet50_2, resnext50_32x4d,
)
from .lenet_vgg_mobilenet import (  # noqa: F401
    LeNet, VGG, vgg11, vgg13, vgg16, vgg19, MobileNetV2, mobilenet_v2,
    AlexNet, alexnet,
)
from .extra import (  # noqa: F401
    DenseNet, densenet121, densenet161, densenet169, densenet201,
    SqueezeNet, squeezenet1_0, squeezenet1_1,
    MobileNetV1, mobilenet_v1,
    MobileNetV3, mobilenet_v3_small, mobilenet_v3_large,
    ShuffleNetV2, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x2_0,
    GoogLeNet, googlenet, InceptionV3, inception_v3,
)
