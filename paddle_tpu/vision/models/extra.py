"""DenseNet / SqueezeNet / MobileNetV1 / MobileNetV3 / ShuffleNetV2 /
GoogLeNet / InceptionV3 (capability match for the rest of the reference
model zoo, python/paddle/vision/models/*.py).

Constructor/attribute naming follows the reference so state_dicts map
1:1, but the module bodies are written against this framework's nn API.
All archs are static-shape and NCHW, which XLA lays out for the MXU.
"""
from __future__ import annotations

from ... import nn
from ... import ops


def _conv_bn(cin, cout, k, stride=1, padding=0, groups=1, act="relu"):
    layers = [nn.Conv2D(cin, cout, k, stride=stride, padding=padding,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(cout)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    return nn.Sequential(*layers)


# ======================= DenseNet =======================

class _DenseLayer(nn.Layer):
    def __init__(self, cin, growth_rate, bn_size, dropout):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(cin)
        self.conv1 = nn.Conv2D(cin, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        out = self.conv1(ops.relu(self.norm1(x)))
        out = self.conv2(ops.relu(self.norm2(out)))
        return ops.concat([x, self.dropout(out)], axis=1)


class _Transition(nn.Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm = nn.BatchNorm2D(cin)
        self.conv = nn.Conv2D(cin, cout, 1, bias_attr=False)

    def forward(self, x):
        return ops.avg_pool2d(self.conv(ops.relu(self.norm(x))), 2, 2)


class DenseNet(nn.Layer):
    """ref: vision/models/densenet.py (121/161/169/201/264 configs)."""

    _cfgs = {121: (64, 32, (6, 12, 24, 16)),
             161: (96, 48, (6, 12, 36, 24)),
             169: (64, 32, (6, 12, 32, 32)),
             201: (64, 32, (6, 12, 48, 32)),
             264: (64, 32, (6, 12, 64, 48))}

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_c, growth, blocks = self._cfgs[layers]
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        c = init_c
        feats = []
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i < len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        self.features = nn.Sequential(*feats)
        self.final_norm = nn.BatchNorm2D(c)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = ops.relu(self.final_norm(self.features(self.stem(x))))
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


# ======================= SqueezeNet =======================

class _Fire(nn.Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(cin, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = ops.relu(self.squeeze(x))
        return ops.concat([ops.relu(self.expand1(s)),
                           ops.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """ref: vision/models/squeezenet.py (1.0 / 1.1 variants)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier_conv = nn.Conv2D(512, num_classes, 1)
        self.dropout = nn.Dropout(0.5)

    def forward(self, x):
        x = self.features(x)
        x = ops.relu(self.classifier_conv(self.dropout(x)))
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        return ops.flatten(x, 1)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)


# ======================= MobileNetV1 =======================

class MobileNetV1(nn.Layer):
    """ref: vision/models/mobilenetv1.py — depthwise-separable stacks."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + [
               (512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, stride=2, padding=1)]
        for cin, cout, stride in cfg:
            layers.append(_conv_bn(c(cin), c(cin), 3, stride=stride,
                                   padding=1, groups=c(cin)))
            layers.append(_conv_bn(c(cin), c(cout), 1))
        self.features = nn.Sequential(*layers)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


# ======================= MobileNetV3 =======================

class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, rd=4):
        super().__init__()
        self.fc1 = nn.Conv2D(ch, ch // rd, 1)
        self.fc2 = nn.Conv2D(ch // rd, ch, 1)

    def forward(self, x):
        s = ops.adaptive_avg_pool2d(x, 1)
        s = ops.relu(self.fc1(s))
        s = ops.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, cin, exp, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if exp != cin:
            layers.append(_conv_bn(cin, exp, 1, act=act))
        layers.append(_conv_bn(exp, exp, k, stride=stride, padding=k // 2,
                               groups=exp, act=act))
        if use_se:
            layers.append(_SqueezeExcite(exp))
        layers.append(_conv_bn(exp, cout, 1, act="none"))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3(nn.Layer):
    """ref: vision/models/mobilenetv3.py (small / large)."""

    _small = [  # k, exp, cout, se, act, stride
        (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
        (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
        (5, 240, 40, True, "hardswish", 1),
        (5, 240, 40, True, "hardswish", 1),
        (5, 120, 48, True, "hardswish", 1),
        (5, 144, 48, True, "hardswish", 1),
        (5, 288, 96, True, "hardswish", 2),
        (5, 576, 96, True, "hardswish", 1),
        (5, 576, 96, True, "hardswish", 1)]
    _large = [
        (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
        (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
        (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
        (3, 240, 80, False, "hardswish", 2),
        (3, 200, 80, False, "hardswish", 1),
        (3, 184, 80, False, "hardswish", 1),
        (3, 184, 80, False, "hardswish", 1),
        (3, 480, 112, True, "hardswish", 1),
        (3, 672, 112, True, "hardswish", 1),
        (5, 672, 160, True, "hardswish", 2),
        (5, 960, 160, True, "hardswish", 1),
        (5, 960, 160, True, "hardswish", 1)]

    def __init__(self, config="small", scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = self._small if config == "small" else self._large
        last_exp = 576 if config == "small" else 960

        def c(ch):
            # reference _make_divisible: round to /8 but never drop below
            # 90% of the unrounded width (vision/models/mobilenetv3.py)
            v = ch * scale
            new = max(8, int(v + 4) // 8 * 8)
            if new < 0.9 * v:
                new += 8
            return new

        layers = [_conv_bn(3, c(16), 3, stride=2, padding=1,
                           act="hardswish")]
        cin = c(16)
        for k, exp, cout, se, act, stride in cfg:
            layers.append(_MBV3Block(cin, c(exp), c(cout), k, stride, se,
                                     act))
            cin = c(cout)
        layers.append(_conv_bn(cin, c(last_exp), 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_exp), 1280), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(1280, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.classifier(ops.flatten(x, 1))
        return x


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3("small", scale=scale, **kw)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3("large", scale=scale, **kw)


# ======================= ShuffleNetV2 =======================

def _channel_shuffle(x, groups):
    return ops.channel_shuffle(x, groups)


class _ShuffleUnit(nn.Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        if stride == 1:
            in_b = cin // 2
        else:
            in_b = cin
            self.branch1 = nn.Sequential(
                _conv_bn(in_b, in_b, 3, stride=stride, padding=1,
                         groups=in_b, act="none"),
                _conv_bn(in_b, branch, 1))
        self.branch2 = nn.Sequential(
            _conv_bn(in_b if stride > 1 else branch, branch, 1),
            _conv_bn(branch, branch, 3, stride=stride, padding=1,
                     groups=branch, act="none"),
            _conv_bn(branch, branch, 1))

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = ops.split(x, 2, axis=1)
            out = ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = ops.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """ref: vision/models/shufflenetv2.py."""

    _stage_out = {0.25: (24, 24, 48, 96, 512),
                  0.33: (24, 32, 64, 128, 512),
                  0.5: (24, 48, 96, 192, 1024),
                  1.0: (24, 116, 232, 464, 1024),
                  1.5: (24, 176, 352, 704, 1024),
                  2.0: (24, 244, 488, 976, 2048)}

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c0, c1, c2, c3, c4 = self._stage_out[scale]
        self.stem = nn.Sequential(_conv_bn(3, c0, 3, stride=2, padding=1),
                                  nn.MaxPool2D(3, stride=2, padding=1))
        stages = []
        cin = c0
        for cout, repeat in zip((c1, c2, c3), (4, 8, 4)):
            stages.append(_ShuffleUnit(cin, cout, 2))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(cout, cout, 1))
            cin = cout
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(cin, c4, 1)
        if num_classes > 0:
            self.fc = nn.Linear(c4, num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.stem(x)))
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.fc(ops.flatten(x, 1))
        return x


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


# ======================= GoogLeNet / InceptionV3 =======================

class _InceptionA(nn.Layer):
    """GoogLeNet inception block (v1 style with 1x1/3x3/5x5/pool)."""

    def __init__(self, cin, c1, c3r, c3, c5r, c5, cp):
        super().__init__()
        self.b1 = _conv_bn(cin, c1, 1)
        self.b3 = nn.Sequential(_conv_bn(cin, c3r, 1),
                                _conv_bn(c3r, c3, 3, padding=1))
        self.b5 = nn.Sequential(_conv_bn(cin, c5r, 1),
                                _conv_bn(c5r, c5, 5, padding=2))
        self.bp = _conv_bn(cin, cp, 1)

    def forward(self, x):
        pooled = ops.max_pool2d(x, 3, stride=1, padding=1)
        return ops.concat([self.b1(x), self.b3(x), self.b5(x),
                           self.bp(pooled)], axis=1)


class GoogLeNet(nn.Layer):
    """ref: vision/models/googlenet.py (aux heads omitted at inference;
    kept as attributes for state_dict parity when training)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _conv_bn(64, 64, 1), _conv_bn(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3 = nn.Sequential(
            _InceptionA(192, 64, 96, 128, 16, 32, 32),
            _InceptionA(256, 128, 128, 192, 32, 96, 64))
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc4 = nn.Sequential(
            _InceptionA(480, 192, 96, 208, 16, 48, 64),
            _InceptionA(512, 160, 112, 224, 24, 64, 64),
            _InceptionA(512, 128, 128, 256, 24, 64, 64),
            _InceptionA(512, 112, 144, 288, 32, 64, 64),
            _InceptionA(528, 256, 160, 320, 32, 128, 128))
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc5 = nn.Sequential(
            _InceptionA(832, 256, 160, 320, 32, 128, 128),
            _InceptionA(832, 384, 192, 384, 48, 128, 128))
        self.dropout = nn.Dropout(0.2)
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.pool4(self.inc4(self.pool3(self.inc3(
            self.stem(x))))))
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.fc(self.dropout(ops.flatten(x, 1)))
        return x


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


class _InceptionV3A(nn.Layer):
    def __init__(self, cin, pool_feat):
        super().__init__()
        self.b1 = _conv_bn(cin, 64, 1)
        self.b5 = nn.Sequential(_conv_bn(cin, 48, 1),
                                _conv_bn(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_conv_bn(cin, 64, 1),
                                _conv_bn(64, 96, 3, padding=1),
                                _conv_bn(96, 96, 3, padding=1))
        self.bp = _conv_bn(cin, pool_feat, 1)

    def forward(self, x):
        p = ops.avg_pool2d(x, 3, stride=1, padding=1)
        return ops.concat([self.b1(x), self.b5(x), self.b3(x),
                           self.bp(p)], axis=1)


class _InceptionV3Reduce(nn.Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = _conv_bn(cin, 384, 3, stride=2)
        self.b3d = nn.Sequential(_conv_bn(cin, 64, 1),
                                 _conv_bn(64, 96, 3, padding=1),
                                 _conv_bn(96, 96, 3, stride=2))

    def forward(self, x):
        p = ops.max_pool2d(x, 3, stride=2)
        return ops.concat([self.b3(x), self.b3d(x), p], axis=1)


class _InceptionV3C(nn.Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _conv_bn(cin, 192, 1)
        self.b7 = nn.Sequential(
            _conv_bn(cin, c7, 1),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _conv_bn(cin, c7, 1),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = _conv_bn(cin, 192, 1)

    def forward(self, x):
        p = ops.avg_pool2d(x, 3, stride=1, padding=1)
        return ops.concat([self.b1(x), self.b7(x), self.b7d(x),
                           self.bp(p)], axis=1)


class InceptionV3(nn.Layer):
    """ref: vision/models/inceptionv3.py — the 299x299 v3 trunk with the
    A (35x35), reduction, C (17x17) stages and a simplified final stage
    (3x3-split E blocks rendered as dense 3x3s for static shapes)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.inc_a = nn.Sequential(
            _InceptionV3A(192, 32), _InceptionV3A(256, 64),
            _InceptionV3A(288, 64))
        self.reduce1 = _InceptionV3Reduce(288)
        self.inc_c = nn.Sequential(
            _InceptionV3C(768, 128), _InceptionV3C(768, 160),
            _InceptionV3C(768, 160), _InceptionV3C(768, 192))
        self.tail = nn.Sequential(
            _conv_bn(768, 1280, 3, stride=2),
            _conv_bn(1280, 2048, 1))
        self.dropout = nn.Dropout(0.5)
        if num_classes > 0:
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.tail(self.inc_c(self.reduce1(self.inc_a(self.stem(x)))))
        if self.with_pool:
            x = ops.adaptive_avg_pool2d(x, 1)
        if self.num_classes > 0:
            x = self.fc(self.dropout(ops.flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
