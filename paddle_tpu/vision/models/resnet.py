"""ResNet family (ref: python/paddle/vision/models/resnet.py) — config 1 of
BASELINE.md. Structure matches the reference so state_dicts map 1:1."""
from __future__ import annotations

from ... import nn
from ... import ops




def _norm(norm_layer, ch, df):
    """Pass data_format only to norm layers that accept it (custom
    norm_layer callables may not). The no-kwarg fallback is only legal
    in the default NCHW layout — an NHWC model MUST layout-configure
    its norms, so there the TypeError propagates."""
    if df == "NCHW":
        try:
            return norm_layer(ch, data_format=df)
        except TypeError:
            return norm_layer(ch)
    return norm_layer(ch, data_format=df)

class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=df)
        self.bn1 = _norm(norm_layer, planes, df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = _norm(norm_layer, planes, df)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        df = data_format
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = _norm(norm_layer, width, df)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=dilation,
                               groups=groups, dilation=dilation,
                               bias_attr=False, data_format=df)
        self.bn2 = _norm(norm_layer, width, df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = _norm(norm_layer, planes * self.expansion, df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW",
                 space_to_depth_stem=False):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.dilation = 1
        # data_format="NHWC" is the TPU-preferred layout: convolutions
        # keep channels in the minor (lane) dimension so XLA tiles them
        # onto the MXU without inserting transposes
        self.data_format = data_format
        # space-to-depth stem (the MLPerf-ResNet TPU trick): the 7x7/s2
        # conv over 3 channels wastes the 128-lane MXU minor dimension
        # (3/128 utilization); rearranging 2x2 pixel blocks into
        # channels turns it into a mathematically IDENTICAL 4x4/s1 conv
        # over 12 channels on a half-resolution image. conv1's weights
        # are stored in the standard [64, 3, 7, 7] layout (checkpoints
        # stay compatible) and transformed on the fly in _stem.
        if space_to_depth_stem and data_format != "NHWC":
            raise ValueError(
                "space_to_depth_stem requires data_format='NHWC' "
                "(the TPU layout it exists for)")
        self.space_to_depth_stem = space_to_depth_stem
        df = data_format
        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=df)
        self.bn1 = nn.BatchNorm2D(self.inplanes, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, data_format=df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                nn.BatchNorm2D(planes * block.expansion, data_format=df))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, data_format=df))
        return nn.Sequential(*layers)

    def _stem_conv(self, x):
        if not self.space_to_depth_stem:
            return self.conv1(x)
        # x: [N, H, W, 3] -> [N, H/2, W/2, 12], channel index (ph, pw, c)
        n, h, w, c = x.shape
        y = ops.reshape(x, (n, h // 2, 2, w // 2, 2, c))
        y = ops.transpose(y, (0, 1, 3, 2, 4, 5))
        y = ops.reshape(y, (n, h // 2, w // 2, 4 * c))
        # weights [O, 3, 7, 7]: pad spatial to 8 at the FRONT so index
        # dh+1 = 2*jh + ph factors exactly into (block tap jh, parity
        # ph); tap (jh=0, ph=0) is the zero row the padding added
        wt = self.conv1.weight
        o = wt.shape[0]
        w8 = ops.pad(wt, [0, 0, 0, 0, 1, 0, 1, 0])
        w8 = ops.reshape(w8, (o, c, 4, 2, 4, 2))        # jh, ph, jw, pw
        w8 = ops.transpose(w8, (0, 3, 5, 1, 2, 4))      # o,ph,pw,c,jh,jw
        w2 = ops.reshape(w8, (o, 4 * c, 4, 4))
        # original reads rows 2*ho + [-3..3]; in block space taps land
        # on blocks ho + [-2..1] -> padding (2 before, 1 after)
        return ops.conv2d(y, w2, stride=1, padding=[(2, 1), (2, 1)],
                          data_format="NHWC")

    def forward(self, x):
        x = self.relu(self.bn1(self._stem_conv(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access; load a local "
            "state_dict with set_state_dict instead")
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)
