"""Vision ops (ref: python/paddle/vision/ops.py: roi_align, nms,
deform_conv2d...)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import register_op
from ..core.tensor import Tensor


@register_op("nms")
def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Dynamic-output op -> eager only (returns kept indices)."""
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    b = boxes[order]
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = (x2 - x1) * (y2 - y1)

    def iou(i, js):
        xx1 = jnp.maximum(x1[i], x1[js])
        yy1 = jnp.maximum(y1[i], y1[js])
        xx2 = jnp.minimum(x2[i], x2[js])
        yy2 = jnp.minimum(y2[i], y2[js])
        inter = jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0)
        return inter / (areas[i] + areas[js] - inter + 1e-10)

    keep_mask = jnp.ones(n, bool)
    for i in range(n):
        if not bool(keep_mask[i]):
            continue
        rest = jnp.arange(n) > i
        sup = (iou(i, jnp.arange(n)) > iou_threshold) & rest
        keep_mask = keep_mask & ~sup
    kept = order[jnp.nonzero(keep_mask)[0]]
    if top_k is not None:
        kept = kept[:top_k]
    return kept.astype(jnp.int64)


@register_op("roi_align")
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear grid sampling (ref: vision/ops.py roi_align)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    num_rois = boxes.shape[0]
    # map each roi to its batch image
    counts = boxes_num.astype(jnp.int32)
    batch_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                           total_repeat_length=num_rois)
    offset = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x2 - x1, 1e-3)
    rh = jnp.maximum(y2 - y1, 1e-3)
    ys = (jnp.arange(oh) + 0.5) / oh  # [oh]
    xs = (jnp.arange(ow) + 0.5) / ow
    gy = y1[:, None] + rh[:, None] * ys[None, :]  # [R, oh]
    gx = x1[:, None] + rw[:, None] * xs[None, :]  # [R, ow]

    def bilinear(img, yy, xx):
        # img [c,h,w]; yy [oh], xx [ow]
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy - y0, 0, 1)[None, :, None]
        wx = jnp.clip(xx - x0, 0, 1)[None, None, :]
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1_]
        v10 = img[:, y1_][:, :, x0]
        v11 = img[:, y1_][:, :, x1_]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    out = jax.vmap(lambda bi, yy, xx: bilinear(x[bi], yy, xx))(
        batch_idx, gy, gx)
    return out  # [R, c, oh, ow]


@register_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    px = prior_box[:, 0] + pw / 2
    py = prior_box[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0]
        th = target_box[:, 3] - target_box[:, 1]
        tx = target_box[:, 0] + tw / 2
        ty = target_box[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        if prior_box_var is not None:
            out = out / prior_box_var
        return out
    if code_type == "decode_center_size":
        # ref: phi/kernels/impl/box_coder.h DecodeCenterSize — deltas
        # [M, 4] or [N, M, 4] against priors [M, 4]
        tb = target_box if target_box.ndim == 3 else target_box[None]
        if prior_box_var is not None:
            tb = tb * prior_box_var
        cx = tb[..., 0] * pw + px
        cy = tb[..., 1] * ph + py
        w = jnp.exp(tb[..., 2]) * pw
        h = jnp.exp(tb[..., 3]) * ph
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=-1)
        return out if target_box.ndim == 3 else out[0]
    raise NotImplementedError(code_type)


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError(
        "deform_conv2d needs a dedicated gather kernel; tracked for the "
        "Pallas kernel milestone")
