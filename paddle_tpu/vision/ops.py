"""Vision ops (ref: python/paddle/vision/ops.py: roi_align, nms,
deform_conv2d...)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import register_op
from ..core.tensor import Tensor


@register_op("nms")
def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Dynamic-output op -> eager only (returns kept indices)."""
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    b = boxes[order]
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = (x2 - x1) * (y2 - y1)

    def iou(i, js):
        xx1 = jnp.maximum(x1[i], x1[js])
        yy1 = jnp.maximum(y1[i], y1[js])
        xx2 = jnp.minimum(x2[i], x2[js])
        yy2 = jnp.minimum(y2[i], y2[js])
        inter = jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0)
        return inter / (areas[i] + areas[js] - inter + 1e-10)

    keep_mask = jnp.ones(n, bool)
    for i in range(n):
        if not bool(keep_mask[i]):
            continue
        rest = jnp.arange(n) > i
        sup = (iou(i, jnp.arange(n)) > iou_threshold) & rest
        keep_mask = keep_mask & ~sup
    kept = order[jnp.nonzero(keep_mask)[0]]
    if top_k is not None:
        kept = kept[:top_k]
    return kept.astype(jnp.int64)


@register_op("roi_align")
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign via bilinear grid sampling (ref: vision/ops.py roi_align)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    num_rois = boxes.shape[0]
    # map each roi to its batch image
    counts = boxes_num.astype(jnp.int32)
    batch_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                           total_repeat_length=num_rois)
    offset = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - offset
    y1 = boxes[:, 1] * spatial_scale - offset
    x2 = boxes[:, 2] * spatial_scale - offset
    y2 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x2 - x1, 1e-3)
    rh = jnp.maximum(y2 - y1, 1e-3)
    ys = (jnp.arange(oh) + 0.5) / oh  # [oh]
    xs = (jnp.arange(ow) + 0.5) / ow
    gy = y1[:, None] + rh[:, None] * ys[None, :]  # [R, oh]
    gx = x1[:, None] + rw[:, None] * xs[None, :]  # [R, ow]

    def bilinear(img, yy, xx):
        # img [c,h,w]; yy [oh], xx [ow]
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(yy - y0, 0, 1)[None, :, None]
        wx = jnp.clip(xx - x0, 0, 1)[None, None, :]
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1_]
        v10 = img[:, y1_][:, :, x0]
        v11 = img[:, y1_][:, :, x1_]
        return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                + v10 * wy * (1 - wx) + v11 * wy * wx)

    out = jax.vmap(lambda bi, yy, xx: bilinear(x[bi], yy, xx))(
        batch_idx, gy, gx)
    return out  # [R, c, oh, ow]


@register_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True):
    pw = prior_box[:, 2] - prior_box[:, 0]
    ph = prior_box[:, 3] - prior_box[:, 1]
    px = prior_box[:, 0] + pw / 2
    py = prior_box[:, 1] + ph / 2
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0]
        th = target_box[:, 3] - target_box[:, 1]
        tx = target_box[:, 0] + tw / 2
        ty = target_box[:, 1] + th / 2
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        if prior_box_var is not None:
            out = out / prior_box_var
        return out
    if code_type == "decode_center_size":
        # ref: phi/kernels/impl/box_coder.h DecodeCenterSize — deltas
        # [M, 4] or [N, M, 4] against priors [M, 4]
        tb = target_box if target_box.ndim == 3 else target_box[None]
        if prior_box_var is not None:
            tb = tb * prior_box_var
        cx = tb[..., 0] * pw + px
        cy = tb[..., 1] * ph + py
        w = jnp.exp(tb[..., 2]) * pw
        h = jnp.exp(tb[..., 3]) * ph
        out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                        axis=-1)
        return out if target_box.ndim == 3 else out[0]
    raise NotImplementedError(code_type)


@register_op("deformable_conv")
def deform_conv2d(x, offset, weight, mask=None, bias=None, stride=1,
                  padding=0, dilation=1, deformable_groups=1, groups=1):
    """Deformable convolution v1/v2 (ref: python/paddle/vision/ops.py
    deform_conv2d; phi/kernels/impl/deformable_conv_kernel_impl.h).
    TPU rendering: the sampled im2col is a dense gather + bilinear
    interpolation (all static shapes), and the conv becomes ONE MXU
    matmul over the sampled patches — no per-position scatter loops.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo] (y/x pairs);
    mask: [N, dg*kh*kw, Ho, Wo] (v2 modulation, None = v1);
    weight: [Cout, Cin//groups, kh, kw].
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else \
        tuple(dilation)
    N, Cin, H, W = x.shape
    Cout, _, kh, kw = weight.shape
    Ho = (H + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
    Wo = (W + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1
    dg = deformable_groups
    K = kh * kw

    off = offset.reshape(N, dg, K, 2, Ho, Wo).astype(jnp.float32)
    # base sampling grid (kernel tap positions per output pixel)
    gy = jnp.arange(Ho) * s[0] - p[0]
    gx = jnp.arange(Wo) * s[1] - p[1]
    ky = jnp.arange(kh) * d[0]
    kx = jnp.arange(kw) * d[1]
    base_y = gy[None, :, None] + ky.reshape(kh, 1, 1)   # [kh, Ho, 1]
    base_x = gx[None, None, :] + kx.reshape(kw, 1, 1)   # [kw, 1, Wo]
    base_y = jnp.broadcast_to(base_y[:, None], (kh, kw, Ho, Wo))
    base_x = jnp.broadcast_to(base_x[None, :].reshape(1, kw, 1, Wo),
                              (kh, kw, Ho, Wo))
    base = jnp.stack([base_y, base_x]).reshape(2, K, Ho, Wo)
    sy = base[0][None, None] + off[:, :, :, 0]          # [N, dg, K, Ho, Wo]
    sx = base[1][None, None] + off[:, :, :, 1]

    # bilinear sample; out-of-image taps contribute 0 (kernel contract)
    y0 = jnp.floor(sy); x0 = jnp.floor(sx)
    wy1 = (sy - y0); wx1 = (sx - x0)
    vals = 0.0
    xs = x.reshape(N, dg, Cin // dg, H, W).astype(jnp.float32)
    for dy_, wy_ in ((y0, 1.0 - wy1), (y0 + 1, wy1)):
        for dx_, wx_ in ((x0, 1.0 - wx1), (x0 + 1, wx1)):
            ok = ((dy_ >= 0) & (dy_ < H) & (dx_ >= 0) & (dx_ < W))
            iy = jnp.clip(dy_, 0, H - 1).astype(jnp.int32)
            ix = jnp.clip(dx_, 0, W - 1).astype(jnp.int32)
            # gather per (n, dg): [N, dg, C', K, Ho, Wo]
            g = jnp.take_along_axis(
                xs.reshape(N, dg, Cin // dg, H * W)[:, :, :, None],
                (iy * W + ix).reshape(N, dg, 1, K * Ho * Wo)[:, :, :,
                                                             None, :]
                .astype(jnp.int32).reshape(N, dg, 1, 1, K * Ho * Wo),
                axis=-1).reshape(N, dg, Cin // dg, K, Ho, Wo)
            vals = vals + g * (wy_ * wx_ * ok)[:, :, None]
    if mask is not None:
        vals = vals * mask.reshape(N, dg, 1, K, Ho, Wo)

    # cols: [N, Cin*K, Ho*Wo] -> grouped matmul with weight
    cols = vals.reshape(N, Cin, K, Ho * Wo)
    wf = weight.astype(jnp.float32).reshape(
        groups, Cout // groups, (Cin // groups) * K)
    cols = cols.reshape(N, groups, (Cin // groups) * K, Ho * Wo)
    out = jnp.einsum("gok,ngkp->ngop", wf, cols,
                     preferred_element_type=jnp.float32)
    out = out.reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + bias.reshape(1, Cout, 1, 1)
    return out.astype(x.dtype)


def read_file(filename, name=None):
    """Read a file's bytes as a uint8 1-D Tensor (ref:
    python/paddle/vision/ops.py:1337 read_file). Host IO -> eager-only."""
    import numpy as np
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor._wrap(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte Tensor to [C, H, W] uint8 (ref:
    python/paddle/vision/ops.py decode_jpeg, phi decode_jpeg nvjpeg
    kernel). Host-side decode (PIL) — image IO is input-pipeline work
    that belongs on the host, the TPU sees the decoded tensor."""
    import io as _io
    import numpy as np
    from PIL import Image
    raw = bytes(np.asarray(x._data if isinstance(x, Tensor) else x)
                .astype(np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[None]                      # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)         # [C, H, W]
    return Tensor._wrap(jnp.asarray(arr))


@register_op("yolo_loss")
def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0):
    """YOLOv3 loss (ref: python/paddle/vision/ops.py:52 yolo_loss;
    phi/kernels/cpu/yolo_loss_kernel.cc semantics). x: [N, S*(5+C), H, W];
    gt_box: [N, B, 4] normalized cx/cy/w/h; gt_label: [N, B] int.
    Static-shape rendering: GT->anchor assignment is a fixed-size
    scatter (invalid GTs scatter out of bounds and are dropped), the
    three loss parts are masked elementwise sums. Returns [N] loss."""
    N, _, H, W = x.shape
    S = len(anchor_mask)
    C = class_num
    B = gt_box.shape[1]
    x = x.reshape(N, S, 5 + C, H, W).astype(jnp.float32)
    tx, ty = x[:, :, 0], x[:, :, 1]
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]

    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)  # [A, 2]
    mask_idx = jnp.asarray(anchor_mask, jnp.int32)             # [S]
    in_w = W * downsample_ratio
    in_h = H * downsample_ratio

    gtb = gt_box.astype(jnp.float32)
    gw, gh = gtb[..., 2], gtb[..., 3]                          # [N, B]
    valid = (gw > 0) & (gh > 0)
    gscore = (jnp.ones((N, B), jnp.float32) if gt_score is None
              else gt_score.astype(jnp.float32))

    # ---- best-anchor match per GT (shape IoU over ALL anchors) ----
    aw = an_all[:, 0] / in_w
    ah = an_all[:, 1] / in_h
    inter = (jnp.minimum(gw[..., None], aw) *
             jnp.minimum(gh[..., None], ah))
    union = gw[..., None] * gh[..., None] + aw * ah - inter
    an_iou = inter / jnp.maximum(union, 1e-10)                 # [N,B,A]
    best = jnp.argmax(an_iou, axis=-1).astype(jnp.int32)       # [N, B]
    # position of best anchor inside anchor_mask (or -1)
    in_mask = best[..., None] == mask_idx                      # [N,B,S]
    mpos = jnp.where(in_mask.any(-1),
                     jnp.argmax(in_mask, axis=-1), -1).astype(jnp.int32)

    gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
    live = valid & (mpos >= 0)
    # scatter GT targets into [N, S, H, W] maps; dead GTs scatter OOB
    sm = jnp.where(live, mpos, S)
    nidx = jnp.broadcast_to(jnp.arange(N)[:, None], (N, B))
    def smap(vals, init=0.0):
        m = jnp.full((N, S, H, W), init, jnp.float32)
        return m.at[nidx, sm, gj, gi].set(vals.astype(jnp.float32))
    pw = an_all[jnp.clip(best, 0, an_all.shape[0] - 1), 0]
    ph = an_all[jnp.clip(best, 0, an_all.shape[0] - 1), 1]
    obj_map = smap(jnp.where(live, gscore, 0.0))
    tx_t = smap(gtb[..., 0] * W - gi)
    ty_t = smap(gtb[..., 1] * H - gj)
    tw_t = smap(jnp.log(jnp.maximum(gw * in_w / jnp.maximum(pw, 1e-9),
                                    1e-9)))
    th_t = smap(jnp.log(jnp.maximum(gh * in_h / jnp.maximum(ph, 1e-9),
                                    1e-9)))
    scale_t = smap(2.0 - gw * gh)           # box loss weight
    lbl = jnp.clip(gt_label.astype(jnp.int32), 0, C - 1)
    cls_t = jnp.zeros((N, S, H, W, C), jnp.float32).at[
        nidx, sm, gj, gi, lbl].set(1.0)
    pos = obj_map > 0

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    # ---- box + class losses on responsible cells ----
    loss_xy = (bce(tx, tx_t) + bce(ty, ty_t)) * scale_t * pos
    loss_wh = (jnp.abs(tw - tw_t) + jnp.abs(th - th_t)) * scale_t * pos
    smooth = 1.0 / max(C, 1) if (use_label_smooth and C > 1) else 0.0
    cls_target = cls_t * (1 - smooth) + smooth / max(C, 1) \
        if smooth else cls_t
    loss_cls = (bce(tcls.transpose(0, 1, 3, 4, 2), cls_target)
                * pos[..., None]).sum(-1)

    # ---- objectness: ignore preds whose IoU with any GT > thresh ----
    grid_x = (jnp.arange(W)[None, None, None] + jax.nn.sigmoid(tx)) / W
    grid_y = (jnp.arange(H)[None, None, :, None] + jax.nn.sigmoid(ty)) \
        / H
    pw_map = an_all[mask_idx, 0][None, :, None, None]
    ph_map = an_all[mask_idx, 1][None, :, None, None]
    pred_w = jnp.exp(tw) * pw_map / in_w
    pred_h = jnp.exp(th) * ph_map / in_h

    def box_iou(cx1, cy1, w1, h1, cx2, cy2, w2, h2):
        l1, r1 = cx1 - w1 / 2, cx1 + w1 / 2
        t1, b1 = cy1 - h1 / 2, cy1 + h1 / 2
        l2, r2 = cx2 - w2 / 2, cx2 + w2 / 2
        t2, b2 = cy2 - h2 / 2, cy2 + h2 / 2
        iw = jnp.maximum(jnp.minimum(r1, r2) - jnp.maximum(l1, l2), 0)
        ih = jnp.maximum(jnp.minimum(b1, b2) - jnp.maximum(t1, t2), 0)
        inter = iw * ih
        return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

    ious = box_iou(
        grid_x[..., None], grid_y[..., None], pred_w[..., None],
        pred_h[..., None],
        gtb[:, None, None, None, :, 0], gtb[:, None, None, None, :, 1],
        gtb[:, None, None, None, :, 2], gtb[:, None, None, None, :, 3])
    ious = jnp.where(valid[:, None, None, None], ious, 0.0)
    ignore = (ious.max(-1) > ignore_thresh) & ~pos
    loss_obj = bce(tobj, obj_map) * jnp.where(ignore, 0.0, 1.0)
    loss_obj = jnp.where(pos, loss_obj * obj_map,
                         loss_obj)  # positives weighted by gt_score

    total = (loss_xy + loss_wh + loss_cls + loss_obj)
    return total.sum(axis=(1, 2, 3))
