"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy/host
side preprocessing (runs on CPU workers, not TPU)."""
from __future__ import annotations

import numbers
import random

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _as_hwc(img).astype(np.float32)
        if img.dtype == np.uint8 or img.max() > 1.5:
            img = img / 255.0
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return Tensor(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean
            s = self.std
        return (img - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        if isinstance(self.size, int):
            if h < w:
                nh, nw = self.size, int(w * self.size / h)
            else:
                nh, nw = int(h * self.size / w), self.size
        else:
            nh, nw = self.size
        ys = (np.arange(nh) + 0.5) * h / nh - 0.5
        xs = (np.arange(nw) + 0.5) * w / nw - 0.5
        ys = np.clip(ys, 0, h - 1)
        xs = np.clip(xs, 0, w - 1)
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        img = img.astype(np.float32)
        out = (img[y0][:, x0] * (1 - wy) * (1 - wx)
               + img[y0][:, x1] * (1 - wy) * wx
               + img[y1][:, x0] * wy * (1 - wx)
               + img[y1][:, x1] * wy * wx)
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else size

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, [(p, p), (p, p), (0, 0)])
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[:, ::-1]
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[::-1]
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return self._resize(img[i:i + th, j:j + tw])
        return self._resize(CenterCrop(min(h, w))(img))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size)(img)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)
