"""Test harness config.

Per the build brief: tests run on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count) so multi-chip sharding logic is
exercised without TPU hardware. Must run before jax import."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

# the environment's sitecustomize pre-imports jax with the TPU plugin;
# jax_platforms can still be flipped before any computation runs
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the fast tier's wall-clock is
# compile-dominated (measured 10m49s CPU of a 12m31s -n2 run), and the
# same executables recompile every run without it. First run populates
# ~/.cache/paddle_tpu/xla_test_cache; later runs skip straight to
# execution. Harmless if unsupported (guarded).
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.expanduser("~/.cache/paddle_tpu/xla_test_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu", "tests must run on the CPU mesh"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


# ---------------------------------------------------------------------------
# Test tiering (ref: per-dir testslist.csv timeout/run_type metadata,
# /root/reference/test/collective/README.md:1-30). Files marked `slow`
# (model zoo, multi-model XLA-compile-heavy suites) are excluded from the
# default tier so `pytest tests/` stays under ~5 minutes; run them with
# `pytest --runslow` (CI's long tier).
# ---------------------------------------------------------------------------
def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="also run tests marked slow")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running (model zoo / many XLA compiles); "
        "excluded unless --runslow is given")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: run with --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
