"""Payload for the multi-node elastic restart test: at epoch 0, rank 1
(the second NODE's worker) crashes mid-job; at epoch 1 every rank
finishes. Rank 0 sleeps long enough that only a COORDINATED kill (the
elastic rendezvous noticing the peer node's failure) can end its epoch-0
run — proving whole-job restart, not per-node retry."""
import os
import sys
import time


def main():
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    epoch = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    print(f"ELASTIC_START rank={rank} epoch={epoch}", flush=True)
    if epoch == 0:
        if rank == 1:
            time.sleep(0.5)
            print(f"ELASTIC_CRASH rank={rank} epoch={epoch}", flush=True)
            sys.exit(7)
        # healthy rank: block far longer than the test timeout — only
        # the launcher's coordinated kill can end this epoch
        time.sleep(300)
        sys.exit(0)
    print(f"ELASTIC_OK rank={rank} epoch={epoch}", flush=True)


if __name__ == "__main__":
    main()
