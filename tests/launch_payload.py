"""Payload for the launch-CLI multi-process test (run by
test_launch.py through `python -m paddle_tpu.distributed.launch`, the
reference's test_dist_base.py:1217 subprocess pattern).

Each process: bootstrap via init_parallel_env (jax.distributed), build
a GLOBAL 8-device mesh spanning both processes, run one dp-sharded
train step with globally-sharded data, and print the loss — the
launcher's parent test asserts both ranks print the same finite value.
"""
import sys

import numpy as np


def main():
    import jax
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    rank = dist.get_rank()
    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    assert n_global == 8 and n_local == 4, (n_global, n_local)

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.models import gpt_tiny, GPTForCausalLM, \
        GPTPretrainingCriterion
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    pt.seed(0)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    model.train()
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    crit = GPTPretrainingCriterion()
    step = TrainStep(model, opt, lambda m, i, l: crit(m(i), l),
                     mesh=mesh, shard_data=P("dp", None))

    rng = np.random.default_rng(0)  # same on every process (SPMD)
    ids = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    loss = step(ids, labels)
    val = float(np.asarray(jax.device_get(loss._data)))
    assert np.isfinite(val)
    print(f"LAUNCH_OK rank={rank} world={n_global // n_local} "
          f"loss={val:.6f}", flush=True)


if __name__ == "__main__":
    main()
