"""OpTest-style conformance harness.

Analog of the reference's single most reusable test asset
(/root/reference/test/legacy_test/op_test.py:417): each op is checked
against a numpy reference in BOTH eager and jit-traced modes, and analytic
grads are checked against numeric finite differences (op_test.py:2944
check_grad semantics)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(op_fn, np_ref, inputs, attrs=None, rtol=1e-4, atol=1e-5,
                 modes=("eager", "jit")):
    """inputs: dict name -> np array (positional order preserved)."""
    attrs = attrs or {}
    np_out = np_ref(*inputs.values(), **attrs)
    if not isinstance(np_out, (tuple, list)):
        np_out = (np_out,)

    for mode in modes:
        tensors = [paddle.to_tensor(v) for v in inputs.values()]
        if mode == "eager":
            out = op_fn(*tensors, **attrs)
        else:
            import jax

            def traced(*arrs):
                ts = [Tensor._wrap(a) for a in arrs]
                o = op_fn(*ts, **attrs)
                flat, _ = jax.tree_util.tree_flatten(
                    o, is_leaf=lambda x: isinstance(x, Tensor))
                return tuple(t._data if isinstance(t, Tensor) else t
                             for t in flat)

            out = jax.jit(traced)(*[t._data for t in tensors])
        import jax
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        got = [np.asarray(t._data if isinstance(t, Tensor) else t)
               for t in flat]
        assert len(got) >= len(np_out), (
            f"{op_fn}: expected {len(np_out)} outputs, got {len(got)}")
        for g, e in zip(got, np_out):
            np.testing.assert_allclose(
                g.astype(np.float64) if g.dtype != bool else g,
                np.asarray(e).astype(np.float64)
                if np.asarray(e).dtype != bool else np.asarray(e),
                rtol=rtol, atol=atol,
                err_msg=f"op {op_fn} mode={mode}")


def check_grad(op_fn, inputs, attrs=None, grad_inputs=None, eps=1e-3,
               rtol=1e-2, atol=1e-3, reduce_fn=None, method="auto"):
    """Analytic grad (tape reverse-mode) vs an INDEPENDENT reference.

    method='jacfwd' (default): forward-mode jax.jacfwd of the pure op —
    exercises none of the registry's vjp machinery, runs as one
    vectorized compiled call (the reference op_test's per-element
    finite difference made broad coverage too expensive, VERDICT r1
    weak item 8). method='fd': central finite differences, for ops
    whose forward has no JVP rule (e.g. custom_vjp kernels).
    method='auto': jacfwd, falling back to fd."""
    attrs = attrs or {}
    names = list(inputs)
    grad_inputs = grad_inputs or names

    def run(vals):
        ts = {k: paddle.to_tensor(v, stop_gradient=(k not in grad_inputs))
              for k, v in vals.items()}
        out = op_fn(*ts.values(), **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        if reduce_fn is not None:
            out = reduce_fn(out)
        else:
            out = out.sum()
        return out, ts

    out, ts = run(inputs)
    out.backward()
    analytic = {k: np.asarray(ts[k].grad._data) for k in grad_inputs}

    ref = None
    if method in ("auto", "jacfwd"):
        try:
            ref = _grad_jacfwd(op_fn, inputs, attrs, grad_inputs,
                               reduce_fn)
        except Exception:
            if method == "jacfwd":
                raise
    if ref is None:
        ref = _grad_fd(run, inputs, grad_inputs, eps)

    for k in grad_inputs:
        np.testing.assert_allclose(analytic[k], ref[k], rtol=rtol,
                                   atol=atol,
                                   err_msg=f"grad of input {k} for {op_fn}")


def _grad_jacfwd(op_fn, inputs, attrs, grad_inputs, reduce_fn):
    """Vectorized forward-mode gradient of the scalarized op."""
    import jax
    import jax.numpy as jnp

    names = list(inputs)
    gidx = [i for i, n in enumerate(names) if n in grad_inputs]

    def scalar_fn(*garrs):
        vals = dict(inputs)
        for i, a in zip(gidx, garrs):
            vals[names[i]] = a
        ts = [Tensor._wrap(jnp.asarray(v)) for v in vals.values()]
        out = op_fn(*ts, **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        if reduce_fn is not None:
            out = reduce_fn(out)
        else:
            out = out.sum()
        return out._data if isinstance(out, Tensor) else out

    garrs = [jnp.asarray(inputs[names[i]]) for i in gidx]
    grads = jax.jacfwd(scalar_fn, argnums=tuple(range(len(garrs))))(
        *garrs)
    return {names[i]: np.asarray(g) for i, g in zip(gidx, grads)}


def _grad_fd(run, inputs, grad_inputs, eps):
    """Central finite differences (the reference op_test fallback)."""
    ref = {}
    for k in grad_inputs:
        base = inputs[k].astype(np.float64)
        num = np.zeros_like(base)
        numf = num.reshape(-1)
        flat = base.reshape(-1)
        for i in range(flat.size):
            for sgn in (1, -1):
                vals = {n: v.copy() for n, v in inputs.items()}
                f = vals[k].reshape(-1)
                f[i] += sgn * eps
                o, _ = run(vals)
                numf[i] += sgn * float(o.item()) / (2 * eps)
        ref[k] = num
    return ref
