"""OpTest-style conformance harness.

Analog of the reference's single most reusable test asset
(/root/reference/test/legacy_test/op_test.py:417): each op is checked
against a numpy reference in BOTH eager and jit-traced modes, and analytic
grads are checked against numeric finite differences (op_test.py:2944
check_grad semantics)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(op_fn, np_ref, inputs, attrs=None, rtol=1e-4, atol=1e-5,
                 modes=("eager", "jit")):
    """inputs: dict name -> np array (positional order preserved)."""
    attrs = attrs or {}
    np_out = np_ref(*inputs.values(), **attrs)
    if not isinstance(np_out, (tuple, list)):
        np_out = (np_out,)

    for mode in modes:
        tensors = [paddle.to_tensor(v) for v in inputs.values()]
        if mode == "eager":
            out = op_fn(*tensors, **attrs)
        else:
            import jax

            def traced(*arrs):
                ts = [Tensor._wrap(a) for a in arrs]
                o = op_fn(*ts, **attrs)
                flat, _ = jax.tree_util.tree_flatten(
                    o, is_leaf=lambda x: isinstance(x, Tensor))
                return tuple(t._data if isinstance(t, Tensor) else t
                             for t in flat)

            out = jax.jit(traced)(*[t._data for t in tensors])
        import jax
        flat, _ = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        got = [np.asarray(t._data if isinstance(t, Tensor) else t)
               for t in flat]
        assert len(got) >= len(np_out), (
            f"{op_fn}: expected {len(np_out)} outputs, got {len(got)}")
        for g, e in zip(got, np_out):
            np.testing.assert_allclose(
                g.astype(np.float64) if g.dtype != bool else g,
                np.asarray(e).astype(np.float64)
                if np.asarray(e).dtype != bool else np.asarray(e),
                rtol=rtol, atol=atol,
                err_msg=f"op {op_fn} mode={mode}")


def check_grad(op_fn, inputs, attrs=None, grad_inputs=None, eps=1e-3,
               rtol=1e-2, atol=1e-3, reduce_fn=None):
    """Analytic grad (tape) vs numeric finite difference."""
    attrs = attrs or {}
    names = list(inputs)
    grad_inputs = grad_inputs or names

    def run(vals):
        ts = {k: paddle.to_tensor(v, stop_gradient=(k not in grad_inputs))
              for k, v in vals.items()}
        out = op_fn(*ts.values(), **attrs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        if reduce_fn is not None:
            out = reduce_fn(out)
        else:
            out = out.sum()
        return out, ts

    out, ts = run(inputs)
    out.backward()
    analytic = {k: np.asarray(ts[k].grad._data) for k in grad_inputs}

    for k in grad_inputs:
        base = inputs[k].astype(np.float64)
        num = np.zeros_like(base)
        flat = base.reshape(-1)
        numf = num.reshape(-1)
        for i in range(flat.size):
            for sgn in (1, -1):
                vals = {n: v.copy() for n, v in inputs.items()}
                f = vals[k].reshape(-1)
                f[i] += sgn * eps
                o, _ = run(vals)
                numf[i] += sgn * float(o.item()) / (2 * eps)
        np.testing.assert_allclose(analytic[k], num, rtol=rtol, atol=atol,
                                   err_msg=f"grad of input {k} for {op_fn}")
