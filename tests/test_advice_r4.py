"""Round-4 advisor-fix regression tests (ADVICE.md round 3)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_take_raise_validates_eager():
    x = paddle.to_tensor(np.arange(6.0).reshape(2, 3))
    idx = paddle.to_tensor(np.array([0, 5], np.int64))
    out = paddle.take(x, idx, mode="raise")
    np.testing.assert_allclose(np.asarray(out._data), [0.0, 5.0])
    bad = paddle.to_tensor(np.array([0, 6], np.int64))
    with pytest.raises(IndexError):
        paddle.take(x, bad, mode="raise")
    neg_bad = paddle.to_tensor(np.array([-7], np.int64))
    with pytest.raises(IndexError):
        paddle.take(x, neg_bad, mode="raise")
    # wrap mode still never raises
    out = paddle.take(x, bad, mode="wrap")
    np.testing.assert_allclose(np.asarray(out._data), [0.0, 0.0])


def test_take_raise_clips_under_trace():
    import jax
    x = paddle.to_tensor(np.arange(6.0))

    def f(i):
        return paddle.take(x, paddle.to_tensor(i), mode="raise")._data

    out = jax.jit(f)(np.array([7], np.int64))
    # traced path cannot raise; clips to the last element
    np.testing.assert_allclose(np.asarray(out), [5.0])


def test_exec_cache_lru_eviction(monkeypatch):
    from paddle_tpu.ops import registry

    monkeypatch.setattr(registry, "_EXEC_CACHE_MAX_PER_OP", 4)
    opdef = paddle.take.op_def
    opdef.exec_cache.clear()
    x = paddle.to_tensor(np.arange(8.0))
    # fill 4 distinct signatures (different index lengths)
    for n in range(1, 5):
        paddle.take(x, paddle.to_tensor(np.arange(n, dtype=np.int64)))
    keys_before = [k for k, v in opdef.exec_cache.items()]
    assert len(keys_before) == 4
    # touch signature n=1 so it becomes most-recent
    paddle.take(x, paddle.to_tensor(np.arange(1, dtype=np.int64)))
    # a 5th signature evicts exactly one entry — the LRU one (n=2),
    # NOT the whole cache
    paddle.take(x, paddle.to_tensor(np.arange(5, dtype=np.int64)))
    keys_after = list(opdef.exec_cache.keys())
    assert len(keys_after) == 4
    assert keys_before[0] in keys_after  # n=1 survived (was touched)
    assert keys_before[1] not in keys_after  # n=2 was the LRU victim
    opdef.exec_cache.clear()


def test_graph_break_closure_reads_fresh_cell():
    from paddle_tpu.jit import to_static

    scale = 2.0

    @to_static(full_graph=False)
    def f(x):
        y = x * scale
        print("break here")  # forces a graph break region boundary
        return y + scale

    x = paddle.to_tensor(np.array([1.0, 2.0]))
    out1 = np.asarray(f(x)._data)
    np.testing.assert_allclose(out1, [4.0, 6.0])
    scale = 3.0  # noqa: F841 — mutated closed-over variable
    out2 = np.asarray(f(x)._data)
    np.testing.assert_allclose(out2, [6.0, 9.0])


def test_flash_attn_unpadded_traced_cu_seqlens():
    import jax
    import jax.numpy as jnp
    import paddle_tpu.nn.functional as F

    total, heads, dim = 8, 2, 4
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((total, heads, dim)), jnp.float32)

    def run(cu):
        out = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q),
            cu_seqlens_q=cu, cu_seqlens_k=cu,
            max_seqlen_q=total, max_seqlen_k=total, causal=True)
        return out[0]._data if isinstance(out, tuple) else out._data

    cu = jnp.asarray([0, 5, 8], jnp.int32)
    eager = np.asarray(run(cu))
    jitted = np.asarray(jax.jit(run)(cu))  # must not raise TracerError
    np.testing.assert_allclose(eager, jitted, rtol=2e-2, atol=2e-2)


def test_multinode_token_warning(capsys):
    import argparse
    import importlib
    launch_main = importlib.import_module(
        "paddle_tpu.distributed.launch.main")

    launch_main._RPC_TOKEN_CACHE = None
    args = argparse.Namespace(nnodes=2, master="10.0.0.1:8765")
    import os
    old = os.environ.pop("PADDLE_RPC_TOKEN", None)
    try:
        with pytest.warns(RuntimeWarning, match="PADDLE_RPC_TOKEN"):
            tok = launch_main._job_rpc_token(args)
        assert len(tok) == 32
    finally:
        launch_main._RPC_TOKEN_CACHE = None
        if old is not None:
            os.environ["PADDLE_RPC_TOKEN"] = old
