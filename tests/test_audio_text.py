"""paddle.audio / paddle.text conformance.

Window functions and mel/DCT matrices check against scipy/librosa-style
formulas computed in numpy; the layer pipeline checks against a
straightforward numpy STFT feature extraction.
"""
import numpy as np
import pytest

import paddle_tpu as pt


def npy(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestFunctional:
    def test_hz_mel_roundtrip(self):
        from paddle_tpu.audio import functional as AF
        for htk in (False, True):
            f = np.array([0.0, 440.0, 1000.0, 4000.0, 11025.0], np.float32)
            mel = AF.hz_to_mel(pt.to_tensor(f), htk=htk)
            back = AF.mel_to_hz(mel, htk=htk)
            np.testing.assert_allclose(npy(back), f, rtol=1e-4, atol=1e-2)

    def test_hz_to_mel_scalar_and_known_values(self):
        from paddle_tpu.audio import functional as AF
        # HTK formula at 1000 Hz: 2595*log10(1+1000/700) ≈ 999.99
        assert abs(AF.hz_to_mel(1000.0, htk=True) - 999.9855) < 1e-2
        # slaney is linear below 1 kHz: f / (200/3)
        assert abs(AF.hz_to_mel(500.0) - 7.5) < 1e-4

    def test_fbank_matrix_properties(self):
        from paddle_tpu.audio import functional as AF
        fb = npy(AF.compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # every filter has some support except possibly edge cases
        assert (fb.sum(axis=1) > 0).sum() >= 38

    def test_window_matches_scipy(self):
        from paddle_tpu.audio import functional as AF
        import scipy.signal as ss
        for name in ("hann", "hamming", "blackman", "bartlett", "boxcar",
                     "triang", "cosine"):
            got = npy(AF.get_window(name, 64))
            ref = ss.get_window(name, 64, fftbins=True)
            np.testing.assert_allclose(got, ref, atol=1e-6, err_msg=name)
        got = npy(AF.get_window(("gaussian", 7.0), 64))
        ref = ss.get_window(("gaussian", 7.0), 64, fftbins=True)
        np.testing.assert_allclose(got, ref, atol=1e-6)
        got = npy(AF.get_window(("kaiser", 12.0), 64))
        ref = ss.get_window(("kaiser", 12.0), 64, fftbins=True)
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_windows_numerics(self):
        from paddle_tpu.audio import functional as AF
        for name in ("hann", "hamming", "blackman", "bartlett", "boxcar",
                     "triang", "gaussian", "exponential", "kaiser",
                     "tukey", "cosine", "taylor"):
            w = npy(AF.get_window(name, 64))
            assert w.shape == (64,), name
            assert np.isfinite(w).all(), name
            assert w.max() <= 1.0 + 1e-6, name
        # periodic hann: w[0] == 0, symmetric interior
        w = npy(AF.get_window("hann", 8))
        np.testing.assert_allclose(w[0], 0.0, atol=1e-12)
        hann_ref = 0.5 - 0.5 * np.cos(2 * np.pi * np.arange(8) / 8)
        np.testing.assert_allclose(w, hann_ref, atol=1e-6)

    def test_power_to_db(self):
        from paddle_tpu.audio import functional as AF
        s = np.array([1.0, 0.1, 0.01], np.float32)
        db = npy(AF.power_to_db(pt.to_tensor(s), top_db=None))
        np.testing.assert_allclose(db, [0.0, -10.0, -20.0], atol=1e-4)
        db = npy(AF.power_to_db(pt.to_tensor(s), top_db=15.0))
        np.testing.assert_allclose(db, [0.0, -10.0, -15.0], atol=1e-4)

    def test_create_dct_ortho(self):
        from paddle_tpu.audio import functional as AF
        d = npy(AF.create_dct(13, 40))
        assert d.shape == (40, 13)
        # orthonormal columns
        np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-5)


class TestFeatureLayers:
    def test_spectrogram_matches_numpy(self):
        import paddle_tpu.audio as audio
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 2048)).astype(np.float32)
        layer = audio.Spectrogram(n_fft=256, hop_length=128, center=False)
        got = npy(layer(pt.to_tensor(x)))
        win = npy(audio.functional.get_window("hann", 256))
        frames = np.stack([x[:, i * 128:i * 128 + 256]
                           for i in range((2048 - 256) // 128 + 1)], -1)
        ref = np.abs(np.fft.rfft(frames * win[None, :, None], axis=1)) ** 2
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_melspectrogram_is_fbank_of_spectrogram(self):
        import paddle_tpu.audio as audio
        rng = np.random.default_rng(1)
        x = rng.standard_normal((1, 1024)).astype(np.float32)
        mel = audio.MelSpectrogram(sr=16000, n_fft=256, n_mels=20,
                                   center=False)
        got = npy(mel(pt.to_tensor(x)))
        spec = npy(mel._spectrogram(pt.to_tensor(x)))
        fb = npy(mel.fbank_matrix)
        np.testing.assert_allclose(got, np.einsum("mb,nbt->nmt", fb, spec),
                                   rtol=1e-4, atol=1e-5)
        assert got.shape[1] == 20

    def test_mfcc_shape_and_finite(self):
        import paddle_tpu.audio as audio
        rng = np.random.default_rng(2)
        x = rng.standard_normal((2, 4096)).astype(np.float32)
        mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)
        out = npy(mfcc(pt.to_tensor(x)))
        assert out.shape[0] == 2 and out.shape[1] == 13
        assert np.isfinite(out).all()


class TestText:
    def test_viterbi_decoder_layer(self):
        import paddle_tpu.text as text
        rng = np.random.default_rng(3)
        pots = pt.to_tensor(rng.standard_normal((2, 6, 5)).astype(np.float32))
        trans = pt.to_tensor(rng.standard_normal((5, 5)).astype(np.float32))
        lens = pt.to_tensor(np.array([6, 4], np.int64))
        dec = text.ViterbiDecoder(trans, include_bos_eos_tag=False)
        scores, paths = dec(pots, lens)
        assert npy(scores).shape == (2,)
        assert npy(paths).shape == (2, 6)
        # path entries past the length are zero-padded
        assert (npy(paths)[1, 4:] == 0).all()
