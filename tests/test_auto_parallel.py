"""Semi-auto parallel (DistTensor) + distributed checkpoint tests.
Mirrors the reference's test/auto_parallel reshard pairwise matrix +
semi_auto_parallel e2e patterns on the 8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import (
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard,
    shard_layer, shard_optimizer, ShardingStage1, ShardingStage3,
)
from jax.sharding import PartitionSpec as P


@pytest.fixture
def mesh2d():
    return ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["x", "y"])


class TestShardTensor:
    def test_shard_and_spec(self, mesh2d):
        t = shard_tensor(np.arange(32, dtype=np.float32).reshape(8, 4),
                         mesh2d, [Shard(0), Replicate()])
        assert t._data.sharding.spec == P("x", None)
        np.testing.assert_allclose(
            t.numpy(), np.arange(32).reshape(8, 4))

    def test_two_axes_one_dim(self, mesh2d):
        t = shard_tensor(np.zeros((8, 4), np.float32), mesh2d,
                         [Shard(0), Shard(0)])
        assert t._data.sharding.spec == P(("x", "y"), None)

    def test_ops_on_dist_tensors(self, mesh2d):
        a = shard_tensor(np.random.randn(8, 16).astype(np.float32),
                         mesh2d, [Shard(0), Replicate()])
        b = shard_tensor(np.random.randn(16, 8).astype(np.float32),
                         mesh2d, [Replicate(), Shard(1)])
        c = pt.ops.matmul(a, b)  # GSPMD propagates
        np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy(),
                                   rtol=2e-4, atol=2e-4)


class TestReshard:
    """Pairwise reshard matrix {r,s,p} x {r,s} (ref: test/auto_parallel/
    reshard_p_to_r.py family)."""

    def _roundtrip(self, mesh, src, dst):
        x = np.random.randn(8, 8).astype(np.float32)
        t = shard_tensor(x, mesh, src)
        out = reshard(t, mesh, dst)
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-6)

    def test_r_to_s(self, mesh2d):
        self._roundtrip(mesh2d, [Replicate(), Replicate()],
                        [Shard(0), Replicate()])

    def test_s_to_r(self, mesh2d):
        self._roundtrip(mesh2d, [Shard(0), Replicate()],
                        [Replicate(), Replicate()])

    def test_s_to_s_transpose(self, mesh2d):
        self._roundtrip(mesh2d, [Shard(0), Replicate()],
                        [Shard(1), Replicate()])

    def test_reshard_is_differentiable(self, mesh2d):
        x = pt.to_tensor(np.random.randn(8, 8).astype(np.float32),
                         stop_gradient=False)
        t = reshard(x, mesh2d, [Shard(0), Replicate()])
        loss = pt.ops.mean(t ** 2)
        loss.backward()
        assert x.grad is not None
        np.testing.assert_allclose(x.grad.numpy(),
                                   2 * x.numpy() / x.numpy().size,
                                   rtol=1e-5)


class TestShardLayerOptimizer:
    def test_shard_layer_tp(self, mesh2d):
        m = pt.nn.Linear(16, 32)

        def tp(name, sub, mesh):
            if hasattr(sub, "weight") and sub.weight is not None:
                shard_tensor(sub.weight, mesh, [Replicate(), Shard(1)])
            if getattr(sub, "bias", None) is not None:
                shard_tensor(sub.bias, mesh, [Replicate(), Shard(0)])

        shard_layer(m, mesh2d, tp)
        assert m.weight._data.sharding.spec == P(None, "y")
        x = pt.to_tensor(np.random.randn(4, 16).astype(np.float32))
        y = m(x)
        assert y.shape == [4, 32]

    def test_shard_optimizer_follows_params(self, mesh2d):
        m = pt.nn.Linear(16, 32)
        shard_layer(m, mesh2d, lambda n, s, mm: [
            shard_tensor(p, mm, [Replicate(), Shard(1)])
            for _, p in s.named_parameters(include_sublayers=False)
            if p.ndim == 2])
        opt = shard_optimizer(pt.optimizer.AdamW(
            learning_rate=1e-3, parameters=m.parameters()))
        x = pt.to_tensor(np.random.randn(4, 16).astype(np.float32))
        pt.ops.mean(m(x) ** 2).backward()
        opt.step()
        st = opt._inner_opt._accumulators[id(m.weight)]
        m1 = [v for v in st.values()
              if getattr(v, "shape", ()) == (16, 32)][0]
        assert m1.sharding.spec == P(None, "y")
        # param placement preserved through the step
        assert m.weight._data.sharding.spec == P(None, "y")

    def test_sharding_stage3_shards_params(self):
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        m = pt.nn.Linear(16, 32)
        opt = shard_optimizer(
            pt.optimizer.AdamW(learning_rate=1e-3,
                               parameters=m.parameters()),
            shard_fn=ShardingStage3(mesh))
        x = pt.to_tensor(np.random.randn(4, 16).astype(np.float32))
        pt.ops.mean(m(x) ** 2).backward()
        opt.step()
        assert "dp" in str(m.weight._data.sharding.spec)

    def test_train_convergence_semi_auto(self, mesh2d):
        pt.seed(0)
        m = pt.nn.Linear(8, 8)
        shard_layer(m, mesh2d, lambda n, s, mm: [
            shard_tensor(p, mm, [Replicate(), Shard(1)])
            for _, p in s.named_parameters(include_sublayers=False)
            if p.ndim == 2])
        opt = shard_optimizer(pt.optimizer.SGD(
            learning_rate=0.5, parameters=m.parameters()))
        x = pt.to_tensor(np.random.randn(16, 8).astype(np.float32))
        losses = []
        for _ in range(10):
            loss = pt.ops.mean((m(x) - x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5


class TestHybridGPT:
    @pytest.mark.slow  # >25s on the 1-core CI box; --runslow tier
    def test_tp_pp_dp_pipeline_training(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.topology import (
            set_hybrid_communicate_group)
        from paddle_tpu.models.gpt import gpt_tiny
        from paddle_tpu.models.gpt_hybrid import gpt_pipeline_model
        strategy = dist.fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 2}
        dist.fleet.init(strategy=strategy)
        cfg = gpt_tiny(hidden_dropout_prob=0.0,
                       attention_dropout_prob=0.0)
        model = gpt_pipeline_model(cfg, recompute_interval=1)
        pp = dist.fleet.distributed_model(model)
        opt = dist.fleet.distributed_optimizer(pt.optimizer.AdamW(
            learning_rate=1e-3, parameters=model.parameters()))
        ids = np.random.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1)
        losses = [float(pp.train_batch(
            [pt.to_tensor(ids), pt.to_tensor(labels)], opt).numpy())
            for _ in range(4)]
        assert losses[-1] < losses[0]
        set_hybrid_communicate_group(None)

    def test_hybrid_flat_model_matches_dense(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.topology import (
            set_hybrid_communicate_group)
        from paddle_tpu.models.gpt import gpt_tiny
        from paddle_tpu.models.gpt_hybrid import GPTForCausalLMHybrid
        strategy = dist.fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        dist.fleet.init(strategy=strategy)
        cfg = gpt_tiny(hidden_dropout_prob=0.0,
                       attention_dropout_prob=0.0)
        m = GPTForCausalLMHybrid(cfg)
        m.eval()
        ids = pt.to_tensor(
            np.random.randint(0, cfg.vocab_size, (2, 8)).astype(np.int32))
        logits = m(ids)
        assert logits.shape == [2, 8, cfg.vocab_size]
        # TP logits equal a dense recomputation with the same weights
        import jax.numpy as jnp
        x = m.embeddings.word_embeddings.weight.numpy()[ids.numpy()] + \
            m.embeddings.position_embeddings.weight.numpy()[
                np.arange(8)][None]
        ref_first = m.layers[0].ln1(pt.to_tensor(x))
        qkv_ref = ref_first.numpy() @ \
            m.layers[0].attn.qkv_proj.weight.numpy() + \
            m.layers[0].attn.qkv_proj.bias.numpy()
        qkv_tp = m.layers[0].attn.qkv_proj(ref_first).numpy()
        np.testing.assert_allclose(qkv_tp, qkv_ref, rtol=2e-4, atol=2e-4)
        set_hybrid_communicate_group(None)


class TestDistCheckpoint:
    def test_save_load_reshard(self, tmp_path, mesh2d):
        x = np.random.randn(8, 16).astype(np.float32)
        t = shard_tensor(x.copy(), mesh2d, [Shard(0), Replicate()])
        dist.checkpoint.save_state_dict({"w": t}, str(tmp_path))
        # load into a DIFFERENTLY-sharded destination
        t2 = shard_tensor(np.zeros_like(x), mesh2d,
                          [Replicate(), Shard(1)])
        dist.checkpoint.load_state_dict({"w": t2}, str(tmp_path))
        np.testing.assert_allclose(t2.numpy(), x, rtol=1e-6)
        assert t2._data.sharding.spec == P(None, "y")

    def test_shard_dedup_on_disk(self, tmp_path):
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        t = shard_tensor(np.random.randn(8, 4).astype(np.float32), mesh,
                         [Shard(0)])
        dist.checkpoint.save_state_dict({"w": t}, str(tmp_path))
        import os
        files = [f for f in os.listdir(tmp_path) if f.endswith(".npy")]
        assert len(files) == 8  # one unique shard per device
        rep = shard_tensor(np.random.randn(8, 4).astype(np.float32), mesh,
                           [Replicate()])
        dist.checkpoint.save_state_dict({"r": rep}, str(tmp_path / "r"))
        files = [f for f in os.listdir(tmp_path / "r")
                 if f.endswith(".npy")]
        assert len(files) == 1  # replicas deduped

    def test_model_roundtrip(self, tmp_path, mesh2d):
        from paddle_tpu.models import gpt_tiny, GPTForCausalLM
        m = GPTForCausalLM(gpt_tiny())
        sd = m.state_dict()
        dist.checkpoint.save_state_dict(sd, str(tmp_path))
        m2 = GPTForCausalLM(gpt_tiny())
        sd2 = m2.state_dict()
        dist.checkpoint.load_state_dict(sd2, str(tmp_path))
        for k in sd:
            np.testing.assert_allclose(sd2[k].numpy(), sd[k].numpy(),
                                       rtol=1e-6)


class TestPartialReshard:
    """reshard_p_to_r / p_to_s family (ADVICE r1 medium): in
    single-controller mode each rank's local partial is the same array,
    so the pending sum realizes as n * x (matches the reference's
    all-reduce over n identical locals)."""

    def _mesh(self):
        return dist.ProcessMesh(np.arange(8).reshape(4, 2),
                                dim_names=["x", "y"])

    def test_p_to_r_applies_pending_sum(self):
        mesh = self._mesh()
        x = np.random.randn(8, 6).astype(np.float32)
        t = dist.shard_tensor(x, mesh, [dist.Partial(), dist.Replicate()])
        out = dist.reshard(t, mesh, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_allclose(out.numpy(), 4.0 * x, rtol=1e-6)

    def test_p_to_s_reduce_scatter(self):
        mesh = self._mesh()
        x = np.random.randn(8, 6).astype(np.float32)
        t = dist.shard_tensor(x, mesh, [dist.Partial(), dist.Replicate()])
        out = dist.reshard(t, mesh, [dist.Shard(0), dist.Replicate()])
        np.testing.assert_allclose(out.numpy(), 4.0 * x, rtol=1e-6)
        spec = out._data.sharding.spec
        assert "x" in str(spec)

    def test_r_to_p_roundtrip(self):
        mesh = self._mesh()
        x = np.random.randn(4, 4).astype(np.float32)
        t = dist.shard_tensor(x, mesh, [dist.Replicate(), dist.Replicate()])
        p = dist.reshard(t, mesh, [dist.Partial(), dist.Replicate()])
        np.testing.assert_allclose(p.numpy(), x / 4.0, rtol=1e-6)
        r = dist.reshard(p, mesh, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_allclose(r.numpy(), x, rtol=1e-6)

    def test_partial_avg_identity(self):
        mesh = self._mesh()
        x = np.random.randn(4, 4).astype(np.float32)
        t = dist.shard_tensor(x, mesh,
                              [dist.Partial("avg"), dist.Replicate()])
        out = dist.reshard(t, mesh, [dist.Replicate(), dist.Replicate()])
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-6)


class TestDistCheckpointMeshChange:
    """Save on one mesh layout, load on another (VERDICT r1 item 8):
    shard-wise placement with no full-host assembly, plus dtype cast."""

    @pytest.mark.parametrize("src_pl,dst_pl", [
        ([Shard(0), Replicate()], [Replicate(), Shard(0)]),
        ([Shard(0), Shard(1)], [Shard(1), Shard(0)]),
        ([Replicate(), Replicate()], [Shard(0), Shard(1)]),
        ([Shard(1), Replicate()], [Replicate(), Replicate()]),
    ])
    def test_mesh_layout_matrix(self, tmp_path, src_pl, dst_pl):
        # save on a 4x2 mesh, load on a 2x4 mesh
        src_mesh = ProcessMesh(np.arange(8).reshape(4, 2),
                               dim_names=["x", "y"])
        dst_mesh = ProcessMesh(np.arange(8).reshape(2, 4),
                               dim_names=["x", "y"])
        x = np.random.randn(8, 16).astype(np.float32)
        t = shard_tensor(x.copy(), src_mesh, src_pl)
        dist.checkpoint.save_state_dict({"w": t}, str(tmp_path))
        t2 = shard_tensor(np.zeros_like(x), dst_mesh, dst_pl)
        dist.checkpoint.load_state_dict({"w": t2}, str(tmp_path))
        np.testing.assert_allclose(t2.numpy(), x, rtol=1e-6)

    def test_dtype_cast_on_load(self, tmp_path, mesh2d):
        x = np.random.randn(8, 16).astype(np.float32)
        t = shard_tensor(x.copy(), mesh2d, [Shard(0), Replicate()])
        dist.checkpoint.save_state_dict({"w": t}, str(tmp_path))
        import jax.numpy as jnp
        t2 = shard_tensor(np.zeros((8, 16), np.float32), mesh2d,
                          [Replicate(), Shard(1)])
        t2._data = t2._data.astype(jnp.bfloat16)
        dist.checkpoint.load_state_dict({"w": t2}, str(tmp_path))
        assert str(t2._data.dtype) == "bfloat16"
        np.testing.assert_allclose(t2.astype("float32").numpy(), x,
                                   rtol=1e-2, atol=1e-2)

    def test_bf16_saved_shards_roundtrip(self, tmp_path, mesh2d):
        import jax.numpy as jnp
        x = np.random.randn(8, 16).astype(np.float32)
        t = shard_tensor(x.copy(), mesh2d, [Shard(0), Shard(1)])
        t._data = t._data.astype(jnp.bfloat16)
        dist.checkpoint.save_state_dict({"w": t}, str(tmp_path))
        t2 = shard_tensor(np.zeros((8, 16), np.float32), mesh2d,
                          [Shard(1), Shard(0)])
        t2._data = t2._data.astype(jnp.bfloat16)
        dist.checkpoint.load_state_dict({"w": t2}, str(tmp_path))
        np.testing.assert_allclose(
            t2.astype("float32").numpy(),
            np.asarray(jnp.asarray(x).astype(jnp.bfloat16)
                       .astype(jnp.float32)))

    def test_shape_mismatch_raises(self, tmp_path, mesh2d):
        x = np.random.randn(8, 16).astype(np.float32)
        t = shard_tensor(x.copy(), mesh2d, [Shard(0), Replicate()])
        dist.checkpoint.save_state_dict({"w": t}, str(tmp_path))
        t2 = shard_tensor(np.zeros((4, 16), np.float32), mesh2d,
                          [Replicate(), Replicate()])
        with pytest.raises(ValueError, match="saved shape"):
            dist.checkpoint.load_state_dict({"w": t2}, str(tmp_path))


class TestReshardPairwiseMatrix:
    """Full {r, s(dim), p} x {r, s(dim), p} conversion matrix (VERDICT
    r1 missing item 10; ref test/auto_parallel/reshard_* and
    phi/core/distributed/auto_parallel/reshard/). Values are checked
    against the semantics table: the only value-changing conversions
    are p->anything (apply the pending sum: n * x for identical
    single-controller locals) and r->p (split: x / n)."""

    N = 4  # first mesh axis size

    def _mesh(self):
        return dist.ProcessMesh(np.arange(8).reshape(self.N, 2),
                                dim_names=["x", "y"])

    PLACEMENTS = {
        "r": lambda: Replicate(),
        "s0": lambda: Shard(0),
        "s1": lambda: Shard(1),
        "p": lambda: Partial(),
    }

    @pytest.mark.parametrize("src", ["r", "s0", "s1", "p"])
    @pytest.mark.parametrize("dst", ["r", "s0", "s1", "p"])
    def test_pairwise(self, src, dst):
        mesh = self._mesh()
        x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16) / 7.0
        t = dist.shard_tensor(x.copy(), mesh,
                              [self.PLACEMENTS[src](), Replicate()])
        out = dist.reshard(t, mesh,
                           [self.PLACEMENTS[dst](), Replicate()])
        # value semantics
        factor = 1.0
        if src == "p" and dst != "p":
            factor = float(self.N)     # pending sum applied
        elif src != "p" and dst == "p":
            factor = 1.0 / self.N      # split into n partials
        np.testing.assert_allclose(out.numpy(), factor * x, rtol=1e-6,
                                   err_msg=f"{src}->{dst}")
        # layout semantics
        spec = str(out._data.sharding.spec)
        if dst in ("s0", "s1"):
            assert "x" in spec, (src, dst, spec)
        else:
            assert "x" not in spec, (src, dst, spec)
        # placement metadata round-trips
        assert out._dist_attr.placements[0] == self.PLACEMENTS[dst]()

    @pytest.mark.parametrize("src_dim,dst_dim", [(0, 1), (1, 0)])
    def test_shard_dim_moves(self, src_dim, dst_dim):
        mesh = self._mesh()
        x = np.random.default_rng(0).standard_normal((8, 16)) \
            .astype(np.float32)
        t = dist.shard_tensor(x.copy(), mesh,
                              [Shard(src_dim), Replicate()])
        out = dist.reshard(t, mesh, [Shard(dst_dim), Replicate()])
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-6)

    def test_two_axis_transitions(self):
        mesh = self._mesh()
        x = np.random.default_rng(1).standard_normal((8, 16)) \
            .astype(np.float32)
        # (s0, s1) -> (s1, s0) -> (r, r) -> (p, r) -> (r, r)
        t = dist.shard_tensor(x.copy(), mesh, [Shard(0), Shard(1)])
        t = dist.reshard(t, mesh, [Shard(1), Shard(0)])
        np.testing.assert_allclose(t.numpy(), x, rtol=1e-6)
        t = dist.reshard(t, mesh, [Replicate(), Replicate()])
        np.testing.assert_allclose(t.numpy(), x, rtol=1e-6)
        t = dist.reshard(t, mesh, [Partial(), Replicate()])
        t = dist.reshard(t, mesh, [Replicate(), Replicate()])
        np.testing.assert_allclose(t.numpy(), x, rtol=1e-5)
