"""Autograd engine tests (ref test strategy: test/autograd/ +
eager backward semantics, SURVEY §3.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer, grad, no_grad


def _t(x, sg=False):
    return paddle.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


class TestBackward:
    def test_simple_chain(self):
        x = _t([2.0])
        y = x * x + 3.0 * x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_fan_out_accumulation(self):
        x = _t([3.0])
        y = x * x
        z = y + y + x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [13.0])  # 2*2x + 1

    def test_deep_graph(self):
        x = _t([[1.0, 2.0], [3.0, 4.0]])
        w = _t([[0.5, 0.1], [0.2, 0.3]])
        h = paddle.matmul(x, w)
        h = paddle.tanh(h)
        loss = (h * h).sum()
        loss.backward()
        assert x.grad is not None and w.grad is not None
        assert x.grad.shape == [2, 2]

    def test_grad_accumulates_across_backwards(self):
        x = _t([1.0])
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])

    def test_clear_grad(self):
        x = _t([1.0])
        (x * 2).backward()
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = _t([1.0], sg=True)
        y = _t([1.0])
        z = x * y
        z.backward()
        assert x.grad is None
        assert y.grad is not None

    def test_detach(self):
        x = _t([2.0])
        y = (x * x).detach()
        z = y * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0])  # only y*dx

    def test_non_scalar_backward_with_grad(self):
        x = _t([[1.0, 2.0]])
        y = x * 2
        y.backward(paddle.to_tensor(np.ones((1, 2), np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), [[2.0, 2.0]])

    def test_backward_non_scalar_raises(self):
        x = _t([[1.0, 2.0]])
        y = x * 2
        with pytest.raises(RuntimeError):
            y.backward()

    def test_multi_output_op(self):
        x = _t([[3.0, 1.0], [2.0, 4.0]])
        vals, idx = paddle.topk(x, k=1, axis=1)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[1.0, 0.0], [0.0, 1.0]])

    def test_retain_graph(self):
        x = _t([2.0])
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_no_grad_context(self):
        x = _t([1.0])
        with no_grad():
            y = x * 2
        assert y.stop_gradient
        assert y._grad_node is None

    def test_hooks(self):
        x = _t([1.0])
        seen = []

        def hook(g):
            seen.append(g.numpy().copy())
            return g * 2

        x.register_hook(hook)
        (x * 3).backward()
        assert len(seen) == 1
        np.testing.assert_allclose(x.grad.numpy(), [6.0])


class TestGradAPI:
    def test_grad_basic(self):
        x = _t([3.0])
        y = x * x
        (gx,) = grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [6.0])
        assert x.grad is None  # side-effect free

    def test_grad_intermediate(self):
        x = _t([2.0])
        y = x * x
        z = y * 3
        (gy,) = grad(z, y)
        np.testing.assert_allclose(gy.numpy(), [3.0])

    def test_grad_unused(self):
        x = _t([1.0])
        u = _t([1.0])
        y = x * 2
        res = grad(y, [x, u], allow_unused=True)
        assert res[1] is None

    def test_double_backward_via_retain(self):
        x = _t([2.0])
        y = x * x * x
        (g1,) = grad(y, x, create_graph=True)
        np.testing.assert_allclose(g1.numpy(), [12.0])


class TestHigherOrder:
    """Real create_graph double backward (ref:
    /root/reference/paddle/fluid/eager/general_grad.h create_graph path,
    python/paddle/autograd/autograd.py jacobian/hessian)."""

    def test_create_graph_returns_differentiable(self):
        x = _t([2.0, 3.0])
        y = (x * x * x).sum()
        (g,) = grad(y, [x], create_graph=True)
        assert not g.stop_gradient  # NOT silently detached
        (g2,) = grad(g.sum(), [x])
        np.testing.assert_allclose(g2.numpy(), [12.0, 18.0])  # 6x

    def test_third_order(self):
        x = _t([2.0])
        y = x * x * x * x  # d3/dx3 = 24x
        (g1,) = grad(y.sum(), [x], create_graph=True)
        (g2,) = grad(g1.sum(), [x], create_graph=True)
        (g3,) = grad(g2.sum(), [x])
        np.testing.assert_allclose(g3.numpy(), [48.0])

    def test_double_backward_through_matmul(self):
        rng = np.random.RandomState(0)
        x = _t(rng.randn(3, 4))
        w = _t(rng.randn(4, 2))
        y = paddle.matmul(x, w)
        loss = (y * y).sum()
        (gw,) = grad(loss, [w], create_graph=True)
        # grad-norm penalty: d(|gw|^2)/dw = 2 * d(gw)/dw . gw
        penalty = (gw * gw).sum()
        (g2,) = grad(penalty, [w])
        # analytic: gw = 2 x^T x w  =>  d(|gw|^2)/dw = 2*(2x^Tx)^T(2x^Tx) w...
        A = 2.0 * x.numpy().T @ x.numpy()
        gw_ref = A @ w.numpy()
        np.testing.assert_allclose(gw.numpy(), gw_ref, rtol=1e-4)
        g2_ref = 2.0 * A.T @ gw_ref
        np.testing.assert_allclose(g2.numpy(), g2_ref, rtol=1e-4)

    def test_gradient_penalty_training_step(self):
        """WGAN-GP style step: loss + lambda*|dD/dx|^2 trains end-to-end."""
        rng = np.random.RandomState(1)
        w = _t(rng.randn(4, 1) * 0.1)
        x = _t(rng.randn(8, 4), sg=False)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        d_out = paddle.matmul(x, w).sum()
        (gx,) = grad(d_out, [x], create_graph=True)
        gp = (gx * gx).sum()
        loss = d_out + 10.0 * gp
        loss.backward()
        assert w.grad is not None
        g_before = w.grad.numpy().copy()
        assert np.all(np.isfinite(g_before))
        w_before = w.numpy().copy()
        opt.step()
        assert not np.allclose(w.numpy(), w_before)

    def test_create_graph_through_pylayer(self):
        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 3.0 * x * x

        x = _t([2.0])
        y = Cube.apply(x)
        (g,) = grad(y.sum(), [x], create_graph=True)
        np.testing.assert_allclose(g.numpy(), [12.0])
        (g2,) = grad(g.sum(), [x])
        np.testing.assert_allclose(g2.numpy(), [12.0])  # d(3x^2)/dx = 6x

    def test_jacobian_vs_jax(self):
        import jax
        import jax.numpy as jnp

        xa = np.random.RandomState(0).randn(3).astype(np.float32)
        xt = _t(xa)
        yt = paddle.sin(xt) * 2.0
        J = paddle.autograd.jacobian(yt, xt)
        Jref = jax.jacfwd(lambda a: jnp.sin(a) * 2.0)(xa)
        np.testing.assert_allclose(np.asarray(J), np.asarray(Jref),
                                   atol=1e-5)
        assert list(J.shape) == [3, 3]

    def test_jacobian_batch_axis(self):
        rng = np.random.RandomState(2)
        xa = rng.randn(4, 3).astype(np.float32)
        w = rng.randn(3, 2).astype(np.float32)
        xt = _t(xa)
        yt = paddle.matmul(xt, paddle.to_tensor(w))
        J = paddle.autograd.jacobian(yt, xt, batch_axis=0)
        assert list(J.shape) == [4, 2, 3]
        # per-sample jacobian of x@w is w^T
        np.testing.assert_allclose(np.asarray(J)[0], w.T, atol=1e-5)

    def test_hessian(self):
        xa = np.array([1.0, 2.0, 3.0], np.float32)
        xt = _t(xa)
        yt = (xt ** 3).sum()
        H = paddle.autograd.hessian(yt, xt)
        np.testing.assert_allclose(np.asarray(H), np.diag(6 * xa),
                                   atol=1e-4)

    def test_grad_does_not_pollute_other_leaf_grads(self):
        """grad()/jacobian must not accumulate into .grad of requires-grad
        leaves outside `inputs` (GeneralGrad only_inputs semantics)."""
        rng = np.random.RandomState(4)
        w = _t(rng.randn(3, 2))  # trainable param NOT in inputs
        x = _t(rng.randn(2, 3))
        y = paddle.matmul(x, w)
        J = paddle.autograd.jacobian(y, x)
        assert w.grad is None
        assert x.grad is None
        assert list(J.shape) == [4, 6]

    def test_hessian_batch_axis(self):
        rng = np.random.RandomState(5)
        xa = rng.randn(4, 3).astype(np.float32)
        xt = _t(xa)
        y = (xt ** 3).sum(axis=1)  # per-sample scalar, shape (4,)
        H = paddle.autograd.hessian(y, xt, batch_axis=0)
        assert list(H.shape) == [4, 3, 3]
        for b in range(4):
            np.testing.assert_allclose(np.asarray(H)[b],
                                       np.diag(6 * xa[b]), atol=1e-4)

    def test_hessian_quadratic_form(self):
        rng = np.random.RandomState(3)
        A = rng.randn(4, 4).astype(np.float32)
        A = A + A.T
        xt = _t(rng.randn(4))
        At = paddle.to_tensor(A)
        y = (xt.reshape([1, 4]) @ At @ xt.reshape([4, 1])).sum() * 0.5
        H = paddle.autograd.hessian(y, xt)
        np.testing.assert_allclose(np.asarray(H), A, atol=1e-4)


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 2

        x = _t([1.0, 2.0])
        y = Double.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_custom_nonstandard_grad(self):
        class StraightThrough(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return paddle.sign(x)

            @staticmethod
            def backward(ctx, dy):
                return dy  # pretend identity

        x = _t([0.5, -0.5])
        y = StraightThrough.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


class TestAmpAutograd:
    def test_autocast_matmul_bf16(self):
        x = _t(np.random.randn(4, 4))
        w = _t(np.random.randn(4, 4))
        with paddle.amp.auto_cast(level="O1"):
            y = paddle.matmul(x, w)
        assert y.dtype == paddle.bfloat16
        y.astype("float32").sum().backward()
        # master grads arrive in fp32 on the fp32 leaves
        assert w.grad.dtype == paddle.float32

    def test_grad_scaler(self):
        model = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x = _t(np.random.randn(2, 4), sg=True)
        with paddle.amp.auto_cast():
            loss = model(x).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        assert opt._step_count == 1
