"""Autograd engine tests (ref test strategy: test/autograd/ +
eager backward semantics, SURVEY §3.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer, grad, no_grad


def _t(x, sg=False):
    return paddle.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


class TestBackward:
    def test_simple_chain(self):
        x = _t([2.0])
        y = x * x + 3.0 * x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_fan_out_accumulation(self):
        x = _t([3.0])
        y = x * x
        z = y + y + x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [13.0])  # 2*2x + 1

    def test_deep_graph(self):
        x = _t([[1.0, 2.0], [3.0, 4.0]])
        w = _t([[0.5, 0.1], [0.2, 0.3]])
        h = paddle.matmul(x, w)
        h = paddle.tanh(h)
        loss = (h * h).sum()
        loss.backward()
        assert x.grad is not None and w.grad is not None
        assert x.grad.shape == [2, 2]

    def test_grad_accumulates_across_backwards(self):
        x = _t([1.0])
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])

    def test_clear_grad(self):
        x = _t([1.0])
        (x * 2).backward()
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = _t([1.0], sg=True)
        y = _t([1.0])
        z = x * y
        z.backward()
        assert x.grad is None
        assert y.grad is not None

    def test_detach(self):
        x = _t([2.0])
        y = (x * x).detach()
        z = y * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0])  # only y*dx

    def test_non_scalar_backward_with_grad(self):
        x = _t([[1.0, 2.0]])
        y = x * 2
        y.backward(paddle.to_tensor(np.ones((1, 2), np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), [[2.0, 2.0]])

    def test_backward_non_scalar_raises(self):
        x = _t([[1.0, 2.0]])
        y = x * 2
        with pytest.raises(RuntimeError):
            y.backward()

    def test_multi_output_op(self):
        x = _t([[3.0, 1.0], [2.0, 4.0]])
        vals, idx = paddle.topk(x, k=1, axis=1)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[1.0, 0.0], [0.0, 1.0]])

    def test_retain_graph(self):
        x = _t([2.0])
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_no_grad_context(self):
        x = _t([1.0])
        with no_grad():
            y = x * 2
        assert y.stop_gradient
        assert y._grad_node is None

    def test_hooks(self):
        x = _t([1.0])
        seen = []

        def hook(g):
            seen.append(g.numpy().copy())
            return g * 2

        x.register_hook(hook)
        (x * 3).backward()
        assert len(seen) == 1
        np.testing.assert_allclose(x.grad.numpy(), [6.0])


class TestGradAPI:
    def test_grad_basic(self):
        x = _t([3.0])
        y = x * x
        (gx,) = grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [6.0])
        assert x.grad is None  # side-effect free

    def test_grad_intermediate(self):
        x = _t([2.0])
        y = x * x
        z = y * 3
        (gy,) = grad(z, y)
        np.testing.assert_allclose(gy.numpy(), [3.0])

    def test_grad_unused(self):
        x = _t([1.0])
        u = _t([1.0])
        y = x * 2
        res = grad(y, [x, u], allow_unused=True)
        assert res[1] is None

    def test_double_backward_via_retain(self):
        x = _t([2.0])
        y = x * x * x
        (g1,) = grad(y, x, create_graph=True)
        np.testing.assert_allclose(g1.numpy(), [12.0])


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return dy * 2

        x = _t([1.0, 2.0])
        y = Double.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])

    def test_custom_nonstandard_grad(self):
        class StraightThrough(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return paddle.sign(x)

            @staticmethod
            def backward(ctx, dy):
                return dy  # pretend identity

        x = _t([0.5, -0.5])
        y = StraightThrough.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


class TestAmpAutograd:
    def test_autocast_matmul_bf16(self):
        x = _t(np.random.randn(4, 4))
        w = _t(np.random.randn(4, 4))
        with paddle.amp.auto_cast(level="O1"):
            y = paddle.matmul(x, w)
        assert y.dtype == paddle.bfloat16
        y.astype("float32").sum().backward()
        # master grads arrive in fp32 on the fp32 leaves
        assert w.grad.dtype == paddle.float32

    def test_grad_scaler(self):
        model = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        x = _t(np.random.randn(2, 4), sg=True)
        with paddle.amp.auto_cast():
            loss = model(x).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        assert opt._step_count == 1
