"""Training autopilot (paddle_tpu/resilience/supervisor.py): the
closed-loop self-healing supervisor + TrainControl client, the
hardened RPC retry path under it, resume_latest's restored-step
metadata, checkpoint load-time resharding at N-1, the supervisor.act
chaos point — and the multi-process acceptance test driving all three
detector families end-to-end with zero human steps.

Module-level imports stay light: spawned children re-import this
module (spawn start method); heavyweight imports belong inside the
functions that run after the JAX_PLATFORMS=cpu env guard."""
import json
import multiprocessing
import os
import signal
import socket
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _autopilot_clean():
    """Every test starts with empty stores, no aggregator, no attached
    supervisor, no armed flight recorder and no armed faults."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import fleet, flight, numerics, tracing
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience import supervisor as sv
    numerics.disable()
    obs.disable()
    obs.reset()
    tracing.clear()
    faults.clear_all()
    saved = (fleet._PROCESS, fleet._ROLE, fleet._ROLE_EXPLICIT)
    fleet._PROCESS, fleet._ROLE, fleet._ROLE_EXPLICIT = None, None, False
    yield
    if sv._SUPERVISOR is not None:
        sv._SUPERVISOR.close()
    if fleet._AGGREGATOR is not None:
        fleet._AGGREGATOR.close()
    fleet._PROCESS, fleet._ROLE, fleet._ROLE_EXPLICIT = saved
    flight.disarm()
    faults.clear_all()
    # numerics.enable() is process-global and survives obs.reset();
    # left on it poisons later test modules (pipeline-parallel steps
    # can't pack_stats across stage sub-meshes).
    numerics.disable()
    obs.disable()
    obs.reset()
    tracing.clear()


def _divergence_event(step, reasons, **extra):
    """A trainer-shipped numerics.divergence trace event, as
    numerics._fire emits it."""
    args = {"step": step, "reasons": list(reasons), "source": "step",
            "first_nonfinite_param": None, "grad_norm": None,
            "loss_scale": None}
    args.update(extra)
    return {"name": "numerics.divergence", "ph": "X", "pid": 1,
            "tid": 1, "ts": time.perf_counter() * 1e6, "dur": 0.0,
            "args": args}


# ---------------------------------------------------------------------------
# satellite: supervisor-grade RPC hardening
# ---------------------------------------------------------------------------
class TestRpcHardening:
    def _retry_counts(self):
        from paddle_tpu import observability as obs
        rows = obs.snapshot().get("paddle_tpu_rpc_retries_total",
                                  {}).get("series", {})
        return (rows.get(("retried",), 0.0), rows.get(("gave_up",), 0.0))

    def test_wedged_peer_cannot_hang_the_caller(self):
        """A peer that accepts but never answers: every socket op is
        bounded by the per-call timeout, retries back off
        exponentially (bounded), and the give-up is counted."""
        from paddle_tpu.distributed import rpc
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            srv.listen(4)
            ip, port = srv.getsockname()
            base_r, base_g = self._retry_counts()
            t0 = time.perf_counter()
            with pytest.raises(OSError):
                rpc.call_endpoint(f"{ip}:{port}", len, args=("x",),
                                  timeout=0.3, retries=2,
                                  backoff_s=0.01)
            dt = time.perf_counter() - t0
            assert dt < 3.0     # 3 bounded attempts + tiny backoffs
            r, g = self._retry_counts()
            assert r - base_r == 2.0
            assert g - base_g == 1.0
        finally:
            srv.close()

    def test_dead_endpoint_retries_then_gives_up(self):
        from paddle_tpu.distributed import rpc
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        _, port = s.getsockname()
        s.close()                       # nothing listens here now
        base_r, base_g = self._retry_counts()
        with pytest.raises(OSError):
            rpc.call_endpoint(f"127.0.0.1:{port}", len, args=("x",),
                              timeout=0.5, retries=3, backoff_s=0.005)
        r, g = self._retry_counts()
        assert r - base_r == 3.0 and g - base_g == 1.0

    def test_remote_exception_is_never_retried(self):
        """status=err is a SUCCESSFUL round trip: retrying would
        re-execute a non-idempotent call. The remote exception
        propagates immediately and no retry is counted."""
        from paddle_tpu.distributed import rpc
        server, ep = rpc.serve()
        try:
            base_r, _ = self._retry_counts()
            with pytest.raises(ValueError, match="remote boom"):
                rpc.call_endpoint(ep, _raise_value_error, timeout=10.0,
                                  retries=5)
            r, _ = self._retry_counts()
            assert r == base_r
        finally:
            server.shutdown()
            server.server_close()

    def test_default_call_has_no_retries(self):
        from paddle_tpu.distributed import rpc
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        _, port = s.getsockname()
        s.close()
        base_r, base_g = self._retry_counts()
        with pytest.raises(OSError):
            rpc.call_endpoint(f"127.0.0.1:{port}", len, args=("x",),
                              timeout=0.5)
        assert self._retry_counts() == (base_r, base_g)


def _raise_value_error():
    raise ValueError("remote boom")


# ---------------------------------------------------------------------------
# satellite: resume_latest returns restored step/metadata
# ---------------------------------------------------------------------------
class TestResumeLatestMetadata:
    def test_returns_step_and_meta_str_compatible(self, tmp_path):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.distributed import checkpoint as ckpt
        sd = {"w": pt.to_tensor(np.arange(6, dtype=np.float32))}
        ckpt.save_state_dict(sd, str(tmp_path / "step_30"))
        sd["w"]._data = sd["w"]._data + 1
        ckpt.save_state_dict(sd, str(tmp_path / "step_200"))
        sd["w"]._data = sd["w"]._data * 0
        res = ckpt.resume_latest(sd, str(tmp_path))
        # the old contract: a plain-str path
        assert isinstance(res, str)
        assert res == str(tmp_path / "step_200")
        assert os.path.basename(res) == "step_200"
        # the new contract: restored step + parsed metadata ride along
        assert res.step == 200
        assert res.meta["w"]["global_shape"] == [6]
        assert "__manifest__" in res.meta
        assert np.asarray(sd["w"]._data)[3] == 4.0

    def test_unnumbered_checkpoint_has_step_none(self, tmp_path):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.distributed import checkpoint as ckpt
        sd = {"w": pt.to_tensor(np.ones(3, np.float32))}
        ckpt.save_state_dict(sd, str(tmp_path / "latest"))
        res = ckpt.resume_latest(sd, str(tmp_path))
        assert res == str(tmp_path / "latest")
        assert res.step is None

    def test_empty_root_still_returns_none(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        assert ckpt.resume_latest({}, str(tmp_path)) is None
        assert ckpt.resume_latest({}, str(tmp_path / "absent")) is None


# ---------------------------------------------------------------------------
# satellite: checkpoint load-time resharding at N-1
# ---------------------------------------------------------------------------
class TestElasticReshard:
    def _sharded(self, ndev, value=None, shape=(56, 3)):
        import jax
        import numpy as np
        import paddle_tpu as pt
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        devs = jax.devices()[:ndev]
        mesh = Mesh(np.array(devs), ("x",))
        sh = NamedSharding(mesh, PartitionSpec("x", None))
        arr = value if value is not None \
            else np.zeros(shape, np.float32)
        return pt.Tensor(jax.device_put(np.asarray(arr, np.float32),
                                        sh))

    def test_8_rank_checkpoint_restores_bit_exact_on_7_rank_mesh(
            self, tmp_path):
        """The elastic-restart path: state saved from an 8-device mesh
        loads bit-exact onto a 7-device mesh layout (load-time
        resharding re-slices the saved shard files per destination
        device; no intermediate full-array materialization on the
        destination's behalf is ever checked in — only values)."""
        import numpy as np
        from paddle_tpu.distributed import checkpoint as ckpt
        rng = np.random.default_rng(3)
        vals = rng.standard_normal((56, 3)).astype(np.float32)
        t8 = self._sharded(8, vals)
        ckpt.save_state_dict({"w": t8}, str(tmp_path / "step_5"))
        t7 = self._sharded(7)
        res = ckpt.resume_latest({"w": t7}, str(tmp_path))
        assert res.step == 5
        out = np.asarray(t7._data)
        assert out.dtype == np.float32
        assert np.array_equal(out, vals)        # bit-exact
        # and the restored array actually LIVES on the 7-device mesh
        shards = t7._data.addressable_shards
        assert len({s.device for s in shards}) == 7
        for s in shards:                        # each shard's slice too
            assert np.array_equal(np.asarray(s.data), vals[s.index])

    def test_7_rank_save_restores_onto_8(self, tmp_path):
        """The N+1 direction (a healed fleet growing back) uses the
        same machinery."""
        import numpy as np
        from paddle_tpu.distributed import checkpoint as ckpt
        rng = np.random.default_rng(4)
        vals = rng.standard_normal((56, 3)).astype(np.float32)
        t7 = self._sharded(7, vals)
        ckpt.save_state_dict({"w": t7}, str(tmp_path / "step_9"))
        t8 = self._sharded(8)
        ckpt.load_state_dict({"w": t8}, str(tmp_path / "step_9"))
        assert np.array_equal(np.asarray(t8._data), vals)
        assert len({s.device for s in t8._data.addressable_shards}) == 8


# ---------------------------------------------------------------------------
# the structured numerics.divergence trace event (detection transport)
# ---------------------------------------------------------------------------
class TestDivergenceTraceEvent:
    def test_real_divergence_emits_structured_event(self, tmp_path):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import numerics as num, tracing
        from paddle_tpu.resilience import faults
        obs.enable()
        num.enable(interval=1)
        rng = np.random.default_rng(10)
        lin = pt.nn.Linear(8, 8)
        params = lin.parameters()
        for p in params:
            p.set_value(pt.to_tensor(
                rng.standard_normal(p.shape).astype(np.float32)))
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        target = params[0].name

        def step():
            loss = (lin(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()

        step()
        with faults.inject("numerics.check",
                           exc=num.PoisonGradient(param=target),
                           times=1, match={"where": "step"}):
            step()
        num.flush()
        evs = [e for e in tracing.events()
               if e["name"] == "numerics.divergence"]
        assert len(evs) == 1
        args = evs[0]["args"]
        assert args["reasons"] == ["nonfinite"]
        assert args["first_nonfinite_param"] == target
        assert args["source"]       # step / optimizer_fused / amp ...
        assert isinstance(args["step"], int)


# ---------------------------------------------------------------------------
# supervisor unit coverage (in-process aggregator, no spawn)
# ---------------------------------------------------------------------------
class TestSupervisorUnit:
    def _sup(self, tmp_path, **policy):
        from paddle_tpu.observability import fleet, flight
        from paddle_tpu.resilience import supervisor as sv
        flight.arm(str(tmp_path / "flight"), min_interval_s=0.0)
        agg = fleet.FleetAggregator(stale_after_s=0.5)
        sup = sv.Supervisor(agg, ckpt_root=str(tmp_path / "ck"),
                            policy=sv.Policy(**policy))
        return agg, sup

    def _bundles(self, tmp_path, reason=None):
        from paddle_tpu.observability import flight
        out = []
        for p in flight.bundles(str(tmp_path / "flight")):
            b = flight.load_bundle(p)
            if reason is None or b["meta"]["reason"] == reason:
                out.append(b)
        return out

    def test_divergence_opens_episode_commands_rollback(self, tmp_path):
        from paddle_tpu.observability import fleet
        agg, sup = self._sup(tmp_path)
        sup.poll("t0", step=6)
        agg.ingest(fleet.make_bundle("t0", "trainer", 1, trace=[
            _divergence_event(7, ["nonfinite"],
                              first_nonfinite_param="w2")]))
        cmd = sup.poll("t0", step=7)
        assert cmd["cmd"] == "rollback"
        assert cmd["policy"] == "skip_batch"
        assert cmd["skip_step"] == 7
        assert cmd["ckpt_root"] == str(tmp_path / "ck")
        r = sup.report("t0", cmd["episode"],
                       {"ok": True, "resumed_step": 5})
        assert r == {"ok": True, "episode": cmd["episode"],
                     "outcome": "remediated"}
        bundles = self._bundles(tmp_path, "autopilot_remediation")
        assert len(bundles) == 1
        det = bundles[0]["meta"]["detail"]
        assert det["kind"] == "nan"
        assert det["outcome"] == "remediated"
        assert det["mttr_s"] >= 0.0
        assert det["detection_latency_s"] >= 0.0
        phases = [e["phase"] for e in det["timeline"]]
        assert phases == ["detection", "action_attempt", "action",
                          "outcome"]

    def test_same_episode_folds_repeat_detections(self, tmp_path):
        from paddle_tpu.observability import fleet
        agg, sup = self._sup(tmp_path)
        sup.poll("t0")
        for seq in (1, 2):      # absorbing NaN keeps re-signalling
            agg.ingest(fleet.make_bundle("t0", "trainer", seq, trace=[
                _divergence_event(7 + seq, ["nonfinite"])]))
        assert len(sup.episodes(done=False)) == 1
        cmd = sup.poll("t0")
        assert cmd["cmd"] == "rollback"
        assert sup.poll("t0") is None       # ONE command, not two

    def test_clean_bundles_zero_episodes_zero_bundles(self, tmp_path):
        from paddle_tpu.observability import fleet
        agg, sup = self._sup(tmp_path)
        sup.poll("t0", step=1)
        for seq in (1, 2, 3):
            agg.ingest(fleet.make_bundle("t0", "trainer", seq))
        assert sup.scan()["open"] == 0
        assert sup.poll("t0") is None
        assert self._bundles(tmp_path) == []
        snap = agg.registry.snapshot()
        eps = snap["paddle_tpu_autopilot_episodes_total"]["series"]
        assert not any(v for v in eps.values())

    def test_dead_rank_evicted_and_controller_told_to_restart(
            self, tmp_path):
        from paddle_tpu.observability import fleet
        agg, sup = self._sup(tmp_path, heartbeat_stale_s=0.5)
        sup.poll("chief", step=0)
        agg.ingest(fleet.make_bundle("rank3", "trainer", 1))
        agg.ingest(fleet.make_bundle("chief", "chief", 1))
        now = time.time() + 5.0
        assert sup.scan(now)["open"] == 1   # chief (controller) exempt
        cmd = sup.poll("chief")
        assert cmd["cmd"] == "restart"
        assert cmd["evicted"] == "rank3"
        sup.report("chief", cmd["episode"], {"ok": True, "world": 7})
        bundles = self._bundles(tmp_path, "autopilot_remediation")
        assert len(bundles) == 1
        det = bundles[0]["meta"]["detail"]
        assert det["kind"] == "dead_rank"
        actions = [e["action"] for e in det["timeline"]
                   if e["phase"] == "action"]
        assert actions == ["evict_rank", "elastic_restart"]
        # the evicted rank never retriggers
        assert sup.scan(now + 1.0)["open"] == 0

    def test_sustained_straggler_evicted(self, tmp_path):
        from paddle_tpu.observability import fleet
        agg, sup = self._sup(tmp_path, straggler_sustain_s=0.5)
        agg.straggler_threshold_s = 0.05
        sup.poll("chief")
        t0 = time.perf_counter() * 1e6
        for proc, ts in (("chief", t0), ("rank2", t0 + 2e5)):
            arrival = {"name": "comms.arrival", "ph": "X", "pid": 1,
                       "tid": 1, "ts": ts, "dur": 0.0,
                       "args": {"op": "allreduce", "group": "g0",
                                "seq": 1}}
            agg.ingest(fleet.make_bundle(proc, "trainer", 1,
                                         trace=[arrival]))
        assert agg.stragglers() == {"allreduce": "rank2"}
        now = time.time()
        assert sup.scan(now)["open"] == 0           # not sustained yet
        assert sup.scan(now + 1.0)["open"] == 1     # sustained -> act
        cmd = sup.poll("chief")
        assert cmd["cmd"] == "restart" and cmd["evicted"] == "rank2"
        sup.report("chief", cmd["episode"], {"ok": True})
        [b] = self._bundles(tmp_path, "autopilot_remediation")
        assert b["meta"]["detail"]["kind"] == "straggler"

    def test_repeated_scale_floor_escalates_named_failure(
            self, tmp_path):
        from paddle_tpu.observability import fleet
        from paddle_tpu.resilience.supervisor import AutopilotFailure
        agg, sup = self._sup(tmp_path, scale_floor_max=2)
        sup.poll("t0")
        agg.ingest(fleet.make_bundle("t0", "trainer", 1, trace=[
            _divergence_event(5, ["loss_scale_floor"], source="amp",
                              loss_scale=1.0)]))
        cmd = sup.poll("t0")
        assert cmd["cmd"] == "rollback" \
            and cmd["policy"] == "reraise_scale"
        sup.report("t0", cmd["episode"], {"ok": True})
        agg.ingest(fleet.make_bundle("t0", "trainer", 2, trace=[
            _divergence_event(11, ["loss_scale_floor"], source="amp",
                              loss_scale=1.0)]))
        stop = sup.poll("t0")
        assert stop["cmd"] == "stop"
        assert "loss-scale floor" in stop["error"]
        assert isinstance(sup.failure, AutopilotFailure)
        assert sup.failure.kind == "scale_floor"
        assert sup.failure.episodes       # actionable: history attached
        bundles = self._bundles(tmp_path, "autopilot_remediation")
        assert [b["meta"]["detail"]["outcome"] for b in bundles] == \
            ["remediated", "escalated"]
        snap = agg.registry.snapshot()
        eps = snap["paddle_tpu_autopilot_episodes_total"]["series"]
        assert eps[("scale_floor", "remediated")] == 1.0
        assert eps[("scale_floor", "escalated")] == 1.0

    def test_act_crash_leaves_journal_next_scan_completes(
            self, tmp_path):
        """satellite: chaos inside remediation. The supervisor.act
        fault point kills the first rollback attempt; the episode's
        pending-action journal survives, every checkpoint stays
        un-torn, and the next scan() completes the recovery."""
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.observability import fleet
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.resilience import faults
        from paddle_tpu.resilience import supervisor as sv
        agg, sup = self._sup(tmp_path)
        root = str(tmp_path / "ck")
        sd = {"w": pt.to_tensor(np.arange(4, dtype=np.float32))}
        for s in (1, 2):
            ckpt.save_state_dict(sd, os.path.join(root, f"step_{s}"))
            sd["w"]._data = sd["w"]._data + 1
        sup.poll("t0")
        with faults.inject("supervisor.act", exc=RuntimeError("chaos"),
                           times=1):
            agg.ingest(fleet.make_bundle("t0", "trainer", 1, trace=[
                _divergence_event(3, ["nonfinite"])]))
            assert sup.poll("t0") is None   # action died pre-commit
        [ep] = sup.episodes(done=False)
        assert ep["pending"], "journal must survive the crash"
        assert [e["phase"] for e in ep["timeline"]] == \
            ["detection", "action_attempt"]
        # checkpoints are un-torn: remediation only ever READS them
        for name in os.listdir(root):
            assert ckpt.verify_checkpoint(os.path.join(root, name)) \
                == []
        sup.scan()                          # next pass retries
        cmd = sup.poll("t0")
        assert cmd and cmd["cmd"] == "rollback"
        # the trainer-side apply completes against the intact root
        ctl = sv.TrainControl("unused:0", "t0")
        out = ctl.apply(cmd, state_dict=sd, root=root)
        assert out["ok"] and out["resumed_step"] == 2
        assert np.asarray(sd["w"]._data)[0] == 1.0
        sup.report("t0", cmd["episode"], out)
        [b] = self._bundles(tmp_path, "autopilot_remediation")
        assert b["meta"]["detail"]["outcome"] == "remediated"
        snap = agg.registry.snapshot()
        fails = snap["paddle_tpu_autopilot_action_failures_total"][
            "series"]
        assert fails[("rollback_resume",)] == 1.0

    def test_nan_past_rollback_budget_escalates(self, tmp_path):
        from paddle_tpu.observability import fleet
        agg, sup = self._sup(tmp_path, max_rollbacks=1)
        sup.poll("t0")
        agg.ingest(fleet.make_bundle("t0", "trainer", 1, trace=[
            _divergence_event(3, ["nonfinite"])]))
        cmd = sup.poll("t0")
        sup.report("t0", cmd["episode"], {"ok": True})
        agg.ingest(fleet.make_bundle("t0", "trainer", 2, trace=[
            _divergence_event(9, ["nonfinite"])]))
        stop = sup.poll("t0")
        assert stop["cmd"] == "stop"
        assert sup.failure is not None and sup.failure.kind == "nan"


# ---------------------------------------------------------------------------
# single-process fleet echo (found by the in-process autopilot bench):
# the aggregator ingests shipped trace events into the local ring; a
# co-resident agent must not ship them back out, or one divergence
# event re-detects on every heartbeat forever
# ---------------------------------------------------------------------------
class TestInProcessFleetNoEcho:
    def test_ingested_events_never_reshipped(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        obs.enable()
        agg = fleet.serve_aggregator()
        seen = []
        agg.add_observer(lambda proc, b: seen.extend(
            ev["name"] for ev in b.get("trace") or ()))
        fleet.set_identity(process="solo", role="trainer")
        agent = fleet.FleetAgent(agg.endpoint, interval_s=3600.0,
                                 timeout_s=30.0)
        tracing.add_event("numerics.divergence",
                          time.perf_counter() * 1e6, 0.0,
                          args={"step": 1, "reasons": ["nonfinite"]})
        for _ in range(4):
            assert agent.ship()
        assert seen.count("numerics.divergence") == 1
        agg.close()


# ---------------------------------------------------------------------------
# GradScaler.set_loss_scaling (the reraise_scale remediation primitive)
# ---------------------------------------------------------------------------
class TestSetLossScaling:
    def test_reraise_rearms_sentinel_for_second_collapse(
            self, tmp_path):
        """A floored run only has skipped steps — no clean publish
        ever re-arms the divergence latch. set_loss_scaling must
        re-arm it so the SECOND collapse fires its own bundle (the
        input to the supervisor's repeated-floor escalation)."""
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.observability import flight, numerics as num
        from paddle_tpu.resilience import faults
        obs.enable()
        num.enable(interval=1, loss_scale_floor=2.0)
        flight.arm(str(tmp_path), min_interval_s=0.0)
        rng = np.random.default_rng(11)
        lin = pt.nn.Linear(6, 6)
        params = lin.parameters()
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        scaler = GradScaler(init_loss_scaling=8.0, decr_ratio=0.5,
                            decr_every_n_nan_or_inf=1)
        x = pt.to_tensor(rng.standard_normal((4, 6)).astype(np.float32))

        def poisoned_step():
            loss = (lin(x) ** 2).mean()
            scaler.scale(loss).backward()
            with faults.inject("numerics.check",
                               exc=num.PoisonGradient(
                                   param=params[0].name),
                               times=1, match={"where": "amp"}):
                scaler.step(opt)
            scaler.update()
            opt.clear_grad()

        while float(scaler.get_loss_scaling()) > 2.0:
            poisoned_step()
        assert len(flight.bundles(str(tmp_path))) == 1
        scaler.set_loss_scaling(32.0)
        assert float(scaler.get_loss_scaling()) == 32.0
        assert scaler._good_steps == 0 and scaler._bad_steps == 0
        while float(scaler.get_loss_scaling()) > 2.0:
            poisoned_step()
        bundles = flight.bundles(str(tmp_path))
        assert len(bundles) == 2
        b = flight.load_bundle(bundles[1])
        assert b["meta"]["detail"]["reasons"] == ["loss_scale_floor"]


# ---------------------------------------------------------------------------
# obs_top autopilot panel
# ---------------------------------------------------------------------------
class TestObsTopAutopilotPanel:
    def _obs_top(self):
        tools = os.path.join(REPO, "tools")
        sys.path.insert(0, tools)
        try:
            import obs_top
        finally:
            sys.path.remove(tools)
        return obs_top

    def test_renders_episodes_actions_and_latencies(self, tmp_path):
        obs_top = self._obs_top()
        from paddle_tpu.observability import fleet
        from paddle_tpu.resilience import supervisor as sv
        agg = fleet.FleetAggregator()
        sup = sv.Supervisor(agg, ckpt_root=str(tmp_path),
                            policy=sv.Policy())
        sup.poll("t0")
        agg.ingest(fleet.make_bundle("t0", "trainer", 1, trace=[
            _divergence_event(4, ["nonfinite"])]))
        cmd = sup.poll("t0")
        sup.report("t0", cmd["episode"], {"ok": True})
        doc = json.loads(agg.to_json())
        frame = obs_top.render(doc)
        assert "== autopilot ==" in frame
        assert "nan" in frame and "remediated" in frame
        assert "rollback_resume=1" in frame
        assert "last=rollback_resume" in frame
        assert "detection" in frame and "mttr" in frame
        agg.close()

    def test_clean_registry_renders_no_panel(self):
        obs_top = self._obs_top()
        from paddle_tpu.observability import fleet
        agg = fleet.FleetAggregator()
        agg.ingest(fleet.make_bundle("t0", "trainer", 1))
        frame = obs_top.render(json.loads(agg.to_json()))
        assert "== autopilot ==" not in frame
        agg.close()


# ---------------------------------------------------------------------------
# multi-process chaos acceptance: injected fault -> detection ->
# automated remediation -> training resumes, zero human steps
# ---------------------------------------------------------------------------
def _toy_layers(seed):
    """Deterministic 2-layer MLP; identical construction in the worker
    and the parent's oracle so replay comparisons are bit-exact."""
    import numpy as np
    import paddle_tpu as pt
    rng = np.random.default_rng(seed)
    lin1, lin2 = pt.nn.Linear(8, 8), pt.nn.Linear(8, 1)
    params = [p for l in (lin1, lin2) for p in l.parameters()]
    for p in params:
        p.set_value(pt.to_tensor(
            rng.standard_normal(p.shape).astype(np.float32)))
    opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
    return (lin1, lin2), params, opt


def _toy_train_step(layers, opt, step):
    """One eager step on the batch deterministically derived from the
    step index — replaying step s always consumes the same data."""
    import numpy as np
    import paddle_tpu as pt
    l1, l2 = layers
    rng = np.random.default_rng(100000 + step)
    x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
    h = pt.ops.tanh(l1(x))
    loss = (l2(h) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()


def _nan_trainer(endpoint, ckpt_root, poison_step, n_steps, q):
    """Scenario 1 worker: deterministic training, PoisonGradient chaos
    at `poison_step`, autopilot-commanded rollback + skip-batch
    resume. Reports its final params for the parent's bit-exact oracle
    comparison."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import numpy as np
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, numerics as num
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.resilience import faults
        from paddle_tpu.resilience import supervisor as sv

        obs.enable()
        num.enable(interval=1)
        fleet.set_identity(process="trainer0", role="trainer")
        agent = fleet.FleetAgent(endpoint, interval_s=60.0,
                                 timeout_s=30.0)
        ctl = sv.TrainControl(endpoint, "trainer0", timeout_s=30.0,
                              retries=2)
        layers, params, opt = _toy_layers(seed=5)
        sd = {p.name: p for p in params}
        state = {"step": 0}
        faults.inject("numerics.check",
                      exc=num.PoisonGradient(param=params[0].name),
                      times=1, match={"where": "step"},
                      when=lambda ctx: state["step"] == poison_step)
        step = 0
        skip = set()
        remediations = []
        stray_cmds = 0
        while step < n_steps:
            state["step"] = step
            cmd = ctl.poll(step=step)
            if cmd is not None:
                if cmd.get("cmd") != "rollback":
                    stray_cmds += 1
                    continue
                out = ctl.apply(cmd, state_dict=sd, root=ckpt_root)
                step = out["resumed_step"] + 1
                if cmd.get("policy") == "skip_batch":
                    skip.add(step)  # first replayed batch = poison
                ctl.report(cmd["episode"], **out)
                remediations.append(out)
                continue
            if step in skip:
                step += 1
                continue
            _toy_train_step(layers, opt, step)
            num.flush()
            if all(np.isfinite(np.asarray(p._data)).all()
                   for p in params):
                ckpt.save_state_dict(
                    sd, os.path.join(ckpt_root, f"step_{step}"))
            agent.ship()
            step += 1
        agent.stop()
        final = [np.asarray(p._data).tobytes() for p in params]
        q.put(("ok", {"final": final, "remediations": remediations,
                      "stray_cmds": stray_cmds,
                      "poison_fired": faults.fired("numerics.check")}))
    except BaseException as e:
        q.put(("error", repr(e)))
        raise


def _hb_rank(endpoint, name, q):
    """Scenario 2 worker: a rank whose only job is heartbeating until
    the parent SIGKILLs it."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        fleet.set_identity(process=name, role="trainer")
        agent = fleet.FleetAgent(endpoint, interval_s=0.2,
                                 timeout_s=30.0)
        agent.start()
        q.put(("up", os.getpid()))
        time.sleep(600)         # parent kills us long before this
    except BaseException as e:
        q.put(("error", repr(e)))
        raise


def _elastic_chief(endpoint, ckpt_root, q):
    """Scenario 2 worker: the controller. Trains a sharded toy model
    on the 8-device mesh, checkpoints every step; on the autopilot's
    restart command rebuilds a 7-device mesh and resumes from the
    resharded checkpoint, proving loss keeps descending at N-1."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.resilience import supervisor as sv

        obs.enable()
        fleet.set_identity(process="chief", role="chief")
        agent = fleet.FleetAgent(endpoint, interval_s=0.2,
                                 timeout_s=30.0)
        agent.start()
        ctl = sv.TrainControl(endpoint, "chief", timeout_s=30.0,
                              retries=2)
        devs = jax.devices()
        rng = np.random.default_rng(7)
        X = jnp.asarray(rng.standard_normal((16, 56)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 1)), jnp.float32)

        def loss_fn(w):
            return jnp.mean((X @ w - y) ** 2)

        grad_fn = jax.grad(loss_fn)

        def sharded(ndev, value):
            mesh = Mesh(np.array(devs[:ndev]), ("x",))
            sh = NamedSharding(mesh, PartitionSpec("x", None))
            return pt.Tensor(jax.device_put(
                np.asarray(value, np.float32), sh))

        t = sharded(8, rng.standard_normal((56, 1)))
        sd = {"w": t}
        losses = []
        restart = None
        step = 0
        deadline = time.time() + 120.0
        while time.time() < deadline:
            cmd = ctl.poll(step=step)
            if cmd is not None and cmd.get("cmd") == "restart":
                t = sharded(7, np.zeros((56, 1)))
                sd = {"w": t}
                res = ckpt.resume_latest(sd, ckpt_root)
                ndev = len({s.device
                            for s in t._data.addressable_shards})
                restart = {"at": len(losses),
                           "resumed_step": res.step, "ndev": ndev,
                           "evicted": cmd["evicted"]}
                step = res.step + 1
                ctl.report(cmd["episode"], ok=True, world=ndev,
                           resumed_step=res.step)
                continue
            w = t._data
            t._data = w - 0.02 * grad_fn(w)
            losses.append(float(loss_fn(t._data)))
            ckpt.save_state_dict(
                sd, os.path.join(ckpt_root, f"step_{step}"))
            step += 1
            if restart is not None \
                    and len(losses) - restart["at"] >= 4:
                break
            time.sleep(0.05)
        agent.stop()
        q.put(("ok", {"losses": losses, "restart": restart}))
    except BaseException as e:
        q.put(("error", repr(e)))
        raise


def _amp_trainer(endpoint, ckpt_root, q):
    """Scenario 3 worker: persistently poisoned AMP steps collapse the
    loss scale to the floor repeatedly; the autopilot remediates once
    (rollback + reraise_scale) then escalates — the worker reports the
    named AutopilotFailure the poll raised."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.observability import fleet, numerics as num
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.resilience import faults
        from paddle_tpu.resilience import supervisor as sv

        obs.enable()
        num.enable(interval=1, loss_scale_floor=2.0)
        fleet.set_identity(process="amp0", role="trainer")
        agent = fleet.FleetAgent(endpoint, interval_s=60.0,
                                 timeout_s=30.0)
        ctl = sv.TrainControl(endpoint, "amp0", timeout_s=30.0,
                              retries=2)
        layers, params, opt = _toy_layers(seed=9)
        sd = {p.name: p for p in params}
        ckpt.save_state_dict(sd, os.path.join(ckpt_root, "step_0"))
        scaler = GradScaler(init_loss_scaling=8.0, decr_ratio=0.5,
                            decr_every_n_nan_or_inf=1)
        faults.inject("numerics.check",
                      exc=num.PoisonGradient(param=params[0].name),
                      match={"where": "amp"})
        outcomes = []
        try:
            for step in range(60):
                cmd = ctl.poll(step=step)
                if cmd is not None:
                    out = ctl.apply(cmd, state_dict=sd,
                                    root=ckpt_root, scaler=scaler)
                    outcomes.append(out)
                    ctl.report(cmd["episode"], **out)
                    continue
                rng = np.random.default_rng(200000 + step)
                x = pt.to_tensor(
                    rng.standard_normal((4, 8)).astype(np.float32))
                l1, l2 = layers
                h = pt.ops.tanh(l1(x))
                loss = (l2(h) ** 2).mean()
                scaler.scale(loss).backward()
                scaler.step(opt)
                scaler.update()
                opt.clear_grad()
                num.flush()
                agent.ship()
            q.put(("no_failure", {"outcomes": outcomes}))
        except sv.AutopilotFailure as e:
            agent.ship()
            q.put(("autopilot_failure",
                   {"msg": str(e), "kind": e.kind,
                    "outcomes": outcomes}))
    except BaseException as e:
        q.put(("error", repr(e)))
        raise


class TestChaosAcceptance:
    def _serve(self, tmp_path, tag, **policy):
        from paddle_tpu.observability import fleet, flight
        from paddle_tpu.resilience import supervisor as sv
        fldir = str(tmp_path / f"flight_{tag}")
        flight.arm(fldir, min_interval_s=0.0)
        agg = fleet.serve_aggregator(
            stale_after_s=policy.get("heartbeat_stale_s", 10.0))
        sup = sv.attach(sv.Supervisor(
            agg, ckpt_root=str(tmp_path / f"ck_{tag}"),
            policy=sv.Policy(**policy)))
        return agg, sup, fldir

    def _teardown(self, agg, sup):
        from paddle_tpu.observability import flight
        sup.close()
        agg.close()
        flight.disarm()

    def _autopilot_bundles(self, fldir):
        from paddle_tpu.observability import flight
        out = []
        for p in flight.bundles(fldir):
            b = flight.load_bundle(p)
            if b["meta"]["reason"] == "autopilot_remediation":
                out.append(b["meta"]["detail"])
        return out

    def _get(self, q, timeout=180):
        status, payload = q.get(timeout=timeout)
        assert status not in ("error",), payload
        return status, payload

    def test_injected_faults_detected_remediated_resumed(
            self, tmp_path):
        """The acceptance loop, three scenarios, zero human steps:
        (1) PoisonGradient -> numerics divergence -> rollback +
        bit-exact skip-batch resume; (2) SIGKILLed rank -> heartbeat
        staleness -> evict + elastic restart at N-1 with resharded
        state and loss still descending; (3) repeated AMP loss-scale
        floor -> one remediation, then a named AutopilotFailure.
        Each episode leaves exactly one autopilot_remediation flight
        bundle; clean stretches perform zero remediations."""
        import numpy as np
        ctx = multiprocessing.get_context("spawn")

        # ---- scenario 1: NaN -> rollback -> bit-exact resume ----
        poison_step, n_steps = 5, 10
        agg, sup, fldir = self._serve(tmp_path, "nan")
        q = ctx.Queue()
        p = ctx.Process(target=_nan_trainer, args=(
            agg.endpoint, sup.ckpt_root, poison_step, n_steps, q))
        p.start()
        status, rep = self._get(q)
        p.join(60)
        assert p.exitcode == 0
        assert rep["poison_fired"] == 1
        assert rep["stray_cmds"] == 0
        assert len(rep["remediations"]) == 1
        rem = rep["remediations"][0]
        assert rem["resumed_step"] == poison_step - 1
        # oracle: the same run with the poisoned batch skipped,
        # trained start-to-finish with no faults — BIT-exact equal
        layers, params, opt = _toy_layers(seed=5)
        for s in range(n_steps):
            if s != poison_step:
                _toy_train_step(layers, opt, s)
        for i, pm in enumerate(params):
            assert np.asarray(pm._data).tobytes() == \
                rep["final"][i], i
        details = self._autopilot_bundles(fldir)
        assert len(details) == 1
        assert details[0]["kind"] == "nan"
        assert details[0]["outcome"] == "remediated"
        assert [e["phase"] for e in details[0]["timeline"]] == \
            ["detection", "action_attempt", "action", "outcome"]
        assert details[0]["mttr_s"] > 0.0
        self._teardown(agg, sup)

        # ---- scenario 2: SIGKILLed rank -> elastic restart at N-1 --
        agg, sup, fldir = self._serve(tmp_path, "dead",
                                      heartbeat_stale_s=1.0)
        qc, qr = ctx.Queue(), ctx.Queue()
        chief = ctx.Process(target=_elastic_chief,
                            args=(agg.endpoint, sup.ckpt_root, qc))
        rank = ctx.Process(target=_hb_rank,
                           args=(agg.endpoint, "rank1", qr))
        chief.start()
        rank.start()
        status, pid = self._get(qr)
        assert status == "up"
        deadline = time.time() + 60.0
        while time.time() < deadline \
                and "rank1" not in agg.health():
            time.sleep(0.1)
        os.kill(pid, signal.SIGKILL)
        rank.join(30)
        assert rank.exitcode == -signal.SIGKILL
        # the autopilot watch loop: scan until the episode closes
        deadline = time.time() + 90.0
        while time.time() < deadline:
            sup.scan()
            done = [e for e in sup.episodes()
                    if e["state"] == "done"]
            if done:
                break
            time.sleep(0.2)
        status, rep = self._get(qc)
        chief.join(60)
        assert chief.exitcode == 0
        assert rep["restart"] is not None
        assert rep["restart"]["evicted"] == "rank1"
        assert rep["restart"]["ndev"] == 7       # N-1 mesh, resharded
        at = rep["restart"]["at"]
        losses = rep["losses"]
        assert len(losses) >= at + 4
        post = losses[at:]
        # loss keeps DESCENDING after the resharded restart
        assert all(b < a for a, b in zip(post, post[1:]))
        assert post[-1] < losses[at - 1]
        details = self._autopilot_bundles(fldir)
        assert len(details) == 1
        assert details[0]["kind"] == "dead_rank"
        assert details[0]["outcome"] == "remediated"
        actions = [e["action"] for e in details[0]["timeline"]
                   if e["phase"] == "action"]
        assert actions == ["evict_rank", "elastic_restart"]
        self._teardown(agg, sup)

        # ---- scenario 3: repeated AMP floor -> AutopilotFailure ----
        agg, sup, fldir = self._serve(tmp_path, "amp",
                                      scale_floor_max=2)
        q = ctx.Queue()
        p = ctx.Process(target=_amp_trainer,
                        args=(agg.endpoint, sup.ckpt_root, q))
        p.start()
        status, rep = self._get(q)
        p.join(60)
        assert status == "autopilot_failure", rep
        assert rep["kind"] == "scale_floor"
        assert "loss-scale floor" in rep["msg"]
        assert len(rep["outcomes"]) == 1        # one remediation first
        assert rep["outcomes"][0]["policy"] == "reraise_scale"
        assert rep["outcomes"][0]["loss_scale"] > 2.0
        details = self._autopilot_bundles(fldir)
        assert [d["outcome"] for d in details] == \
            ["remediated", "escalated"]
        assert all(d["kind"] == "scale_floor" for d in details)
        assert sup.failure is not None
        self._teardown(agg, sup)
