"""Batched + whole-graph backward dispatch (ISSUE 10/13):
bit-identical-gradients suite (whole_graph vs batched vs per_node
across hooks, retain_graph, create_graph, multi-consumer fan-in, dead
output slots, the fused-optimizer end-to-end path), mode controls,
fused-segment degradation, the whole-graph trace cache
(hit/miss/bypass telemetry, invalidation), the backward compile-family
budget, and the bandwidth-window-validated autotune sweep."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.autograd import dispatch_queue as dq
from paddle_tpu.kernels.pallas import autotune


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()
    dq.set_dispatch_mode("whole_graph")


def _params(seed=0, n=16):
    rng = np.random.default_rng(seed)
    w1 = pt.to_tensor(rng.standard_normal((n, n)).astype(np.float32),
                      stop_gradient=False)
    w2 = pt.to_tensor(rng.standard_normal((n, n)).astype(np.float32),
                      stop_gradient=False)
    x = pt.to_tensor(rng.standard_normal((4, n)).astype(np.float32))
    return w1, w2, x


def _bit_identical(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return a.dtype == b.dtype and np.array_equal(a, b)


# ---------------------------------------------------------------------------
# bit-identical gradients: batched vs per_node
# ---------------------------------------------------------------------------
class TestBitIdenticalGradients:
    def _both_modes(self, fn):
        """Run `fn` under every dispatch mode; gradients must be
        bit-identical to the per_node reference in all of them."""
        with dq.backward_dispatch_mode("per_node"):
            a = fn()
        for mode in ("batched", "whole_graph"):
            with dq.backward_dispatch_mode(mode):
                b = fn()
            assert len(a) == len(b)
            for ga, gb in zip(a, b):
                assert _bit_identical(ga, gb), mode
        return a

    def test_linear_chain(self):
        def run():
            w1, w2, x = _params()
            loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                    ** 2).mean()
            loss.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        self._both_modes(run)

    def test_hooks_fire_identically(self):
        fired = {"per_node": 0, "batched": 0, "whole_graph": 0}

        def run():
            mode = dq.dispatch_mode()
            w1, w2, x = _params()
            h = pt.ops.tanh(pt.matmul(x, w1))

            def hook(g):
                fired[mode] += 1
                return g * 2
            h.register_hook(hook)
            loss = (pt.matmul(h, w2) ** 2).mean()
            loss.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        self._both_modes(run)
        assert fired["per_node"] == fired["batched"] \
            == fired["whole_graph"] == 1

    def test_leaf_hook_identical(self):
        def run():
            w1, w2, x = _params()
            w1.register_hook(lambda g: g * 3)
            loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                    ** 2).mean()
            loss.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        self._both_modes(run)

    def test_retain_graph_double_backward(self):
        def run():
            w1, w2, x = _params()
            loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                    ** 2).mean()
            loss.backward(retain_graph=True)
            loss.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        self._both_modes(run)

    def test_create_graph_second_order(self):
        def run():
            w1, w2, x = _params()
            loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                    ** 2).mean()
            (g,) = pt.autograd.grad(loss, [w1], create_graph=True)
            (gg,) = pt.autograd.grad(g.sum(), [w1])
            return [gg.numpy()]
        self._both_modes(run)

    def test_multi_consumer_fan_in(self):
        def run():
            w1, w2, x = _params()
            y = pt.ops.tanh(pt.matmul(x, w1))
            z = (y * y + pt.ops.tanh(y) + pt.matmul(y, w2)).mean()
            z.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        self._both_modes(run)

    def test_dead_output_slot_uses_zero_cache(self):
        def run():
            w1, _, x = _params()
            h = pt.matmul(x, w1)
            a, b = pt.split(h, 2, axis=1)    # b's cotangent slot is dead
            loss = (a ** 2).mean()
            loss.backward()
            return [w1.grad.numpy()]
        dq.clear_const_caches()
        self._both_modes(run)
        assert dq._ZEROS               # the dead slot hit the cache

    def test_grad_targets_and_explicit_seed(self):
        def run():
            w1, w2, x = _params()
            h = pt.ops.tanh(pt.matmul(x, w1))
            loss = (pt.matmul(h, w2) ** 2).mean()
            seed = pt.to_tensor(np.float32(2.0))
            (gh, gw) = pt.autograd.grad(loss, [h, w1],
                                        grad_outputs=[seed],
                                        allow_unused=True)
            return [gh.numpy(), gw.numpy()]
        self._both_modes(run)

    def test_fused_optimizer_end_to_end(self):
        def run():
            rng = np.random.default_rng(7)
            lin1, lin2 = pt.nn.Linear(16, 16), pt.nn.Linear(16, 16)
            for p in lin1.parameters() + lin2.parameters():
                p.set_value(pt.to_tensor(
                    rng.standard_normal(p.shape).astype(np.float32)))
            opt = pt.optimizer.AdamW(
                learning_rate=1e-2,
                parameters=lin1.parameters() + lin2.parameters())
            x = pt.to_tensor(
                rng.standard_normal((4, 16)).astype(np.float32))
            for _ in range(3):
                loss = (lin2(pt.ops.tanh(lin1(x))) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            return [p.numpy()
                    for p in lin1.parameters() + lin2.parameters()]
        self._both_modes(run)


# ---------------------------------------------------------------------------
# fusion behavior: runs form, degrade, and stay observable
# ---------------------------------------------------------------------------
class TestFusion:
    def _batch_series(self):
        return obs.snapshot()[
            "paddle_tpu_dispatch_batch_size"]["series"].get(())

    def test_chain_fuses_into_one_dispatch(self):
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("batched"):
            loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                    ** 2).mean()
            loss.backward()
        val = self._batch_series()
        # the 5-node chain (matmul-tanh-matmul-pow-mean) is one run
        assert val["count"] == 1
        assert val["max"] == 5
        gap = obs.snapshot()[
            "paddle_tpu_dispatch_gap_seconds"]["series"][()]
        assert gap["count"] == 0       # no inter-dispatch host gaps

    def test_mid_chain_hook_degrades_to_per_node(self):
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("batched"):
            h = pt.ops.tanh(pt.matmul(x, w1))
            h.register_hook(lambda g: g)
            loss = (pt.matmul(h, w2) ** 2).mean()
            loss.backward()
        val = self._batch_series()
        # the hooked node breaks the run: >1 dispatch, none covering
        # the whole 5-node graph
        assert val["count"] > 1
        assert val["max"] < 5
        assert val["sum"] == 5         # every node still dispatched

    def test_per_node_mode_records_no_batch_sizes(self):
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("per_node"):
            (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
             ** 2).mean().backward()
        assert self._batch_series()["count"] == 0

    def test_fused_chain_executable_is_cached(self):
        dq.clear_chain_cache()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("batched"):
            for _ in range(3):
                loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                        ** 2).mean()
                loss.backward()
                w1.clear_gradient()
                w2.clear_gradient()
        assert dq.chain_cache_size() == 1   # one chain shape, reused

    def test_failed_composition_degrades_and_pins_entries(self):
        # a chain whose fused call raises is disabled (per-node from
        # then on) but STAYS cached holding its entry refs, so an
        # exec-cache eviction + id reuse can never alias its key
        dq.clear_chain_cache()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("batched"):
            loss = (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
                    ** 2).mean()
            loss.backward(retain_graph=True)
            (key, fused), = dq._CHAIN_CACHE.items()
            fused.disabled = True          # simulate a failed trace
            w1.clear_gradient()
            w2.clear_gradient()
            # degrades: head dispatches per-node, and the REMAINDER of
            # the graph may legitimately fuse as a fresh sub-chain
            loss.backward()
        assert w1.grad is not None
        assert dq._CHAIN_CACHE[key].disabled      # stays disabled
        assert dq._CHAIN_CACHE[key].entries       # refs still pinned
        assert dq.chain_cache_size() == \
            sum(1 for v in dq._CHAIN_CACHE.values() if not v.disabled)

    def test_backward_fused_compile_family_records(self):
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("batched"):
            (pt.matmul(pt.ops.tanh(pt.matmul(x, w1)), w2)
             ** 2).mean().backward()
        comp = obs.snapshot()["paddle_tpu_compile_total"]["series"]
        assert comp[("backward_fused", "compile")] == 1
        fl = obs.snapshot()["paddle_tpu_executable_flops"]["series"]
        assert fl[("backward_fused",)] > 0


# ---------------------------------------------------------------------------
# whole-graph fusion (ISSUE 13): fan-in crossing, graph trace cache,
# degradation ladder
# ---------------------------------------------------------------------------
class TestWholeGraph:
    def _snap(self, name):
        return obs.snapshot()[name]["series"]

    def _graph_cache(self):
        # zero-valued rows are label sets other tests registered
        # before obs.reset() (reset zeroes values but keeps series)
        s = self._snap("paddle_tpu_backward_graph_cache_total")
        return {k[0]: int(v) for k, v in s.items() if v}

    def _fan_in_loss(self, w1, w2, x):
        # y feeds THREE consumers: the PR 10 chain engine fragments
        # here, the whole-graph engine accumulates y's cotangent
        # inside the fused trace
        y = pt.ops.tanh(pt.matmul(x, w1))
        return (y * y + pt.ops.tanh(y) + pt.matmul(y, w2)).mean()

    def test_fan_in_fuses_into_one_dispatch(self):
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            self._fan_in_loss(w1, w2, x).backward()
        batch = self._snap("paddle_tpu_dispatch_batch_size")[()]
        assert batch["count"] == 1          # the WHOLE graph, one call
        assert batch["max"] == batch["sum"] >= 6
        gap = self._snap("paddle_tpu_dispatch_gap_seconds")[()]
        assert gap["count"] == 0
        assert self._graph_cache() == {"miss": 1}

    def test_chain_mode_fragments_the_same_graph(self):
        # the A/B rung: batched (PR 10) stops at the fan-in junction,
        # whole_graph does not — same graph, different dispatch counts
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("batched"):
            self._fan_in_loss(w1, w2, x).backward()
        batch = self._snap("paddle_tpu_dispatch_batch_size")[()]
        assert batch["count"] > 1
        # chain mode records no whole-graph cache outcomes
        assert self._graph_cache() == {}

    def test_steady_state_hits_graph_cache(self):
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            for _ in range(3):
                self._fan_in_loss(w1, w2, x).backward()
                w1.clear_gradient()
                w2.clear_gradient()
        assert self._graph_cache() == {"miss": 1, "hit": 2}
        assert dq.chain_cache_size() == 1   # one whole-graph entry

    def test_root_seeded_interior_and_queue_absorption(self):
        # two roots backward()ed together: the second root is an
        # interior node of the first's graph AND sits ready in the
        # queue when the walk starts — both PR 10 exclusions (root
        # seeds, non-empty queue) must now ride the fused run
        def run():
            w1, w2, x = _params()
            h = pt.ops.tanh(pt.matmul(x, w1))
            loss = (pt.matmul(h, w2) ** 2).mean() + h.sum()
            loss.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        with dq.backward_dispatch_mode("per_node"):
            ref = run()
        obs.enable()
        with dq.backward_dispatch_mode("whole_graph"):
            got = run()
        for a, b in zip(ref, got):
            assert _bit_identical(a, b)
        batch = self._snap("paddle_tpu_dispatch_batch_size")[()]
        assert batch["count"] == 1          # still ONE fused dispatch
        assert self._graph_cache().get("bypass", 0) == 0

    def test_mid_graph_hook_degrades_only_locally(self):
        # a hook on one interior tensor splits the graph into two
        # fused segments around the hooked node — it does NOT collapse
        # the backward to per-node, and the hooked node itself heads
        # the second segment after its hook fires host-side
        dq.clear_chain_cache()
        obs.enable()
        fired = []
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            h = pt.ops.tanh(pt.matmul(x, w1))
            h.register_hook(lambda g: fired.append(1) or g * 2)
            loss = (pt.matmul(h, w2) ** 2).mean()
            loss.backward()
        assert fired == [1]
        batch = self._snap("paddle_tpu_dispatch_batch_size")[()]
        assert batch["count"] == 2          # two segments, no 1-runs
        assert batch["min"] >= 2
        assert batch["sum"] == 5            # every node dispatched
        assert self._graph_cache() == {"bypass": 1}

    def test_cache_invalidation_on_topology_change(self):
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            self._fan_in_loss(w1, w2, x).backward()
            w1.clear_gradient()
            w2.clear_gradient()
            # different topology (extra consumer of y) must MISS
            y = pt.ops.tanh(pt.matmul(x, w1))
            (y * y + pt.ops.tanh(y) + pt.matmul(y, w2)
             + y.sum()).mean().backward()
        gc = self._graph_cache()
        assert gc["miss"] == 2 and "hit" not in gc
        assert dq.chain_cache_size() == 2

    def test_cache_invalidation_on_exec_entry_change(self):
        # a re-created exec-cache entry has a NEW uid: the whole-graph
        # key must miss instead of silently reusing a trace derived
        # from the dead entry
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            self._fan_in_loss(w1, w2, x).backward()
            w1.clear_gradient()
            w2.clear_gradient()
            pt.ops.tanh.op_def.exec_cache.clear()   # entries rebuild
            self._fan_in_loss(w1, w2, x).backward()
        gc = self._graph_cache()
        assert gc["miss"] == 2 and "hit" not in gc

    def test_clear_chain_cache_clears_graph_cache(self):
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            self._fan_in_loss(w1, w2, x).backward()
            w1.clear_gradient()
            w2.clear_gradient()
            dq.clear_chain_cache()          # ONE cache for both tiers
            assert dq.chain_cache_size() == 0
            self._fan_in_loss(w1, w2, x).backward()
        assert self._graph_cache() == {"miss": 2}

    def test_disabled_segment_memoizes_head(self):
        # an untraceable whole-graph composition must not cost a
        # re-plan (O(remaining) host work) on every later backward:
        # the head's entry uid is memoized, the head dispatches
        # per-node outright, and the REMAINDER still fuses
        dq.clear_chain_cache()
        obs.enable()
        w1, w2, x = _params()
        with dq.backward_dispatch_mode("whole_graph"):
            loss = self._fan_in_loss(w1, w2, x)
            loss.backward(retain_graph=True)        # miss, whole graph
            (_key, fused), = dq._CHAIN_CACHE.items()
            fused.disabled = True                   # simulate bad trace
            w1.clear_gradient()
            w2.clear_gradient()
            loss.backward(retain_graph=True)        # disabled hit
            assert dq._DISABLED_HEAD_UIDS           # head memoized
            w1.clear_gradient()
            w2.clear_gradient()
            loss.backward()                         # memo: no re-plan
        gc = self._graph_cache()
        # first backward covered the whole graph; the two degraded
        # ones fragmented (head per-node + fused remainder)
        assert gc == {"miss": 1, "bypass": 2}
        batch = self._snap("paddle_tpu_dispatch_batch_size")[()]
        assert batch["min"] == 1                    # the degraded head
        # N + 2*(1 + (N-1)) = 3N nodes dispatched over the 3 backwards
        assert batch["sum"] == 3 * batch["max"]
        assert w1.grad is not None
        dq.clear_chain_cache()
        assert not dq._DISABLED_HEAD_UIDS           # cleared with cache

    def test_retain_graph_whole_graph_bit_identical(self):
        def run():
            w1, w2, x = _params()
            loss = self._fan_in_loss(w1, w2, x)
            loss.backward(retain_graph=True)
            loss.backward()
            return [w1.grad.numpy(), w2.grad.numpy()]
        with dq.backward_dispatch_mode("per_node"):
            ref = run()
        with dq.backward_dispatch_mode("whole_graph"):
            got = run()
        for a, b in zip(ref, got):
            assert _bit_identical(a, b)

    def test_create_graph_second_order_fan_in(self):
        def run():
            w1, w2, x = _params()
            loss = self._fan_in_loss(w1, w2, x)
            (g,) = pt.autograd.grad(loss, [w1], create_graph=True)
            (gg,) = pt.autograd.grad(g.sum(), [w1])
            return [gg.numpy()]
        with dq.backward_dispatch_mode("per_node"):
            ref = run()
        with dq.backward_dispatch_mode("whole_graph"):
            got = run()
        assert _bit_identical(ref[0], got[0])

    def test_dead_output_slot_fan_in(self):
        def run():
            w1, _, x = _params()
            h = pt.matmul(x, w1)
            a, b = pt.split(h, 2, axis=1)   # b's cotangent slot dead
            loss = (a ** 2).mean() + (a * a).sum()
            loss.backward()
            return [w1.grad.numpy()]
        with dq.backward_dispatch_mode("per_node"):
            ref = run()
        with dq.backward_dispatch_mode("whole_graph"):
            got = run()
        assert _bit_identical(ref[0], got[0])


# ---------------------------------------------------------------------------
# backward compile-family budget (ISSUE 13 satellite): steady-state
# eager training is O(1) executables and O(1) dispatches per step
# ---------------------------------------------------------------------------
class TestBackwardFamilyBudget:
    BUDGET = 2      # ONE whole-graph executable expected for a fixed
                    # MLP train loop; 2 leaves headroom for a seed-
                    # layout variant, never a per-step zoo

    def test_mlp_train_loop_is_one_fused_dispatch_per_step(self):
        dq.clear_chain_cache()
        rng = np.random.default_rng(11)
        layers = [pt.nn.Linear(16, 16) for _ in range(3)]
        for lyr in layers:
            for p in lyr.parameters():
                p.set_value(pt.to_tensor(
                    rng.standard_normal(p.shape).astype(np.float32)))
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-3, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))

        def step():
            h = x
            for lyr in layers[:-1]:
                h = pt.ops.tanh(lyr(h))
            loss = (layers[-1](h) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()

        with dq.backward_dispatch_mode("whole_graph"):
            for _ in range(2):              # warmup: trace + compile
                step()
            obs.enable()
            for _ in range(3):              # steady state, observed
                step()
        snap = obs.snapshot()
        gc = {k[0]: int(v) for k, v in snap[
            "paddle_tpu_backward_graph_cache_total"]["series"].items()
            if v}
        assert gc == {"hit": 3}             # every step: cached whole graph
        batch = snap["paddle_tpu_dispatch_batch_size"]["series"][()]
        assert batch["count"] == 3          # EXACTLY 1 fused call/step
        assert batch["min"] == batch["max"] >= 6
        comp = snap["paddle_tpu_compile_total"]["series"]
        fused_compiles = sum(v for (fam, _out), v in comp.items()
                             if fam == "backward_fused" and v)
        # steady state compiled NOTHING new (warmup predates obs)
        assert fused_compiles == 0
        # the process-global cache holds the one whole-graph entry
        # this loop uses (other tests' entries were cleared above)
        assert dq.chain_cache_size() <= self.BUDGET


# ---------------------------------------------------------------------------
# mode controls
# ---------------------------------------------------------------------------
class TestModeControls:
    def test_default_is_whole_graph(self):
        assert dq.dispatch_mode() == "whole_graph"
        assert dq._VALID_MODES == ("whole_graph", "batched", "per_node")

    def test_set_and_restore(self):
        old = dq.set_dispatch_mode("per_node")
        assert old == "whole_graph"
        assert dq.dispatch_mode() == "per_node"
        dq.set_dispatch_mode(old)

    def test_invalid_mode_raises(self):
        with pytest.raises(ValueError):
            dq.set_dispatch_mode("warp_speed")

    def test_context_manager_restores_on_error(self):
        with pytest.raises(RuntimeError):
            with dq.backward_dispatch_mode("per_node"):
                assert dq.dispatch_mode() == "per_node"
                raise RuntimeError("boom")
        assert dq.dispatch_mode() == "whole_graph"


# ---------------------------------------------------------------------------
# const caches
# ---------------------------------------------------------------------------
class TestConstCaches:
    def test_zero_cotangent_cached_per_aval(self):
        import jax
        dq.clear_const_caches()
        aval = jax.ShapeDtypeStruct((3, 4), np.dtype("float32"))
        z1 = dq.zero_cotangent_array(aval)
        z2 = dq.zero_cotangent_array(aval)
        assert z1 is z2
        assert np.asarray(z1).sum() == 0.0

    def test_float0_zeros_for_integer_avals(self):
        import jax
        dq.clear_const_caches()
        aval = jax.ShapeDtypeStruct((2,), np.dtype("int32"))
        z = dq.zero_cotangent_array(aval)
        assert isinstance(z, np.ndarray)
        assert z.dtype == jax.dtypes.float0
        assert dq.is_float0(z)

    def test_ones_seed_cached(self):
        dq.clear_const_caches()
        s1 = dq.ones_seed_array((), np.dtype("float32"))
        s2 = dq.ones_seed_array((), np.dtype("float32"))
        assert s1 is s2
        assert float(np.asarray(s1)) == 1.0

    def test_is_float0_cheap_path(self):
        import jax.numpy as jnp
        assert not dq.is_float0(jnp.zeros((2,)))
        assert not dq.is_float0(np.zeros((2,), np.float32))


# ---------------------------------------------------------------------------
# bandwidth-window-validated autotune (ISSUE 10 flash prong)
# ---------------------------------------------------------------------------
class TestAutotuneWindow:
    @pytest.fixture(autouse=True)
    def _isolated_cache(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CACHE_DIR", str(tmp_path))
        autotune.clear()
        autotune.drain_sweeps()
        yield
        autotune.clear()
        autotune.drain_sweeps()

    def test_sweep_in_window_persists_winner(self, monkeypatch):
        monkeypatch.setattr(autotune, "measure_effective_bw",
                            lambda **kw: 250e9)
        times = {(1,): 0.5, (2,): 0.1}
        win = autotune.tune(("t", "case_a"), [(1,), (2,)],
                            lambda c: times[c],
                            bw_window=(233e9, 314e9))
        assert win == (2,)
        # persisted: a fresh lookup hits without re-measuring
        assert autotune.lookup(("t", "case_a")) == (2,)
        (sweep,) = autotune.drain_sweeps()
        assert sweep["window_validated"] and sweep["persisted"]
        assert sweep["winner"] == [2]
        assert sweep["candidates"]["(2,)"] == pytest.approx(0.1)

    def test_degraded_window_discards_sweep(self, monkeypatch):
        monkeypatch.setattr(autotune, "measure_effective_bw",
                            lambda **kw: 50e9)     # far below window
        times = {(1,): 0.5, (2,): 0.1}
        win = autotune.tune(("t", "case_b"), [(1,), (2,)],
                            lambda c: times[c],
                            bw_window=(233e9, 314e9))
        assert win == (1,)                  # defaults, not the winner
        assert autotune.lookup(("t", "case_b")) is None   # NOT frozen
        (sweep,) = autotune.drain_sweeps()
        assert sweep["window_validated"] is False
        assert not sweep["persisted"]

    def test_post_sweep_probe_outside_window_discards(self, monkeypatch):
        probes = iter([250e9])             # pre ok, post degraded

        def probe(**kw):
            return next(probes, 50e9)
        monkeypatch.setattr(autotune, "measure_effective_bw", probe)
        win = autotune.tune(("t", "case_c"), [(1,), (2,)],
                            lambda c: {(1,): 0.5, (2,): 0.1}[c],
                            bw_window=(233e9, 314e9))
        assert win == (1,)
        assert autotune.lookup(("t", "case_c")) is None

    def test_no_window_keeps_legacy_behavior(self):
        win = autotune.tune(("t", "case_d"), [(1,), (2,)],
                            lambda c: {(1,): 0.5, (2,): 0.1}[c])
        assert win == (2,)
        assert autotune.lookup(("t", "case_d")) == (2,)
        (sweep,) = autotune.drain_sweeps()
        assert sweep["bw_window"] is None
        assert sweep["window_validated"] is None

    def test_kill_switch_bypasses(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS_AUTOTUNE", "0")
        assert not autotune.enabled()
        # the flash use site returns hand-tuned defaults untouched
        # (import_module: the package __init__ shadows the submodule
        # name with the function it re-exports)
        from importlib import import_module
        fa = import_module("paddle_tpu.kernels.pallas.flash_attention")
        import jax.numpy as jnp
        q = jnp.zeros((1, 256, 256), jnp.float32)
        out = fa._autotuned_blocks(
            "fwd", q, q, 2, 2, True, False, (256, 1024),
            run_shape=None, normalize=lambda bq, bk: (bq, bk))
        assert out == (256, 1024)

    def test_dedup_candidates_shared_helper(self):
        norm = lambda bq, bk: (min(bq, 128), min(bk, 128))
        # all collapse to (128, 128): one effective candidate
        assert autotune.dedup_candidates(
            [(256, 512), (128, 1024), (512, 512)], norm) == [(128, 128)]
        kept = autotune.dedup_candidates(
            [(256, 512), (128, 1024), (512, 512)], norm,
            keep_original=True)
        assert kept == [(256, 512)]

    def test_measure_effective_bw_returns_rate(self):
        bw = autotune.measure_effective_bw(nbytes=1 << 20, iters=2)
        assert bw is None or bw > 0
