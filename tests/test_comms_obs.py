"""Collective & mesh observability (paddle_tpu/observability/comms.py
+ the instrumented distributed/communication.py): per-collective
latency/bytes/bandwidth telemetry with completion-edge honesty, the
async Work.wait() timing fix, goodput accounting, the comms perf-ledger
families, the aggregator's cross-rank straggler attribution + the
`collective_skew` flight trigger — and the real spawn boundary: 8 rank
processes running an all_reduce loop with one rank delayed via the
resilience fault harness, attributed by the aggregator.

Module-level imports stay light: spawned children re-import this
module (spawn start method), and heavyweight imports belong inside
the functions that run after the JAX_PLATFORMS=cpu env guard."""
import json
import multiprocessing
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _comms_clean():
    """Every test starts disabled with empty stores, no injected
    faults, no armed flight recorder, and no peak overrides."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import flight, perf
    from paddle_tpu.resilience import faults
    obs.disable()
    obs.reset()
    faults.clear_all()
    yield
    from paddle_tpu.observability import fleet
    if fleet._AGGREGATOR is not None:
        fleet._AGGREGATOR.close()
    flight.disarm()
    faults.clear_all()
    perf.set_device_peaks()
    perf.set_interconnect_peaks()
    obs.disable()
    obs.reset()


def _series(name):
    from paddle_tpu import observability as obs
    rec = obs.snapshot().get(name)
    return rec["series"] if rec else {}


def _nonzero(name):
    out = {}
    for key, val in _series(name).items():
        if isinstance(val, dict):
            if val["count"]:
                out[key] = val
        elif val:
            out[key] = val
    return out


# ---------------------------------------------------------------------------
# eager collectives: every public op records (latency + bytes +
# launches + arrival), with completion-edge timing
# ---------------------------------------------------------------------------
class TestCollectiveTelemetry:
    def _world(self):
        import paddle_tpu.distributed as dist
        return dist.new_group()

    def test_every_eager_collective_records(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        obs.enable()
        g = self._world()
        n = g.nranks
        x = np.ones((n, 8 * n), np.float32)

        dist.all_reduce(pt.to_tensor(x))
        dist.reduce(pt.to_tensor(x), dst=0)
        dist.broadcast(pt.to_tensor(x), src=0)
        dist.all_gather(pt.to_tensor(x))
        gathered = []
        dist.all_gather(gathered, pt.to_tensor(x))
        dist.reduce_scatter(pt.to_tensor(x))
        dist.all_to_all(pt.to_tensor(x))
        outs = []
        dist.all_to_all(outs, [pt.to_tensor(x[i]) for i in range(n)])
        dist.scatter(pt.to_tensor(x), src=0)
        dist.barrier()
        dist.send(pt.to_tensor(x[0]), dst=g.ranks[-1])
        dist.recv(pt.to_tensor(np.zeros_like(x[0])), src=g.ranks[0])

        hist = _nonzero("paddle_tpu_collective_seconds")
        ops = {op for (op, grp) in hist}
        assert {"all_reduce", "reduce", "broadcast", "all_gather",
                "reduce_scatter", "all_to_all", "scatter", "barrier",
                "send", "recv"} <= ops
        assert all(grp == "world" for (_, grp) in hist)
        # all_gather ran twice (both call styles)
        assert hist[("all_gather", "world")]["count"] == 2
        launches = _nonzero("paddle_tpu_collective_launches_total")
        assert all(mode == "eager" for (_, mode) in launches)
        by = _nonzero("paddle_tpu_collective_bytes_total")
        assert by[("all_reduce",)] == x.nbytes / n   # per-rank payload
        assert by[("barrier",)] if ("barrier",) in by else True
        bw = _nonzero("paddle_tpu_collective_algbw_bytes_per_sec")
        assert bw[("all_reduce",)] > 0
        # spans + arrivals in the ring
        names = {e["name"] for e in obs.trace_events()}
        assert "comms.all_reduce" in names and "comms.arrival" in names
        arr = [e for e in obs.trace_events()
               if e["name"] == "comms.arrival"
               and e["args"]["op"] == "all_reduce"]
        assert arr[0]["args"]["group"] == "world"
        assert arr[0]["args"]["seq"] == 1

    def test_call_seq_increments_and_survives_reset(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        obs.enable()
        g = self._world()
        x = np.ones((g.nranks, 4), np.float32)
        dist.all_reduce(pt.to_tensor(x))
        dist.all_reduce(pt.to_tensor(x))
        seqs = [e["args"]["seq"] for e in obs.trace_events()
                if e["name"] == "comms.arrival"]
        first_pair = seqs[-2:]
        assert first_pair[1] == first_pair[0] + 1
        obs.reset()       # window reset must NOT reset the seq counter
        dist.all_reduce(pt.to_tensor(x))
        seqs2 = [e["args"]["seq"] for e in obs.trace_events()
                 if e["name"] == "comms.arrival"]
        assert seqs2 == [first_pair[1] + 1]

    def test_in_trace_collectives_count_only(self, monkeypatch):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed import communication as comm
        obs.enable()
        comm.init_default_group()
        monkeypatch.setattr(comm, "_in_trace", lambda g: True)
        monkeypatch.setattr(comm.jax.lax, "psum",
                            lambda x, axis: x)
        comm.all_reduce(pt.to_tensor(np.ones((4,), np.float32)))
        launches = _nonzero("paddle_tpu_collective_launches_total")
        assert launches == {("all_reduce", "in_trace"): 1.0}
        # count-only: no latency sample, no arrival event, no span
        assert _nonzero("paddle_tpu_collective_seconds") == {}
        assert obs.trace_events() == []
        by = _nonzero("paddle_tpu_collective_bytes_total")
        assert by[("all_reduce",)] == 16.0   # the local view's bytes

    def test_ppermute_counts_in_trace(self, monkeypatch):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.distributed import communication as comm
        from paddle_tpu import observability as obs
        obs.enable()
        g = comm.init_default_group()
        monkeypatch.setattr(comm.jax.lax, "ppermute",
                            lambda x, axis, perm: x)
        comm.ppermute(pt.to_tensor(np.ones((2, 2), np.float32)), g,
                      [(0, 1)])
        launches = _nonzero("paddle_tpu_collective_launches_total")
        assert launches == {("ppermute", "in_trace"): 1.0}

    def test_async_wait_closes_timing_and_is_idempotent(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        obs.enable()
        g = self._world()
        x = np.ones((g.nranks, 16), np.float32)
        w = dist.all_reduce(pt.to_tensor(x), sync_op=False)
        # launch counted immediately; NO lating sample until wait()
        assert _nonzero("paddle_tpu_collective_launches_total")[
            ("all_reduce", "eager")] == 1.0
        assert _nonzero("paddle_tpu_collective_seconds") == {}
        time.sleep(0.02)
        assert w.wait() is True
        hist = _nonzero("paddle_tpu_collective_seconds")
        assert hist[("all_reduce", "world")]["count"] == 1
        # the span closed at wait(): duration covers launch->wait
        assert hist[("all_reduce", "world")]["min"] >= 0.02
        w.wait()          # double-wait: no second sample
        assert _nonzero("paddle_tpu_collective_seconds")[
            ("all_reduce", "world")]["count"] == 1

    def test_unwaited_async_counts_but_no_latency(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        obs.enable()
        g = self._world()
        x = np.ones((g.nranks, 4), np.float32)
        dist.all_reduce(pt.to_tensor(x), sync_op=False)   # dropped
        assert _nonzero("paddle_tpu_collective_launches_total")[
            ("all_reduce", "eager")] == 1.0
        assert _nonzero("paddle_tpu_collective_seconds") == {}

    def test_link_utilization_honest_about_unknown_device(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import perf
        obs.enable()
        g = self._world()
        x = np.ones((g.nranks, 64), np.float32)
        dist.all_reduce(pt.to_tensor(x))
        # CPU box: no interconnect peak -> NO utilization series
        assert _nonzero("paddle_tpu_collective_link_utilization") == {}
        perf.set_interconnect_peaks(ici=1e9, dcn=1e8)
        dist.all_reduce(pt.to_tensor(x))
        util = _nonzero("paddle_tpu_collective_link_utilization")
        assert ("all_reduce", "ici") in util
        assert ("all_reduce", "dcn") in util
        bw = _series("paddle_tpu_collective_algbw_bytes_per_sec")[
            ("all_reduce",)]
        assert util[("all_reduce", "ici")] == pytest.approx(bw / 1e9)

    def test_fault_point_delays_arrival_and_span(self):
        """The comms.collective fault point fires before the arrival
        timestamp and inside the span window: a delayed rank's arrival
        is late AND its comms span covers the delay (the pair the
        straggler attribution + flight bundle acceptance rely on)."""
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        from paddle_tpu.resilience import faults
        obs.enable()
        g = self._world()
        x = np.ones((g.nranks, 4), np.float32)
        t0 = time.perf_counter_ns() / 1000.0
        with faults.inject("comms.collective", delay=0.15,
                           match={"op": "all_reduce"}):
            dist.all_reduce(pt.to_tensor(x))
        arr = [e for e in obs.trace_events()
               if e["name"] == "comms.arrival"][-1]
        span = [e for e in obs.trace_events()
                if e["name"] == "comms.all_reduce"][-1]
        assert arr["ts"] - t0 >= 0.15e6          # arrival is late
        assert span["dur"] >= 0.15e6             # span covers the delay

    def test_disabled_mode_zero_alloc_instrumentation_layer(self):
        """Tracemalloc guard over the comms instrumentation entry
        points with observability off: start() returns None after one
        flag check, count/note_reshard/finish/Work.wait are no-ops —
        an absolute near-zero bound, so a per-op retained leak in the
        instrumentation layer cannot hide in a two-window delta. (The
        full collective bodies allocate through jax regardless of
        observability — measured identical on the uninstrumented
        revision — so the layer is guarded directly and the full paths
        by the records-nothing test below.)"""
        import tracemalloc
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import comms
        from paddle_tpu.distributed.communication import Work
        assert not obs.enabled()
        w = Work(None, None)

        def window(iters):
            tracemalloc.start()
            base = tracemalloc.get_traced_memory()[0]
            for _ in range(iters):
                rec = comms.start("all_reduce", "world", 64)
                comms.finish(rec)
                comms.count("all_reduce", "world", 64)
                comms.note_reshard("all_gather", "mp", 64)
                comms.note_train_step(0.1, None)
                w.wait()
            grown = tracemalloc.get_traced_memory()[0] - base
            tracemalloc.stop()
            return grown

        window(4000)        # warm call-site + interpreter residuals
        g1 = window(4000)
        g2 = window(4000)
        assert g2 < 1024, (g1, g2)
        assert abs(g2 - g1) < 1024, (g1, g2)

    def test_disabled_mode_records_nothing_across_every_collective(self):
        """Every instrumented collective path with observability off:
        no series, no trace events, no arrival marks, no window
        accumulation — the paths run, the instrumentation stays
        silent."""
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import comms, tracing
        assert not obs.enabled()
        g = self._world()
        n = g.nranks
        x = np.ones((n, 8 * n), np.float32)
        for _ in range(3):
            dist.all_reduce(pt.to_tensor(x))
            dist.reduce(pt.to_tensor(x), dst=0)
            dist.broadcast(pt.to_tensor(x), src=0)
            dist.all_gather(pt.to_tensor(x))
            dist.reduce_scatter(pt.to_tensor(x))
            dist.all_to_all(pt.to_tensor(x))
            dist.scatter(pt.to_tensor(x), src=0)
            dist.barrier()
            dist.send(pt.to_tensor(x[0]), dst=g.ranks[-1])
            dist.recv(pt.to_tensor(np.zeros_like(x[0])),
                      src=g.ranks[0])
            dist.all_reduce(pt.to_tensor(x), sync_op=False).wait()
        assert tracing.events() == []
        assert _nonzero("paddle_tpu_collective_seconds") == {}
        assert _nonzero("paddle_tpu_collective_launches_total") == {}
        assert _nonzero("paddle_tpu_collective_bytes_total") == {}
        assert comms.family_records() == {}


# ---------------------------------------------------------------------------
# reshard sites (meta_parallel boundaries): count + bytes + marker
# ---------------------------------------------------------------------------
class TestReshardSites:
    def test_sequence_parallel_notes_reshards(self):
        import numpy as np
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed import fleet as _fl
        from paddle_tpu.distributed.meta_parallel import (
            sequence_parallel as sp)
        from paddle_tpu.distributed.topology import (
            get_hybrid_communicate_group)
        hcg = get_hybrid_communicate_group()
        if hcg is None:
            import paddle_tpu.distributed as dist
            strategy = dist.fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 8,
                                       "pp_degree": 1}
            dist.fleet.init(is_collective=True, strategy=strategy)
            hcg = get_hybrid_communicate_group()
        if "mp" not in getattr(hcg.mesh, "shape", {}):
            pytest.skip("ambient hybrid mesh (from an earlier test "
                        "file) lacks an mp axis")
        obs.enable()
        x = np.ones((2, 8, 4), np.float32)
        sp.scatter(x)
        sp.all_gather(x)
        sp.reduce_scatter(x)
        launches = _nonzero("paddle_tpu_collective_launches_total")
        assert launches[("scatter", "reshard")] == 1.0
        assert launches[("all_gather", "reshard")] == 1.0
        assert launches[("reduce_scatter", "reshard")] == 1.0
        # marker events, no latency histograms
        markers = [e for e in obs.trace_events()
                   if e["name"] == "comms.reshard"]
        assert {m["args"]["op"] for m in markers} == {
            "scatter", "all_gather", "reduce_scatter"}
        assert all(m["dur"] == 0.0 for m in markers)
        assert _nonzero("paddle_tpu_collective_seconds") == {}


# ---------------------------------------------------------------------------
# goodput accounting
# ---------------------------------------------------------------------------
class TestGoodput:
    def test_fractions_with_pinned_peaks(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import comms, perf
        obs.enable()
        perf.set_device_peaks(1e12, 1e11)
        # simulate: 40ms of comms inside a 100ms step whose cost model
        # implies 30ms of device time
        comms._STEP_COMMS[0] = 0.04
        cost = perf.CostModel(flops=3e10, bytes_accessed=1e9)
        comms.note_train_step(0.1, cost)
        good = _nonzero("paddle_tpu_train_goodput_fraction")
        assert good[("comms",)] == pytest.approx(0.4)
        assert good[("compute",)] == pytest.approx(0.3)
        assert good[("stall",)] == pytest.approx(0.3)
        # the accumulator was consumed
        assert comms._STEP_COMMS[0] == 0.0

    def test_unknown_device_publishes_comms_fraction_only(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import comms, perf
        obs.enable()
        assert perf.device_peaks() is None       # CPU box
        comms._STEP_COMMS[0] = 0.01
        comms.note_train_step(0.1, perf.CostModel(flops=1e9,
                                                  bytes_accessed=1e6))
        good = _nonzero("paddle_tpu_train_goodput_fraction")
        assert ("comms",) in good
        assert ("compute",) not in good and ("stall",) not in good

    def test_trainstep_emits_goodput(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.observability import perf
        obs.enable()
        perf.set_device_peaks(1e12, 1e11)
        lin = pt.nn.Linear(8, 8)
        step = TrainStep(lin, pt.optimizer.SGD(
            learning_rate=1e-3, parameters=lin.parameters()),
            lambda m, a: (m(a) ** 2).mean())
        xa = np.ones((4, 8), np.float32)
        for _ in range(5):
            step(xa)
        good = _series("paddle_tpu_train_goodput_fraction")
        assert ("comms",) in good                # sampled every step
        # compute/stall need the cost model; present when AOT worked
        if step._step_fn.expected is not None:
            assert ("compute",) in good and ("stall",) in good


# ---------------------------------------------------------------------------
# perf-ledger comms families
# ---------------------------------------------------------------------------
class TestCommsLedger:
    def test_family_records_shape(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import comms, perf
        obs.enable()
        perf.set_interconnect_peaks(ici=1e9)
        g = dist.new_group()
        x = np.ones((g.nranks, 256), np.float32)
        for _ in range(3):
            dist.all_reduce(pt.to_tensor(x))
        recs = comms.family_records()
        rec = recs["comms_all_reduce"]
        assert rec["runs"] == 3
        assert rec["achieved_bytes_per_s"] > 0
        assert rec["utilization_ici"] == pytest.approx(
            rec["achieved_bytes_per_s"] / 1e9, rel=0.05)
        obs.reset()                              # window clears
        assert comms.family_records() == {}

    def test_perf_ledger_check_baselines_per_op(self, tmp_path):
        from tools import perf_ledger

        def rec(rev, bps):
            return {"rev": rev, "config": "comms", "ts": 1.0,
                    "device": "cpu", "families": {
                        "comms_all_reduce": {
                            "runs": 5, "compiles": 0, "seconds": 1.0,
                            "expected": None,
                            "achieved_flops_per_s": None,
                            "achieved_bytes_per_s": bps,
                            "utilization_hbm": None,
                            "utilization_flops": None,
                            "utilization_ici": None}}}

        path = tmp_path / "ledger.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(rec("rev_a", 100e6)) + "\n")
            f.write(json.dumps(rec("rev_b", 10e6)) + "\n")
        records, bad = perf_ledger.load(str(path))
        assert bad == 0
        verdict = perf_ledger.check(records, tol=0.2)
        assert not verdict["pass"]
        fam = verdict["configs"]["comms"]["families"][
            "comms_all_reduce"]
        assert fam["regressed"] and fam["baseline_rev"] == "rev_a"
        # recovery passes
        with open(path, "a") as f:
            f.write(json.dumps(rec("rev_c", 120e6)) + "\n")
        records, _ = perf_ledger.load(str(path))
        assert perf_ledger.check(records, tol=0.2)["pass"]


# ---------------------------------------------------------------------------
# aggregator-side straggler attribution (in-process bundles)
# ---------------------------------------------------------------------------
def _arrival_ev(op, group, seq, ts_us):
    return {"name": "comms.arrival", "ph": "X", "pid": 1, "tid": 1,
            "ts": ts_us, "dur": 0.0,
            "args": {"op": op, "group": group, "seq": seq}}


def _bundle(proc, bseq, events):
    from paddle_tpu.observability import fleet
    return fleet.make_bundle(proc, "rank", bseq, trace=list(events))


class TestStragglerAttribution:
    def test_skew_and_straggler_published(self):
        from paddle_tpu.observability.fleet import FleetAggregator
        agg = FleetAggregator(straggler_threshold_s=0.5)
        agg.ingest(_bundle("r0", 1, [_arrival_ev("all_reduce", "world",
                                                 1, 1_000_000.0)]))
        agg.ingest(_bundle("r1", 1, [_arrival_ev("all_reduce", "world",
                                                 1, 1_050_000.0)]))
        snap = agg.registry.snapshot()
        assert snap["paddle_tpu_collective_skew_seconds"]["series"][
            ("all_reduce",)] == pytest.approx(0.05)
        # under threshold: nobody named
        st = snap.get("paddle_tpu_collective_straggler",
                      {"series": {}})["series"]
        assert not any(st.values())
        # the slow rank crosses the threshold late
        agg.ingest(_bundle("r2", 1, [_arrival_ev("all_reduce", "world",
                                                 1, 3_000_000.0)]))
        snap = agg.registry.snapshot()
        assert snap["paddle_tpu_collective_skew_seconds"]["series"][
            ("all_reduce",)] == pytest.approx(2.0)
        st = snap["paddle_tpu_collective_straggler"]["series"]
        flagged = {k for k, v in st.items() if v}
        assert flagged == {("all_reduce", "r2")}

    def test_straggler_clears_when_fleet_heals(self):
        from paddle_tpu.observability.fleet import FleetAggregator
        agg = FleetAggregator(straggler_threshold_s=0.5)
        agg.ingest(_bundle("r0", 1, [
            _arrival_ev("all_reduce", "world", 1, 0.0)]))
        agg.ingest(_bundle("r1", 1, [
            _arrival_ev("all_reduce", "world", 1, 2_000_000.0)]))
        st = agg.registry.snapshot()[
            "paddle_tpu_collective_straggler"]["series"]
        assert st[("all_reduce", "r1")] == 1.0
        # next collective: tight arrivals -> the flag clears
        agg.ingest(_bundle("r0", 2, [
            _arrival_ev("all_reduce", "world", 2, 5_000_000.0)]))
        agg.ingest(_bundle("r1", 2, [
            _arrival_ev("all_reduce", "world", 2, 5_001_000.0)]))
        st = agg.registry.snapshot()[
            "paddle_tpu_collective_straggler"]["series"]
        assert not any(st.values())

    def test_flight_bundle_once_per_key(self, tmp_path):
        from paddle_tpu.observability import flight
        from paddle_tpu.observability.fleet import FleetAggregator
        flight.arm(str(tmp_path / "fl"), collective_skew_s=1.0,
                   min_interval_s=0.0)
        agg = FleetAggregator(straggler_threshold_s=0.5)
        slow_span = {"name": "comms.all_reduce", "ph": "X", "pid": 9,
                     "tid": 1, "ts": 0.0, "dur": 2_000_000.0,
                     "args": {"group": "world", "bytes": 64}}
        agg.ingest(_bundle("r0", 1, [
            _arrival_ev("all_reduce", "world", 7, 0.0)]))
        agg.ingest(_bundle("r1", 1, [
            _arrival_ev("all_reduce", "world", 7, 2_000_000.0),
            slow_span]))
        bundles = flight.bundles()
        assert len(bundles) == 1
        assert "collective_skew" in os.path.basename(bundles[0])
        loaded = flight.load_bundle(bundles[0])
        assert loaded["meta"]["detail"]["straggler"] == "r1"
        assert loaded["meta"]["detail"]["op"] == "all_reduce"
        slow = [e for e in loaded["trace"]
                if e["name"] == "comms.all_reduce"
                and e["dur"] >= 1_000_000.0]
        assert slow, "flight trace must hold the slow collective span"
        # a third rank landing on the SAME key must not re-trigger
        agg.ingest(_bundle("r2", 1, [
            _arrival_ev("all_reduce", "world", 7, 2_500_000.0)]))
        assert len(flight.bundles()) == 1

    def test_arrival_table_bounded(self):
        from paddle_tpu.observability.fleet import FleetAggregator
        agg = FleetAggregator()
        cap = agg.ARRIVAL_KEY_CAP
        evs = [_arrival_ev("all_reduce", "world", i, float(i))
               for i in range(cap + 10)]
        agg.ingest(_bundle("r0", 1, evs))
        assert len(agg._arrivals) == cap


# ---------------------------------------------------------------------------
# obs_top "== comms ==" panel
# ---------------------------------------------------------------------------
class TestObsTopCommsPanel:
    def _obs_top(self):
        tools = os.path.join(REPO, "tools")
        sys.path.insert(0, tools)
        try:
            import obs_top
        finally:
            sys.path.remove(tools)
        return obs_top

    def test_renders_ops_goodput_and_straggler(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.distributed as dist
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import comms, perf
        from paddle_tpu.observability.fleet import FleetAggregator
        obs_top = self._obs_top()
        obs.enable()
        perf.set_device_peaks(1e12, 1e11)
        g = dist.new_group()
        x = np.ones((g.nranks, 64), np.float32)
        prev = json.loads(obs.to_json())
        for _ in range(3):
            dist.all_reduce(pt.to_tensor(x))
        comms._STEP_COMMS[0] = 0.02
        comms.note_train_step(0.1, perf.CostModel(
            flops=3e10, bytes_accessed=1e9))
        doc = json.loads(obs.to_json())
        frame = obs_top.render(doc, prev, dt=1.0)
        assert "== comms ==" in frame
        line = [ln for ln in frame.splitlines()
                if ln.strip().startswith("all_reduce")][0]
        assert "p50=" in line and "MB/s" in line
        assert "goodput" in frame and "compute=" in frame
        # straggler view from an aggregator export
        agg = FleetAggregator(straggler_threshold_s=0.5)
        agg.ingest(_bundle("r0", 1, [
            _arrival_ev("all_reduce", "world", 1, 0.0)]))
        agg.ingest(_bundle("r5", 1, [
            _arrival_ev("all_reduce", "world", 1, 1_500_000.0)]))
        fdoc = json.loads(agg.to_json())
        fframe = obs_top.render(fdoc)
        assert "skew" in fframe and "straggler=r5" in fframe

    def test_no_comms_series_renders_no_panel(self):
        obs_top = self._obs_top()
        assert "== comms ==" not in obs_top.render({})


# ---------------------------------------------------------------------------
# the real spawn boundary: 8 rank processes, one delayed all_reduce,
# attributed by the aggregator — and no false straggler when clean
# ---------------------------------------------------------------------------
def _rank_worker(endpoint, name, barrier, straggle, q):
    """Spawned rank: warms its all_reduce with observability OFF (so
    startup staggering never enters the arrival record), then runs a
    clean lockstep round and a second round where one rank injects a
    comms.collective delay, shipping bundles after each round."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        from paddle_tpu.resilience import faults
        import paddle_tpu.distributed as dist

        g = dist.new_group()
        x = np.ones((g.nranks, 512), np.float32)
        dist.all_reduce(pt.to_tensor(x))        # warm, unrecorded
        fleet.set_identity(process=name, role="rank")
        agent = fleet.FleetAgent(endpoint, interval_s=3600.0,
                                 timeout_s=60.0)
        obs.enable()
        barrier.wait(timeout=600)               # clean round, lockstep
        for _ in range(2):
            dist.all_reduce(pt.to_tensor(x))
        ok1 = agent.ship()
        barrier.wait(timeout=600)               # parent asserts clean
        barrier.wait(timeout=600)               # delayed round starts
        if straggle:
            faults.inject("comms.collective", delay=1.5, times=1,
                          match={"op": "all_reduce"})
        dist.all_reduce(pt.to_tensor(x))
        ok2 = agent.ship()
        q.put((name, bool(ok1 and ok2)))
    except BaseException as e:                  # report, don't hang
        q.put((name, f"ERROR: {e!r}"))
        raise


class TestMultiProcessStraggler:
    def test_eight_rank_all_reduce_delay_attributed(self, tmp_path):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, flight
        obs.enable()
        flight.arm(str(tmp_path / "flight"), collective_skew_s=1.0,
                   min_interval_s=0.0)
        agg = fleet.serve_aggregator(stale_after_s=600.0,
                                     straggler_threshold_s=0.5)
        ctx = multiprocessing.get_context("spawn")
        barrier = ctx.Barrier(9)
        q = ctx.Queue()
        names = [f"rank{i}" for i in range(8)]
        procs = [ctx.Process(target=_rank_worker,
                             args=(agg.endpoint, n, barrier,
                                   n == "rank5", q))
                 for n in names]
        for p in procs:
            p.start()
        try:
            barrier.wait(timeout=600)     # workers warm; clean round
            barrier.wait(timeout=600)     # all clean bundles shipped
            snap = agg.registry.snapshot()
            skews = snap["paddle_tpu_collective_skew_seconds"][
                "series"]
            assert skews[("all_reduce",)] < 0.5, skews
            st = snap.get("paddle_tpu_collective_straggler",
                          {"series": {}})["series"]
            assert not any(st.values()), \
                f"false straggler on the clean run: {st}"
            assert flight.bundles() == []
            barrier.wait(timeout=600)     # release the delayed round
            reports = dict(q.get(timeout=300) for _ in range(8))
            assert all(v is True for v in reports.values()), reports
        finally:
            for p in procs:
                p.join(120)
                if p.is_alive():
                    p.kill()
        # the delayed rank is named, exactly once, with the evidence
        snap = agg.registry.snapshot()
        assert snap["paddle_tpu_collective_skew_seconds"]["series"][
            ("all_reduce",)] >= 1.0
        st = snap["paddle_tpu_collective_straggler"]["series"]
        flagged = {k for k, v in st.items() if v}
        assert flagged == {("all_reduce", "rank5")}
        bundles = flight.bundles()
        skew_bundles = [b for b in bundles
                        if "collective_skew" in os.path.basename(b)]
        assert len(skew_bundles) == 1, bundles
        loaded = flight.load_bundle(skew_bundles[0])
        assert loaded["meta"]["detail"]["straggler"] == "rank5"
        slow = [e for e in loaded["trace"]
                if e["name"] == "comms.all_reduce"
                and e["dur"] >= 1_000_000.0]
        assert slow, \
            "the flight trace must hold the slow comms.all_reduce span"
