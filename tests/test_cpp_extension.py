"""Custom C++ host-op loading (paddle.utils.cpp_extension parity).

Compiles a real C++ source with g++ and drives it eagerly and under jit
(ref test style: test/custom_op/test_custom_relu_op_jit.py).
"""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.utils import cpp_extension

SRC = textwrap.dedent("""
    extern "C" void square_add_f32(
        const void* const* inputs, const long long* sizes, int n_inputs,
        void* output, long long out_elems) {
        const float* x = static_cast<const float*>(inputs[0]);
        const float* y = static_cast<const float*>(inputs[1]);
        float* out = static_cast<float*>(output);
        for (long long i = 0; i < out_elems; ++i) {
            out[i] = x[i] * x[i] + y[i];
        }
    }

    extern "C" void negate_f32(
        const void* const* inputs, const long long* sizes, int n_inputs,
        void* output, long long out_elems) {
        const float* x = static_cast<const float*>(inputs[0]);
        float* out = static_cast<float*>(output);
        for (long long i = 0; i < out_elems; ++i) out[i] = -x[i];
    }
""")


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    src = tmp_path_factory.mktemp("csrc") / "ops.cc"
    src.write_text(SRC)
    return cpp_extension.load("test_ops", [str(src)], verbose=False)


def test_discovers_both_ops(ext):
    assert callable(ext.square_add_f32)
    assert callable(ext.negate_f32)
    with pytest.raises(AttributeError, match="loaded ops"):
        ext.missing_op


def test_eager_matches_numpy(ext):
    rng = np.random.default_rng(0)
    x = rng.standard_normal(64).astype(np.float32)
    y = rng.standard_normal(64).astype(np.float32)
    out = ext.square_add_f32(pt.to_tensor(x), pt.to_tensor(y))
    np.testing.assert_allclose(out.numpy(), x * x + y, rtol=1e-6)
    np.testing.assert_allclose(ext.negate_f32(pt.to_tensor(x)).numpy(),
                               -x, rtol=1e-6)


def test_under_jit(ext):
    import jax
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 8)).astype(np.float32)

    @jax.jit
    def f(a):
        t = ext.square_add_f32(pt.Tensor(a), pt.Tensor(a))
        return t._data

    np.testing.assert_allclose(np.asarray(f(x)), x * x + x, rtol=1e-6)


def test_build_cache_reuses_so(ext, tmp_path):
    src = tmp_path / "ops2.cc"
    src.write_text(SRC)
    m1 = cpp_extension.load("cache_probe", [str(src)])
    lib1 = m1._lib._name
    m2 = cpp_extension.load("cache_probe", [str(src)])
    assert m2._lib._name == lib1          # same hashed artifact

def test_cuda_extension_refused():
    with pytest.raises(RuntimeError, match="Pallas"):
        cpp_extension.CUDAExtension()
    with pytest.raises(RuntimeError, match="Pallas"):
        cpp_extension.load("x", ["a.cc"], extra_cuda_cflags=["-O2"])


class TestVendorPluginRegistry:
    """C5: PJRT plugin registration is the CustomDevice analog."""

    def test_bogus_plugin_fails_cleanly_without_registration(self):
        from paddle_tpu import device
        with pytest.raises(RuntimeError, match="failed to load"):
            device.register_pjrt_plugin(
                "fakevendor", "/nonexistent/libfake_pjrt.so")
        assert "fakevendor" not in device.get_all_custom_device_type()
        assert not device.is_compiled_with_custom_device("fakevendor")

    def test_non_pjrt_library_rejected(self, tmp_path):
        # a real .so that is not a PJRT plugin must also fail cleanly
        src = tmp_path / "notpjrt.cc"
        src.write_text('extern "C" int nothing() { return 0; }')
        import subprocess, sys
        lib = tmp_path / "libnotpjrt.so"
        subprocess.run(["g++", "-shared", "-fPIC", str(src), "-o",
                        str(lib)], check=True)
        from paddle_tpu import device
        with pytest.raises(RuntimeError, match="failed to load"):
            device.register_pjrt_plugin("notpjrt", str(lib))
