"""Text/audio dataset zoo + synthetic-fallback honesty (VERDICT r4
next-9 / missing-5): real local-archive parsing is exercised with
miniature fixture archives in the same formats the reference downloads;
the synthetic fallback must WARN (or raise with allow_synthetic=False),
never silently."""
import os
import tarfile
import wave
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.text import Imdb, Imikolov, UCIHousing
from paddle_tpu.audio.datasets import ESC50, TESS
from paddle_tpu.vision.datasets import MNIST, Cifar10, Flowers


# -- fixture archives ------------------------------------------------------
def _mini_imdb(tmp_path):
    root = tmp_path / "aclImdb"
    texts = {
        ("train", "pos"): ["great movie great fun", "great great cast"],
        ("train", "neg"): ["bad movie bad plot", "bad bad bad acting"],
        ("test", "pos"): ["great fun indeed"],
        ("test", "neg"): ["bad beyond words"],
    }
    for (split, lab), docs in texts.items():
        d = root / split / lab
        d.mkdir(parents=True)
        for i, t in enumerate(docs):
            (d / f"{i}_7.txt").write_text(t)
    out = tmp_path / "aclImdb_v1.tar.gz"
    with tarfile.open(out, "w:gz") as tf:
        tf.add(root, arcname="aclImdb")
    return str(out)


def _mini_imikolov(tmp_path):
    root = tmp_path / "simple-examples" / "data"
    root.mkdir(parents=True)
    train = "the cat sat\nthe dog sat\nthe cat ran\n"
    valid = "the dog ran\n"
    (root / "ptb.train.txt").write_text(train)
    (root / "ptb.valid.txt").write_text(valid)
    out = tmp_path / "simple-examples.tgz"
    with tarfile.open(out, "w:gz") as tf:
        tf.add(root.parent, arcname="simple-examples")
    return str(out)


def _housing(tmp_path):
    rng = np.random.RandomState(0)
    raw = rng.standard_normal((506, 14)).astype(np.float32)
    path = tmp_path / "housing.data"
    np.savetxt(path, raw)
    return str(path)


def _wav(path, seed, sr=22050, n=1103):
    pcm = (np.random.RandomState(seed).standard_normal(n) * 3000).astype(
        np.int16)
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(pcm.tobytes())


# -- text ------------------------------------------------------------------
def test_imdb_parses_local_archive(tmp_path):
    f = _mini_imdb(tmp_path)
    train = Imdb(data_file=f, mode="train", cutoff=2)
    # freq: great x5, bad x6 -> dict {bad, great} + <unk>
    assert set(train.word_idx) == {"bad", "great", "<unk>"}
    assert len(train) == 4
    assert sorted(np.bincount(train.labels).tolist()) == [2, 2]
    doc, label = train[0]
    assert doc.dtype == np.int64
    test = Imdb(data_file=f, mode="test", cutoff=2)
    assert len(test) == 2
    # test split reuses the TRAIN dict; unseen words -> <unk>
    unk = test.word_idx["<unk>"]
    assert any(unk in d for d, _ in [test[i] for i in range(2)])


def test_imikolov_ngram_and_seq(tmp_path):
    f = _mini_imikolov(tmp_path)
    ds = Imikolov(data_file=f, data_type="NGRAM", window_size=3,
                  mode="train", min_word_freq=2)
    # dict: the(3), cat(2), sat(2) + markers
    assert {"the", "cat", "sat"} <= set(ds.word_idx)
    assert "dog" not in ds.word_idx
    for gram in ds:
        assert gram.shape == (3,)
    seq = Imikolov(data_file=f, data_type="SEQ", mode="test",
                   min_word_freq=2)
    x, y = seq[0]
    np.testing.assert_array_equal(x[1:], y[:-1])
    assert x[0] == seq.word_idx["<s>"]
    assert y[-1] == seq.word_idx["<e>"]


def test_uci_housing_split_and_normalization(tmp_path):
    f = _housing(tmp_path)
    train = UCIHousing(data_file=f, mode="train")
    test = UCIHousing(data_file=f, mode="test")
    assert len(train) == 404 and len(test) == 102
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
    allx = np.stack([train[i][0] for i in range(len(train))]
                    + [test[i][0] for i in range(len(test))])
    # min-max-centred: range <= 1, mean ~ 0 per feature
    assert np.all(allx.max(0) - allx.min(0) <= 1.0 + 1e-5)
    np.testing.assert_allclose(allx.mean(0), 0.0, atol=1e-5)


# -- audio -----------------------------------------------------------------
def test_esc50_local_dir(tmp_path):
    d = tmp_path / "audio"
    d.mkdir()
    # {fold}-{clip}-{take}-{target}.wav
    for i, (fold, target) in enumerate(
            [(1, 3), (2, 7), (3, 7), (1, 11)]):
        _wav(d / f"{fold}-{100+i}-A-{target}.wav", seed=i)
    train = ESC50(audio_dir=str(d), mode="train", split=1)
    dev = ESC50(audio_dir=str(d), mode="dev", split=1)
    assert len(train) == 2 and len(dev) == 2
    x, label = train[0]
    assert x.dtype == np.float32 and label == 7
    mfcc = ESC50(audio_dir=str(d), mode="dev", split=1, feat_type="mfcc",
                 n_mfcc=13)
    feat, _ = mfcc[0]
    assert feat.shape[0] == 13


def test_tess_local_dir(tmp_path):
    d = tmp_path / "tess"
    d.mkdir()
    for i, emo in enumerate(["angry", "happy", "sad", "neutral"]):
        _wav(d / f"OAF_word_{emo}.wav", seed=i)
    allfiles = TESS(audio_dir=str(d), mode="train", n_folds=2, split=2)
    assert len(allfiles) >= 1
    x, label = allfiles[0]
    assert 0 <= label < len(TESS.EMOTIONS)


# -- honesty ---------------------------------------------------------------
@pytest.mark.parametrize("ctor", [
    lambda **kw: MNIST(**kw),
    lambda **kw: Cifar10(**kw),
    lambda **kw: Flowers(**kw),
    lambda **kw: Imdb(**kw),
    lambda **kw: Imikolov(**kw),
    lambda **kw: UCIHousing(**kw),
    lambda **kw: ESC50(**kw),
    lambda **kw: TESS(**kw),
])
def test_synthetic_fallback_warns_and_can_raise(ctor):
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        ds = ctor()
    assert len(ds) > 0
    with pytest.raises(FileNotFoundError):
        ctor(allow_synthetic=False)


def test_real_files_do_not_warn(tmp_path):
    f = _housing(tmp_path)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        UCIHousing(data_file=f, mode="train")
