"""Prefill/decode disaggregation (inference/disagg.py): role pools,
KV-page migration, and the handoff rungs.

Oracle: a role-less single LLMEngine (itself oracle-pinned against
models.generation.generate in test_llm_engine). Greedy decoding is
deterministic, so the disaggregated fleet's outputs must be
bit-identical whichever handoff rung served each request — real page
migration, prefix-hash re-admission, or the fallback after a prefill
replica was SIGKILLed mid-migration."""
import os
import signal
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import (DisaggActuator, DisaggRouter,
                                  LLMEngine, calibrate_kv_scales)
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.resilience import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENGINE_KW = dict(max_batch=2, block_size=16, decode_chunk=4,
                 prompt_quantum=16, max_model_len=96)


@pytest.fixture(scope="module")
def tiny_gpt():
    pt.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def tiny_llama():
    pt.seed(0)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_all()
    obs.disable()
    obs.reset()
    yield
    faults.clear_all()
    obs.disable()
    obs.reset()


def _factory(model, **overrides):
    kw = dict(ENGINE_KW, **overrides)

    def make(_i):
        return LLMEngine(model, **kw)
    return make


def _prompts(lengths, seed=0, vocab=128):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (int(n),)).astype(np.int32)
            for n in lengths]


def _oracle(model, prompts, n_new, **overrides):
    eng = _factory(model, **overrides)(0)
    out = {}
    for i, p in enumerate(prompts):
        eng.add_request(i, p, n_new)
    while eng.has_unfinished:
        for r in eng.step():
            assert r.ok, r.error
            out[r.request_id] = tuple(int(t) for t in r.output_ids)
    return out


def _reconciled(engine):
    """Idle-pool invariant: every page is free or parked reusable —
    only the engine's trash page stays leased."""
    c = engine.cache
    return c.available_blocks == c.allocator.num_blocks - 1


# ---------------------------------------------------------------------------
# KV-page serialization round-trip (the migration wire format)
# ---------------------------------------------------------------------------
class TestKVPageRoundTrip:
    def _roundtrip(self, model, **engine_overrides):
        prompts = _prompts((41, 37), seed=3)
        src = _factory(model, **engine_overrides)(0)
        dst = _factory(model, **engine_overrides)(1)
        for i, p in enumerate(prompts):
            src.add_request(i, p, 4)
        while src.has_unfinished:
            src.step()

        p = prompts[0]
        hashes = src.cache.block_hashes(p)
        assert len(hashes) >= 2
        payload = src.export_kv_pages(hashes)
        assert payload["v"] == 1 and payload["start"] == 0
        assert len(payload["pages"]) == len(hashes)
        assert [e["hash"] for e in payload["pages"]] == list(hashes)

        n = dst.import_kv_pages(payload)
        assert n == len(hashes)
        # registered under the same hashes: a full-chain peek hits
        ncached, pages = dst.cache.match_prefix(p, hashes)
        assert len(pages) == len(hashes)
        assert ncached == len(hashes) * dst.block_size
        # page BYTES survive the trip bit-exactly (rope'd keys, int8
        # codes — whatever the pool dtype holds)
        back = dst.export_kv_pages(hashes)
        for a, b in zip(payload["pages"], back["pages"]):
            np.testing.assert_array_equal(a["k"], b["k"])
            np.testing.assert_array_equal(a["v"], b["v"])
        # import is idempotent (re-delivered chunk after a retry)
        assert dst.import_kv_pages(payload) == len(hashes)
        assert _reconciled(src) and _reconciled(dst)

        # the migrated prefix is SERVABLE: admission with the same
        # hash chain leases the imported pages and greedy decode
        # matches the source engine bit-for-bit
        want = _oracle(model, [p], 6, **engine_overrides)[0]
        dst.add_request("re", p, 6, prefix_hashes=hashes)
        got = []
        while dst.has_unfinished:
            for r in dst.step():
                assert r.ok, r.error
                got = tuple(int(t) for t in r.output_ids)
        assert got == want
        assert dst.stats["prefix_cache_hit_tokens"] >= \
            len(hashes) * dst.block_size

    def test_roundtrip_fp_llama_rope_layout(self, tiny_llama):
        """LLaMA pools hold ROPE'D keys — the wire format must ship
        them verbatim (re-rotating would corrupt the chain)."""
        self._roundtrip(tiny_llama)

    def test_roundtrip_int8_pool(self, tiny_gpt):
        scales = calibrate_kv_scales(
            tiny_gpt, _prompts((24,), seed=9)[0][None])
        self._roundtrip(tiny_gpt, kv_quant_scales=scales)

    def test_scale_mismatch_rejected(self, tiny_gpt):
        """int8 pages are raw codes — importing them under different
        quant scales would silently decode garbage, so mismatched
        scale digests must be refused (the fallback rung serves)."""
        p = _prompts((41,), seed=3)[0]
        s1 = calibrate_kv_scales(tiny_gpt, p[None])
        src = _factory(tiny_gpt, kv_quant_scales=s1)(0)
        dst = _factory(tiny_gpt, kv_quant_scales=(
            s1[0] * 2.0, s1[1] * 2.0))(1)
        src.generate([p], max_new_tokens=2)
        hashes = src.cache.block_hashes(p)
        payload = src.export_kv_pages(hashes)
        with pytest.raises(ValueError, match="incompatible"):
            dst.import_kv_pages(payload)


# ---------------------------------------------------------------------------
# Disaggregated serving: both handoff rungs bit-identical
# ---------------------------------------------------------------------------
class TestDisaggBitExact:
    N_NEW = 12

    def _serve(self, router, prompts, n_new=N_NEW):
        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=n_new)
        got = {}
        deadline = time.monotonic() + 300
        while router.has_unfinished:
            assert time.monotonic() < deadline, "drain wedged"
            for r in router.step():
                assert r.ok, (r.request_id, r.finish_reason, r.error)
                got[r.request_id] = tuple(int(t) for t in r.output_ids)
        return got

    def test_migrated_rung_bit_identical(self, tiny_gpt):
        prompts = _prompts((37, 20, 45, 33), seed=0)
        want = _oracle(tiny_gpt, prompts, self.N_NEW)
        router = DisaggRouter(_factory(tiny_gpt),
                              n_prefill=1, n_decode=1)
        got = self._serve(router, prompts)
        assert got == want
        # handoff accounting: one handoff per completed session, the
        # migrated path dominant under the default config (every
        # prompt here spans >= 1 full block)
        s = router.stats
        assert s["handoffs"] == len(prompts)
        assert s["handoff_migrated"] == len(prompts)
        assert s["handoff_fallback"] == 0
        assert s["migrated_bytes"] > 0
        for h in router.replicas:
            assert _reconciled(h.engine)
        # every request ran both stages: prefill pool routed N, decode
        # pool routed N more
        assert s["routed"] == 2 * len(prompts)

    def test_readmission_rung_bit_identical(self, tiny_gpt):
        """migrate=False pins the degraded rung: the decode replica
        re-prefills from the original prompt."""
        prompts = _prompts((37, 20, 45, 33), seed=0)
        want = _oracle(tiny_gpt, prompts, self.N_NEW)
        router = DisaggRouter(_factory(tiny_gpt), migrate=False,
                              n_prefill=1, n_decode=1)
        got = self._serve(router, prompts)
        assert got == want
        s = router.stats
        assert s["handoffs"] == len(prompts)
        assert s["handoff_readmitted"] == len(prompts)
        assert s["migrated_bytes"] == 0

    def test_single_token_requests_skip_handoff(self, tiny_gpt):
        """max_new_tokens=1 IS pure prefill — it serves one-stage on
        the prefill pool, no decode handoff."""
        prompts = _prompts((37, 20), seed=0)
        want = _oracle(tiny_gpt, prompts, 1)
        router = DisaggRouter(_factory(tiny_gpt),
                              n_prefill=1, n_decode=1)
        got = self._serve(router, prompts, n_new=1)
        assert got == want
        assert router.stats["handoffs"] == 0

    def test_decode_pool_lost_degrades(self, tiny_gpt):
        """An empty decode pool must not strand handoffs: candidates
        degrade to the whole live set and the prefill replica serves
        the decode stage itself."""
        router = DisaggRouter(_factory(tiny_gpt),
                              n_prefill=1, n_decode=1)
        prompts = _prompts((37, 33), seed=1)
        want = _oracle(tiny_gpt, prompts, self.N_NEW)
        (decode_h,) = router.pool("decode")
        assert router.retire_replica(decode_h.name) == decode_h.name
        got = self._serve(router, prompts)
        assert got == want
        assert router.stats["handoffs"] == len(prompts)


# ---------------------------------------------------------------------------
# Migration under LRU-eviction pressure on the receiving pool
# ---------------------------------------------------------------------------
class TestMigrationUnderPressure:
    def test_partial_import_into_tiny_pool(self, tiny_gpt):
        """The receiving pool can't hold the chain: import registers a
        valid PREFIX, reports the shortfall, and leaks nothing."""
        p = _prompts((65,), seed=4)[0]      # 4 full blocks
        src = _factory(tiny_gpt)(0)
        # 4 blocks: 1 leased trash page + 3 free — one short of the
        # 4-block chain, so the import MUST stop partial (it never
        # evicts its own just-imported chain to place the tail)
        dst = _factory(tiny_gpt, num_blocks=4)(1)
        src.generate([p], max_new_tokens=2)
        hashes = src.cache.block_hashes(p)
        assert len(hashes) == 4
        n = dst.import_kv_pages(src.export_kv_pages(hashes))
        assert 0 < n < len(hashes)
        # whatever landed is a chain PREFIX — match_prefix walks it
        ncached, pages = dst.cache.match_prefix(p, hashes)
        assert len(pages) == n
        assert _reconciled(dst)

    def test_evicted_before_readmission_falls_back(self, tiny_gpt):
        """Migrated pages evicted (LRU churn) between import and
        re-admission: the engine re-prefills the tail from the
        original prompt — outputs identical, allocator reconciled."""
        p = _prompts((65,), seed=4)[0]
        want = _oracle(tiny_gpt, [p], 6)[0]
        src = _factory(tiny_gpt)(0)
        dst = _factory(tiny_gpt, num_blocks=12)(1)
        src.generate([p], max_new_tokens=2)
        hashes = src.cache.block_hashes(p)
        assert dst.import_kv_pages(src.export_kv_pages(hashes)) \
            == len(hashes)
        # churn the receiving pool until the migrated chain is gone
        churn = _prompts((65, 65, 65), seed=7)
        dst.generate(churn, max_new_tokens=2)
        ncached, _pages = dst.cache.match_prefix(p, hashes)
        assert ncached < len(hashes) * dst.block_size
        # re-admission with the full hash chain still serves exactly:
        # the scheduler leases whatever prefix survived and
        # re-prefills the evicted tail
        dst.add_request("re", p, 6, prefix_hashes=hashes)
        got = None
        while dst.has_unfinished:
            for r in dst.step():
                assert r.ok, r.error
                got = tuple(int(t) for t in r.output_ids)
        assert got == want
        assert _reconciled(dst)


# ---------------------------------------------------------------------------
# Role-aware elastic scaling
# ---------------------------------------------------------------------------
class TestDisaggScaling:
    def test_grow_for_routes_by_breached_series(self, tiny_gpt):
        router = DisaggRouter(_factory(tiny_gpt),
                              n_prefill=1, n_decode=1)
        act = DisaggActuator(router)
        assert act.replicas() == 2
        act.grow_for({"series": "paddle_tpu_request_ttft_seconds",
                      "slo": "ttft_p95"})
        assert len(router.pool("prefill")) == 2
        act.grow_for({"series": "paddle_tpu_request_tpot_seconds",
                      "slo": "tpot_p95"})
        assert len(router.pool("decode")) == 2
        # unknown series balances; pools are even, so either grows
        act.grow_for({"series": "paddle_tpu_request_e2e_seconds"})
        assert act.replicas() == 5

    def test_retire_never_strands_a_role(self, tiny_gpt):
        router = DisaggRouter(_factory(tiny_gpt),
                              n_prefill=2, n_decode=1)
        act = DisaggActuator(router)
        name = act.retire()     # only prefill can spare one
        assert name is not None
        assert len(router.pool("prefill")) == 1
        assert len(router.pool("decode")) == 1
        assert act.retire() is None     # both pools at 1 — refuse

    def test_replica_keeps_role_across_restart(self, tiny_gpt):
        from paddle_tpu.inference import ReplicaGone
        router = DisaggRouter(_factory(tiny_gpt), n_prefill=1,
                              n_decode=1, cooldown_s=0.0)
        (h,) = router.pool("prefill")
        router._fail_replica(h, ReplicaGone("chaos"))
        assert not h.live
        router.step()           # cooldown elapsed -> reintegrate
        assert h.live and h.role == "prefill"
        assert router.pool("prefill") == [h]


# ---------------------------------------------------------------------------
# Chaos: prefill replica SIGKILLed mid-migration (process fleet)
# ---------------------------------------------------------------------------
def _chaos_model():
    """Module-level so the replica spawn context can pickle it by
    reference (the worker re-imports this test module)."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


CHAOS_ENGINE_KW = dict(max_batch=4, block_size=16, decode_chunk=4,
                       prompt_quantum=16, max_model_len=96)


class TestChaosMidMigration:
    def test_sigkill_prefill_mid_migration_falls_back(self, tmp_path):
        """The prefill replica dies between migration chunks: the
        in-handoff request falls back to re-admission on the decode
        pool and every output stays bit-identical to a never-killed
        single engine."""
        from paddle_tpu.inference.replica_proc import (
            process_engine_factory)

        prompts = _prompts((37, 41, 45), seed=6)
        n_new = 8
        want = _oracle(_chaos_model(), prompts, n_new,
                       **CHAOS_ENGINE_KW)

        router = DisaggRouter(
            process_engine_factory(
                _chaos_model, engine_kwargs=CHAOS_ENGINE_KW,
                exec_cache_dir=str(tmp_path),
                name_prefix="disagg-prefill", role="engine_prefill"),
            process_engine_factory(
                _chaos_model, engine_kwargs=CHAOS_ENGINE_KW,
                exec_cache_dir=str(tmp_path),
                name_prefix="disagg-decode", role="engine_decode"),
            n_prefill=1, n_decode=1, migrate_chunk_pages=1,
            cooldown_s=0.05, max_cooldown_s=0.1)
        try:
            (prefill_h,) = router.pool("prefill")
            victim_pid = prefill_h.engine.pid
            killed = []

            def kill_prefill(ctx):
                # fires between export and import of chunk 0: the
                # exported chunk still imports (the decode end is
                # alive), then the NEXT export RPC finds the peer gone
                if not killed:
                    os.kill(victim_pid, signal.SIGKILL)
                    killed.append(ctx)
                return True
            faults.inject("disagg.migrate", delay=0.5, times=1,
                          when=kill_prefill)

            for i, p in enumerate(prompts):
                router.submit(i, p, max_new_tokens=n_new)
            got = {}
            deadline = time.monotonic() + 300
            while router.has_unfinished:
                assert time.monotonic() < deadline, "drain wedged"
                for r in router.step():
                    assert r.ok, (r.request_id, r.finish_reason,
                                  r.error)
                    got[r.request_id] = tuple(
                        int(t) for t in r.output_ids)

            assert killed, "chaos never fired"
            assert got == want
            s = router.stats
            assert s["handoffs"] == len(prompts)
            assert s["handoff_fallback"] >= 1
            assert s["failovers"] >= 1
            # the breaker replaced the dead prefill process
            assert prefill_h.live
            assert prefill_h.engine.pid != victim_pid
        finally:
            faults.clear_all()
            for h in router.replicas.handles:
                eng = h.engine
                if eng is not None:
                    try:
                        eng.shutdown(timeout_s=10)
                    except Exception:
                        pass


# ---------------------------------------------------------------------------
# obs_top "== disagg ==" panel
# ---------------------------------------------------------------------------
class TestObsTopDisaggPanel:
    def _obs_top(self):
        tools = os.path.join(REPO, "tools")
        sys.path.insert(0, tools)
        try:
            import obs_top
        finally:
            sys.path.remove(tools)
        return obs_top

    def test_panel_renders(self, tiny_gpt):
        obs.enable()
        router = DisaggRouter(_factory(tiny_gpt),
                              n_prefill=1, n_decode=1)
        prompts = _prompts((37, 33), seed=0)
        for i, p in enumerate(prompts):
            router.submit(i, p, max_new_tokens=6)
        deadline = time.monotonic() + 300
        while router.has_unfinished:
            assert time.monotonic() < deadline
            router.step()
        import json
        obs_top = self._obs_top()
        out = obs_top.render(json.loads(obs.to_json()))
        assert "== disagg ==" in out
        assert "prefill" in out and "decode" in out
        assert "migrated" in out
