"""Eager dispatch executable cache (VERDICT r2 missing #7; ref
motivation: /root/reference/paddle/phi/README.md §1.2.1 — per-op
dispatch overhead is why PHI exists; SURVEY §7.3 hard-part 1)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops
import paddle_tpu.ops.registry as R


def _clear_all():
    for od in R.OPS.values():
        od.exec_cache.clear()


@pytest.fixture(autouse=True)
def _fresh_cache():
    _clear_all()
    yield
    _clear_all()


def _t(x, sg=False):
    return pt.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


class TestExecCache:
    def test_cache_populates_and_hits(self):
        x = _t(np.random.RandomState(0).randn(4, 4))
        y = ops.tanh(x)
        n1 = R.exec_cache_size()
        assert n1 >= 1
        y2 = ops.tanh(x)  # same signature: cache hit, no new entry
        assert R.exec_cache_size() == n1
        np.testing.assert_array_equal(np.asarray(y.numpy()),
                                      np.asarray(y2.numpy()))
        ops.tanh(_t(np.random.RandomState(1).randn(2, 8)))  # new shape
        assert R.exec_cache_size() > n1

    def test_cached_grads_match_uncached(self):
        rng = np.random.RandomState(1)
        xa = rng.randn(4, 6).astype(np.float32)
        wa = rng.randn(6, 3).astype(np.float32)

        def run():
            x = _t(xa)
            w = _t(wa)
            loss = (ops.tanh(pt.matmul(x, w)) ** 2).mean()
            loss.backward()
            return float(loss.numpy()), x.grad.numpy(), w.grad.numpy()

        l1, gx1, gw1 = run()          # populates + uses cache
        saved = R._cache_key
        R._cache_key = lambda *a, **k: None  # force uncached path
        try:
            l2, gx2, gw2 = run()
        finally:
            R._cache_key = saved
        # jit may reassociate reductions: allow float-noise-level slack
        np.testing.assert_allclose(l1, l2, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(gx1, gx2, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(gw1, gw2, rtol=1e-5, atol=1e-7)

    def test_rng_ops_never_cached(self):
        """A cached executable would bake the PRNG key — dropout must
        produce a DIFFERENT mask every call and stay out of the cache."""
        x = _t(np.ones((64, 64)))
        a = ops.dropout(x, p=0.5, training=True)
        b = ops.dropout(x, p=0.5, training=True)
        assert not np.array_equal(np.asarray(a.numpy()),
                                  np.asarray(b.numpy()))
        # the blacklist sentinel, not an executable, is what got stored
        assert any(v is R._UNCACHEABLE
                   for od in R.OPS.values()
                   for v in od.exec_cache.values())

    def test_dynamic_shape_ops_fall_back(self):
        x = _t(np.array([1.0, 0.0, 2.0, 0.0]))
        idx = ops.nonzero(x)
        assert np.asarray(idx.numpy()).shape[0] == 2
        # repeated calls still work (blacklisted, eager fallback)
        x2 = _t(np.array([1.0, 1.0, 2.0, 0.0]))
        assert np.asarray(ops.nonzero(x2).numpy()).shape[0] == 3

    def test_static_args_key_separation(self):
        x = _t(np.random.RandomState(2).randn(4, 4))
        a = ops.sum(x, axis=0)
        b = ops.sum(x, axis=1)
        np.testing.assert_allclose(np.asarray(a.numpy()),
                                   np.asarray(x.numpy()).sum(0),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(b.numpy()),
                                   np.asarray(x.numpy()).sum(1),
                                   rtol=1e-6)

    def test_double_backward_still_works_with_cache(self):
        x = _t([2.0, 3.0])
        y = (x * x * x).sum()
        (g,) = pt.autograd.grad(y, [x], create_graph=True)
        (g2,) = pt.autograd.grad(g.sum(), [x])
        np.testing.assert_allclose(g2.numpy(), [12.0, 18.0], rtol=1e-5)

    def test_static_type_distinction(self):
        """2, 2.0 and True are ==/hash-equal python values but must not
        share an executable (int exponent -> int result)."""
        x = _t(np.array([2.0, 3.0]))
        xi = pt.to_tensor(np.array([2, 3], np.int32))
        a = ops.pow(xi, 2)
        b = ops.pow(xi, 2.0)
        assert "int" in str(a.dtype)
        assert "float" in str(b.dtype)
        np.testing.assert_allclose(np.asarray(b.numpy()), [4.0, 9.0])
        c = ops.pow(x, True)   # bool exponent: own cache slot
        np.testing.assert_allclose(np.asarray(c.numpy()), [2.0, 3.0])
