"""Distributed core tests: collectives, topology, fleet, mpu layers,
recompute, MoE, pipeline — all on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist


@pytest.fixture(autouse=True)
def _reset_groups():
    dist.destroy_process_group()
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group
    set_hybrid_communicate_group(None)
    yield
    dist.destroy_process_group()
    set_hybrid_communicate_group(None)


class TestCollectives:
    """Eager rank-major collectives (paddle API semantics: dim0 == rank)."""

    def test_all_reduce_sum(self):
        x = pt.to_tensor(np.arange(8 * 4, dtype=np.float32).reshape(8, 4))
        expect = np.broadcast_to(x.numpy().sum(0, keepdims=True), (8, 4))
        dist.all_reduce(x)
        np.testing.assert_allclose(x.numpy(), expect)

    def test_all_reduce_max(self):
        x = pt.to_tensor(np.arange(8.0))
        dist.all_reduce(x, op=dist.ReduceOp.MAX)
        np.testing.assert_allclose(x.numpy(), np.full(8, 7.0))

    def test_broadcast(self):
        x = pt.to_tensor(np.arange(8.0))
        dist.broadcast(x, src=3)
        np.testing.assert_allclose(x.numpy(), np.full(8, 3.0))

    def test_all_gather_concat(self):
        x = pt.to_tensor(np.arange(16, dtype=np.float32).reshape(8, 2))
        out = dist.all_gather(x)
        assert out.shape == [8, 16]
        np.testing.assert_allclose(out.numpy()[0], np.arange(16.0))
        np.testing.assert_allclose(out.numpy()[5], np.arange(16.0))

    def test_reduce_scatter(self):
        x = pt.to_tensor(np.ones((8, 8), np.float32))
        out = dist.reduce_scatter(x)
        assert out.shape == [8, 1]
        np.testing.assert_allclose(out.numpy(), np.full((8, 1), 8.0))

    def test_all_to_all(self):
        g = 8
        x = np.zeros((g, g), np.float32)
        for r in range(g):
            x[r] = r * 10 + np.arange(g)  # rank r sends r*10+c to rank c
        out = dist.all_to_all(pt.to_tensor(x))
        expect = x.T
        np.testing.assert_allclose(out.numpy(), expect)

    def test_subgroup(self):
        g = dist.new_group([0, 1, 2, 3])
        x = pt.to_tensor(np.arange(4.0))
        dist.all_reduce(x, group=g)
        np.testing.assert_allclose(x.numpy(), np.full(4, 6.0))

    def test_reduce_to_dst(self):
        x = pt.to_tensor(np.ones(8, np.float32))
        dist.reduce(x, dst=2)
        expect = np.ones(8)
        expect[2] = 8.0
        np.testing.assert_allclose(x.numpy(), expect)

    def test_world(self):
        dist.init_parallel_env()
        assert dist.get_world_size() == 8
        assert dist.get_rank() == 0
        assert dist.is_initialized()


class TestTopologyFleet:
    def test_hcg_axes(self):
        hcg = dist.HybridCommunicateGroup(dp=2, mp=2, pp=2)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert hcg.mesh.shape == {"dp": 2, "pp": 2, "sharding": 1,
                                  "sep": 1, "mp": 2}

    def test_fleet_init(self):
        strategy = dist.fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        dist.fleet.init(is_collective=True, strategy=strategy)
        hcg = dist.fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2

    def test_fleet_dp_absorbs(self):
        strategy = dist.fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": -1, "mp_degree": 2}
        dist.fleet.init(strategy=strategy)
        hcg = dist.fleet.get_hybrid_communicate_group()
        assert hcg.get_data_parallel_world_size() == 4


def _init_fleet(dp=1, mp=1, pp=1, sharding=1, accumulate_steps=1):
    strategy = dist.fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": pp,
                               "sharding_degree": sharding}
    strategy.pipeline_configs = {"accumulate_steps": accumulate_steps}
    dist.fleet.init(strategy=strategy)
    return strategy


class TestMpuLayers:
    def test_column_row_match_dense(self):
        _init_fleet(dp=2, mp=4)
        from paddle_tpu.distributed.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear)
        pt.seed(3)
        col = ColumnParallelLinear(16, 32, gather_output=False)
        row = RowParallelLinear(32, 16)
        x = pt.to_tensor(np.random.randn(4, 16).astype(np.float32))
        y = row(col(x))
        # dense reference with the same weights
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(y.numpy(), ref, rtol=2e-4, atol=2e-4)
        # weights really sharded
        from jax.sharding import PartitionSpec as P
        assert col.weight._data.sharding.spec == P(None, "mp")
        assert row.weight._data.sharding.spec == P("mp", None)

    def test_mp_backward(self):
        _init_fleet(mp=4, dp=2)
        from paddle_tpu.distributed.meta_parallel import (
            ColumnParallelLinear, RowParallelLinear)
        col = ColumnParallelLinear(8, 16, gather_output=False)
        row = RowParallelLinear(16, 8)
        x = pt.to_tensor(np.random.randn(2, 8).astype(np.float32),
                         stop_gradient=False)
        loss = pt.ops.mean(row(col(x)) ** 2)
        loss.backward()
        assert col.weight.grad is not None
        assert row.weight.grad is not None
        assert np.isfinite(col.weight.grad.numpy()).all()

    def test_vocab_parallel_embedding(self):
        _init_fleet(mp=8)
        from paddle_tpu.distributed.meta_parallel import (
            VocabParallelEmbedding)
        emb = VocabParallelEmbedding(64, 16)
        ids = pt.to_tensor(np.array([[1, 5, 63]], np.int32))
        out = emb(ids)
        assert out.shape == [1, 3, 16]
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   emb.weight.numpy()[1], rtol=1e-6)

    def test_parallel_cross_entropy(self):
        _init_fleet(mp=8)
        from paddle_tpu.distributed.meta_parallel import (
            ColumnParallelLinear, ParallelCrossEntropy)
        lin = ColumnParallelLinear(16, 64, gather_output=False)
        x = pt.to_tensor(np.random.randn(4, 16).astype(np.float32))
        logits = lin(x)
        label = pt.to_tensor(np.array([1, 2, 3, 4], np.int32))
        loss = ParallelCrossEntropy()(logits, label)
        ref = pt.ops.cross_entropy(
            pt.to_tensor(logits.numpy()), label, reduction="none")
        np.testing.assert_allclose(loss.numpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-5)


class TestRecompute:
    def test_matches_plain(self):
        from paddle_tpu.distributed.meta_parallel import recompute
        lin = pt.nn.Linear(8, 8)
        x = pt.to_tensor(np.random.randn(4, 8).astype(np.float32),
                         stop_gradient=False)
        y1 = pt.ops.mean(lin(x) ** 2)
        y1.backward()
        g_plain = lin.weight.grad.numpy().copy()
        gx_plain = x.grad.numpy().copy()
        lin.weight.clear_grad()
        x2 = pt.to_tensor(x.numpy(), stop_gradient=False)
        y2 = pt.ops.mean(recompute(lin, x2) ** 2)
        y2.backward()
        np.testing.assert_allclose(y1.numpy(), y2.numpy(), rtol=1e-5)
        np.testing.assert_allclose(lin.weight.grad.numpy(), g_plain,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(x2.grad.numpy(), gx_plain, rtol=1e-5,
                                   atol=1e-6)

    def test_rng_tracker(self):
        from paddle_tpu.distributed.meta_parallel import (
            get_rng_state_tracker, model_parallel_random_seed)
        model_parallel_random_seed(42)
        tr = get_rng_state_tracker()
        with tr.rng_state():
            a = pt.ops.dropout(pt.ones([100]), p=0.5)
        with tr.rng_state():
            b = pt.ops.dropout(pt.ones([100]), p=0.5)
        # sequential draws from the tracked stream must differ
        assert not np.allclose(a.numpy(), b.numpy())


class TestMoE:
    def test_moe_forward_backward(self):
        _init_fleet(mp=4, dp=2)
        from paddle_tpu.distributed.meta_parallel import MoELayer
        moe = MoELayer(d_model=16, d_hidden=32, num_experts=8, top_k=2)
        x = pt.to_tensor(np.random.randn(2, 8, 16).astype(np.float32),
                         stop_gradient=False)
        out = moe(x)
        assert out.shape == [2, 8, 16]
        loss = pt.ops.mean(out ** 2) + 0.01 * pt.ops.mean(moe.aux_loss)
        loss.backward()
        assert moe.w1.grad is not None
        assert np.isfinite(moe.w1.grad.numpy()).all()

    def test_moe_routes_all_tokens_with_capacity(self):
        from paddle_tpu.distributed.meta_parallel import MoELayer
        moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, top_k=1,
                       capacity_factor=4.0)
        x = pt.to_tensor(np.random.randn(1, 16, 8).astype(np.float32))
        out = moe(x)
        # with huge capacity nothing is dropped: output norm > 0
        assert float(pt.ops.mean(out ** 2).numpy()) > 0


class TestPipeline:
    def _build(self, accumulate_steps=2):
        strategy = _init_fleet(pp=2, dp=2, mp=2,
                               accumulate_steps=accumulate_steps)
        from paddle_tpu.distributed.meta_parallel import (
            LayerDesc, PipelineLayer)
        import paddle_tpu.nn as nn

        class Blk(pt.nn.Layer):
            def __init__(self, d):
                super().__init__()
                self.lin = nn.Linear(d, d)

            def forward(self, x):
                return pt.ops.relu(self.lin(x))

        descs = [LayerDesc(Blk, 16) for _ in range(4)] + \
            [LayerDesc(pt.nn.Linear, 16, 4)]
        model = PipelineLayer(
            layers=descs, loss_fn=lambda out, lbl: pt.ops.cross_entropy(
                out, lbl), seg_method="uniform")
        return model, strategy

    def test_pipeline_layer_stages(self):
        model, _ = self._build()
        assert len(model.stages) == 2
        assert model.segment_parts == [0, 3, 5]
        # stage params live on their stage's sub-mesh
        p0 = model.stages[0][0].lin.weight
        p1 = model.stages[1][0].lin.weight
        assert p0._data.sharding.mesh is not p1._data.sharding.mesh

    def test_train_batch(self):
        model, strategy = self._build(accumulate_steps=2)
        mp_model = dist.fleet.distributed_model(model)
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        opt = dist.fleet.distributed_optimizer(opt)
        x = np.random.randn(8, 16).astype(np.float32)
        y = np.random.randint(0, 4, (8,)).astype(np.int32)
        losses = [float(mp_model.train_batch(
            [pt.to_tensor(x), pt.to_tensor(y)], opt).numpy())
            for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_shared_layer_desc(self):
        strategy = _init_fleet(pp=2, dp=4, accumulate_steps=1)
        from paddle_tpu.distributed.meta_parallel import (
            LayerDesc, SharedLayerDesc, PipelineLayer)

        def head(layer, x):
            return pt.ops.matmul(x, layer.weight, transpose_y=True)

        descs = [
            SharedLayerDesc("emb", pt.nn.Embedding, 32, 16),
            LayerDesc(pt.nn.Linear, 16, 16),
            SharedLayerDesc("emb", pt.nn.Embedding, 32, 16,
                            forward_func=head),
        ]
        model = PipelineLayer(layers=descs, loss_fn=None)
        # shared layer built once
        n_emb = sum(1 for n, _ in model.named_parameters()
                    if "weight" in n)
        ids = pt.to_tensor(np.array([[1, 2]], np.int32))
        out = model(ids)
        assert out.shape == [1, 2, 32]
        loss = pt.ops.mean(out ** 2)
        loss.backward()
        emb_layer = model._shared["emb"][0]
        assert emb_layer.weight.grad is not None


class TestShardingStage1:
    def test_opt_states_sharded(self):
        _init_fleet(dp=2, sharding=4)
        m = pt.nn.Linear(16, 64)
        model = dist.fleet.distributed_model(m)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
        opt = dist.fleet.distributed_optimizer(opt)
        x = pt.to_tensor(np.random.randn(8, 16).astype(np.float32))
        loss = pt.ops.mean(model(x) ** 2)
        loss.backward()
        opt.step()
        st = opt._inner_opt._accumulators[id(m.weight)]
        from jax.sharding import PartitionSpec as P
        specs = [v.sharding.spec for v in st.values()
                 if getattr(v, "ndim", 0) > 0]
        assert any("sharding" in str(s) for s in specs), specs
        opt.clear_grad()
        assert m.weight.grad is None


class TestShardingWithPipeline:
    def test_opt_states_follow_stage_submesh(self):
        # ADVICE r1 (medium): pp>1 + sharding>1 — accumulators must live
        # on each param's stage sub-mesh, not the full hybrid mesh.
        _init_fleet(mp=2, pp=2, sharding=2, accumulate_steps=2)
        from paddle_tpu.distributed.fleet import fleet
        from paddle_tpu.distributed.meta_parallel import (
            PipelineLayer, LayerDesc)

        descs = [
            LayerDesc(pt.nn.Linear, 16, 32),
            LayerDesc(pt.nn.Linear, 32, 32),
            LayerDesc(pt.nn.Linear, 32, 16),
            LayerDesc(pt.nn.Linear, 16, 8),
        ]
        model = PipelineLayer(layers=descs,
                              loss_fn=lambda out, lbl:
                              pt.ops.mean((out - lbl) ** 2))
        pipe = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            pt.optimizer.AdamW(learning_rate=1e-3,
                               parameters=model.parameters()))
        x = pt.to_tensor(np.random.randn(4, 16).astype(np.float32))
        y = pt.to_tensor(np.random.randn(4, 8).astype(np.float32))
        loss = pipe.train_batch([x, y], opt)
        assert np.isfinite(float(loss.numpy()))
        # accumulators of a stage-resident param sit on that param's mesh
        from jax.sharding import NamedSharding
        for p in model.parameters():
            st = opt._inner_opt._accumulators.get(id(p))
            if not st:
                continue
            psh = p._data.sharding
            for v in st.values():
                if getattr(v, "ndim", 0) == 0:
                    continue
                assert isinstance(v.sharding, NamedSharding)
                assert set(v.sharding.mesh.devices.flat) == \
                    set(psh.mesh.devices.flat)
        # second step exercises the committed states end-to-end
        loss = pipe.train_batch([x, y], opt)
        assert np.isfinite(float(loss.numpy()))


class TestP2PMatching:
    def test_recv_matches_destination_in_pair_group(self):
        dist.init_parallel_env()
        g = dist.new_group([2, 5])
        a = pt.to_tensor(np.full((4,), 1.0, np.float32))
        b = pt.to_tensor(np.full((4,), 2.0, np.float32))
        dist.send(a, dst=5, group=g)
        dist.send(b, dst=2, group=g)
        out = pt.to_tensor(np.zeros((4,), np.float32))
        dist.recv(out, src=5, group=g)   # message addressed to rank 2
        np.testing.assert_allclose(out.numpy(), b.numpy())
        out2 = pt.to_tensor(np.zeros((4,), np.float32))
        dist.recv(out2, src=2, group=g)  # message addressed to rank 5
        np.testing.assert_allclose(out2.numpy(), a.numpy())

    def test_recv_without_send_raises(self):
        dist.init_parallel_env()
        g = dist.new_group([0, 1])
        out = pt.to_tensor(np.zeros((4,), np.float32))
        with pytest.raises(RuntimeError, match="no outstanding send"):
            dist.recv(out, src=1, group=g)

    def test_recv_no_match_for_receiver_raises(self):
        dist.init_parallel_env()
        g = dist.new_group([0, 1])
        a = pt.to_tensor(np.ones((2,), np.float32))
        dist.send(a, dst=1, group=g)
        out = pt.to_tensor(np.zeros((2,), np.float32))
        # src=0 means receiver is rank 1 -> matches. src=1 -> receiver 0,
        # but the only pending send is addressed to 1.
        with pytest.raises(RuntimeError, match="addressed to rank 0"):
            dist.recv(out, src=1, group=g)
        dist.recv(out, src=0, group=g)
        np.testing.assert_allclose(out.numpy(), a.numpy())

    def test_recv_no_group_rank_collision(self):
        # code-review r2: a group-local index must not collide with a
        # member's global rank. group [1,3]: send(dst=1) is addressed to
        # GLOBAL rank 1; recv(src=1) (receiver = rank 3) must NOT get it.
        dist.init_parallel_env()
        g = dist.new_group([1, 3])
        a = pt.to_tensor(np.ones((2,), np.float32))
        dist.send(a, dst=1, group=g)
        out = pt.to_tensor(np.zeros((2,), np.float32))
        with pytest.raises(RuntimeError, match="addressed to rank 3"):
            dist.recv(out, src=1, group=g)
        dist.recv(out, src=3, group=g)  # receiver = rank 1 -> matches
        np.testing.assert_allclose(out.numpy(), a.numpy())


class TestDataParallel:
    """The dygraph DataParallel wrapper (VERDICT r2 weak #6: previously
    untested). ref: python/paddle/distributed/parallel.py:202."""

    def test_wrapper_delegates_and_trains(self):
        dist.init_parallel_env()
        inner = pt.nn.Linear(8, 4)
        dp = dist.DataParallel(inner)
        # wrapper exposes the inner layer's API
        assert len(dp.parameters()) == len(inner.parameters())
        assert set(dp.state_dict()) == set(inner.state_dict())
        x = pt.to_tensor(np.random.RandomState(0).randn(16, 8).astype(
            np.float32))
        loss = dp.scale_loss((dp(x) ** 2).mean())
        loss.backward()
        dp.apply_collective_grads()  # documented no-op under GSPMD
        assert inner.weight.grad is not None
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=dp.parameters())
        w0 = inner.weight.numpy().copy()
        opt.step()
        assert not np.allclose(inner.weight.numpy(), w0)

    def test_state_dict_round_trip(self):
        dist.init_parallel_env()
        inner = pt.nn.Linear(4, 4)
        dp = dist.DataParallel(inner)
        sd = {k: v for k, v in dp.state_dict().items()}
        inner2 = pt.nn.Linear(4, 4)
        dp2 = dist.DataParallel(inner2)
        dp2.set_state_dict(sd)
        np.testing.assert_allclose(inner2.weight.numpy(),
                                   inner.weight.numpy())
