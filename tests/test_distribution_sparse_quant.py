"""Distribution zoo + sparse + quantization conformance.

Distributions and KL pairs check against torch.distributions (same
math as the reference's python/paddle/distribution/); sparse against
dense equivalents; QAT trains through the STE."""
import numpy as np
import pytest
import torch.distributions as TD

import paddle_tpu as pt
from paddle_tpu import distribution as D

RNG = np.random.default_rng(0)


class TestDistributionZoo:
    CASES = {
        "Laplace": ((0.3, 1.2), TD.Laplace),
        "Cauchy": ((0.3, 1.2), TD.Cauchy),
        "Gumbel": ((0.3, 1.2), TD.Gumbel),
        "LogNormal": ((0.3, 1.2), TD.LogNormal),
    }

    @pytest.mark.parametrize("name", sorted(CASES))
    def test_log_prob_matches_torch(self, name):
        args, tcls = self.CASES[name]
        d = getattr(D, name)(*args)
        td = tcls(*[float(a) for a in args])
        v = np.array([0.5, 1.5, 2.5], np.float32)
        np.testing.assert_allclose(
            d.log_prob(pt.to_tensor(v)).numpy(),
            td.log_prob(__import__("torch").from_numpy(v)).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_geometric_log_prob(self):
        d = D.Geometric(0.3)
        td = TD.Geometric(0.3)
        import torch
        v = np.array([0.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            d.log_prob(pt.to_tensor(v)).numpy(),
            td.log_prob(torch.from_numpy(v)).numpy(), rtol=1e-5)

    def test_sampling_moments(self):
        for d, mean, std in [
            (D.Laplace(1.0, 2.0), 1.0, np.sqrt(8.0)),
            (D.Gumbel(0.0, 1.0), 0.5772, np.pi / np.sqrt(6)),
            (D.LogNormal(0.0, 0.5), np.exp(0.125), None),
        ]:
            s = d.sample((100000,)).numpy()
            assert abs(s.mean() - mean) < 0.05 * max(1, abs(mean)), \
                (type(d).__name__, s.mean(), mean)
            if std is not None:
                assert abs(s.std() - std) < 0.05 * std

    def test_independent_reinterprets(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (3,) and ind.event_shape == (4,)
        v = RNG.standard_normal((3, 4)).astype(np.float32)
        np.testing.assert_allclose(
            ind.log_prob(pt.to_tensor(v)).numpy(),
            base.log_prob(pt.to_tensor(v)).numpy().sum(-1), rtol=1e-6)

    def test_transformed_matches_closed_form(self):
        td_dist = D.TransformedDistribution(D.Normal(0.0, 1.0),
                                            [D.ExpTransform()])
        ln = D.LogNormal(0.0, 1.0)
        for v in (0.5, 2.0, 7.0):
            np.testing.assert_allclose(
                float(td_dist.log_prob(v).numpy()),
                float(ln.log_prob(v).numpy()), rtol=1e-5)

    def test_transform_inverses(self):
        x = RNG.standard_normal((8,)).astype(np.float32)
        for t in [D.AffineTransform(1.0, 2.0), D.ExpTransform(),
                  D.SigmoidTransform(), D.TanhTransform()]:
            y = t.forward(pt.to_tensor(x))
            back = t.inverse(y)
            np.testing.assert_allclose(back.numpy(), x, rtol=1e-4,
                                       atol=1e-5)

    def test_stickbreaking_simplex(self):
        x = RNG.standard_normal((5, 3)).astype(np.float32)
        t = D.StickBreakingTransform()
        y = t.forward(pt.to_tensor(x)).numpy()
        assert y.shape == (5, 4)
        np.testing.assert_allclose(y.sum(-1), 1.0, rtol=1e-5)
        back = t.inverse(pt.to_tensor(y)).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-4)

    KL_PAIRS = [
        (lambda: (D.Normal(0.3, 1.2), D.Normal(-0.5, 0.7)),
         lambda: (TD.Normal(0.3, 1.2), TD.Normal(-0.5, 0.7))),
        (lambda: (D.Laplace(0.3, 1.2), D.Laplace(-0.5, 0.7)),
         lambda: (TD.Laplace(0.3, 1.2), TD.Laplace(-0.5, 0.7))),
        (lambda: (D.Gamma(2.0, 3.0), D.Gamma(1.5, 2.0)),
         lambda: (TD.Gamma(2.0, 3.0), TD.Gamma(1.5, 2.0))),
        (lambda: (D.Beta(2.0, 3.0), D.Beta(1.5, 2.5)),
         lambda: (TD.Beta(2.0, 3.0), TD.Beta(1.5, 2.5))),
        (lambda: (D.Geometric(0.3), D.Geometric(0.6)),
         lambda: (TD.Geometric(0.3), TD.Geometric(0.6))),
        (lambda: (D.Bernoulli(0.3), D.Bernoulli(0.6)),
         lambda: (TD.Bernoulli(0.3), TD.Bernoulli(0.6))),
        (lambda: (D.Gumbel(0.3, 1.2), D.Gumbel(-0.5, 0.7)),
         lambda: (TD.Gumbel(0.3, 1.2), TD.Gumbel(-0.5, 0.7))),
    ]

    @pytest.mark.parametrize("idx", range(len(KL_PAIRS)))
    def test_kl_matches_torch(self, idx):
        (mk, tmk) = self.KL_PAIRS[idx]
        p, q = mk()
        tp, tq = tmk()
        np.testing.assert_allclose(
            float(D.kl_divergence(p, q).numpy()),
            float(TD.kl_divergence(tp, tq)), rtol=1e-3, atol=1e-4)

    def test_kl_unknown_pair_raises(self):
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Cauchy(0.0, 1.0), D.Normal(0.0, 1.0))


class TestSparse:
    def _coo(self):
        idx = np.array([[0, 1, 2], [1, 2, 0]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        return pt.sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])

    def test_coo_roundtrip(self):
        t = self._coo()
        d = t.to_dense().numpy()
        assert d[0, 1] == 1 and d[1, 2] == 2 and d[2, 0] == 3
        assert t.nnz() == 3 and t.is_sparse() and t.is_sparse_coo()

    def test_csr_roundtrip(self):
        c = pt.sparse.sparse_csr_tensor(
            [0, 1, 2, 3], [1, 2, 0],
            np.array([1.0, 2.0, 3.0], np.float32), [3, 3])
        d = c.to_dense().numpy()
        assert d[0, 1] == 1 and d[1, 2] == 2 and d[2, 0] == 3
        assert c.is_sparse_csr()
        coo = c.to_sparse_coo()
        np.testing.assert_allclose(coo.to_dense().numpy(), d)

    def test_matmul_and_masked(self):
        t = self._coo()
        d = t.to_dense().numpy()
        y = RNG.standard_normal((3, 4)).astype(np.float32)
        np.testing.assert_allclose(pt.sparse.matmul(t, y).numpy(),
                                   d @ y, rtol=1e-5)
        a = RNG.standard_normal((3, 5)).astype(np.float32)
        b = RNG.standard_normal((5, 3)).astype(np.float32)
        mm = pt.sparse.masked_matmul(a, b, t)
        np.testing.assert_allclose(mm.to_dense().numpy(),
                                   (a @ b) * (d != 0), rtol=1e-4,
                                   atol=1e-5)

    def test_elementwise_and_values_ops(self):
        t = self._coo()
        d = t.to_dense().numpy()
        np.testing.assert_allclose(
            pt.sparse.add(t, t).to_dense().numpy(), 2 * d)
        np.testing.assert_allclose(t.square().to_dense().numpy(), d * d)
        relu = pt.sparse.nn.ReLU()
        np.testing.assert_allclose(relu(t).to_dense().numpy(),
                                   np.maximum(d, 0))

    def test_addmm(self):
        t = self._coo()
        d = t.to_dense().numpy()
        x = RNG.standard_normal((3, 3)).astype(np.float32)
        y = RNG.standard_normal((3, 3)).astype(np.float32)
        out = pt.sparse.addmm(x, t, y, beta=0.5, alpha=2.0).numpy()
        np.testing.assert_allclose(out, 0.5 * x + 2.0 * (d @ y),
                                   rtol=1e-5)


class TestQuantization:
    def test_qat_trains_and_converts(self):
        from paddle_tpu.quantization import (
            QuantConfig, QAT, FakeQuanterWithAbsMaxObserver)
        pt.seed(0)
        model = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                                 pt.nn.Linear(16, 4))
        q = FakeQuanterWithAbsMaxObserver(moving_rate=0.9, bit_length=8)
        qat = QAT(QuantConfig(activation=q, weight=q))
        qmodel = qat.quantize(model)
        x = pt.to_tensor(RNG.standard_normal((16, 8)).astype(np.float32))
        y = pt.to_tensor(RNG.standard_normal((16, 4)).astype(np.float32))
        qmodel.train()
        for _ in range(10):  # observer warmup
            qmodel(x)
        opt = pt.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=qmodel.parameters())
        losses = []
        for _ in range(30):
            loss = pt.ops.mean((qmodel(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
        conv = qat.convert(qmodel)
        out = conv(x)
        assert out.shape == [16, 4]

    def test_grad_flows_through_quanter(self):
        from paddle_tpu.quantization import (
            FakeQuanterWithAbsMaxObserverLayer)
        quanter = FakeQuanterWithAbsMaxObserverLayer()
        quanter.train()
        x = pt.to_tensor(np.linspace(-1, 1, 8).astype(np.float32),
                         stop_gradient=False)
        quanter(x)  # warm the scale to cover the range
        out = quanter(x)
        out.sum().backward()
        g = x.grad.numpy()
        assert np.count_nonzero(g) > 0  # STE passes gradient through

    def test_quantize_dequantize_roundtrip(self):
        from paddle_tpu.quantization import (quantize_linear,
                                             dequantize_linear)
        w = RNG.standard_normal((32,)).astype(np.float32)
        scale = np.abs(w).max()
        q = quantize_linear(w, scale=scale)
        assert str(q._data.dtype) == "int8"
        dq = dequantize_linear(q, scale=scale)
        assert np.abs(dq.numpy() - w).max() < scale / 50

    def test_ptq_collects_scales(self):
        from paddle_tpu.quantization import (
            QuantConfig, PTQ, FakeQuanterWithAbsMaxObserver)
        pt.seed(1)
        model = pt.nn.Sequential(pt.nn.Linear(4, 4))
        q = FakeQuanterWithAbsMaxObserver()
        ptq = PTQ(QuantConfig(activation=q, weight=None))
        m = ptq.quantize(model)
        x = pt.to_tensor(RNG.standard_normal((8, 4)).astype(np.float32))
        for _ in range(5):
            m(x)
        quanter = m._sub_layers["0"].activation_quanter
        assert float(quanter.scale.numpy()[0]) > 0.5  # calibrated


class TestReviewRegressions:
    """code-review r2 findings on this module set."""

    def test_stft_autograd_flows(self):
        sig = RNG.standard_normal((256,)).astype(np.float32)
        x = pt.to_tensor(sig, stop_gradient=False)
        spec = pt.signal.stft(x, n_fft=64, hop_length=32)
        (spec.abs() ** 2).sum().backward()
        assert x.grad is not None
        assert np.count_nonzero(x.grad.numpy()) > 0

    def test_sparse_transpose_preserves_csr(self):
        c = pt.sparse.sparse_csr_tensor(
            [0, 1, 2, 3], [1, 2, 0],
            np.array([1.0, 2.0, 3.0], np.float32), [3, 3])
        out = pt.sparse.transpose(c, [1, 0])
        assert out.is_sparse_csr()
        out.crows()  # must not raise
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   c.to_dense().numpy().T)

    def test_weight_ste_masks_out_of_range(self):
        from paddle_tpu.quantization import (
            QuantConfig, QAT, FakeQuanterWithAbsMaxObserver)
        pt.seed(2)
        lin = pt.nn.Linear(4, 4)
        qat = QAT(QuantConfig(
            activation=None, weight=FakeQuanterWithAbsMaxObserver()))
        qm = qat.quantize(pt.nn.Sequential(lin))
        qm.train()
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(5):
            qm(x)
        qm(x).sum().backward()
        inner = qm._sub_layers["0"]._inner
        g = inner.weight.grad.numpy()
        assert np.count_nonzero(g) > 0  # grads flow through the STE

    def test_quanter_no_tracer_leak_under_jit(self):
        import jax
        from paddle_tpu.quantization import (
            FakeQuanterWithAbsMaxObserverLayer)
        quanter = FakeQuanterWithAbsMaxObserverLayer()
        quanter.train()
        x = np.linspace(-1, 1, 8).astype(np.float32)
        quanter(pt.to_tensor(x))  # eager calibration

        def f(arr):
            return quanter(pt.Tensor._wrap(arr))._data

        out = jax.jit(f)(x)       # traced call must not poison state
        assert not isinstance(quanter.scale._data, jax.core.Tracer)
        quanter(pt.to_tensor(x))  # eager again still works
        assert np.isfinite(np.asarray(out)).all()
