"""AST dygraph-to-static conversion (L5 SOT/AST path analog).

Mirrors the reference's dy2static tests
(test/dygraph_to_static/test_ifelse.py, test_while_op.py): tensor-
predicate if/while must stage into one graph under
@to_static(full_graph=True) and agree with eager execution.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import ast_transform, convert_ifelse


def t(x, dtype=np.float32):
    return pt.to_tensor(np.asarray(x, dtype))


class TestIfConversion:
    def test_tensor_if_stages_and_matches_eager(self):
        def f(x):
            if ops.sum(x) > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        sf = to_static(f, full_graph=True)
        for data in ([1.0, 2.0], [-5.0, 1.0]):
            got = sf(t(data)).numpy()
            ref = f(t(data)).numpy()
            np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_elif_chain(self):
        def f(x):
            s = ops.sum(x)
            if s > 10.0:
                r = x * 3.0
            elif s > 0.0:
                r = x * 2.0
            else:
                r = x * 0.0
            return r

        sf = to_static(f, full_graph=True)
        for data in ([20.0], [1.0], [-3.0]):
            np.testing.assert_allclose(sf(t(data)).numpy(),
                                       f(t(data)).numpy(), rtol=1e-6)

    def test_nested_if(self):
        def f(x):
            if ops.sum(x) > 0:
                if ops.max(x) > 5.0:
                    y = x * 10.0
                else:
                    y = x * 2.0
            else:
                y = -x
            return y

        sf = to_static(f, full_graph=True)
        for data in ([6.0], [1.0], [-1.0]):
            np.testing.assert_allclose(sf(t(data)).numpy(),
                                       f(t(data)).numpy(), rtol=1e-6)

    def test_python_bool_branch_untouched(self):
        def f(x, flag=True):
            if flag:             # plain python predicate stays python
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        sf = to_static(f, full_graph=True)
        np.testing.assert_allclose(sf(t([1.0])).numpy(), [2.0])

    def test_grad_flows_through_staged_branch(self):
        def f(x):
            if ops.sum(x) > 0:
                y = x * 3.0
            else:
                y = x * 5.0
            return ops.sum(y)

        sf = to_static(f, full_graph=True)
        x = t([1.0, 2.0])
        x.stop_gradient = False
        loss = sf(x)
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0], rtol=1e-6)
        x2 = t([-1.0, -2.0])
        x2.stop_gradient = False
        sf(x2).backward()
        np.testing.assert_allclose(x2.grad.numpy(), [5.0, 5.0], rtol=1e-6)

    def test_one_sided_assignment_raises_clearly(self):
        def f(x):
            if ops.sum(x) > 0:
                y = x * 2.0
            return x if "y" not in dir() else y  # noqa: F821

        # conversion itself happens; the traced branch mismatch must be
        # reported with the initialize-before-if hint
        def g(x):
            if ops.sum(x) > 0:
                y = x * 2.0
            else:
                pass
            return y  # noqa: F821

        sf = to_static(g, full_graph=True)
        with pytest.raises(RuntimeError, match="Initialize"):
            sf(t([1.0]))


class TestWhileConversion:
    def test_tensor_while_stages(self):
        def f(x):
            total = ops.zeros_like(x)
            while ops.sum(total) < 10.0:
                total = total + x
            return total

        sf = to_static(f, full_graph=True)
        got = sf(t([3.0])).numpy()
        np.testing.assert_allclose(got, [12.0])  # 4 iterations of +3

    def test_while_matches_eager_loop(self):
        def f(x, n):
            i = t(0.0)
            acc = x
            while i < n:
                acc = acc * 2.0
                i = i + 1.0
            return acc

        sf = to_static(f, full_graph=True)
        np.testing.assert_allclose(sf(t([1.5]), t(3.0)).numpy(), [12.0])

    def test_while_grad(self):
        def f(x):
            i = t(0.0)
            y = x
            while i < 3.0:
                y = y * 2.0
                i = i + 1.0
            return ops.sum(y)

        sf = to_static(f, full_graph=True)
        x = t([1.0])
        x.stop_gradient = False
        sf(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])


class TestFallbacks:
    def test_return_in_branch_falls_back_with_clear_error(self):
        def f(x):
            if ops.sum(x) > 0:
                return x * 2.0
            return x - 1.0

        sf = to_static(f, full_graph=True)
        with pytest.raises(RuntimeError, match="data-dependent"):
            sf(t([1.0]))

    def test_no_source_falls_back_silently(self):
        import functools
        exec_ns = {}
        exec("def f(x):\n    return x + 1.0\n", exec_ns)
        assert ast_transform(exec_ns["f"]) is None

    def test_eager_concrete_tensor_predicate(self):
        # converted functions run eagerly too: concrete Tensor predicate
        # takes the plain python path
        def f(x):
            if ops.sum(x) > 0:
                return_val = x * 2.0
            else:
                return_val = -x
            return return_val

        conv = ast_transform(f)
        assert conv is not None
        np.testing.assert_allclose(conv(t([2.0])).numpy(), [4.0])
        np.testing.assert_allclose(conv(t([-2.0])).numpy(), [2.0])


class TestLayerIntegration:
    def test_layer_forward_with_control_flow(self):
        import paddle_tpu.nn as nn

        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)
                if ops.mean(h) > 0:
                    out = h * 2.0
                else:
                    out = h * 0.5
                return out

        m = to_static(Gate(), full_graph=True)
        x = t(np.ones((2, 4)))
        out = m(x)
        assert list(out.shape) == [2, 4]
        ref_h = m.lin(x)
        factor = 2.0 if float(ops.mean(ref_h).numpy()) > 0 else 0.5
        np.testing.assert_allclose(out.numpy(), ref_h.numpy() * factor,
                                   rtol=1e-5)


class TestConversionBailouts:
    def test_import_inside_branch_survives(self):
        def f(x):
            if ops.sum(x) > 0:
                import math
                y = x * math.e
            else:
                import math
                y = x * math.pi
            return y

        sf = to_static(f, full_graph=True)
        np.testing.assert_allclose(sf(t([1.0])).numpy(),
                                   [float(np.e)], rtol=1e-6)
        np.testing.assert_allclose(sf(t([-1.0])).numpy(),
                                   [-float(np.pi)], rtol=1e-6)

    def test_functools_wrapped_bails_out(self):
        import functools

        def deco(g):
            @functools.wraps(g)
            def inner(*a, **k):
                return g(*a, **k) + 100.0
            return inner

        @deco
        def f(x):
            if ops.sum(x) > 0:
                y = x
            else:
                y = -x
            return ops.sum(y)

        assert ast_transform(f) is None  # wrapper behavior preserved

    def test_zero_arg_super_bails_out(self):
        import paddle_tpu.nn as nn

        class Base(nn.Layer):
            def forward(self, x):
                return x + 1.0

        class Child(Base):
            def forward(self, x):
                h = super().forward(x)
                if ops.sum(h) > 1e9:
                    h = h * 0.0
                else:
                    h = h * 1.0
                return h

        c = Child()
        assert ast_transform(c.forward) is None  # super() cell unsupported
        # and the layer still runs eagerly
        np.testing.assert_allclose(c(t([1.0])).numpy(), [2.0])
