"""Elastic restart (ref fleet/elastic/manager.py) + auto-tuner
(ref auto_tuner/tuner.py, prune.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, Config, default_candidates, estimate_memory_bytes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestAutoTuner:
    CFG = {
        "world_size": 8,
        "global_batch_size": 16,
        "model_num_params": 1.3e9,
        "hidden_size": 2048,
        "num_heads": 16,
        "num_layers": 24,
        "seq_length": 1024,
        "hbm_bytes": 16 * 2**30,
    }

    def test_candidates_divide_world(self):
        c = default_candidates(self.CFG)
        assert all(8 % d == 0 for d in c["dp_degree"])
        assert all(16 % m == 0 for m in c["micro_batch_size"])

    def test_prune_rules(self):
        tuner = AutoTuner(self.CFG)
        seen = []
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            seen.append(cfg)
            tuner.add_cfg(cfg)
        assert seen, "grid produced no valid configs"
        for cfg in seen:
            assert cfg.world == 8
            assert self.CFG["hidden_size"] % cfg.mp_degree == 0
            assert self.CFG["num_layers"] % cfg.pp_degree == 0
            # memory model holds for every surviving config
            assert estimate_memory_bytes(cfg, self.CFG) <= \
                0.92 * self.CFG["hbm_bytes"]

    def test_memory_model_monotone_in_sharding(self):
        base = dict(dp_degree=1, mp_degree=1, pp_degree=1,
                    sharding_degree=8, micro_batch_size=1)
        m1 = estimate_memory_bytes(Config(**base, sharding_stage=1),
                                   self.CFG)
        m2 = estimate_memory_bytes(Config(**base, sharding_stage=2),
                                   self.CFG)
        m3 = estimate_memory_bytes(Config(**base, sharding_stage=3),
                                   self.CFG)
        assert m3 < m2 < m1
        # replicated 1.3B on 16G must be pruned, stage-3 8-way must fit
        assert m1 - (m3) > 1e9

    def test_replicated_large_model_pruned(self):
        cfg = {**self.CFG, "dp_degree": [8], "mp_degree": [1],
               "pp_degree": [1], "sharding_degree": [1],
               "micro_batch_size": [1]}
        tuner = AutoTuner(cfg)
        assert tuner.search_once() is None  # 1.3B replicated > HBM
        # grid recorded nothing runnable
        assert tuner.best_cfg() is None

    def test_tune_picks_fastest_and_prunes_history(self):
        cfg = {**self.CFG, "global_batch_size": 128,
               "model_num_params": 3e8, "seq_length": 256,
               "sharding_degree": [8], "dp_degree": [1],
               "mp_degree": [1], "pp_degree": [1],
               "sharding_stage": [3],
               "micro_batch_size": [1, 2, 4, 8, 16]}
        calls = []

        def runner(c):
            calls.append(c.micro_batch_size)
            if c.micro_batch_size >= 4:
                raise MemoryError("oom")
            return 1.0 / c.micro_batch_size  # bigger mbs = faster

        best = AutoTuner(cfg).tune(runner)
        assert best is not None and best.micro_batch_size == 2
        # mbs=4 failed; 8 and 16 pruned by history without running
        assert calls == [1, 2, 4]


class TestElasticRestart:
    def test_job_restarts_until_success(self, tmp_path):
        # worker fails on the first epoch (restart_count 0), succeeds
        # after the elastic relaunch
        script = tmp_path / "flaky.py"
        script.write_text(
            "import os, sys\n"
            "rc = int(os.environ.get('PADDLE_RESTART_COUNT', '0'))\n"
            "rank = os.environ.get('PADDLE_TRAINER_ID')\n"
            "print(f'attempt={rc} rank={rank}', flush=True)\n"
            "sys.exit(0 if rc >= 1 else 7)\n")
        from paddle_tpu.distributed.launch.main import scrub_backend_env
        env = scrub_backend_env(dict(os.environ))
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        log_dir = str(tmp_path / "logs")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--max_restarts", "2",
             "--log_dir", log_dir, str(script)],
            env=env, cwd=REPO, timeout=300, capture_output=True,
            text=True)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "elastic restart 1/2" in proc.stderr
        logs = ""
        for r in (0, 1):
            with open(os.path.join(log_dir, f"workerlog.{r}")) as f:
                logs += f.read()
        assert "attempt=0" in logs and "attempt=1" in logs

    def test_restarts_exhausted_propagates_rc(self, tmp_path):
        script = tmp_path / "dead.py"
        script.write_text("import sys; sys.exit(5)\n")
        from paddle_tpu.distributed.launch.main import scrub_backend_env
        env = scrub_backend_env(dict(os.environ))
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "1", "--max_restarts", "1",
             str(script)],
            env=env, cwd=REPO, timeout=120, capture_output=True,
            text=True)
        assert proc.returncode == 5
        assert "elastic restart 1/1" in proc.stderr

    def test_negative_restarts_rejected(self, tmp_path):
        script = tmp_path / "s.py"
        script.write_text("print('hi')\n")
        from paddle_tpu.distributed.launch.main import launch
        assert launch(["--max_restarts", "-1", str(script)]) == 2
        # multi-node without a master is still rejected; multi-node WITH
        # --max_restarts is now supported (coordinated elastic restart,
        # tests/test_launch.py::TestMultiNodeElastic)
        assert launch(["--nnodes", "2", "--node_rank", "0",
                       "--max_restarts", "1", str(script)]) == 2

    def test_recompute_variant_not_pruned_by_dense_oom(self):
        from paddle_tpu.distributed.auto_tuner import (
            prune_by_history, Config)
        failed = Config(sharding_degree=8, micro_batch_size=2,
                        use_recompute=False, error="MemoryError: oom")
        candidate = Config(sharding_degree=8, micro_batch_size=2,
                           use_recompute=True)
        assert prune_by_history({}, candidate, [failed]) is None
        same = Config(sharding_degree=8, micro_batch_size=4,
                      use_recompute=False)
        assert prune_by_history({}, same, [failed]) is not None


class TestCostModel:
    """Analytic step-time estimate (VERDICT r2 missing #6; ref:
    distributed/auto_parallel/static/cost/, tuner/rule_based_tuner.py)."""

    TC = dict(world_size=8, model_num_params=1.3e9, hidden_size=2048,
              seq_length=2048, num_layers=24, global_batch_size=32)

    def test_ranking_prefers_low_comm_low_bubble(self):
        from paddle_tpu.distributed.auto_tuner import (
            Config, rank_candidates)
        cands = [Config(dp_degree=8), Config(mp_degree=8),
                 Config(pp_degree=8, micro_batch_size=4),
                 Config(dp_degree=8, use_recompute=True)]
        ranked = rank_candidates(self.TC, cands)
        assert all(c.time_per_step_estimate is not None for c in ranked)
        # dp-only beats: recompute (extra flops), mp8 (4 ARs/layer),
        # pp8 at 8 micros (bubble + p2p)
        assert ranked[0].dp_degree == 8 and not ranked[0].use_recompute
        est = {(c.dp_degree, c.mp_degree, c.pp_degree, c.use_recompute):
               c.time_per_step_estimate for c in ranked}
        assert est[(8, 1, 1, False)] < est[(8, 1, 1, True)]
        assert est[(8, 1, 1, False)] < est[(1, 8, 1, False)]
        assert est[(8, 1, 1, False)] < est[(1, 1, 8, False)]

    def test_grid_search_orders_by_estimate(self):
        from paddle_tpu.distributed.auto_tuner import GridSearch
        tc = dict(self.TC, rank_by_cost_model=True,
                  micro_batch_size=[1], sharding_degree=[1])
        gs = GridSearch(tc)
        ests = [c.time_per_step_estimate for c in gs._all]
        assert ests == sorted(ests)

    def test_ranking_matches_two_measured_trials(self):
        """The VERDICT validation: the model's ordering agrees with two
        REAL measured CPU-mesh trials. The pair differs in pure compute
        (recompute re-runs every block forward in backward), so the
        measured signal is structural, not noise."""
        import time
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import amp
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
        from paddle_tpu.models.gpt import GPTConfig
        from paddle_tpu.optimizer import AdamW
        from paddle_tpu.distributed.auto_tuner import (
            Config, estimate_step_time)

        tc = dict(world_size=1, model_num_params=3.5e6, hidden_size=256,
                  seq_length=128, num_layers=4, global_batch_size=4)

        def build(use_recompute):
            pt.seed(5)
            cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                            num_heads=4, max_position_embeddings=128,
                            hidden_dropout_prob=0.0,
                            attention_dropout_prob=0.0,
                            recompute=use_recompute)
            m = GPTForCausalLM(cfg)
            m.train()
            opt = AdamW(learning_rate=1e-4, parameters=m.parameters())
            crit = GPTPretrainingCriterion()

            def loss_fn(mm, ids, labels):
                return crit(mm(ids), labels)

            step = TrainStep(m, opt, loss_fn)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, 512, (4, 128)).astype(np.int32)
            lbl = rng.integers(0, 512, (4, 128)).astype(np.int32)
            step(ids, lbl)
            float(step(ids, lbl).numpy())
            return step, ids, lbl

        def timed(step, ids, lbl, n=2):
            t0 = time.perf_counter()
            for _ in range(n):
                loss = step(ids, lbl)
            float(loss.numpy())
            return (time.perf_counter() - t0) / n

        # INTERLEAVED A/B over best-of-3 trial windows (min-of-6
        # each): both variants sample the same load conditions, so
        # shared-worker CPU contention cancels out of the ranking
        # (sequential trials flipped it under pytest -n 2). A window
        # whose noise spike still flipped the ordering is retried —
        # the running min over MORE interleaved samples only converges
        # toward the true ordering, and remat is structurally slower
        # (it re-runs every block forward in backward), so a window
        # that shows it decisively slower is terminal evidence while a
        # flipped one is only ever noise.
        plain = build(False)
        remat = build(True)
        measured_plain = measured_remat = float("inf")
        for _window in range(3):
            for _ in range(6):
                measured_plain = min(measured_plain, timed(*plain))
                measured_remat = min(measured_remat, timed(*remat))
            if measured_remat > measured_plain * 1.02:
                break               # decisively ordered — stop early
        est_plain = estimate_step_time(Config(use_recompute=False), tc)
        est_remat = estimate_step_time(Config(use_recompute=True), tc)
        # the model predicts remat is slower; the measurement agrees
        assert est_remat > est_plain
        assert measured_remat > measured_plain, (
            measured_plain, measured_remat)
