"""Elastic restart (ref fleet/elastic/manager.py) + auto-tuner
(ref auto_tuner/tuner.py, prune.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (
    AutoTuner, Config, default_candidates, estimate_memory_bytes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestAutoTuner:
    CFG = {
        "world_size": 8,
        "global_batch_size": 16,
        "model_num_params": 1.3e9,
        "hidden_size": 2048,
        "num_heads": 16,
        "num_layers": 24,
        "seq_length": 1024,
        "hbm_bytes": 16 * 2**30,
    }

    def test_candidates_divide_world(self):
        c = default_candidates(self.CFG)
        assert all(8 % d == 0 for d in c["dp_degree"])
        assert all(16 % m == 0 for m in c["micro_batch_size"])

    def test_prune_rules(self):
        tuner = AutoTuner(self.CFG)
        seen = []
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            seen.append(cfg)
            tuner.add_cfg(cfg)
        assert seen, "grid produced no valid configs"
        for cfg in seen:
            assert cfg.world == 8
            assert self.CFG["hidden_size"] % cfg.mp_degree == 0
            assert self.CFG["num_layers"] % cfg.pp_degree == 0
            # memory model holds for every surviving config
            assert estimate_memory_bytes(cfg, self.CFG) <= \
                0.92 * self.CFG["hbm_bytes"]

    def test_memory_model_monotone_in_sharding(self):
        base = dict(dp_degree=1, mp_degree=1, pp_degree=1,
                    sharding_degree=8, micro_batch_size=1)
        m1 = estimate_memory_bytes(Config(**base, sharding_stage=1),
                                   self.CFG)
        m2 = estimate_memory_bytes(Config(**base, sharding_stage=2),
                                   self.CFG)
        m3 = estimate_memory_bytes(Config(**base, sharding_stage=3),
                                   self.CFG)
        assert m3 < m2 < m1
        # replicated 1.3B on 16G must be pruned, stage-3 8-way must fit
        assert m1 - (m3) > 1e9

    def test_replicated_large_model_pruned(self):
        cfg = {**self.CFG, "dp_degree": [8], "mp_degree": [1],
               "pp_degree": [1], "sharding_degree": [1],
               "micro_batch_size": [1]}
        tuner = AutoTuner(cfg)
        assert tuner.search_once() is None  # 1.3B replicated > HBM
        # grid recorded nothing runnable
        assert tuner.best_cfg() is None

    def test_tune_picks_fastest_and_prunes_history(self):
        cfg = {**self.CFG, "global_batch_size": 128,
               "model_num_params": 3e8, "seq_length": 256,
               "sharding_degree": [8], "dp_degree": [1],
               "mp_degree": [1], "pp_degree": [1],
               "sharding_stage": [3],
               "micro_batch_size": [1, 2, 4, 8, 16]}
        calls = []

        def runner(c):
            calls.append(c.micro_batch_size)
            if c.micro_batch_size >= 4:
                raise MemoryError("oom")
            return 1.0 / c.micro_batch_size  # bigger mbs = faster

        best = AutoTuner(cfg).tune(runner)
        assert best is not None and best.micro_batch_size == 2
        # mbs=4 failed; 8 and 16 pruned by history without running
        assert calls == [1, 2, 4]


class TestElasticRestart:
    def test_job_restarts_until_success(self, tmp_path):
        # worker fails on the first epoch (restart_count 0), succeeds
        # after the elastic relaunch
        script = tmp_path / "flaky.py"
        script.write_text(
            "import os, sys\n"
            "rc = int(os.environ.get('PADDLE_RESTART_COUNT', '0'))\n"
            "rank = os.environ.get('PADDLE_TRAINER_ID')\n"
            "print(f'attempt={rc} rank={rank}', flush=True)\n"
            "sys.exit(0 if rc >= 1 else 7)\n")
        from paddle_tpu.distributed.launch.main import scrub_backend_env
        env = scrub_backend_env(dict(os.environ))
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        log_dir = str(tmp_path / "logs")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--max_restarts", "2",
             "--log_dir", log_dir, str(script)],
            env=env, cwd=REPO, timeout=300, capture_output=True,
            text=True)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "elastic restart 1/2" in proc.stderr
        logs = ""
        for r in (0, 1):
            with open(os.path.join(log_dir, f"workerlog.{r}")) as f:
                logs += f.read()
        assert "attempt=0" in logs and "attempt=1" in logs

    def test_restarts_exhausted_propagates_rc(self, tmp_path):
        script = tmp_path / "dead.py"
        script.write_text("import sys; sys.exit(5)\n")
        from paddle_tpu.distributed.launch.main import scrub_backend_env
        env = scrub_backend_env(dict(os.environ))
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "1", "--max_restarts", "1",
             str(script)],
            env=env, cwd=REPO, timeout=120, capture_output=True,
            text=True)
        assert proc.returncode == 5
        assert "elastic restart 1/1" in proc.stderr

    def test_negative_restarts_rejected(self, tmp_path):
        script = tmp_path / "s.py"
        script.write_text("print('hi')\n")
        from paddle_tpu.distributed.launch.main import launch
        assert launch(["--max_restarts", "-1", str(script)]) == 2
        assert launch(["--nnodes", "2", "--node_rank", "0",
                       "--master", "127.0.0.1:1", "--max_restarts", "1",
                       str(script)]) == 2

    def test_recompute_variant_not_pruned_by_dense_oom(self):
        from paddle_tpu.distributed.auto_tuner import (
            prune_by_history, Config)
        failed = Config(sharding_degree=8, micro_batch_size=2,
                        use_recompute=False, error="MemoryError: oom")
        candidate = Config(sharding_degree=8, micro_batch_size=2,
                           use_recompute=True)
        assert prune_by_history({}, candidate, [failed]) is None
        same = Config(sharding_degree=8, micro_batch_size=4,
                      use_recompute=False)
        assert prune_by_history({}, same, [failed]) is not None
