"""Embedding observability: the `paddle_tpu_embedding_*` series and
spans recorded by the host / sharded tables (README "Terabyte-scale
embeddings" metric + span tables) and the obs_top "== embedding =="
panel rendered from a snapshot document."""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_clean():
    from paddle_tpu import observability as obs
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _obs_top():
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        import obs_top
    finally:
        sys.path.remove(tools)
    return obs_top


def _train_host(tmp_path, steps=3):
    from paddle_tpu.embedding import HostEmbedding
    emb = HostEmbedding(256, 8, optimizer="adagrad", learning_rate=0.2,
                        init_std=0.05, seed=1,
                        mmap_path=str(tmp_path / "emb.bin"),
                        hot_rows=32, rows_per_page=8)
    rng = np.random.default_rng(0)
    for s in range(steps):
        ids = rng.integers(0, 256, (16,)).astype(np.int64)
        out = emb(pt.to_tensor(ids))
        out.sum().backward()
        emb.prefetch(ids)           # will be invalidated by the update
        emb.apply_updates()
    return emb


# ---------------------------------------------------------------------------
# series + spans
# ---------------------------------------------------------------------------
def test_host_embedding_series_recorded(tmp_path):
    from paddle_tpu import observability as obs
    obs.enable()
    _train_host(tmp_path)
    snap = obs.snapshot()
    rows = snap["paddle_tpu_embedding_rows_total"]["series"]
    assert rows[("lookup",)] > 0 and rows[("update",)] > 0
    for hist in ("paddle_tpu_embedding_lookup_seconds",
                 "paddle_tpu_embedding_update_seconds"):
        series = snap[hist]["series"]
        assert sum(s["count"] for s in series.values()) > 0, hist
    tier = snap["paddle_tpu_embedding_tier_rows_total"]["series"]
    assert tier.get(("hot",), 0) + tier.get(("cold",), 0) > 0
    pf = snap["paddle_tpu_embedding_prefetch_total"]["series"]
    assert pf[("invalidated",)] > 0
    # byte gauges published by the update path
    logical = snap["paddle_tpu_embedding_logical_bytes"]["series"]
    resident = snap["paddle_tpu_embedding_resident_bytes"]["series"]
    disk = snap["paddle_tpu_embedding_disk_bytes"]["series"]
    (lv,), (rv,), (dv,) = (logical.values(), resident.values(),
                           disk.values())
    assert lv > rv > 0 and dv >= 0


def test_embedding_spans_recorded(tmp_path):
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import tracing
    from paddle_tpu.embedding import (
        ShardedHostEmbedding, save_shards, resume_latest_shards)
    from paddle_tpu.embedding import HostEmbedding
    obs.enable()
    emb = ShardedHostEmbedding(128, 4, init_std=0.05, seed=1)
    ids = np.arange(64, dtype=np.int64).reshape(8, 8)
    out = emb(pt.to_tensor(ids))
    out.sum().backward()
    emb.apply_updates()
    # lookup/update spans wrap the HOST table's gather/apply (the
    # sharded exchange has its own span around the all_to_alls)
    host = HostEmbedding(32, 4, init_std=0.05, seed=1)
    hout = host(pt.to_tensor(np.arange(8, dtype=np.int64)))
    hout.sum().backward()
    host.apply_updates()
    save_shards(emb, str(tmp_path), step=1)
    resume_latest_shards(ShardedHostEmbedding(128, 4, init_std=0.05,
                                              seed=1), str(tmp_path))
    names = {e["name"] for e in tracing.events()}
    for want in ("embedding.lookup", "embedding.exchange",
                 "embedding.update", "embedding.shard_save",
                 "embedding.shard_restore"):
        assert want in names, (want, sorted(names))


def test_disabled_records_nothing(tmp_path):
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import tracing
    _train_host(tmp_path)           # obs disabled by the fixture
    rec = obs.snapshot().get("paddle_tpu_embedding_rows_total")
    if rec is not None:             # registered by an earlier test
        assert all(v == 0 for v in rec["series"].values())
    assert tracing.events() == []


# ---------------------------------------------------------------------------
# obs_top "== embedding ==" panel
# ---------------------------------------------------------------------------
def test_obs_top_embedding_panel_renders(tmp_path):
    from paddle_tpu import observability as obs
    obs.enable()
    prev = json.loads(obs.to_json())
    _train_host(tmp_path)
    doc = json.loads(obs.to_json())
    frame = _obs_top().render(doc, prev, dt=1.0)
    assert "== embedding ==" in frame
    lines = {ln.strip().split()[0]: ln for ln in frame.splitlines()
             if ln.strip()}
    assert "p50=" in lines["lookup"] and "rows/s" in lines["lookup"]
    assert "rows=" in lines["update"]
    assert "hit=" in lines["tier"] and "evictions=" in lines["tier"]
    assert "invalidated=" in lines["prefetch"]
    assert "logical=" in lines["bytes"] and "resident=" in lines["bytes"]


def test_obs_top_sharded_exchange_line(tmp_path):
    from paddle_tpu import observability as obs
    from paddle_tpu.embedding import ShardedHostEmbedding
    obs.enable()
    emb = ShardedHostEmbedding(128, 4, init_std=0.05, seed=1)
    ids = np.arange(64, dtype=np.int64).reshape(8, 8)
    out = emb(pt.to_tensor(ids))
    out.sum().backward()
    emb.apply_updates()
    frame = _obs_top().render(json.loads(obs.to_json()))
    line = [ln for ln in frame.splitlines()
            if ln.strip().startswith("exchange")][0]
    assert "ids=" in line and "rows=" in line and "grads=" in line
    assert "pad=" in line


def test_obs_top_no_embedding_series_no_panel():
    assert "== embedding ==" not in _obs_top().render({})
