"""Process-row-sharded host embedding
(paddle_tpu/embedding/sharded.py + checkpoint.py) on the 8-virtual-
device CPU mesh: the unique-id all_to_all exchange matches the
unsharded table exactly, training over real collectives descends,
comms telemetry prices every exchange, and the per-shard checkpoints
are crash-safe — round-trip bit-exact, reshard on process-count
change, skip torn steps, and survive a hard kill (real subprocess,
os._exit mid-save) with bit-exact resume.

Module-level imports stay light for the subprocess test (the child
re-execs python with its own env guard)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, DIM, G = 512, 4, 8


def _mk(n=N, dim=DIM, **kw):
    from paddle_tpu.embedding import ShardedHostEmbedding
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.2)
    kw.setdefault("init_std", 0.05)
    kw.setdefault("seed", 3)
    return ShardedHostEmbedding(n, dim, **kw)


def _data(steps=4, per=16, seed=0, n=N, dim=DIM):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n, (steps, G, per)).astype(np.int64)
    tgt = rng.standard_normal((G, per, dim)).astype(np.float32)
    return ids, tgt


def _step(emb, ids, tgt):
    out = emb(pt.to_tensor(ids))
    loss = ((out - pt.to_tensor(tgt)) ** 2).mean()
    loss.backward()
    emb.apply_updates()
    return float(loss.numpy())


def _row_values(emb):
    """{global id -> (value row, acc row)} for every materialized row."""
    out = {}
    for k, sh in enumerate(emb.shards):
        local = np.flatnonzero(sh._init_mask)
        vals = sh._store.read(local)
        acc = sh._acc_store.read(local) \
            if sh._acc_store is not None else vals
        for i, r in enumerate(local):
            out[int(r) * emb.nshards + k] = (vals[i], acc[i])
    return out


# ---------------------------------------------------------------------------
# exchange correctness vs the unsharded table
# ---------------------------------------------------------------------------
def test_sharded_forward_matches_unsharded_exactly():
    from paddle_tpu.embedding import HostEmbedding
    emb = _mk()
    ref = HostEmbedding(N, DIM, optimizer="adagrad", learning_rate=0.2,
                        init_std=0.05, seed=3)
    ids, _ = _data(steps=1)
    a = emb(pt.to_tensor(ids[0])).numpy()
    b = ref(pt.to_tensor(ids[0])).numpy()
    np.testing.assert_array_equal(a, b)
    # device footprint is O(sum of per-worker unique rows)
    total_u = sum(np.unique(ids[0][w]).size for w in range(G))
    assert emb.stats["device_bytes_last"] == total_u * DIM * 4


def test_sharded_training_matches_unsharded():
    from paddle_tpu.embedding import HostEmbedding
    emb = _mk()
    ref = HostEmbedding(N, DIM, optimizer="adagrad", learning_rate=0.2,
                        init_std=0.05, seed=3)
    ids, tgt = _data(steps=3)
    for s in range(3):
        la = _step(emb, ids[s], tgt)
        lb = _step(ref, ids[s], tgt)
        np.testing.assert_allclose(la, lb, rtol=1e-5)
    touched = np.unique(ids)
    sharded = _row_values(emb)
    # duplicate-id grads across workers sum in a different float order
    # than the unsharded gather vjp: allclose, not equal
    np.testing.assert_allclose(
        np.stack([sharded[int(g)][0] for g in touched]),
        ref.table[touched], rtol=1e-5, atol=1e-7)


def test_sharded_training_reduces_loss():
    emb = _mk()
    rng = np.random.default_rng(1)
    # distinct ids -> no conflicting targets, loss can go to ~0
    ids = rng.choice(N, size=(G, 16), replace=False).astype(np.int64)
    tgt = rng.standard_normal((G, 16, DIM)).astype(np.float32)
    first = _step(emb, ids, tgt)
    for _ in range(15):
        last = _step(emb, ids, tgt)
    assert last < first * 0.2, (first, last)


def test_rank_major_shape_enforced():
    emb = _mk()
    with pytest.raises(ValueError, match="rank-major"):
        emb(pt.to_tensor(np.zeros((G - 1, 4), np.int64)))
    with pytest.raises(IndexError):
        emb(pt.to_tensor(np.full((G, 2), N, np.int64)))


def test_num_embeddings_capped_at_int32_ids():
    with pytest.raises(ValueError, match="2\\*\\*31"):
        _mk(n=(1 << 31) + 1)


def test_exchange_telemetry_and_pad_fraction():
    from paddle_tpu import observability as obs
    obs.reset()
    obs.enable()
    try:
        emb = _mk()
        ids, tgt = _data(steps=1)
        _step(emb, ids[0], tgt)
        snap = obs.snapshot()
        xb = snap["paddle_tpu_embedding_exchange_bytes_total"]["series"]
        for payload in ("ids", "rows", "grads"):
            assert xb[(payload,)] > 0, payload
        pad = snap["paddle_tpu_embedding_exchange_pad_fraction"]["series"]
        (pad_val,) = pad.values()
        assert 0.0 <= pad_val < 1.0
        assert 0.0 <= emb.stats["exchange_pad_last"] < 1.0
        # the comms plane priced the exchanges for free
        launches = snap["paddle_tpu_collective_launches_total"]["series"]
        a2a = sum(v for k, v in launches.items() if "all_to_all" in k)
        # 3 lookup all_to_alls + 1 grad all_to_all per step
        assert a2a >= 4
    finally:
        obs.disable()
        obs.reset()


def test_sharded_mmap_tier_matches_ram_tier(tmp_path):
    ram = _mk()
    mm = _mk(mmap_dir=str(tmp_path / "shards"), hot_rows=32,
             rows_per_page=8)
    ids, tgt = _data(steps=2)
    for s in range(2):
        la = _step(ram, ids[s], tgt)
        lb = _step(mm, ids[s], tgt)
        np.testing.assert_array_equal(la, lb)
    a, b = _row_values(ram), _row_values(mm)
    assert a.keys() == b.keys()
    for g in a:
        np.testing.assert_array_equal(a[g][0], b[g][0])
    assert mm.resident_bytes() < mm.host_bytes()
    mm.flush()
    assert mm.disk_bytes() > 0


# ---------------------------------------------------------------------------
# per-shard checkpoints
# ---------------------------------------------------------------------------
def test_checkpoint_round_trip_bit_exact(tmp_path):
    from paddle_tpu.embedding import save_shards, resume_latest_shards
    emb = _mk()
    ids, tgt = _data(steps=2)
    for s in range(2):
        _step(emb, ids[s], tgt)
    save_shards(emb, str(tmp_path), step=2)
    fresh = _mk()
    got = resume_latest_shards(fresh, str(tmp_path))
    assert got is not None and got.endswith("step_2")
    a, b = _row_values(emb), _row_values(fresh)
    assert a.keys() == b.keys()
    for g in a:
        np.testing.assert_array_equal(a[g][0], b[g][0])
        np.testing.assert_array_equal(a[g][1], b[g][1])   # adagrad acc


def test_resume_reshards_8_to_4(tmp_path):
    """A table saved by 8 shard owners restores onto 4: rows are keyed
    by GLOBAL id, so the scatter lands them at their new owners with
    bit-exact values, and untouched rows still lazy-init identically."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.embedding import (
        HostEmbedding, save_shards, resume_latest_shards)
    emb8 = _mk()
    ids, tgt = _data(steps=2)
    for s in range(2):
        _step(emb8, ids[s], tgt)
    save_shards(emb8, str(tmp_path), step=2)

    g4 = dist.new_group(ranks=[0, 1, 2, 3])
    emb4 = _mk(group=g4)
    assert emb4.nshards == 4
    got = resume_latest_shards(emb4, str(tmp_path))
    assert got is not None and got.endswith("step_2")
    a, b = _row_values(emb8), _row_values(emb4)
    assert a.keys() == b.keys()
    for g in a:
        np.testing.assert_array_equal(a[g][0], b[g][0])
        np.testing.assert_array_equal(a[g][1], b[g][1])
    # a row nobody ever touched lazy-inits to the unsharded stream on
    # the NEW sharding too
    untouched = [g for g in range(N) if g not in a][:3]
    ref = HostEmbedding(N, DIM, init_std=0.05, seed=3)
    want = ref(pt.to_tensor(np.asarray(untouched, np.int64))).numpy()
    for i, g in enumerate(untouched):
        sh = emb4.shards[g % 4]
        got_row = sh.read_rows(np.array([g // 4], np.int64))[0]
        np.testing.assert_array_equal(got_row, want[i])


def test_resume_skips_torn_step(tmp_path):
    """A crash mid-save tears at most the step being written: resume
    falls back to the previous step whose full shard set verifies."""
    import shutil
    from paddle_tpu.embedding import save_shards, resume_latest_shards
    emb = _mk()
    ids, tgt = _data(steps=2)
    _step(emb, ids[0], tgt)
    save_shards(emb, str(tmp_path), step=1)
    vals_at_1 = _row_values(emb)
    _step(emb, ids[1], tgt)
    step2 = save_shards(emb, str(tmp_path), step=2)
    # tear step 2: one shard dir vanished mid-crash
    shutil.rmtree(os.path.join(step2, sorted(os.listdir(step2))[0]))
    fresh = _mk()
    got = resume_latest_shards(fresh, str(tmp_path))
    assert got is not None and got.endswith("step_1")
    b = _row_values(fresh)
    assert vals_at_1.keys() == b.keys()
    for g in vals_at_1:
        np.testing.assert_array_equal(vals_at_1[g][0], b[g][0])


def test_resume_empty_root_returns_none(tmp_path):
    from paddle_tpu.embedding import resume_latest_shards
    assert resume_latest_shards(_mk(), str(tmp_path / "none")) is None


# ---------------------------------------------------------------------------
# the real crash boundary: hard-killed trainer, bit-exact resume
# ---------------------------------------------------------------------------
_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()
import numpy as np
import paddle_tpu as pt
from paddle_tpu.embedding import ShardedHostEmbedding, save_shards
from paddle_tpu.embedding.checkpoint import _shard_dir
from paddle_tpu.distributed import checkpoint as dckpt

root = sys.argv[1]
emb = ShardedHostEmbedding(512, 4, optimizer="adagrad",
                           learning_rate=0.2, init_std=0.05, seed=3)
rng = np.random.default_rng(0)
ids = rng.integers(0, 512, (4, 8, 16)).astype(np.int64)
tgt = rng.standard_normal((8, 16, 4)).astype(np.float32)
for s in range(2):
    out = emb(pt.to_tensor(ids[s]))
    ((out - pt.to_tensor(tgt)) ** 2).mean().backward()
    emb.apply_updates()
save_shards(emb, root, step=2)
out = emb(pt.to_tensor(ids[2]))
((out - pt.to_tensor(tgt)) ** 2).mean().backward()
emb.apply_updates()
# begin saving step 3 but die after ONE shard: a torn step on disk
sh = emb.shards[0]
local = np.flatnonzero(sh._init_mask)
state = {"rows": (local * 8).astype(np.int64),
         "values": sh._store.read(local),
         "acc": sh._acc_store.read(local),
         "shard_meta": np.asarray([0, 8, 512, 4], np.int64)}
dckpt.save_state_dict(state, _shard_dir(os.path.join(root, "step_3"), 0, 8))
os._exit(1)   # hard kill: no flush, no cleanup, no atexit
"""


def test_hard_killed_trainer_resumes_bit_exact(tmp_path):
    """A real subprocess trains 3 steps, checkpoints after step 2,
    starts (and tears) the step-3 save, and hard-exits. Resume in this
    process lands on step 2 bit-exact against an uninterrupted
    reference, and training continues to the same final state."""
    from paddle_tpu.embedding import resume_latest_shards
    root = str(tmp_path / "ckpt")
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, root],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1, proc.stderr[-2000:]
    assert os.path.isdir(os.path.join(root, "step_3"))   # torn remains

    # reference: the same schedule uninterrupted, in this process
    ref = _mk()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 512, (4, G, 16)).astype(np.int64)
    tgt = rng.standard_normal((G, 16, DIM)).astype(np.float32)
    for s in range(2):
        _step(ref, ids[s], tgt)
    resumed = _mk()
    got = resume_latest_shards(resumed, root)
    assert got is not None and got.endswith("step_2")
    a, b = _row_values(ref), _row_values(resumed)
    assert a.keys() == b.keys()
    for g in a:
        np.testing.assert_array_equal(a[g][0], b[g][0])
        np.testing.assert_array_equal(a[g][1], b[g][1])
    # continue past the crash point: the resumed trainer tracks the
    # uninterrupted one bit-exactly
    for s in range(2, 4):
        la = _step(ref, ids[s], tgt)
        lb = _step(resumed, ids[s], tgt)
        assert la == lb, (s, la, lb)
    a, b = _row_values(ref), _row_values(resumed)
    for g in a:
        np.testing.assert_array_equal(a[g][0], b[g][0])


# ---------------------------------------------------------------------------
# back-compat: the old import path still works
# ---------------------------------------------------------------------------
def test_ps_shim_reexports():
    from paddle_tpu.distributed import ps
    from paddle_tpu import embedding
    assert ps.HostEmbedding is embedding.HostEmbedding
    assert ps.ShardedEmbedding is embedding.ShardedEmbedding
