"""Tiered row storage for host embedding tables
(paddle_tpu/embedding/store.py): the mmap disk tier's hot-page LRU,
dirty write-back on eviction, honest three-valued byte accounting
(logical / resident / disk), reopen-in-place durability, and
RAM-vs-mmap tier equivalence of the full HostEmbedding training
loop (the acceptance bullet: a larger-than-RAM-budget table serves
bit-identical lookups with `resident_bytes() < host_bytes()`)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.embedding.store import MmapRowStore, RamRowStore
from paddle_tpu.embedding import HostEmbedding


# ---------------------------------------------------------------------------
# MmapRowStore: pages, LRU, write-back
# ---------------------------------------------------------------------------
def test_mmap_read_write_round_trip(tmp_path):
    st = MmapRowStore(100, 4, np.float32, str(tmp_path / "t.bin"),
                      hot_rows=1000, rows_per_page=10)
    rows = np.array([3, 57, 99], np.int64)
    vals = np.arange(12, dtype=np.float32).reshape(3, 4)
    st.write(rows, vals)
    np.testing.assert_array_equal(st.read(rows), vals)
    # untouched rows read as zeros (sparse file holes)
    assert not st.read(np.array([50], np.int64)).any()


def test_mmap_lru_evicts_and_flushes_dirty_pages(tmp_path):
    # capacity: 2 pages of 10 rows
    st = MmapRowStore(100, 4, np.float32, str(tmp_path / "t.bin"),
                      hot_rows=20, rows_per_page=10)
    for p in range(5):                      # touch 5 distinct pages
        st.write(np.array([p * 10], np.int64),
                 np.full((1, 4), float(p + 1), np.float32))
    assert len(st._hot) == 2                # bounded resident set
    assert st.evictions == 3
    # evicted dirty pages were flushed to the backing file: the rows
    # written to pages 0..2 survive re-promotion
    for p in range(3):
        np.testing.assert_array_equal(
            st.read(np.array([p * 10], np.int64)),
            np.full((1, 4), float(p + 1), np.float32))


def test_mmap_byte_accounting(tmp_path):
    st = MmapRowStore(10_000, 8, np.float32, str(tmp_path / "t.bin"),
                      hot_rows=100, rows_per_page=10)
    assert st.host_bytes() == 10_000 * 8 * 4        # logical, always
    assert st.resident_bytes() == 0                 # nothing promoted
    st.write(np.arange(10), np.ones((10, 8), np.float32))
    assert st.resident_bytes() == 10 * 8 * 4        # one hot page
    assert st.resident_bytes() < st.host_bytes()
    st.flush()
    # sparse backing file: only the touched page costs disk blocks
    assert 0 < st.disk_bytes() < st.host_bytes()


def test_mmap_reopen_in_place_sees_flushed_bytes(tmp_path):
    path = str(tmp_path / "t.bin")
    st = MmapRowStore(50, 4, np.float32, path, rows_per_page=10)
    st.write(np.array([7]), np.full((1, 4), 3.5, np.float32))
    st.flush()
    del st
    st2 = MmapRowStore(50, 4, np.float32, path, rows_per_page=10)
    np.testing.assert_array_equal(
        st2.read(np.array([7])),
        np.full((1, 4), 3.5, np.float32))


def test_tier_counters_hot_vs_cold(tmp_path):
    from paddle_tpu import observability as obs
    obs.reset()
    obs.enable()
    try:
        st = MmapRowStore(100, 4, np.float32, str(tmp_path / "t.bin"),
                          hot_rows=1000, rows_per_page=10)
        st.read(np.array([1, 2, 11], np.int64))     # 2 pages faulted
        st.read(np.array([1, 2, 11], np.int64))     # all resident now
        rec = obs.snapshot()["paddle_tpu_embedding_tier_rows_total"]
        assert rec["series"][("cold",)] == 3
        assert rec["series"][("hot",)] == 3
    finally:
        obs.disable()
        obs.reset()


def test_ram_store_is_all_resident():
    st = RamRowStore(100, 4, np.float32)
    assert st.resident_bytes() == st.host_bytes() == 100 * 4 * 4
    assert st.disk_bytes() == 0


# ---------------------------------------------------------------------------
# HostEmbedding on the mmap tier: tier-equivalence of training
# ---------------------------------------------------------------------------
def _train_steps(emb, ids_seq, target):
    losses = []
    for ids in ids_seq:
        out = emb(pt.to_tensor(ids))
        loss = ((out - pt.to_tensor(target)) ** 2).mean()
        loss.backward()
        emb.apply_updates()
        losses.append(float(loss.numpy()))
    return losses


def test_mmap_tier_matches_ram_tier_bit_exact(tmp_path):
    """The acceptance contract: an mmap-tier table whose hot budget is
    far below the table size serves lookups and applies updates
    bit-identically to the all-RAM tier, while actually pinning less
    RAM than the logical table size."""
    rng = np.random.default_rng(3)
    n, dim = 5000, 8
    ids_seq = [rng.integers(0, n, (16,)).astype(np.int64)
               for _ in range(6)]
    target = rng.standard_normal((16, dim)).astype(np.float32)

    ram = HostEmbedding(n, dim, optimizer="adagrad", learning_rate=0.2,
                        init_std=0.05, seed=11)
    mm = HostEmbedding(n, dim, optimizer="adagrad", learning_rate=0.2,
                       init_std=0.05, seed=11,
                       mmap_path=str(tmp_path / "emb.bin"),
                       hot_rows=64, rows_per_page=8)
    l_ram = _train_steps(ram, ids_seq, target)
    l_mm = _train_steps(mm, ids_seq, target)
    np.testing.assert_array_equal(l_ram, l_mm)
    touched = np.unique(np.concatenate(ids_seq))
    np.testing.assert_array_equal(ram.table[touched],
                                  mm._store.read(touched))
    # honest accounting: the mmap tier holds less than the logical
    # table in RAM, and the backing file has real blocks after flush
    assert mm.resident_bytes() < mm.host_bytes()
    assert mm.host_bytes() == ram.host_bytes()      # same logical size
    mm.flush()
    assert mm.disk_bytes() > 0
    assert ram.disk_bytes() == 0


def test_mmap_tier_lazy_init_matches_ram(tmp_path):
    """Deterministic lazy init is tier-independent: first touches on
    the mmap tier produce the same rows as the RAM tier even though
    the pages round-trip through the LRU."""
    ram = HostEmbedding(200, 4, init_std=0.1, seed=7)
    mm = HostEmbedding(200, 4, init_std=0.1, seed=7,
                       mmap_path=str(tmp_path / "e.bin"),
                       hot_rows=8, rows_per_page=4)
    ids = np.array([0, 3, 150, 199], np.int64)
    a = ram(pt.to_tensor(ids)).numpy()
    b = mm(pt.to_tensor(ids)).numpy()
    np.testing.assert_array_equal(a, b)


def test_mmap_table_alias_is_none(tmp_path):
    """The back-compat `emb.table` full-array alias only exists for
    the all-RAM tier; the mmap tier has no single resident array."""
    mm = HostEmbedding(100, 4, mmap_path=str(tmp_path / "e.bin"))
    assert mm.table is None and mm._acc is None
    ram = HostEmbedding(100, 4)
    assert ram.table is not None


def test_out_of_range_raises_on_mmap_tier(tmp_path):
    emb = HostEmbedding(10, 2, mmap_path=str(tmp_path / "e.bin"))
    with pytest.raises(IndexError):
        emb(pt.to_tensor(np.array([10], np.int64)))
