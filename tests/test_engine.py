"""Auto-parallel Engine facade + to_static limitation detection
(VERDICT r1 items 6/8/10)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_parallel.engine import Engine

RNG = np.random.default_rng(0)


def _data(n=32, din=8, dout=4):
    xs = RNG.standard_normal((n, din)).astype(np.float32)
    w = RNG.standard_normal((din, dout)).astype(np.float32)
    ys = xs @ w + 0.01 * RNG.standard_normal((n, dout)).astype(np.float32)
    return xs, ys


class TestEngine:
    def _engine(self):
        pt.seed(0)
        model = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                                 pt.nn.Linear(16, 4))
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        loss = lambda out, y: pt.ops.mean((out - y) ** 2)  # noqa: E731
        return Engine(model=model, loss=loss, optimizer=opt), model

    def test_fit_reduces_loss(self):
        eng, _ = self._engine()
        hist = eng.fit(_data(), batch_size=8, epochs=8, verbose=0)
        first = np.mean(hist["loss"][0])
        last = np.mean(hist["loss"][-1])
        assert last < first * 0.7, (first, last)

    def test_evaluate_returns_loss(self):
        eng, _ = self._engine()
        eng.fit(_data(), batch_size=8, epochs=2, verbose=0)
        res = eng.evaluate(_data(n=16), batch_size=8, verbose=0)
        assert np.isfinite(res["loss"])

    def test_predict_shapes(self):
        eng, _ = self._engine()
        xs, _ = _data(n=10)
        outs = eng.predict((xs, np.zeros((10, 4), np.float32)),
                           batch_size=4, verbose=0)
        total = sum(o.shape[0] for o in outs)
        assert total == 10
        assert all(o.shape[1] == 4 for o in outs)

    def test_fit_with_validation(self):
        eng, _ = self._engine()
        hist = eng.fit(_data(), valid_data=_data(n=16), batch_size=8,
                       epochs=2, verbose=0)
        assert len(hist["loss"]) == 2

    def test_save_load_roundtrip(self, tmp_path):
        eng, model = self._engine()
        eng.fit(_data(), batch_size=8, epochs=1, verbose=0)
        w0 = {k: v.numpy().copy() for k, v in model.state_dict().items()}
        eng.save(str(tmp_path / "ckpt"))
        # perturb then load back
        for p in model.parameters():
            p._data = p._data + 1.0
        eng.load(str(tmp_path / "ckpt"))
        for k, v in model.state_dict().items():
            np.testing.assert_allclose(v.numpy(), w0[k], rtol=1e-6)

    def test_main_program_unsupported(self):
        eng, _ = self._engine()
        with pytest.raises(NotImplementedError, match="Program IR"):
            eng.main_program


class TestToStaticLimitationDetection:
    def test_data_dependent_branch_reports(self):
        @pt.jit.to_static
        def f(x):
            if x.sum() > 0:   # data-dependent python branch
                return x * 2
            return x - 1

        with pytest.raises(RuntimeError,
                           match="data-dependent Python control flow"):
            f(pt.to_tensor(np.ones(4, np.float32)))

    def test_value_branch_free_code_stages_fine(self):
        @pt.jit.to_static
        def g(x):
            return pt.ops.where(x > 0, x * 2, x - 1)

        out = g(pt.to_tensor(np.array([-1.0, 2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [-2.0, 4.0])


class TestEngineReviewRegressions:
    def test_eval_then_fit_still_trains(self):
        # code-review r2: evaluate-first must not permanently detach
        # the optimizer from the train path
        pt.seed(0)
        model = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                                 pt.nn.Linear(16, 4))
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        eng = Engine(model=model,
                     loss=lambda o, y: pt.ops.mean((o - y) ** 2),
                     optimizer=opt)
        eng.evaluate(_data(n=8), batch_size=8, verbose=0)
        hist = eng.fit(_data(), batch_size=8, epochs=8, verbose=0)
        assert np.mean(hist["loss"][-1]) < np.mean(hist["loss"][0]) * 0.7

    def test_probe_refires_after_caught_error(self):
        @pt.jit.to_static
        def f(x):
            if x.sum() > 0:
                return x * 2
            return x - 1

        for _ in range(2):  # second call must re-detect, not miscompile
            with pytest.raises(RuntimeError,
                               match="data-dependent"):
                f(pt.to_tensor(np.ones(4, np.float32)))

    def test_full_graph_false_keeps_eager_branching(self):
        @pt.jit.to_static(full_graph=False)
        def f(x):
            if float(x.sum()) > 0:
                return x * 2
            return x - 1

        out = f(pt.to_tensor(np.ones(2, np.float32)))
        np.testing.assert_allclose(out.numpy(), [2.0, 2.0])

    def test_load_invalidates_live_step(self, tmp_path):
        # code-review r2: loaded weights must not be clobbered by a
        # stale TrainStep sync on the next evaluate/fit
        pt.seed(0)
        model = pt.nn.Sequential(pt.nn.Linear(8, 4))
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
        eng = Engine(model=model,
                     loss=lambda o, y: pt.ops.mean((o - y) ** 2),
                     optimizer=opt)
        eng.fit(_data(din=8, dout=4), batch_size=8, epochs=1, verbose=0)
        eng.save(str(tmp_path / "c"))
        w_saved = {k: v.numpy().copy()
                   for k, v in model.state_dict().items()}
        eng.fit(_data(din=8, dout=4), batch_size=8, epochs=2, verbose=0)
        eng.load(str(tmp_path / "c"))
        eng.evaluate(_data(n=8, din=8, dout=4), batch_size=8, verbose=0)
        for k, v in model.state_dict().items():
            np.testing.assert_allclose(v.numpy(), w_saved[k], rtol=1e-6,
                                       err_msg=k)

    def test_tuple_pair_vs_list_batches(self):
        eng, _ = TestEngine()._engine()
        xs, ys = _data(n=16)
        hist = eng.fit((xs, ys), batch_size=8, epochs=1, verbose=0)
        assert len(hist["loss"][0]) == 2
        # a LIST is a pre-batched stream, never a pair
        eng2, _ = TestEngine()._engine()
        batches = [(xs[:8], ys[:8]), (xs[8:], ys[8:])]
        hist2 = eng2.fit(batches, epochs=1, verbose=0)
        assert len(hist2["loss"][0]) == 2
