"""Persistent executable cache (ISSUE 18): AOT-serialized engine
executables keyed by structural fingerprints — save/load round trips,
the degrade-to-compile contract on corrupt/torn/foreign entries, the
atomic-write discipline, the operator CLI, and the CompileTimed
disk_hit telemetry that makes warm reintegration observable."""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.inference import exec_cache as ec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_clean():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _tiny_compiled(mul=2.0):
    import jax
    import jax.numpy as jnp

    def f(a):
        return (a * mul).sum()

    a = jnp.arange(16, dtype=jnp.float32)
    return jax.jit(f).lower(a).compile(), a


def _key(tag):
    return ec.fingerprint({"test": tag, "code": ec.code_fingerprint()})


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------
class TestFingerprints:
    def test_fingerprint_stable_and_order_free(self):
        a = ec.fingerprint({"b": 2, "a": (1, "x")})
        b = ec.fingerprint({"a": [1, "x"], "b": 2})
        assert a == b and len(a) == 64

    def test_fingerprint_distinguishes_values(self):
        assert ec.fingerprint({"a": 1}) != ec.fingerprint({"a": 2})
        # 1 vs 1.0 vs True are DIFFERENT compile signatures
        assert ec.fingerprint({"a": 1}) != ec.fingerprint({"a": 1.0})

    def test_fingerprint_rejects_unstable_components(self):
        class Opaque:
            pass
        with pytest.raises(TypeError):
            ec.fingerprint({"a": Opaque()})

    def test_device_fingerprint_carries_topology(self):
        fp = ec.device_fingerprint()
        assert fp["platform"] and fp["jax"]
        assert fp["n_local_devices"] >= 1

    def test_code_fingerprint_cached_and_hexy(self):
        a = ec.code_fingerprint()
        assert a == ec.code_fingerprint() and len(a) == 64


# ---------------------------------------------------------------------------
# store round trip + degradation contract
# ---------------------------------------------------------------------------
class TestExecCacheStore:
    def test_save_load_round_trip(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        compiled, a = _tiny_compiled()
        key = _key("round")
        assert store.save(key, compiled, family="t_fam")
        got = store.load(key)
        assert got is not None
        np.testing.assert_allclose(np.asarray(got(a)),
                                   np.asarray(compiled(a)))
        assert store.stats()["saves"] == 1
        assert store.stats()["hits"] == 1

    def test_missing_key_is_silent_miss(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        assert store.load(_key("absent")) is None
        assert store.stats()["misses"] == 1

    def test_corrupt_payload_refused(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        key = _key("corrupt")
        store.save(key, compiled, family="t_fam")
        # bit rot: flip bytes mid-payload; the manifest hash check
        # must refuse the entry and degrade to a miss, never raise
        payload = tmp_path / (key + ".exec")
        blob = bytearray(payload.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        payload.write_bytes(bytes(blob))
        ok, why = store.verify(key)
        assert not ok and "corrupt" in why
        assert store.load(key) is None
        assert store.stats()["corrupt"] == 1

    def test_torn_write_refused(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        key = _key("torn")
        store.save(key, compiled, family="t_fam")
        payload = tmp_path / (key + ".exec")
        payload.write_bytes(payload.read_bytes()[:10])
        ok, why = store.verify(key)
        assert not ok
        assert store.load(key) is None

    def test_foreign_topology_refused(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        key = _key("foreign")
        dev = ec.device_fingerprint()
        store.save(key, compiled, family="t_fam", device=dev)
        other = dict(dev, n_local_devices=dev["n_local_devices"] + 8,
                     mesh_axes=["mp"], mesh_shape=[4])
        ok, why = store.verify(key, device=other)
        assert not ok and "foreign" in why
        assert store.load(key, device=other) is None
        assert store.stats()["foreign"] == 1
        # the matching topology still hits
        assert store.load(key, device=dev) is not None

    def test_entries_and_remove(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        k1, k2 = _key("e1"), _key("e2")
        store.save(k1, compiled, family="fam_a")
        store.save(k2, compiled, family="fam_b")
        recs = {r["key"]: r for r in store.entries()}
        assert set(recs) == {k1, k2}
        assert recs[k1]["family"] == "fam_a"
        assert recs[k1]["payload_bytes"] > 0
        store.remove(k1)
        assert store.keys() == [k2]

    def test_prune_by_age_and_size(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        keys = [_key("p%d" % i) for i in range(3)]
        for k in keys:
            store.save(k, compiled, family="t_fam")
        # age out the first entry by back-dating its manifest
        man = tmp_path / (keys[0] + ".json")
        rec = json.loads(man.read_text())
        rec["created_unix"] -= 10 * 86400
        man.write_text(json.dumps(rec))
        removed = store.prune(max_age_s=86400.0)
        assert removed == [keys[0]]
        # size cap: keep only what fits (one entry's worth)
        one = store.entries()[0]["payload_bytes"]
        removed = store.prune(max_bytes=one)
        assert len(store.keys()) == 1

    def test_prune_reaps_stale_staging_files(self, tmp_path):
        store = ec.ExecCache(str(tmp_path))
        stale = tmp_path / ".tmp-1234-deadbeef"
        stale.write_bytes(b"partial")
        old = os.path.getmtime(stale) - 7200
        os.utime(stale, (old, old))
        store.prune()
        assert not stale.exists()


# ---------------------------------------------------------------------------
# CompileTimed integration: outcome telemetry + stale-entry fallback
# ---------------------------------------------------------------------------
class TestCompileTimedStore:
    def _outcomes(self):
        # series keys are (family, outcome) label tuples
        return obs.snapshot().get("paddle_tpu_compile_total",
                                  {"series": {}})["series"]

    def test_cold_compile_saves_then_warm_disk_hit(self, tmp_path):
        import jax
        from paddle_tpu.observability import perf
        obs.enable()
        store = ec.ExecCache(str(tmp_path))
        key = _key("ct")
        fn = perf.CompileTimed(jax.jit(lambda a: (a * 2).sum()),
                               "t_store_fam", store=store,
                               store_key=key)
        a = np.arange(8, dtype=np.float32)
        cold = np.asarray(fn(a))
        assert store.stats()["saves"] == 1
        # a FRESH CompileTimed (new process stand-in) must come up
        # from disk: outcome=disk_hit, no second compile
        fn2 = perf.CompileTimed(jax.jit(lambda a: (a * 2).sum()),
                                "t_store_fam2", store=store,
                                store_key=key)
        warm = np.asarray(fn2(a))
        np.testing.assert_allclose(cold, warm)
        comp = self._outcomes()
        assert comp[("t_store_fam", "compile")] == 1
        assert comp[("t_store_fam2", "disk_hit")] == 1
        assert ("t_store_fam2", "compile") not in comp
        assert store.stats()["hits"] == 1

    def test_stale_signature_discards_and_recompiles(self, tmp_path):
        import jax
        from paddle_tpu.observability import perf
        obs.enable()
        store = ec.ExecCache(str(tmp_path))
        key = _key("stale")
        fn = perf.CompileTimed(jax.jit(lambda a: (a * 2).sum()),
                               "t_stale_a", store=store, store_key=key)
        fn(np.arange(8, dtype=np.float32))
        # same key, DIFFERENT call signature: the disk entry's first
        # call fails, is discarded, and the same call compiles fresh
        fn2 = perf.CompileTimed(
            jax.jit(lambda a, b: (a * b).sum()), "t_stale_b",
            store=store, store_key=key)
        out = np.asarray(fn2(np.arange(4, dtype=np.float32),
                             np.arange(4, dtype=np.float32)))
        np.testing.assert_allclose(out, float((np.arange(4) ** 2).sum()))
        comp = self._outcomes()
        assert comp[("t_stale_b", "compile")] == 1
        assert ("t_stale_b", "disk_hit") not in comp


# ---------------------------------------------------------------------------
# operator CLI
# ---------------------------------------------------------------------------
class TestExecCacheCLI:
    def _cli(self):
        tools = os.path.join(REPO, "tools")
        sys.path.insert(0, tools)
        try:
            import exec_cache as cli
        finally:
            sys.path.remove(tools)
        return cli

    def _seed(self, tmp_path, n=2):
        store = ec.ExecCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        keys = [_key("cli%d" % i) for i in range(n)]
        for k in keys:
            store.save(k, compiled, family="t_cli")
        return store, keys

    def test_list(self, tmp_path, capsys):
        self._seed(tmp_path)
        cli = self._cli()
        assert cli.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "2 entries" in out and "t_cli" in out

    def test_verify_flags_corruption(self, tmp_path, capsys):
        _, keys = self._seed(tmp_path)
        cli = self._cli()
        assert cli.main([str(tmp_path), "--verify"]) == 0
        payload = tmp_path / (keys[0] + ".exec")
        payload.write_bytes(b"rotten")
        assert cli.main([str(tmp_path), "--verify"]) == 1
        out = capsys.readouterr().out
        assert "BAD" in out

    def test_prune_and_json(self, tmp_path, capsys):
        self._seed(tmp_path)
        cli = self._cli()
        assert cli.main([str(tmp_path), "--prune",
                         "--max-bytes", "0"]) == 0
        assert cli.main([str(tmp_path), "--json"]) == 0
        out = capsys.readouterr().out
        doc = json.loads(out.splitlines()[-1].strip() or "{}") \
            if out.strip().startswith("{") else json.loads(
                out[out.index("{"):])
        assert doc["entries"] == []

    def test_missing_dir_errors(self, tmp_path):
        cli = self._cli()
        assert cli.main([str(tmp_path / "nope")]) == 1
