"""fft + signal conformance vs torch (same numpy conventions as the
reference: python/paddle/fft.py, python/paddle/signal.py)."""
import numpy as np
import pytest
import torch

import paddle_tpu as pt

RNG = np.random.default_rng(0)


def _t2n(t):
    return t.resolve_conj().numpy() if t.is_conj() else t.numpy()


REAL_IN = ["fft", "ifft", "rfft", "ihfft", "fft2", "ifft2", "rfft2",
           "ihfft2", "fftn", "ifftn", "rfftn", "ihfftn"]
COMPLEX_IN = ["hfft", "hfft2", "hfftn", "irfft", "irfft2", "irfftn"]


class TestFFTConformance:
    @pytest.mark.parametrize("name", REAL_IN)
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_real_input(self, name, norm):
        x = RNG.standard_normal((3, 16)).astype(np.float32)
        out = getattr(pt.fft, name)(pt.to_tensor(x), norm=norm)
        ref = _t2n(getattr(torch.fft, name)(torch.from_numpy(x),
                                            norm=norm))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4,
                                   atol=1e-4)

    @pytest.mark.parametrize("name", COMPLEX_IN)
    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_complex_input(self, name, norm):
        x = (RNG.standard_normal((3, 9))
             + 1j * RNG.standard_normal((3, 9))).astype(np.complex64)
        out = getattr(pt.fft, name)(pt.to_tensor(x), norm=norm)
        ref = _t2n(getattr(torch.fft, name)(torch.from_numpy(x),
                                            norm=norm))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3,
                                   atol=1e-3)

    def test_roundtrip(self):
        x = RNG.standard_normal((4, 32)).astype(np.float32)
        rec = pt.fft.ifft(pt.fft.fft(pt.to_tensor(x)))
        np.testing.assert_allclose(rec.numpy().real, x, rtol=1e-5,
                                   atol=1e-5)
        rec = pt.fft.irfft(pt.fft.rfft(pt.to_tensor(x)), n=32)
        np.testing.assert_allclose(rec.numpy(), x, rtol=1e-5, atol=1e-5)

    def test_freq_shift_helpers(self):
        np.testing.assert_allclose(pt.fft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5))
        np.testing.assert_allclose(pt.fft.rfftfreq(8).numpy(),
                                   np.fft.rfftfreq(8))
        x = RNG.standard_normal((4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            pt.fft.fftshift(pt.to_tensor(x)).numpy(), np.fft.fftshift(x))
        np.testing.assert_allclose(
            pt.fft.ifftshift(pt.to_tensor(x)).numpy(),
            np.fft.ifftshift(x))

    def test_bad_norm_raises(self):
        with pytest.raises(ValueError):
            pt.fft.fft(pt.to_tensor(np.ones(4, np.float32)),
                       norm="wrong")

    def test_fft_grad(self):
        # autograd through the registry: d/dx |fft(x)|^2
        x = pt.to_tensor(RNG.standard_normal(8).astype(np.float32),
                         stop_gradient=False)
        out = pt.fft.fft(x)
        (out.abs() ** 2).sum().backward()
        # Parseval: d/dx sum|X|^2 = 2*N*x
        np.testing.assert_allclose(x.grad.numpy(), 2 * 8 * x.numpy(),
                                   rtol=1e-4)


class TestSignal:
    def test_frame_overlap_add_inverse(self):
        x = RNG.standard_normal((128,)).astype(np.float32)
        f = pt.signal.frame(pt.to_tensor(x), 32, 32)  # non-overlapping
        back = pt.signal.overlap_add(f, 32)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)

    def test_frame_matches_manual(self):
        x = np.arange(10, dtype=np.float32)
        f = pt.signal.frame(pt.to_tensor(x), 4, 3).numpy()  # [4, 3]
        assert f.shape == (4, 3)
        np.testing.assert_array_equal(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_array_equal(f[:, 1], [3, 4, 5, 6])

    @pytest.mark.parametrize("n_fft,hop", [(128, 64), (64, 16)])
    def test_stft_matches_torch(self, n_fft, hop):
        sig = RNG.standard_normal((2, 400)).astype(np.float32)
        win = np.hanning(n_fft).astype(np.float32)
        mine = pt.signal.stft(pt.to_tensor(sig), n_fft=n_fft,
                              hop_length=hop,
                              window=pt.to_tensor(win)).numpy()
        ref = torch.stft(torch.from_numpy(sig), n_fft=n_fft,
                         hop_length=hop, window=torch.from_numpy(win),
                         return_complex=True, center=True,
                         pad_mode="reflect").numpy()
        np.testing.assert_allclose(mine, ref, rtol=1e-4, atol=1e-4)

    def test_istft_roundtrip_matches_torch(self):
        sig = RNG.standard_normal((2, 400)).astype(np.float32)
        win = np.hanning(128).astype(np.float32)
        spec = pt.signal.stft(pt.to_tensor(sig), n_fft=128,
                              hop_length=64, window=pt.to_tensor(win))
        rec = pt.signal.istft(spec, n_fft=128, hop_length=64,
                              window=pt.to_tensor(win),
                              length=400).numpy()
        ref = torch.istft(torch.from_numpy(spec.numpy()), n_fft=128,
                          hop_length=64, window=torch.from_numpy(win),
                          length=400).numpy()
        np.testing.assert_allclose(rec, ref, rtol=1e-4, atol=1e-4)
        # perfect reconstruction away from the un-covered tail
        np.testing.assert_allclose(rec[:, :380], sig[:, :380],
                                   rtol=1e-3, atol=1e-3)
