"""Pallas flash-attention kernel conformance.

Runs the real kernel logic on CPU via pallas interpret mode
(pl.pallas_call(interpret=True)) so CI exercises the blockwise
forward AND the FA2-style backward without TPU hardware; a TPU-gated
test covers the compiled path. Mirrors the reference's
test/legacy_test/test_flash_attention.py (composite-vs-fused check).
"""
import importlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

fa = importlib.import_module("paddle_tpu.kernels.pallas.flash_attention")


def _make(b=2, s=256, h=2, d=64, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    return q, k, v


def _interp_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    qm, km, vm = map(fa._bshd_to_bhsd, (q, k, v))
    o, lse = fa._flash_fwd_bhsd(qm, km, vm, sm_scale, causal,
                                block_q=block_q, block_k=block_k,
                                interpret=True)
    return o, lse, (qm, km, vm)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (128, 256)])
def test_fwd_interpret_matches_composite(causal, block_q, block_k):
    q, k, v = _make()
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, _, _ = _interp_fwd(q, k, v, sc, causal, block_q, block_k)
    o = fa._bhsd_to_bshd(o, q.shape[0], q.shape[2])
    ref = fa._xla_attention(q, k, v, None, causal, sc)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_lse_matches_composite(causal):
    q, k, v = _make()
    sc = 1.0 / np.sqrt(q.shape[-1])
    _, lse, (qm, km, _) = _interp_fwd(q, k, v, sc, causal, 128, 128)
    s = jnp.einsum("zqd,zkd->zqk", qm.astype(jnp.float32),
                   km.astype(jnp.float32)) * sc
    if causal:
        qpos = jnp.arange(s.shape[-2])[:, None]
        kpos = jnp.arange(s.shape[-1])[None, :]
        s = jnp.where(qpos >= kpos, s, fa._NEG_INF)
    ref = jax.scipy.special.logsumexp(s, axis=-1)      # [bh, sq]
    np.testing.assert_allclose(np.asarray(lse[:, 0, :]), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # replicated across the sublane tile
    np.testing.assert_array_equal(np.asarray(lse[:, 0, :]),
                                  np.asarray(lse[:, -1, :]))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s,block_q,block_k",
                         [(256, 128, 128), (256, 128, 256),
                          (384, 128, 256), (384, 256, 128)])
def test_bwd_interpret_matches_composite(causal, s, block_q, block_k):
    q, k, v = _make(s=s)
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, lse, (qm, km, vm) = _interp_fwd(q, k, v, sc, causal,
                                       block_q, block_k)
    rng = np.random.default_rng(1)
    do = jnp.asarray(rng.standard_normal(o.shape), o.dtype)
    dq, dk, dv = fa._flash_bwd_bhsd(qm, km, vm, o, lse, do, sc, causal,
                                    block_q=block_q, block_k=block_k,
                                    interpret=True)

    def comp(qm, km, vm):
        s = jnp.einsum("zqd,zkd->zqk", qm, km) * sc
        if causal:
            qpos = jnp.arange(s.shape[-2])[:, None]
            kpos = jnp.arange(s.shape[-1])[None, :]
            s = jnp.where(qpos >= kpos, s, fa._NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("zqk,zkd->zqd", p, vm)

    _, vjp = jax.vjp(comp, qm, km, vm)
    rq, rk, rv = vjp(do)
    for got, ref in ((dq, rq), (dk, rk), (dv, rv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


def test_uneven_final_block_interpret():
    # seq not a multiple of block_k exercises the padded tail path
    q, k, v = _make(s=384)
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, _, _ = _interp_fwd(q, k, v, sc, True, 128, 256)
    o = fa._bhsd_to_bshd(o, q.shape[0], q.shape[2])
    ref = fa._xla_attention(q, k, v, None, True, sc)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_attention_path_gating():
    # CPU backend -> xla; masked -> xla; odd shapes -> xla
    assert fa.attention_path((2, 256, 4, 64), (2, 256, 4, 64)) == "xla"
    assert fa.attention_path((2, 256, 4, 64), (2, 256, 4, 64),
                             masked=True) == "xla"
    assert fa.attention_path((2, 100, 4, 64), (2, 100, 4, 64)) == "xla"


def test_flash_attention_dispatch_cpu_fallback():
    # public entry must agree with the composite on CPU (xla path)
    q, k, v = _make(s=128)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = fa._xla_attention(q, k, v, None, True,
                           1.0 / np.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs TPU")
def test_fwd_bwd_tpu_compiled():
    q, k, v = _make(s=512, dtype=jnp.bfloat16)
    sc = 1.0 / np.sqrt(q.shape[-1])

    def f_p(q, k, v):
        return (_ := fa._flash_core(q, k, v, True, sc, True)).astype(
            jnp.float32).sum()

    def f_x(q, k, v):
        return fa._xla_attention(q, k, v, None, True, sc).astype(
            jnp.float32).sum()

    gp = jax.grad(f_p, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(f_x, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert rel < 1e-2, rel
