"""Pallas flash-attention kernel conformance.

Runs the real kernel logic on CPU via pallas interpret mode
(pl.pallas_call(interpret=True)) so CI exercises the blockwise
forward AND the FA2-style backward without TPU hardware; a TPU-gated
test covers the compiled path. Mirrors the reference's
test/legacy_test/test_flash_attention.py (composite-vs-fused check).

Kernels use the fused-head layout [b, s, h*d]; tests drive them through
the same wrappers the dispatch path uses.
"""
import importlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

fa = importlib.import_module("paddle_tpu.kernels.pallas.flash_attention")


def _make(b=2, s=256, h=2, d=64, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    return q, k, v


def _fuse(x):
    b, s, h, d = x.shape
    return x.reshape(b, s, h * d)


def _interp_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    h = q.shape[2]
    qs = (q * sm_scale).astype(q.dtype)
    o, lse = fa._flash_fwd_fused(_fuse(qs), _fuse(k), _fuse(v), h, causal,
                                 block_q=block_q, block_k=block_k,
                                 interpret=True)
    return o, lse, (_fuse(qs), _fuse(k), _fuse(v))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (128, 256)])
def test_fwd_interpret_matches_composite(causal, block_q, block_k):
    q, k, v = _make()
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, _, _ = _interp_fwd(q, k, v, sc, causal, block_q, block_k)
    ref = fa._xla_attention(q, k, v, None, causal, sc)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_fuse(ref)),
                               rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_lse_matches_composite(causal):
    q, k, v = _make()
    b, s, h, d = q.shape
    sc = 1.0 / np.sqrt(d)
    _, lse, _ = _interp_fwd(q, k, v, sc, causal, 128, 128)
    sco = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                     k.astype(jnp.float32)) * sc
    if causal:
        qpos = jnp.arange(s)[:, None]
        kpos = jnp.arange(s)[None, :]
        sco = jnp.where(qpos >= kpos, sco, fa._NEG_INF)
    ref = jax.scipy.special.logsumexp(sco, axis=-1)      # [b, h, sq]
    got = lse.reshape(b, h, fa._SUBL, s)
    np.testing.assert_allclose(np.asarray(got[:, :, 0]), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # replicated across the sublane tile
    np.testing.assert_array_equal(np.asarray(got[:, :, 0]),
                                  np.asarray(got[:, :, -1]))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s,block_q,block_k",
                         [(256, 128, 128), (256, 128, 256),
                          (384, 128, 256), (384, 256, 128)])
def test_bwd_interpret_matches_composite(causal, s, block_q, block_k):
    q, k, v = _make(s=s)
    b, _, h, d = q.shape
    sc = 1.0 / np.sqrt(d)
    o, lse, (qm, km, vm) = _interp_fwd(q, k, v, sc, causal,
                                       block_q, block_k)
    rng = np.random.default_rng(1)
    do = jnp.asarray(rng.standard_normal(o.shape), o.dtype)
    dq, dk, dv = fa._flash_bwd_fused(qm, km, vm, o, lse, do, h, causal,
                                     block_q=block_q, block_k=block_k,
                                     interpret=True)
    dq = dq * sc  # kernel returns grad wrt the pre-scaled q

    def comp(qm, km, vm):
        qh = qm.reshape(b, s, h, d)
        kh = km.reshape(b, s, h, d)
        vh = vm.reshape(b, s, h, d)
        sco = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * sc
        if causal:
            qpos = jnp.arange(s)[:, None]
            kpos = jnp.arange(s)[None, :]
            sco = jnp.where(qpos >= kpos, sco, fa._NEG_INF)
        p = jax.nn.softmax(sco, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vh).reshape(b, s, h * d)

    _, vjp = jax.vjp(comp, _fuse(q), km, vm)
    rq, rk, rv = vjp(do)
    for got, ref in ((dq, rq), (dk, rk), (dv, rv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)


def test_nonsquare_block_pick():
    # seq 384: block picker must fall back to a divisor (384 = 3*128)
    assert fa._pick_block(384, 512) == 384
    assert fa._pick_block(384, 256) == 128
    assert fa._pick_block(1024, 512) == 512
    q, k, v = _make(s=384)
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, _, _ = _interp_fwd(q, k, v, sc, True, 256, 256)
    ref = fa._xla_attention(q, k, v, None, True, sc)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_fuse(ref)),
                               rtol=5e-5, atol=5e-5)


def test_attention_path_gating():
    # CPU backend -> xla; masked -> xla; odd shapes -> xla
    assert fa.attention_path((2, 256, 4, 64), (2, 256, 4, 64)) == "xla"
    assert fa.attention_path((2, 256, 4, 64), (2, 256, 4, 64),
                             masked=True) == "xla"
    assert fa.attention_path((2, 100, 4, 64), (2, 100, 4, 64)) == "xla"
    # fused-head lane alignment: h*d must be a multiple of 128
    assert not fa._shapes_ok((2, 256, 3, 64), (2, 256, 3, 64))
    assert fa._shapes_ok((2, 256, 4, 64), (2, 256, 4, 64))
    assert fa._shapes_ok((2, 1024, 12, 64), (2, 1024, 12, 64))


def test_flash_attention_dispatch_cpu_fallback():
    # public entry must agree with the composite on CPU (xla path)
    q, k, v = _make(s=128)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = fa._xla_attention(q, k, v, None, True,
                           1.0 / np.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs TPU")
def test_fwd_bwd_tpu_compiled():
    q, k, v = _make(s=512, dtype=jnp.bfloat16)
    sc = 1.0 / np.sqrt(q.shape[-1])

    def f_p(q, k, v):
        return (_ := fa._flash_core(q, k, v, True, sc, True)).astype(
            jnp.float32).sum()

    def f_x(q, k, v):
        return fa._xla_attention(q, k, v, None, True, sc).astype(
            jnp.float32).sum()

    gp = jax.grad(f_p, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(f_x, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert rel < 1e-2, rel


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs TPU")
def test_bwd_tpu_bf16_multi_kblock_partials():
    # seq 2048 -> multiple k-blocks -> the dq partial-sum path runs with
    # bf16-quantized partials; bound the added rounding error vs XLA
    q, k, v = _make(b=1, s=2048, h=2, dtype=jnp.bfloat16, seed=3)
    sc = 1.0 / np.sqrt(q.shape[-1])

    def f_p(q, k, v):
        return fa._flash_core(q, k, v, True, sc, True).astype(
            jnp.float32).sum()

    def f_x(q, k, v):
        return fa._xla_attention(q, k, v, None, True, sc).astype(
            jnp.float32).sum()

    dq_p = jax.grad(f_p)(q, k, v)
    dq_x = jax.grad(f_x)(q, k, v)
    rel = float(jnp.abs(dq_p - dq_x).max() / (jnp.abs(dq_x).max() + 1e-9))
    assert rel < 2e-2, rel
