"""Pallas flash-attention kernel conformance.

Runs the real kernel logic on CPU via pallas interpret mode
(pl.pallas_call(interpret=True)) so CI exercises the blockwise
forward AND the FA2-style backward without TPU hardware; a TPU-gated
test covers the compiled path. Mirrors the reference's
test/legacy_test/test_flash_attention.py (composite-vs-fused check).

Kernels use the fused-head layout [b, s, h*d]; tests drive them through
the same wrappers the dispatch path uses.
"""
import importlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

fa = importlib.import_module("paddle_tpu.kernels.pallas.flash_attention")


def _make(b=2, s=256, h=2, d=64, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    return q, k, v


def _fuse(x):
    b, s, h, d = x.shape
    return x.reshape(b, s, h * d)


def _interp_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    h = q.shape[2]
    qs = (q * sm_scale).astype(q.dtype)
    o, lse = fa._flash_fwd_fused(_fuse(qs), _fuse(k), _fuse(v), h, causal,
                                 block_q=block_q, block_k=block_k,
                                 interpret=True)
    return o, lse, (_fuse(qs), _fuse(k), _fuse(v))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_q,block_k", [(128, 128), (128, 256)])
def test_fwd_interpret_matches_composite(causal, block_q, block_k):
    q, k, v = _make()
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, _, _ = _interp_fwd(q, k, v, sc, causal, block_q, block_k)
    ref = fa._xla_attention(q, k, v, None, causal, sc)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_fuse(ref)),
                               rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_lse_matches_composite(causal):
    q, k, v = _make()
    b, s, h, d = q.shape
    sc = 1.0 / np.sqrt(d)
    _, lse, _ = _interp_fwd(q, k, v, sc, causal, 128, 128)
    sco = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                     k.astype(jnp.float32)) * sc
    if causal:
        qpos = jnp.arange(s)[:, None]
        kpos = jnp.arange(s)[None, :]
        sco = jnp.where(qpos >= kpos, sco, fa._NEG_INF)
    ref = jax.scipy.special.logsumexp(sco, axis=-1)      # [b, h, sq]
    got = lse.reshape(b, h, fa._SUBL, s)
    np.testing.assert_allclose(np.asarray(got[:, :, 0]), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # replicated across the sublane tile
    np.testing.assert_array_equal(np.asarray(got[:, :, 0]),
                                  np.asarray(got[:, :, -1]))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s,block_q,block_k",
                         [(256, 128, 128), (256, 128, 256),
                          (384, 128, 256), (384, 256, 128)])
def test_bwd_interpret_matches_composite(causal, s, block_q, block_k):
    q, k, v = _make(s=s)
    b, _, h, d = q.shape
    sc = 1.0 / np.sqrt(d)
    o, lse, (qm, km, vm) = _interp_fwd(q, k, v, sc, causal,
                                       block_q, block_k)
    rng = np.random.default_rng(1)
    do = jnp.asarray(rng.standard_normal(o.shape), o.dtype)
    dq, dk, dv = fa._flash_bwd_fused(qm, km, vm, o, lse, do, h, causal,
                                     block_q=block_q, block_k=block_k,
                                     interpret=True)
    dq = dq * sc  # kernel returns grad wrt the pre-scaled q

    def comp(qm, km, vm):
        qh = qm.reshape(b, s, h, d)
        kh = km.reshape(b, s, h, d)
        vh = vm.reshape(b, s, h, d)
        sco = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * sc
        if causal:
            qpos = jnp.arange(s)[:, None]
            kpos = jnp.arange(s)[None, :]
            sco = jnp.where(qpos >= kpos, sco, fa._NEG_INF)
        p = jax.nn.softmax(sco, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vh).reshape(b, s, h * d)

    _, vjp = jax.vjp(comp, _fuse(q), km, vm)
    rq, rk, rv = vjp(do)
    for got, ref in ((dq, rq), (dk, rk), (dv, rv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)


def test_nonsquare_block_pick():
    # seq 384: block picker must fall back to a divisor (384 = 3*128)
    assert fa._pick_block(384, 512) == 384
    assert fa._pick_block(384, 256) == 128
    assert fa._pick_block(1024, 512) == 512
    q, k, v = _make(s=384)
    sc = 1.0 / np.sqrt(q.shape[-1])
    o, _, _ = _interp_fwd(q, k, v, sc, True, 256, 256)
    ref = fa._xla_attention(q, k, v, None, True, sc)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_fuse(ref)),
                               rtol=5e-5, atol=5e-5)


def test_attention_path_gating():
    # CPU backend -> xla; masked -> xla; odd shapes -> xla. Each fallback
    # carries a human-readable reason (VERDICT r2 weak #3).
    path, why = fa.attention_path((2, 256, 4, 64), (2, 256, 4, 64))
    assert path == "xla" and "backend" in why
    path, why = fa.attention_path((2, 256, 4, 64), (2, 256, 4, 64),
                                  masked=True)
    assert path == "xla" and "attn_mask" in why
    path, _ = fa.attention_path((2, 100, 4, 64), (2, 100, 4, 64))
    assert path == "xla"
    # fused-head lane alignment: h*d must be a multiple of 128
    assert not fa._shapes_ok((2, 256, 3, 64), (2, 256, 3, 64))
    assert fa._shapes_ok((2, 256, 4, 64), (2, 256, 4, 64))
    assert fa._shapes_ok((2, 1024, 12, 64), (2, 1024, 12, 64))
    # GQA: kv heads must divide q heads with hk*d lane-aligned
    assert fa._shapes_ok((2, 256, 4, 64), (2, 256, 2, 64))
    assert fa._shapes_ok((2, 1024, 12, 128), (2, 1024, 4, 128))
    assert not fa._shapes_ok((2, 256, 4, 64), (2, 256, 3, 64))
    assert not fa._shapes_ok((2, 256, 8, 64), (2, 256, 1, 64))  # 64 lanes
    assert fa._shapes_ok((2, 256, 8, 128), (2, 256, 1, 128))    # MQA ok


def _xla_ref(q, k, v, causal, sc, segment_ids=None):
    return fa._xla_attention(q, k, v, None, causal, sc,
                             segment_ids=segment_ids)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("hk", [1, 2])
def test_gqa_fwd_bwd_interpret(causal, hk):
    """GQA/MQA: q-head h reads kv-head h // (H//Hk) in-kernel."""
    h, d, s, b = 4, 128, 256, 2
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    sc = 1.0 / np.sqrt(d)
    qs = (q * sc).astype(q.dtype).reshape(b, s, h * d)
    km, vm = k.reshape(b, s, hk * d), v.reshape(b, s, hk * d)
    o, lse = fa._flash_fwd_fused(qs, km, vm, h, causal, block_q=128,
                                 block_k=128, interpret=True, Hk=hk)
    ref = _xla_ref(q, k, v, causal, sc)
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(ref.reshape(b, s, h * d)),
                               rtol=5e-5, atol=5e-5)
    do = jnp.asarray(rng.standard_normal(o.shape), o.dtype)
    dq, dk, dv = fa._flash_bwd_fused(qs, km, vm, o, lse, do, h, causal,
                                     block_q=128, block_k=128,
                                     interpret=True, Hk=hk)
    dq = dq * sc

    def comp(qm, km, vm):
        out = _xla_ref(qm.reshape(b, s, h, d), km.reshape(b, s, hk, d),
                       vm.reshape(b, s, hk, d), causal, sc)
        return out.reshape(b, s, h * d)

    _, vjp = jax.vjp(comp, q.reshape(b, s, h * d), km, vm)
    rq, rk, rv = vjp(do)
    for got, ref_g in ((dq, rq), (dk, rk), (dv, rv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref_g),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_segment_ids_fwd_bwd_interpret(causal):
    """Padding + packed-varlen masking via segment ids stays in-kernel."""
    b, s, h, d = 2, 256, 2, 64
    rng = np.random.default_rng(11)
    q, k, v = _make(b=b, s=s, h=h, d=d, seed=11)
    # batch 0: two packed sequences + tail padding; batch 1: all one segment
    seg0 = np.concatenate([np.zeros(100), np.ones(80),
                           -np.ones(76)]).astype(np.int32)
    seg1 = np.zeros(s, np.int32)
    seg = jnp.asarray(np.stack([seg0, seg1]))
    sc = 1.0 / np.sqrt(d)
    qs = (q * sc).astype(q.dtype).reshape(b, s, h * d)
    km, vm = _fuse(k), _fuse(v)
    o, lse = fa._flash_fwd_fused(qs, km, vm, h, causal, block_q=128,
                                 block_k=128, interpret=True,
                                 segment_ids=(seg, seg))
    ref = _xla_ref(q, k, v, causal, sc, segment_ids=(seg, seg))
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(_fuse(ref)),
                               rtol=5e-5, atol=5e-5)
    do = jnp.asarray(rng.standard_normal(o.shape), o.dtype)
    dq, dk, dv = fa._flash_bwd_fused(qs, km, vm, o, lse, do, h, causal,
                                     block_q=128, block_k=128,
                                     interpret=True,
                                     segment_ids=(seg, seg))
    dq = dq * sc

    def comp(qm, km, vm):
        out = _xla_ref(qm.reshape(b, s, h, d), km.reshape(b, s, h, d),
                       vm.reshape(b, s, h, d), causal, sc,
                       segment_ids=(seg, seg))
        return out.reshape(b, s, h * d)

    _, vjp = jax.vjp(comp, _fuse(q), km, vm)
    rq, rk, rv = vjp(do)
    for got, ref_g in ((dq, rq), (dk, rk), (dv, rv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref_g),
                                   rtol=5e-4, atol=5e-4)


def test_cross_length_causal_bottom_right():
    """sq != sk causal is bottom-right aligned (FA2 semantics, ADVICE r2):
    the LAST q row sees all sk keys."""
    b, h, d = 1, 2, 64
    sq, sk = 128, 256
    rng = np.random.default_rng(13)
    q = jnp.asarray(rng.standard_normal((b, sq, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, sk, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, sk, h, d)), jnp.float32)
    sc = 1.0 / np.sqrt(d)
    qs = (q * sc).astype(q.dtype).reshape(b, sq, h * d)
    o, _ = fa._flash_fwd_fused(qs, k.reshape(b, sk, h * d),
                               v.reshape(b, sk, h * d), h, True,
                               block_q=128, block_k=128, interpret=True)
    ref = _xla_ref(q, k, v, True, sc)  # composite also bottom-right
    np.testing.assert_allclose(np.asarray(o),
                               np.asarray(ref.reshape(b, sq, h * d)),
                               rtol=5e-5, atol=5e-5)
    # semantic spot-check vs an explicit bottom-right mask
    s_full = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float64),
                       np.asarray(k, np.float64)) * sc
    qpos = (sk - sq) + np.arange(sq)[:, None]
    kpos = np.arange(sk)[None, :]
    s_full = np.where(qpos >= kpos, s_full, -1e30)
    p = np.exp(s_full - s_full.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    exp = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float64))
    np.testing.assert_allclose(
        np.asarray(o).reshape(b, sq, h, d), exp, rtol=1e-4, atol=1e-4)


def test_flash_attn_unpadded_varlen():
    """Packed varlen wrapper == per-sequence dense attention."""
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import flash_attn_unpadded

    h, d = 2, 64
    lens = [100, 80, 50]
    total = 256  # padded to a 128 multiple
    rng = np.random.default_rng(17)
    qkv = [jnp.asarray(rng.standard_normal((total, h, d)), jnp.float32)
           for _ in range(3)]
    cu = np.cumsum([0] + lens).astype(np.int32)
    out, _ = flash_attn_unpadded(
        paddle.to_tensor(qkv[0]), paddle.to_tensor(qkv[1]),
        paddle.to_tensor(qkv[2]), cu_seqlens_q=cu, cu_seqlens_k=cu,
        causal=True)
    out = np.asarray(out.numpy())
    sc = 1.0 / np.sqrt(d)
    for i in range(len(lens)):
        s0, s1 = cu[i], cu[i + 1]
        qi = qkv[0][None, s0:s1]
        ki = qkv[1][None, s0:s1]
        vi = qkv[2][None, s0:s1]
        ref = _xla_ref(qi, ki, vi, True, sc)[0]
        np.testing.assert_allclose(out[s0:s1], np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)


def test_flash_attention_dispatch_cpu_fallback():
    # public entry must agree with the composite on CPU (xla path)
    q, k, v = _make(s=128)
    out = fa.flash_attention(q, k, v, causal=True)
    ref = fa._xla_attention(q, k, v, None, True,
                           1.0 / np.sqrt(q.shape[-1]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs TPU")
def test_fwd_bwd_tpu_compiled():
    q, k, v = _make(s=512, dtype=jnp.bfloat16)
    sc = 1.0 / np.sqrt(q.shape[-1])

    def f_p(q, k, v):
        return (_ := fa._flash_core(q, k, v, True, sc, True)).astype(
            jnp.float32).sum()

    def f_x(q, k, v):
        return fa._xla_attention(q, k, v, None, True, sc).astype(
            jnp.float32).sum()

    gp = jax.grad(f_p, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(f_x, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert rel < 1e-2, rel


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs TPU")
def test_bwd_tpu_bf16_multi_kblock_partials():
    # seq 2048 -> multiple k-blocks -> the dq partial-sum path runs with
    # bf16-quantized partials; bound the added rounding error vs XLA
    q, k, v = _make(b=1, s=2048, h=2, dtype=jnp.bfloat16, seed=3)
    sc = 1.0 / np.sqrt(q.shape[-1])

    def f_p(q, k, v):
        return fa._flash_core(q, k, v, True, sc, True).astype(
            jnp.float32).sum()

    def f_x(q, k, v):
        return fa._xla_attention(q, k, v, None, True, sc).astype(
            jnp.float32).sum()

    dq_p = jax.grad(f_p)(q, k, v)
    dq_x = jax.grad(f_x)(q, k, v)
    rel = float(jnp.abs(dq_p - dq_x).max() / (jnp.abs(dq_x).max() + 1e-9))
    assert rel < 2e-2, rel
