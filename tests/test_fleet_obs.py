"""Fleet observability plane (paddle_tpu/observability/fleet.py):
snapshot-delta encoding, sequence-numbered shipping with rollback +
dedupe, aggregator health/staleness, capacity ledger records, the
obs_top fleet panel, the disabled-mode overhead guard — and the real
spawn boundary: N worker processes shipping metrics + spans to an
aggregator over the HMAC RPC layer, one killed -9 mid-run.

Module-level imports stay light: spawned children re-import this
module (spawn start method), and heavyweight imports belong inside
the functions that run after the JAX_PLATFORMS=cpu env guard."""
import json
import multiprocessing
import os
import signal
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fleet_clean():
    """Every test starts disabled with empty stores, a neutral fleet
    identity, and no aggregator serving in this process."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import fleet, tracing
    obs.disable()
    obs.reset()
    tracing.clear()
    cap = tracing.capacity()
    saved = (fleet._PROCESS, fleet._ROLE, fleet._ROLE_EXPLICIT)
    fleet._PROCESS, fleet._ROLE, fleet._ROLE_EXPLICIT = None, None, False
    yield
    if fleet._AGGREGATOR is not None:
        fleet._AGGREGATOR.close()
    fleet._PROCESS, fleet._ROLE, fleet._ROLE_EXPLICIT = saved
    obs.disable()
    obs.reset()
    tracing.set_capacity(cap)


def _snap_series(reg, name):
    return reg.snapshot()[name]["series"]


# ---------------------------------------------------------------------------
# delta encoding (the one wire format)
# ---------------------------------------------------------------------------
class TestDeltaSnapshot:
    def _regs(self):
        from paddle_tpu.observability import MetricsRegistry
        return MetricsRegistry(), MetricsRegistry()

    def test_counter_and_gauge_deltas_telescope(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        src, dst = self._regs()
        c = src.counter("t_fd_total", "", ("k",)).labels(k="a")
        g = src.gauge("t_fd_gauge", "")
        c.inc(3)
        g.set(10.0)
        base = None
        for expect_c, expect_g in ((3.0, 10.0), (5.0, 4.0)):
            cur = src.snapshot()
            dst.merge(fleet.delta_snapshot(cur, base))
            base = cur
            assert _snap_series(dst, "t_fd_total")[("a",)] == expect_c
            assert _snap_series(dst, "t_fd_gauge")[()] == expect_g
            if expect_c == 3.0:     # second round: inc + gauge DOWN
                c.inc(2)
                g.set(4.0)

    def test_zero_delta_series_pruned(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        src, _ = self._regs()
        c = src.counter("t_fdp_total", "")
        h = src.histogram("t_fdp_seconds", "")
        c.inc()
        h.observe(0.1)
        cur = src.snapshot()
        assert fleet.delta_snapshot(cur, cur) == {}
        full = fleet.delta_snapshot(cur, None)
        assert set(full) == {"t_fdp_total", "t_fdp_seconds"}

    def test_histogram_delta_buckets_subtract(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        src, dst = self._regs()
        h = src.histogram("t_fdh_seconds", "", buckets=(0.1, 1.0))
        h.observe(0.05)
        base = src.snapshot()
        h.observe(0.5)
        h.observe(2.0)
        delta = fleet.delta_snapshot(src.snapshot(), base)
        val = delta["t_fdh_seconds"]["series"][()]
        assert val["buckets"] == [0, 1, 1] and val["count"] == 2
        dst.merge(delta)
        out = _snap_series(dst, "t_fdh_seconds")[()]
        assert out["count"] == 2 and out["sum"] == pytest.approx(2.5)

    def test_reset_peer_recontributes_in_full(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        src, _ = self._regs()
        c = src.counter("t_fdr_total", "")
        c.inc(9)
        base = src.snapshot()
        src.reset()
        c.inc(2)                    # restarted accounting
        delta = fleet.delta_snapshot(src.snapshot(), base)
        assert delta["t_fdr_total"]["series"][()] == 2.0

    def test_histogram_reset_hidden_by_regrown_count_ships_full(self):
        """A peer that resets and then observes PAST its old total
        count must still be detected (per-bucket backwards movement) —
        otherwise negative bucket deltas would merge into the fleet
        registry."""
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        src, dst = self._regs()
        h = src.histogram("t_fdrh_seconds", "", buckets=(0.1, 1.0))
        for _ in range(5):
            h.observe(0.05)         # 5 in bucket 0
        base = src.snapshot()
        src.reset()
        for _ in range(7):
            h.observe(0.5)          # regrown past the old count
        delta = fleet.delta_snapshot(src.snapshot(), base)
        val = delta["t_fdrh_seconds"]["series"][()]
        assert val["buckets"] == [0, 7, 0] and val["count"] == 7
        dst.merge(delta)
        out = _snap_series(dst, "t_fdrh_seconds")[()]
        assert out["count"] == 7 and min(out["buckets"]) >= 0

    def test_worker_farewell_merges_through_one_path(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        obs.enable()
        obs.registry().counter("t_fw_total", "").inc(3)
        tracing.add_event("t.fw", 1.0, 2.0)
        wf = fleet.worker_farewell()
        assert wf["v"] == fleet.BUNDLE_VERSION and wf["seq"] == 1
        obs.reset()
        fleet.merge_bundle_local(wf)
        assert obs.snapshot()["t_fw_total"]["series"][()] == 3
        assert any(e["name"] == "t.fw" for e in tracing.events())
        # legacy {"metrics","trace"} farewell shape still merges
        obs.reset()
        fleet.merge_bundle_local({"metrics": wf["metrics"],
                                  "trace": wf["trace"]})
        assert obs.snapshot()["t_fw_total"]["series"][()] == 3


# ---------------------------------------------------------------------------
# aggregator semantics (direct ingest — no sockets)
# ---------------------------------------------------------------------------
class TestAggregator:
    def _agg(self, stale_after_s=10.0):
        from paddle_tpu.observability.fleet import FleetAggregator
        return FleetAggregator(stale_after_s=stale_after_s)

    def _bundle(self, proc, seq, series=None, role="replica"):
        from paddle_tpu.observability import MetricsRegistry, fleet
        md = None
        if series is not None:
            src = MetricsRegistry()
            from paddle_tpu.observability import metrics as _m
            _m.enable()
            for name, v in series.items():
                src.counter(name, "test").inc(v)
            md = fleet.delta_snapshot(src.snapshot(), None)
        return fleet.make_bundle(proc, role, seq, metrics_delta=md)

    def test_process_label_dimension(self):
        agg = self._agg()
        agg.ingest(self._bundle("pa", 1, {"t_fa_total": 3}))
        agg.ingest(self._bundle("pb", 1, {"t_fa_total": 5}))
        s = _snap_series(agg.registry, "t_fa_total")
        assert s[("pa",)] == 3 and s[("pb",)] == 5
        expo = agg.to_prometheus()
        assert 'process="pa"' in expo and 'process="pb"' in expo

    def test_seq_dedupe_no_double_count(self):
        agg = self._agg()
        b = self._bundle("pa", 1, {"t_fs_total": 4})
        assert agg.ingest(b)["ok"]
        ack = agg.ingest(b)          # redelivery after a lost ack
        assert ack["duplicate"] and ack["last_seq"] == 1
        stale = self._bundle("pa", 1, {"t_fs_total": 100})
        assert agg.ingest(stale)["duplicate"]
        assert _snap_series(agg.registry, "t_fs_total")[("pa",)] == 4
        assert _snap_series(
            agg.registry,
            "paddle_tpu_fleet_duplicate_bundles_total")[("pa",)] == 2

    def test_schema_skew_quarantined_not_poisoning(self):
        from paddle_tpu.observability import MetricsRegistry, fleet
        from paddle_tpu.observability import metrics as _m
        _m.enable()
        agg = self._agg()
        a = MetricsRegistry()
        a.histogram("t_fq_seconds", "", buckets=(0.1,)).observe(0.05)
        agg.ingest(fleet.make_bundle(
            "pa", "r", 1,
            metrics_delta=fleet.delta_snapshot(a.snapshot(), None)))
        b = MetricsRegistry()
        b.histogram("t_fq_seconds", "", buckets=(9.0,)).observe(1.0)
        agg.ingest(fleet.make_bundle(
            "pb", "r", 1,
            metrics_delta=fleet.delta_snapshot(b.snapshot(), None)))
        snap = agg.registry.snapshot()
        assert snap["t_fq_seconds"]["series"][("pa",)]["count"] == 1
        assert snap["t_fq_skew_seconds"]["series"][("pb",)]["count"] == 1
        assert snap["paddle_tpu_fleet_quarantined_series_total"][
            "series"][("pb",)] == 1

    def test_poison_bundle_rejected_with_accounting_seq_advances(self):
        """Three peers, three schemas for one name: the third cannot
        merge even under quarantine (slot taken by the second). Its
        metric delta is dropped WITH accounting and the seq still
        advances — the agent must not be wedged into redelivering a
        poison bundle forever, and a redelivery must dedupe instead of
        partially re-merging."""
        from paddle_tpu.observability import MetricsRegistry, fleet
        from paddle_tpu.observability import metrics as _m
        _m.enable()
        agg = self._agg()

        def hist_bundle(proc, bucket):
            r = MetricsRegistry()
            r.histogram("t_fp_seconds", "", buckets=(bucket,)) \
                .observe(bucket / 2)
            return fleet.make_bundle(
                proc, "r", 1,
                metrics_delta=fleet.delta_snapshot(r.snapshot(), None))

        assert not agg.ingest(hist_bundle("pa", 0.1))["rejected_metrics"]
        assert not agg.ingest(hist_bundle("pb", 1.0))["rejected_metrics"]
        poison = hist_bundle("pc", 5.0)
        ack = agg.ingest(poison)
        assert ack["ok"] and ack["rejected_metrics"]
        assert agg.processes()["pc"]["last_seq"] == 1
        assert agg.ingest(poison)["duplicate"]   # redelivery dedupes
        snap = agg.registry.snapshot()
        assert snap["t_fp_seconds"]["series"][("pa",)]["count"] == 1
        assert snap["t_fp_skew_seconds"]["series"][("pb",)]["count"] == 1
        assert ("pc",) not in snap["t_fp_seconds"]["series"]
        assert snap["paddle_tpu_fleet_rejected_bundles_total"][
            "series"][("pc",)] == 1

    def test_heartbeat_staleness(self):
        agg = self._agg(stale_after_s=2.0)
        agg.ingest(self._bundle("pa", 1))
        h = agg.health()
        assert h["pa"]["up"] and h["pa"]["age_s"] < 2.0
        h = agg.health(now=time.time() + 5.0)
        assert not h["pa"]["up"]
        assert _snap_series(
            agg.registry,
            "paddle_tpu_fleet_process_up")[("pa",)] == 0.0
        assert _snap_series(
            agg.registry,
            "paddle_tpu_fleet_heartbeat_age_seconds")[("pa",)] > 2.0

    def test_respawned_process_resets_seq_epoch(self):
        """Crash-restart under a reused process name: the new
        incarnation's agent restarts seq at 1 with a new pid — the
        aggregator must open a new epoch instead of deduping the live
        process into staleness. Merged totals keep both lives'
        history; capacity re-baselines."""
        from paddle_tpu.observability import fleet
        agg = self._agg(stale_after_s=60.0)

        def bundle(seq, pid, n):
            b = self._bundle("pr", seq, {"t_rs_total": n})
            b["heartbeat"]["pid"] = pid
            return b

        agg.ingest(bundle(1, 100, 4))
        agg.ingest(bundle(2, 100, 3))
        assert agg.ingest(bundle(2, 100, 9))["duplicate"]  # same life
        # respawn: same name, new pid, seq restarts at 1
        ack = agg.ingest(bundle(1, 200, 5))
        assert ack["ok"] and not ack.get("duplicate")
        assert agg.processes()["pr"]["last_seq"] == 1
        assert agg.processes()["pr"]["pid"] == 200
        assert _snap_series(agg.registry, "t_rs_total")[("pr",)] == 12
        assert _snap_series(
            agg.registry,
            "paddle_tpu_fleet_process_restarts_total")[("pr",)] == 1
        assert agg.health()["pr"]["up"]

    def test_merge_unknown_kind_and_malformed_value_are_skew(self):
        """A newer-revision peer's unknown metric kind, and a
        non-numeric series value, must surface as MergeSkewError (the
        aggregator's rejected-bundle path), never as a bare
        KeyError/TypeError mid-mutation."""
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import MetricsRegistry, fleet
        from paddle_tpu.observability import metrics as _m
        _m.enable()
        dst = MetricsRegistry()
        snap = {"t_uk_things": {
            "kind": "summary", "help": "", "labelnames": (),
            "series": {(): 1.0}}}
        with pytest.raises(obs.MergeSkewError, match="unknown metric"):
            dst.merge(snap, on_skew="quarantine")
        bad_val = {"t_bv_total": {
            "kind": "counter", "help": "", "labelnames": (),
            "series": {(): {"not": "a number"}}}}
        with pytest.raises(obs.MergeSkewError, match="not numeric"):
            dst.merge(bad_val)
        # the aggregator converts either into a counted rejection, not
        # a wedge: the seq advances and the agent moves on
        agg = self._agg()
        b = fleet.make_bundle("pu", "r", 1, metrics_delta=snap)
        assert agg.ingest(b)["rejected_metrics"]
        assert agg.processes()["pu"]["last_seq"] == 1

    def test_unknown_bundle_version_rejected(self):
        agg = self._agg()
        with pytest.raises(ValueError, match="fleet bundle"):
            agg.ingest({"v": 99, "process": "pa", "seq": 1})


# ---------------------------------------------------------------------------
# agent shipping over real sockets (agent + aggregator co-located:
# asserts go against the fleet registry, which is feedback-free)
# ---------------------------------------------------------------------------
class TestAgentShipping:
    def test_ship_rollback_and_redelivery(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        agg = fleet.serve_aggregator()
        c = obs.registry().counter("t_as_total", "")
        agent = fleet.FleetAgent(agg.endpoint, process="p1",
                                 role="replica", interval_s=60.0,
                                 timeout_s=5.0)
        c.inc(5)
        assert agent.ship()
        assert _snap_series(agg.registry, "t_as_total")[("p1",)] == 5
        port = int(agg.endpoint.rsplit(":", 1)[1])
        agg.close()
        c.inc(7)
        assert not agent.ship()     # aggregator gone: rolled back
        assert agent._seq == 1
        fails = obs.snapshot()[
            "paddle_tpu_fleet_agent_ship_failures_total"]["series"][()]
        assert fails == 1
        agg2 = fleet.serve_aggregator(port=port)
        assert agent.ship()         # accumulated delta redelivers
        assert agent._seq == 2
        # the new aggregator sees exactly the un-acknowledged delta
        assert _snap_series(agg2.registry, "t_as_total")[("p1",)] == 7
        agg2.close()

    def test_heartbeat_only_when_disabled(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        assert not obs.enabled()
        agg = fleet.serve_aggregator()
        agent = fleet.FleetAgent(agg.endpoint, process="poff",
                                 role="replica", interval_s=60.0)
        assert agent.ship()
        procs = agg.processes()
        assert procs["poff"]["last_seq"] == 1
        # no series shipped: the fleet registry holds only the
        # aggregator's own bookkeeping (fleet health + the cross-rank
        # collective attribution gauges it publishes itself)
        names = set(agg.registry.snapshot())
        assert all(n.startswith("paddle_tpu_fleet_")
                   or n.startswith("paddle_tpu_collective_")
                   for n in names)
        agg.close()

    def test_ring_rotation_drops_are_counted(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        obs.enable()
        tracing.set_capacity(8)
        agg = fleet.serve_aggregator()
        agent = fleet.FleetAgent(agg.endpoint, process="pr",
                                 role="replica", interval_s=60.0)
        for i in range(30):
            tracing.add_event("t.ring_spam", float(i), 1.0)
        assert agent.ship()
        dropped = obs.snapshot()[
            "paddle_tpu_fleet_agent_dropped_events_total"]["series"]
        assert dropped[("ring",)] == 22      # 30 recorded, ring kept 8
        agg.close()

    def test_outbound_buffer_overflow_counted(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        obs.enable()
        agent = fleet.FleetAgent("127.0.0.1:1", process="pb",
                                 role="replica", interval_s=60.0,
                                 buffer_events=4, timeout_s=0.2)
        for i in range(6):
            tracing.add_event("t.buf_spam", float(i), 1.0)
        assert not agent.ship()      # nothing listens on port 1
        dropped = obs.snapshot()[
            "paddle_tpu_fleet_agent_dropped_events_total"]["series"]
        assert dropped[("buffer",)] == 2
        # the surviving 4 moved into the frozen pending bundle; the
        # buffer now accumulates toward the NEXT bundle
        assert len(agent._buffer) == 0
        assert len(agent._pending[0]["trace"]) == 4

    def test_lost_ack_redelivery_commits_without_double_or_loss(self):
        """Merged-but-ack-lost: the retry redelivers the FROZEN bundle
        verbatim, the aggregator dedupes it, and the agent commits on
        the duplicate-ack — nothing double-merges and nothing grown
        between attempts is lost."""
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        agg = fleet.serve_aggregator()
        c = obs.registry().counter("t_ack_total", "")
        agent = fleet.FleetAgent(agg.endpoint, process="pl",
                                 role="replica", interval_s=60.0,
                                 timeout_s=5.0)
        c.inc(5)
        # attempt 1: the send "fails" after the aggregator merged it
        # (lost ack) — simulated by freezing the bundle via a dead
        # transport, then delivering that exact bundle out of band
        real_rpc = fleet._rpc
        fleet._rpc = lambda: (_ for _ in ()).throw(
            ConnectionError("chaos"))
        try:
            assert not agent.ship()
        finally:
            fleet._rpc = real_rpc
        fleet._ingest_bundle(agent._pending[0])
        c.inc(7)                     # grows between attempts
        assert agent.ship()          # redelivery -> duplicate-ack
        assert agent._seq == 1 and agent._pending is None
        assert _snap_series(agg.registry, "t_ack_total")[("pl",)] == 5
        assert agent.ship()          # next bundle carries the growth
        assert _snap_series(agg.registry, "t_ack_total")[("pl",)] == 12
        assert _snap_series(
            agg.registry,
            "paddle_tpu_fleet_duplicate_bundles_total")[("pl",)] == 1
        agg.close()

    def test_custom_registry_agent_self_accounts_in_it(self):
        """An agent shipping a custom registry keeps its own
        shipped/failures/dropped counters THERE — the plane observes
        itself in whichever store it ships."""
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import MetricsRegistry, fleet
        obs.enable()
        reg = MetricsRegistry()
        agent = fleet.FleetAgent("127.0.0.1:1", process="pc", role="r",
                                 interval_s=60.0, timeout_s=0.2,
                                 registry=reg)
        assert not agent.ship()
        assert _snap_series(
            reg, "paddle_tpu_fleet_agent_ship_failures_total")[()] == 1
        assert "paddle_tpu_fleet_agent_ship_failures_total" not in \
            obs.snapshot() or obs.snapshot()[
                "paddle_tpu_fleet_agent_ship_failures_total"][
                    "series"].get((), 0) == 0

    def test_background_thread_and_farewell(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet
        obs.enable()
        agg = fleet.serve_aggregator()
        c = obs.registry().counter("t_bg_total", "")
        agent = fleet.FleetAgent(agg.endpoint, process="pt",
                                 role="replica", interval_s=0.1)
        agent.start()
        c.inc(2)
        deadline = time.time() + 10.0
        while time.time() < deadline:
            s = agg.registry.snapshot().get("t_bg_total")
            if s and s["series"].get(("pt",)) == 2:
                break
            time.sleep(0.05)
        c.inc(4)                    # lands via the stop() farewell
        agent.stop()
        assert _snap_series(agg.registry, "t_bg_total")[("pt",)] == 6
        agg.close()


# ---------------------------------------------------------------------------
# capacity ledger + obs_top fleet panel
# ---------------------------------------------------------------------------
def _capacity_agg(tok_pa=500.0, tok_pb=250.0):
    """Aggregator with two replica processes' worth of engine counters
    over a ~10s reporting window. Rates measure growth past the FIRST
    bundle, so a heartbeat-only bundle establishes the zero baseline
    and a second bundle carries the work."""
    from paddle_tpu.observability import MetricsRegistry, fleet
    from paddle_tpu.observability import metrics as _m
    from paddle_tpu.observability.fleet import FleetAggregator
    _m.enable()
    agg = FleetAggregator(stale_after_s=60.0)
    for proc, tok in (("pa", tok_pa), ("pb", tok_pb)):
        agg.ingest(fleet.make_bundle(proc, "replica", 1))
        src = MetricsRegistry()
        src.counter("paddle_tpu_engine_events_total", "t",
                    ("event",)).labels(event="decode_tokens").inc(tok)
        src.counter("paddle_tpu_request_finished_total", "t",
                    ("reason",)).labels(reason="eos").inc(tok / 50)
        src.gauge("paddle_tpu_roofline_utilization", "t",
                  ("family", "bound")).labels(
            family="engine_ragged", bound="hbm").set(0.42)
        src.gauge("paddle_tpu_engine_queue_depth", "t",
                  ("queue",)).labels(queue="running").set(3)
        agg.ingest(fleet.make_bundle(
            proc, "replica", 2,
            metrics_delta=fleet.delta_snapshot(src.snapshot(), None)))
        agg._procs[proc]["first_seen"] -= 10.0   # give rates a window
    return agg


class TestCapacityLedger:
    def test_capacity_records(self):
        agg = _capacity_agg()
        recs = {r["process"]: r for r in agg.capacity_records()}
        pa = recs["pa"]
        assert pa["process_role"] == "replica"
        assert pa["tokens_total"] == 500.0
        assert pa["tok_per_s"] == pytest.approx(50.0, rel=0.2)
        assert pa["req_per_s"] == pytest.approx(1.0, rel=0.2)
        assert pa["utilization_hbm"] == 0.42
        assert recs["pb"]["tok_per_s"] == pytest.approx(25.0, rel=0.2)

    def test_first_bundle_history_excluded_from_rates(self):
        """A process whose first bundle carries a long pre-agent
        history must not have that history rated over the inter-bundle
        window (it would inflate req/s / tok/s by orders of magnitude
        — the exact number the elastic scaler sizes fleets from)."""
        from paddle_tpu.observability import MetricsRegistry, fleet
        from paddle_tpu.observability import metrics as _m
        from paddle_tpu.observability.fleet import FleetAggregator
        _m.enable()
        agg = FleetAggregator(stale_after_s=60.0)
        src = MetricsRegistry()
        tokc = src.counter("paddle_tpu_engine_events_total", "t",
                           ("event",)).labels(event="decode_tokens")
        tokc.inc(10000)             # pre-agent history
        base = src.snapshot()
        agg.ingest(fleet.make_bundle(
            "ph", "replica", 1,
            metrics_delta=fleet.delta_snapshot(base, None)))
        tokc.inc(100)               # actual in-window work
        agg.ingest(fleet.make_bundle(
            "ph", "replica", 2,
            metrics_delta=fleet.delta_snapshot(src.snapshot(), base)))
        agg._procs["ph"]["first_seen"] -= 10.0
        rec = agg.capacity_records()[0]
        assert rec["tokens_total"] == 10100.0    # totals keep history
        assert rec["tok_per_s"] == pytest.approx(10.0, rel=0.2)

    def test_ledger_append_and_check_keys_by_role(self, tmp_path):
        from tools import perf_ledger
        path = str(tmp_path / "ledger.jsonl")
        agg = _capacity_agg()
        lines = agg.append_capacity_ledger(path, config="fleet_smoke",
                                           rev="rev_a")
        assert len(lines) == 2
        records, bad = perf_ledger.load(path)
        assert bad == 0 and len(records) == 2
        assert perf_ledger._config_key(records[0][1]) == \
            "fleet_smoke@replica"
        # same-rev-only history: self-consistent, passes
        verdict = perf_ledger.check(records, tol=0.2)
        assert verdict["pass"]

    def test_capacity_regression_fails_check(self, tmp_path):
        from tools import perf_ledger
        path = str(tmp_path / "ledger.jsonl")
        _capacity_agg(tok_pa=500.0, tok_pb=500.0).append_capacity_ledger(
            path, config="fleet_smoke", rev="rev_a")
        _capacity_agg(tok_pa=100.0, tok_pb=100.0).append_capacity_ledger(
            path, config="fleet_smoke", rev="rev_b")
        records, _ = perf_ledger.load(path)
        verdict = perf_ledger.check(records, tol=0.2)
        assert not verdict["pass"]
        cfg = verdict["configs"]["fleet_smoke@replica"]
        assert cfg["capacity"]["tok_per_s"]["regressed"]
        assert cfg["capacity"]["tok_per_s"]["baseline_rev"] == "rev_a"
        # improvement (or parity) passes
        _capacity_agg(tok_pa=600.0, tok_pb=600.0).append_capacity_ledger(
            path, config="fleet_smoke", rev="rev_c")
        records, _ = perf_ledger.load(path)
        assert perf_ledger.check(records, tol=0.2)["pass"]


class TestObsTopFleetPanel:
    def _obs_top(self):
        tools = os.path.join(REPO, "tools")
        sys.path.insert(0, tools)
        try:
            import obs_top
        finally:
            sys.path.remove(tools)
        return obs_top

    def test_renders_processes_and_staleness(self):
        obs_top = self._obs_top()
        agg = _capacity_agg()
        agg._procs["pb"]["last_seen"] -= 3600.0   # long gone
        agg.stale_after_s = 60.0
        doc = json.loads(agg.to_json())
        frame = obs_top.render_fleet(doc)
        assert "== fleet ==" in frame
        pa_line = [ln for ln in frame.splitlines() if "pa" in ln][0]
        pb_line = [ln for ln in frame.splitlines() if "pb" in ln][0]
        assert "up" in pa_line and "inflight=  3" in pa_line
        assert "STALE" in pb_line
        assert "bundles=4" in frame
        # the full dashboard embeds the same panel
        assert "== fleet ==" in obs_top.render(doc)
        # tok/s rate appears between frames
        prev = doc
        agg2 = _capacity_agg(tok_pa=600.0)
        frame2 = obs_top.render_fleet(json.loads(agg2.to_json()),
                                      prev, dt=1.0)
        assert "tok/s" in frame2

    def test_no_fleet_series_renders_nothing(self):
        obs_top = self._obs_top()
        assert obs_top.render_fleet({}) == ""


# ---------------------------------------------------------------------------
# disabled-mode overhead guard (two same-call-site windows — the
# interpreter retains ~2KB per call path regardless of iterations)
# ---------------------------------------------------------------------------
class TestDisabledOverhead:
    def test_agent_and_rpc_context_paths_allocate_nothing(self):
        import tracemalloc
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        from paddle_tpu.distributed import rpc
        assert not obs.enabled()
        c = obs.registry().counter("t_ov_fleet_total", "")
        # an agent merely existing must not change hot-path cost
        fleet.FleetAgent("127.0.0.1:1", process="pov", role="r",
                         interval_s=3600.0)
        rpc._obs()                   # warm the lazy handles

        def window(n):
            tracemalloc.start()
            base = tracemalloc.get_traced_memory()[0]
            for _ in range(n):
                c.inc()
                with tracing.span("t.ov_fleet"):
                    pass
                with tracing.trace_context("00" * 8, "00" * 4):
                    pass
                # the rpc client/server guard branches
                if rpc._obs()["m"]._ENABLED or rpc._obs()["t"].enabled():
                    pytest.fail("observability unexpectedly enabled")
            grown = tracemalloc.get_traced_memory()[0] - base
            tracemalloc.stop()
            return grown

        g1 = window(4000)
        g2 = window(4000)
        assert abs(g2 - g1) < 2048, (g1, g2)
        assert tracing.events() == []


# ---------------------------------------------------------------------------
# the real spawn boundary: N workers ship to an aggregator process,
# one killed -9 mid-run
# ---------------------------------------------------------------------------
def _remote_mark(name):
    """Executed in the AGGREGATOR process via rpc — its rpc.server
    span lands in the aggregator's ring, completing the cross-process
    tree whose client half ships with the worker's bundle."""
    return name


def _fleet_worker(endpoint, name, kill_self, q):
    """Spawned worker: records metrics + a traced cross-process RPC,
    ships two sequence-numbered deltas, reports what it shipped, then
    either dies hard (kill_self) or stops cleanly with a farewell."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        from paddle_tpu.distributed import rpc

        obs.enable()
        fleet.set_identity(process=name, role="replica")
        c = obs.registry().counter("paddle_tpu_test_fleet_work_total",
                                   "test work items")
        agent = fleet.FleetAgent(endpoint, interval_s=60.0,
                                 timeout_s=30.0)
        with tracing.span("t.fleet_work", worker=name):
            assert rpc.call_endpoint(endpoint, _remote_mark,
                                     args=(name,), timeout=30.0) == name
        c.inc(5)
        ok1 = agent.ship()
        c.inc(7)
        ok2 = agent.ship()
        q.put((name, 12 if (ok1 and ok2) else None, agent._seq))
        if kill_self:
            time.sleep(1.0)          # let the queue feeder flush
            os.kill(os.getpid(), signal.SIGKILL)
        c.inc(3)
        agent.stop()                 # farewell carries the last 3
    except BaseException as e:       # report instead of hanging parent
        q.put((name, f"ERROR: {e!r}", -1))
        raise


class TestMultiProcessFleet:
    def test_workers_ship_kill9_marks_stale_no_double_count(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import fleet, tracing
        obs.enable()
        agg = fleet.serve_aggregator(stale_after_s=2.0)
        ctx = multiprocessing.get_context("spawn")
        q = ctx.Queue()
        w1 = ctx.Process(target=_fleet_worker,
                         args=(agg.endpoint, "w1", True, q))
        w2 = ctx.Process(target=_fleet_worker,
                         args=(agg.endpoint, "w2", False, q))
        w1.start()
        w2.start()
        reports = {}
        for _ in range(2):
            name, shipped, seq = q.get(timeout=180)
            reports[name] = (shipped, seq)
        w1.join(60)
        w2.join(60)
        assert w1.exitcode == -signal.SIGKILL
        assert w2.exitcode == 0
        assert reports["w1"][0] == 12 and reports["w2"][0] == 12

        snap = agg.registry.snapshot()
        work = snap["paddle_tpu_test_fleet_work_total"]["series"]
        # every acknowledged delta retained, none double-counted (the
        # sequence numbers the workers reported match the aggregator's
        # accepted seq per process)
        assert work[("w1",)] == 12.0
        assert work[("w2",)] == 12.0 + 3.0   # + the farewell ship
        assert agg.processes()["w1"]["last_seq"] == reports["w1"][1]
        # every process label present in the merged exposition
        expo = agg.to_prometheus()
        assert 'process="w1"' in expo and 'process="w2"' in expo
        # the killed worker goes stale within the configured window
        deadline = time.time() + 15.0
        while time.time() < deadline and agg.health()["w1"]["up"]:
            time.sleep(0.2)
        assert not agg.health()["w1"]["up"]

        # one connected cross-process trace per worker: the worker's
        # rpc.client span (its pid, shipped in the bundle) parents the
        # aggregator-side rpc.server span (this pid)
        evs = tracing.events()
        my_pid = os.getpid()
        for wname in ("w1", "w2"):
            roots = [e for e in evs if e["name"] == "t.fleet_work"
                     and e.get("args", {}).get("worker") == wname]
            assert len(roots) == 1, wname
            root = roots[0]
            assert root["pid"] != my_pid
            clients = [e for e in evs if e["name"] == "rpc.client"
                       and e.get("parent_id") == root["span_id"]]
            assert len(clients) == 1, wname
            client = clients[0]
            assert client["trace_id"] == root["trace_id"]
            servers = [e for e in evs if e["name"] == "rpc.server"
                       and e.get("parent_id") == client["span_id"]]
            assert servers and all(
                s["trace_id"] == root["trace_id"]
                and s["pid"] == my_pid for s in servers), wname
        agg.close()
