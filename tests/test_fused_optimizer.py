"""Fused eager optimizer step (VERDICT r4 next-7): all parameter
updates in ONE donated-buffer executable, conformant with the per-param
eager loop (ref: the reference's multi-tensor fused optimizer kernels,
paddle/phi/kernels/gpu/adamw_kernel.cu MP path)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.optimizer import SGD, Momentum, Adam, AdamW


def _train(optcls, kw, fused, steps=3, dtype="float32", mp=False):
    os.environ["PADDLE_TPU_FUSED_OPT"] = "1" if fused else "0"
    try:
        pt.seed(0)
        lin = pt.nn.Linear(16, 16)
        if dtype != "float32":
            lin = getattr(lin, dtype)()
        x = pt.to_tensor(np.random.default_rng(0).standard_normal(
            (4, 16)).astype(np.float32))
        if dtype != "float32":
            x = x.astype(dtype)
        opt = optcls(learning_rate=0.01, parameters=lin.parameters(),
                     multi_precision=mp, **kw)
        for _ in range(steps):
            loss = (lin(x).astype("float32") ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return [np.asarray(p._data, np.float32)
                for p in lin.parameters()], opt
    finally:
        os.environ.pop("PADDLE_TPU_FUSED_OPT", None)


@pytest.mark.parametrize("optcls,kw", [
    (SGD, {}),
    (Momentum, dict(momentum=0.9, weight_decay=1e-4)),
    (Adam, {}),
    (AdamW, dict(weight_decay=0.01)),
])
def test_fused_step_matches_eager_loop(optcls, kw):
    fused, opt_f = _train(optcls, kw, fused=True)
    eager, _ = _train(optcls, kw, fused=False)
    for a, b in zip(fused, eager):
        # one executable fuses differently (e.g. x/sqrt(y) -> x*rsqrt(y));
        # ulp-level deltas only
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=5e-6)
    # the fused path actually engaged (one compiled entry, no sentinel)
    cache = opt_f.__dict__.get("_fused_step_cache", {})
    assert any(v is not opt_f._FUSED_FAIL for v in cache.values())


def test_fused_step_multi_precision():
    fused, opt = _train(AdamW, dict(weight_decay=0.01), fused=True,
                        dtype="bfloat16", mp=True)
    eager, _ = _train(AdamW, dict(weight_decay=0.01), fused=False,
                      dtype="bfloat16", mp=True)
    for a, b in zip(fused, eager):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)
    # master weights stayed f32
    import jax.numpy as jnp
    assert all(v.dtype == jnp.float32
               for v in opt._master_weights.values())


def test_fused_step_engages_once_per_signature():
    os.environ["PADDLE_TPU_FUSED_OPT"] = "1"
    try:
        pt.seed(0)
        lin = pt.nn.Linear(8, 8)
        opt = SGD(learning_rate=0.01, parameters=lin.parameters())
        x = pt.to_tensor(np.ones((2, 8), np.float32))
        for _ in range(4):
            loss = (lin(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert len(opt.__dict__.get("_fused_step_cache", {})) == 1
    finally:
        os.environ.pop("PADDLE_TPU_FUSED_OPT", None)


def test_bf16_params_without_master_fall_back():
    """Low-precision work arrays keep the exact eager path (weak-typed
    python-float lr semantics)."""
    pt.seed(0)
    lin = pt.nn.Linear(8, 8).bfloat16()
    opt = SGD(learning_rate=0.01, parameters=lin.parameters())
    x = pt.to_tensor(np.ones((2, 8), np.float32)).astype("bfloat16")
    loss = (lin(x).astype("float32") ** 2).mean()
    loss.backward()
    opt.step()
    assert not opt.__dict__.get("_fused_step_cache")


def test_fused_step_keeps_external_refs_alive():
    """Donation-safety contract (VERDICT r5 top_next): the fused step
    donates ONLY optimizer-owned accumulator buffers. Parameter and
    gradient buffers are externally visible — wrapper optimizers
    (LookAhead slow weights, ModelAverage sums), EMA callbacks, and
    user code hold them across step() — so refs captured BEFORE fused
    steps must still be readable after (no 'Array has been deleted')."""
    os.environ["PADDLE_TPU_FUSED_OPT"] = "1"
    try:
        pt.seed(0)
        lin = pt.nn.Linear(8, 8)
        opt = Adam(learning_rate=0.01, parameters=lin.parameters())
        x = pt.to_tensor(np.ones((2, 8), np.float32))
        # prime the accumulators + compile the fused executable
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        assert any(v is not opt._FUSED_FAIL for v in
                   opt.__dict__["_fused_step_cache"].values())
        # external captures across a fused step: raw param buffers,
        # the param's grad, and a state_dict snapshot (which must be
        # a COPY — the accumulators themselves ARE donated)
        held_params = [p._data for p in lin.parameters()]
        held_grads = [p._grad._data for p in lin.parameters()]
        snap = opt.state_dict()
        opt.step()                       # fused again (same signature)
        for buf in held_params + held_grads:
            np.asarray(buf)              # must not raise
        for k, v in snap.items():
            if hasattr(v, "numpy"):
                np.asarray(v.numpy())    # must not raise
        # and the snapshot reflects the pre-step state, not the new one
        m1_now = next(iter(opt._accumulators.values()))["moment1"]
        key = [k for k in snap if k.endswith("_moment1")][0]
        assert not np.allclose(snap[key].numpy(), np.asarray(m1_now))
    finally:
        os.environ.pop("PADDLE_TPU_FUSED_OPT", None)


def test_lookahead_modelaverage_over_fused_inner_steps():
    """The shipped-red seed scenario (test_model_average_and_lookahead
    distilled): wrapper optimizers capture p._data at __init__ and
    read it k fused inner steps later — exactly the external-ref
    pattern the donation contract protects."""
    from paddle_tpu.incubate import LookAhead
    os.environ["PADDLE_TPU_FUSED_OPT"] = "1"
    try:
        pt.seed(0)
        lin = pt.nn.Linear(4, 4)
        inner = SGD(learning_rate=0.1, parameters=lin.parameters())
        la = LookAhead(inner, alpha=0.5, k=2)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        w0 = lin.weight.numpy().copy()
        for _ in range(4):
            loss = (lin(x) ** 2).mean()
            loss.backward()
            la.step()                    # inner fused step + slow mix
            la.clear_grad()
        assert not np.allclose(lin.weight.numpy(), w0)
    finally:
        os.environ.pop("PADDLE_TPU_FUSED_OPT", None)


def test_stable_fingerprint_contract():
    """_stable_fp (the repr-free cache-key builder, graftlint
    unstable-cache-key fix): equal-VALUED hyper objects key
    identically, distinct values NEVER collide — the degradation
    direction is always a spurious recompile, never silent reuse of
    an executable compiled with the wrong constants."""
    from paddle_tpu.optimizer.optimizer import _stable_fp

    class Decay:                       # value object, default __repr__
        def __init__(self, c):
            self._coeff = c

    assert _stable_fp(Decay(1e-4)) == _stable_fp(Decay(1e-4))
    assert _stable_fp(Decay(1e-4)) != _stable_fp(Decay(5e-3))
    # nested / unhashable state still fingerprints by value
    assert _stable_fp(Decay([1, 2])) == _stable_fp(Decay([1, 2]))
    assert _stable_fp(Decay([1, 2])) != _stable_fp(Decay([1, 3]))
    # numpy scalars (no __dict__) key by VALUE, not type tag
    assert _stable_fp(np.float32(0.1)) != _stable_fp(np.float32(0.9))
    assert _stable_fp(np.float32(0.1)) == _stable_fp(np.float32(0.1))
    # slots objects degrade to identity (recompile, never collide)
    class S:
        __slots__ = ("c",)
        def __init__(self, c):
            self.c = c
    assert _stable_fp(S(1.0)) != _stable_fp(S(1.0))
    # every fingerprint is hashable by construction (cache.get never
    # raises), including cyclic object graphs
    cyc = Decay(None)
    cyc._coeff = cyc
    for v in (Decay(1e-4), Decay([1, 2]), np.float32(0.1), S(1.0),
              {"wd": Decay(1e-4)}, cyc):
        hash(_stable_fp(v))


def test_fused_step_hits_across_equal_valued_decay_instances():
    """A FRESH equal-valued weight-decay object must hit the cached
    fused executable (pre-fix: repr() fallback keyed per instance —
    one silent recompile per object)."""
    os.environ["PADDLE_TPU_FUSED_OPT"] = "1"
    try:
        class Decay:
            def __init__(self, c):
                self._coeff = c

        pt.seed(0)
        lin = pt.nn.Linear(8, 8)
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        opt = SGD(learning_rate=1e-3, parameters=lin.parameters(),
                  weight_decay=Decay(1e-4))

        def step():
            loss = (lin(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()

        step()
        assert len(opt._fused_step_cache) == 1
        opt.weight_decay = Decay(1e-4)      # fresh EQUAL instance
        step()
        assert len(opt._fused_step_cache) == 1   # hit, no recompile
        opt.weight_decay = Decay(5e-3)      # mutated value
        step()
        assert len(opt._fused_step_cache) == 2   # recompiled
    finally:
        os.environ.pop("PADDLE_TPU_FUSED_OPT", None)
