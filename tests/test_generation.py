"""generate(): static-cache decode must agree with naive full-context
re-forward decoding (ref decoding semantics: beam/top-p ops in ops.yaml;
cache contract as in test/legacy_test/test_fused_multi_transformer ops).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops
from paddle_tpu.models import GPTForCausalLM, generate
from paddle_tpu.models.gpt import gpt_tiny


@pytest.fixture(scope="module")
def model():
    pt.seed(7)
    m = GPTForCausalLM(gpt_tiny(hidden_dropout_prob=0.0,
                                attention_dropout_prob=0.0))
    m.eval()
    return m


def _naive_greedy(model, ids, n_new):
    ids = np.asarray(ids)
    for _ in range(n_new):
        logits = model(pt.to_tensor(ids.astype(np.int32)))
        nxt = np.argmax(np.asarray(logits.numpy())[:, -1], axis=-1)
        ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
    return ids


@pytest.mark.slow  # >25s on the 1-core CI box; --runslow tier
def test_greedy_matches_full_context(model):
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 1024, (2, 7)).astype(np.int32)
    got = model.generate(pt.to_tensor(prompt), max_new_tokens=6).numpy()
    ref = _naive_greedy(model, prompt, 6)
    np.testing.assert_array_equal(got, ref)


@pytest.mark.slow  # >25s on the 1-core CI box; --runslow tier
def test_eos_freezes_sequences(model):
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 1024, (1, 5)).astype(np.int32)
    ref = _naive_greedy(model, prompt, 8)[0, 5:]
    eos = int(ref[2])  # force an eos hit at the 3rd generated token
    got = model.generate(pt.to_tensor(prompt), max_new_tokens=8,
                         eos_token_id=eos).numpy()[0, 5:]
    np.testing.assert_array_equal(got[:3], ref[:3])
    assert (got[3:] == eos).all()


def test_sampling_modes_run(model):
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 1024, (2, 4)).astype(np.int32)
    out = model.generate(pt.to_tensor(prompt), max_new_tokens=5,
                         do_sample=True, temperature=0.8, top_p=0.9,
                         seed=3).numpy()
    assert out.shape == (2, 9)
    assert (out[:, :4] == prompt).all()
    assert (out >= 0).all() and (out < 1024).all()
    # deterministic under a fixed seed
    out2 = model.generate(pt.to_tensor(prompt), max_new_tokens=5,
                          do_sample=True, temperature=0.8, top_p=0.9,
                          seed=3).numpy()
    np.testing.assert_array_equal(out, out2)


def test_length_guard(model):
    prompt = np.zeros((1, 250), np.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        model.generate(pt.to_tensor(prompt), max_new_tokens=10)


def test_fused_step_matches_eager_path(model):
    """The single-executable donated-buffer decode step must reproduce
    the per-op eager decode exactly (greedy and seeded top-p)."""
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 1024, (2, 5)).astype(np.int32)
    for kw in ({"do_sample": False},
               {"do_sample": True, "top_p": 0.9, "seed": 11},
               {"do_sample": False, "eos_token_id": 13}):
        fused = generate(model, pt.to_tensor(prompt), max_new_tokens=8,
                         use_fused_step=True, **kw)
        eager = generate(model, pt.to_tensor(prompt), max_new_tokens=8,
                         use_fused_step=False, **kw)
        np.testing.assert_array_equal(
            np.asarray(fused._data), np.asarray(eager._data),
            err_msg=f"fused/eager decode diverged for {kw}")


def test_llama_generate_matches_full_context():
    """LLaMA family decode: cached generate() must agree with naive
    full-context re-forward greedy decoding (rotary positions + GQA
    cache both exercised)."""
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    pt.seed(3)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 1024, (2, 6)).astype(np.int32)
    want = _naive_greedy(m, prompt, 6)
    out = generate(m, pt.to_tensor(prompt), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out._data), want)
    # fused and eager paths agree too
    eager = generate(m, pt.to_tensor(prompt), max_new_tokens=6,
                     use_fused_step=False)
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.asarray(eager._data))
