"""graftlint: the tier-1 static-analysis gate + self-tests.

Fast and device-free: graftlint is pure stdlib (never imports jax), so
this whole file runs in seconds under JAX_PLATFORMS=cpu or anywhere
else. Covers, per ISSUE 6:

  * one known-bad AND one known-good fixture per rule family
    (donation, purity, recompile, obs);
  * the acceptance self-test — re-adding ``donate_argnums=(1, 3)`` to
    the fused optimizer makes the donation-safety rule fail, while the
    shipped source is clean;
  * suppression semantics (one line exactly), baseline semantics
    (line-shift survival, new-violation failure, occurrence counts);
  * the repo gate: zero non-baselined findings over paddle_tpu/ +
    tools/ with the checked-in baseline;
  * the per-path exemption list pin, the check_metric_names shim, and
    the bench.py lint config emitting graftlint_report.json.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.graftlint import core as gl                      # noqa: E402
from tools.graftlint import config as glconfig              # noqa: E402


def analyze(src, rules=None, readme="", path="fixture.py"):
    return gl.analyze_source(textwrap.dedent(src), path=path,
                             rule_ids=rules, readme_text=readme)


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
def test_rule_registry_covers_four_families():
    rules = gl.rules()
    fams = {r.family for r in rules.values()}
    assert {"donation", "purity", "recompile", "obs"} <= fams
    for r in rules.values():
        assert r.severity in gl.SEVERITIES
        assert r.invariant and r.history, r.id


# ---------------------------------------------------------------------------
# family 1: donation safety
# ---------------------------------------------------------------------------
class TestDonation:
    def test_bad_lambda_returns_donated_param(self):
        fs = analyze("""
            import jax
            def build():
                return jax.jit(lambda a, b: a, donate_argnums=(0,))
        """, rules={"donate-return-alias"})
        assert rule_ids(fs) == ["donate-return-alias"]
        assert "'a'" in fs[0].message

    def test_bad_function_returns_alias_through_local(self):
        fs = analyze("""
            import jax
            def build():
                def step(x, y):
                    z = x
                    return z, y + 1
                return jax.jit(step, donate_argnums=(0,))
        """, rules={"donate-return-alias"})
        assert rule_ids(fs) == ["donate-return-alias"]

    def test_bad_function_stores_donated_on_object(self):
        fs = analyze("""
            import jax
            def build(holder):
                def step(x):
                    holder.kept = x
                    return x + 1
                return jax.jit(step, donate_argnums=(0,))
        """, rules={"donate-return-alias"})
        assert rule_ids(fs) == ["donate-return-alias"]
        assert "holder.kept" in fs[0].message

    def test_good_rebind_through_call_is_clean(self):
        # the canonical donate-input/return-successor pattern
        # (models/generation.py): rebinding through a call CLEARS the
        # alias, so returning the successor is clean
        fs = analyze("""
            import jax
            def build(fwd):
                def step(x, caches):
                    y, caches = fwd(x, caches)
                    out = (y, caches)
                    return out
                return jax.jit(step, donate_argnums=(1,))
        """, rules={"donate-return-alias"})
        assert fs == []

    def test_bad_call_site_donates_external_buffer(self):
        fs = analyze("""
            import jax
            class Opt:
                def step(self, params, upd):
                    work = []
                    for p in params:
                        work.append(p._data)
                    states = [self._own(p) for p in params]
                    e = jax.jit(upd, donate_argnums=(0,)).lower(
                        work, states).compile()
                    return e(work, states)
        """, rules={"donate-external-buffer"})
        assert rule_ids(fs) == ["donate-external-buffer"]
        assert "p._data" in fs[0].message

    def test_good_call_site_donates_owned_state(self):
        # donating the accessor-call results (owned-by-contract) at
        # position 1 while the external buffers ride a NON-donated
        # position is the fixed-optimizer shape
        fs = analyze("""
            import jax
            class Opt:
                def step(self, params, upd):
                    work = []
                    for p in params:
                        work.append(p._data)
                    states = [self._own(p) for p in params]
                    e = jax.jit(upd, donate_argnums=(1,)).lower(
                        work, states).compile()
                    return e(work, states)
        """, rules={"donate-external-buffer"})
        assert fs == []

    # -- the acceptance self-test -------------------------------------
    def _optimizer_src(self):
        with open(os.path.join(ROOT, "paddle_tpu", "optimizer",
                               "optimizer.py"), encoding="utf-8") as f:
            return f.read()

    def test_fixed_optimizer_is_clean(self):
        fs = [f for f in analyze(self._optimizer_src(),
                                 path="paddle_tpu/optimizer/optimizer.py")
              if f.rule.startswith("donate")]
        assert fs == []

    def test_readding_old_donate_argnums_fails(self):
        """Deleting the donation guard — donating work/grads again via
        donate_argnums=(1, 3) — must trip donation-safety: `work` is
        built from p._data, an externally visible Tensor buffer."""
        src = self._optimizer_src()
        bad = src.replace("donate_argnums=(3,)", "donate_argnums=(1, 3)")
        assert bad != src, "donation guard moved — update this test"
        fs = [f for f in analyze(bad) if f.rule.startswith("donate")]
        assert any(f.rule == "donate-external-buffer" and
                   "p._data" in f.message for f in fs), fs


# ---------------------------------------------------------------------------
# family 2: trace purity / host sync
# ---------------------------------------------------------------------------
class TestPurity:
    def test_bad_scan_body_touches_host(self):
        fs = analyze("""
            import jax
            def body(c, x):
                v = float(c.sum())
                print(v)
                return c, x
            def outer(xs):
                return jax.lax.scan(body, 0, xs)
        """, rules={"host-sync-in-trace"})
        assert rule_ids(fs) == ["host-sync-in-trace"] * 2
        assert "float()" in fs[0].message and "print" in fs[1].message

    def test_bad_one_level_reachability(self):
        # np.asarray one bare-name call below a decorated jit function
        fs = analyze("""
            import jax
            import numpy as np
            def helper(x):
                return np.asarray(x)
            @jax.jit
            def fn(x):
                return helper(x)
        """, rules={"host-sync-in-trace"})
        assert rule_ids(fs) == ["host-sync-in-trace"]
        assert "called from traced" in fs[0].message

    def test_nested_traced_def_reports_once(self):
        # an outer jit function whose nested scan body is ALSO traced:
        # the violation inside the body must be reported exactly once
        # (the nested def gets its own walk; the outer walk skips it)
        fs = analyze("""
            import jax
            import numpy as np
            @jax.jit
            def outer(xs):
                def body(c, x):
                    return c, np.asarray(x)
                return jax.lax.scan(body, 0, xs)
        """, rules={"host-sync-in-trace"})
        assert len(fs) == 1

    def test_bad_time_in_while_body(self):
        fs = analyze("""
            import jax, time
            def cond(c):
                return c[0] < 4
            def body(c):
                t = time.perf_counter()
                return (c[0] + 1, t)
            def run(c0):
                return jax.lax.while_loop(cond, body, c0)
        """, rules={"host-sync-in-trace"})
        assert rule_ids(fs) == ["host-sync-in-trace"]
        assert "trace time" in fs[0].message

    def test_good_device_ops_in_jit_are_clean(self):
        fs = analyze("""
            import jax
            import jax.numpy as jnp
            @jax.jit
            def fn(x):
                y = jnp.asarray(x)          # device-side: fine
                return jnp.argmax(y, axis=-1).astype(jnp.int32)
        """, rules={"host-sync-in-trace", "host-sync"})
        assert fs == []

    def test_host_sync_outside_trace_is_warning_only(self):
        fs = analyze("""
            import numpy as np
            def collect(arr):
                return [int(t) for t in np.asarray(arr)]
        """)
        assert rule_ids(fs) == ["host-sync"]
        assert fs[0].severity == "warning"

    def test_host_clock_flagged_on_dispatch_path_only(self):
        src = """
            import time
            def walk(nodes):
                t0 = time.perf_counter()
                return t0
        """
        fs = analyze(src, rules={"host-clock-in-dispatch"},
                     path="paddle_tpu/autograd/some_walker.py")
        assert rule_ids(fs) == ["host-clock-in-dispatch"]
        assert fs[0].severity == "warning"
        # the registry file is audited too
        fs = analyze(src, rules={"host-clock-in-dispatch"},
                     path="paddle_tpu/ops/registry.py")
        assert rule_ids(fs) == ["host-clock-in-dispatch"]
        # everything off the dispatch hot path is not
        fs = analyze(src, rules={"host-clock-in-dispatch"},
                     path="paddle_tpu/inference/llm_engine.py")
        assert fs == []

    def test_host_clock_ignores_non_clock_time_attrs(self):
        fs = analyze("""
            import time
            def nap():
                time.sleep(0.1)
        """, rules={"host-clock-in-dispatch"},
            path="paddle_tpu/autograd/tape.py")
        assert fs == []


# ---------------------------------------------------------------------------
# family 3: recompile hazards
# ---------------------------------------------------------------------------
class TestRecompile:
    def test_bad_repr_in_fingerprint(self):
        fs = analyze("""
            class Opt:
                def _hyper_fingerprint(self):
                    return (repr(self.weight_decay),)
        """, rules={"unstable-cache-key"})
        assert rule_ids(fs) == ["unstable-cache-key"]
        assert "repr()" in fs[0].message

    def test_bad_fstring_cache_key(self):
        fs = analyze("""
            class Eng:
                def get(self, sb, npb):
                    key = f"{sb}x{npb}"
                    return self._decode_fns.get(key)
        """, rules={"unstable-cache-key"})
        assert rule_ids(fs) == ["unstable-cache-key"]
        assert "f-string" in fs[0].message

    def test_bad_id_in_cache_subscript(self):
        fs = analyze("""
            class Eng:
                def get(self, obj):
                    return self._cache[id(obj)]
        """, rules={"unstable-cache-key"})
        assert rule_ids(fs) == ["unstable-cache-key"]

    def test_good_structural_key_is_clean(self):
        fs = analyze("""
            class Eng:
                def get(self, sb, npb):
                    key = (sb, npb, "verify")
                    return self._decode_fns.get(key)
                def _hyper_fingerprint(self):
                    return (self.beta1, self.beta2)
        """, rules={"unstable-cache-key"})
        assert fs == []

    def test_bad_fstring_key_to_persistent_store(self):
        # the persistent-store verbs (load/save/...) are audited like
        # dict verbs: an f-string key reaching disk is never hit again
        fs = analyze("""
            class Eng:
                def save(self, fam, shape, compiled):
                    self._exec_cache.save(f"{fam}-{shape}", compiled)
        """, rules={"unstable-cache-key"})
        assert rule_ids(fs) == ["unstable-cache-key"]
        assert "f-string" in fs[0].message

    def test_bad_repr_key_built_then_loaded_from_store(self):
        fs = analyze("""
            class Eng:
                def warm(self, obj):
                    key = repr(obj)
                    return self.store.load(key)
        """, rules={"unstable-cache-key"})
        assert rule_ids(fs) == ["unstable-cache-key"]
        assert "repr()" in fs[0].message

    def test_good_structural_key_to_persistent_store(self):
        fs = analyze("""
            class Eng:
                def save(self, key, compiled):
                    self._exec_cache.save(key, compiled, family="eng")
                def warm(self, key):
                    return self.store.load(key)
        """, rules={"unstable-cache-key"})
        assert fs == []

    def test_good_identity_map_not_a_store(self):
        # an id()-keyed identity dict does not speak the persistent-
        # store verb surface and must stay clean (tape.py node_store)
        fs = analyze("""
            class Tape:
                def remember(self, node, val):
                    self.node_store[id(node)] = val
                    return self.node_store.get(id(node))
        """, rules={"unstable-cache-key"})
        assert fs == []

    def test_bad_unhashable_static_arg(self):
        fs = analyze("""
            import jax
            def run(f, x):
                return jax.jit(f, static_argnums=(1,))(x, [4, 8])
        """, rules={"unhashable-static-arg"})
        assert rule_ids(fs) == ["unhashable-static-arg"]

    def test_good_hashable_static_arg(self):
        fs = analyze("""
            import jax
            def run(f, x):
                return jax.jit(f, static_argnums=(1,))(x, (4, 8))
        """, rules={"unhashable-static-arg"})
        assert fs == []


# ---------------------------------------------------------------------------
# family 4: observability discipline
# ---------------------------------------------------------------------------
_README = ("paddle_tpu_good_total paddle_tpu_lat_seconds engine.step "
           "request.prefill engine.decode.seq stats documented: "
           "decode_tokens prefills autopilot actions: rollback_resume "
           "evict_rank elastic_restart escalate")


class TestObsDiscipline:
    def test_bad_metric_counter_without_total(self):
        fs = analyze("""
            c = registry().counter("paddle_tpu_bad_count", "help")
        """, rules={"metric-naming"}, readme=_README + " paddle_tpu_bad_count")
        assert rule_ids(fs) == ["metric-naming"]
        assert "_total" in fs[0].message

    def test_bad_metric_undocumented(self):
        fs = analyze("""
            c = registry().counter("paddle_tpu_undoc_total", "help")
        """, rules={"metric-naming"}, readme=_README)
        assert rule_ids(fs) == ["metric-naming"]
        assert "not documented" in fs[0].message

    def test_good_metric_clean(self):
        fs = analyze("""
            c = registry().counter("paddle_tpu_good_total", "help")
            h = r.histogram("paddle_tpu_lat_seconds", "help", ("op",))
        """, rules={"metric-naming"}, readme=_README)
        assert fs == []

    def test_bad_span_name_undocumented(self):
        fs = analyze("""
            def step(_ot):
                with _ot.span("engine.mystery"):
                    pass
        """, rules={"span-naming"}, readme=_README)
        assert rule_ids(fs) == ["span-naming"]

    def test_good_span_name(self):
        fs = analyze("""
            def step(_ot):
                with _ot.span("engine.step"):
                    _ot.add_event("request.prefill", 0.0, 1.0)
        """, rules={"span-naming"}, readme=_README)
        assert fs == []

    def test_bad_fault_point_undocumented(self):
        fs = analyze("""
            def seq(faults):
                faults.fault_point("engine.unknown.seq", rid=1)
        """, rules={"fault-point-naming"}, readme=_README)
        assert rule_ids(fs) == ["fault-point-naming"]

    def test_good_fault_point(self):
        fs = analyze("""
            def seq(faults):
                faults.fault_point("engine.decode.seq", rid=1)
        """, rules={"fault-point-naming"}, readme=_README)
        assert fs == []

    def test_bad_stats_key_undocumented(self):
        fs = analyze("""
            class E:
                def __init__(self):
                    self.stats = _EngineStats(decode_tokens=0)
                def step(self):
                    self.stats["mystery_key"] += 1
        """, rules={"stats-key-naming"}, readme=_README)
        assert rule_ids(fs) == ["stats-key-naming"]
        assert "mystery_key" in fs[0].message

    def test_bad_autopilot_action_undocumented(self):
        fs = analyze("""
            def _plan(self):
                return [{"action": "reboot_datacenter"}]
        """, rules={"autopilot-action-documented"},
            readme=_README,
            path="paddle_tpu/resilience/supervisor.py")
        assert rule_ids(fs) == ["autopilot-action-documented"]
        assert "reboot_datacenter" in fs[0].message

    def test_good_autopilot_actions(self):
        fs = analyze("""
            def _plan(self):
                return [{"action": "rollback_resume"},
                        {"action": "evict_rank"}]

            def go(self, ep):
                self.act("escalate", ep)
        """, rules={"autopilot-action-documented"},
            readme=_README,
            path="paddle_tpu/resilience/supervisor.py")
        assert fs == []

    def test_autopilot_rule_scoped_to_resilience(self):
        fs = analyze("""
            PLAN = [{"action": "reboot_datacenter"}]
        """, rules={"autopilot-action-documented"},
            readme=_README, path="paddle_tpu/engine/thing.py")
        assert fs == []

    def test_bad_autoscale_action_undocumented(self):
        fs = analyze("""
            SCALE_ACTIONS = ("grow", "annihilate")

            def scan(self):
                self._decide("annihilate", 3, trigger={})
        """, rules={"autoscale-action-documented"},
            readme=_README + " autoscaler actions: grow retire",
            path="paddle_tpu/inference/autoscaler.py")
        assert rule_ids(fs) == ["autoscale-action-documented"]
        assert "annihilate" in fs[0].message

    def test_good_autoscale_actions(self):
        fs = analyze("""
            SCALE_ACTIONS = ("grow", "retire")

            def scan(self):
                self._decide("grow", 1, trigger={})
                self._decide("retire", 2, trigger={})
        """, rules={"autoscale-action-documented"},
            readme=_README + " autoscaler actions: grow retire",
            path="paddle_tpu/inference/autoscaler.py")
        assert fs == []

    def test_autoscale_rule_scoped_to_autoscaler(self):
        fs = analyze("""
            SCALE_ACTIONS = ("annihilate",)
        """, rules={"autoscale-action-documented"},
            readme=_README, path="paddle_tpu/inference/router.py")
        assert fs == []

    def test_bad_role_literal_undocumented(self):
        fs = analyze("""
            ROLES = ("prefill", "shredder")

            def launch(factory):
                return factory(role="engine_shredder")
        """, rules={"role-literal-documented"},
            readme=_README + " pool roles: prefill decode "
                             "engine_prefill engine_decode",
            path="paddle_tpu/inference/disagg.py")
        assert rule_ids(fs) == ["role-literal-documented"] * 2
        assert "shredder" in fs[0].message
        assert "engine_shredder" in fs[1].message

    def test_good_role_literals(self):
        fs = analyze("""
            ROLES = ("prefill", "decode")
            PROCESS_ROLES = ("engine_prefill", "engine_decode")

            def launch(factory):
                return factory(role="engine_prefill")
        """, rules={"role-literal-documented"},
            readme=_README + " pool roles: prefill decode "
                             "engine_prefill engine_decode",
            path="paddle_tpu/inference/disagg.py")
        assert fs == []

    def test_role_rule_scoped_to_inference(self):
        fs = analyze("""
            ROLES = ("shredder",)
        """, rules={"role-literal-documented"},
            readme=_README, path="paddle_tpu/resilience/thing.py")
        assert fs == []

    def test_good_stats_keys(self):
        fs = analyze("""
            class E:
                def __init__(self):
                    self.stats = _EngineStats(decode_tokens=0)
                def step(self):
                    self.stats["prefills"] += 1
        """, rules={"stats-key-naming"}, readme=_README)
        assert fs == []

    def test_dark_collective_flagged(self):
        fs = analyze("""
            def all_reduce(tensor, op=0, group=None, sync_op=True):
                return tensor

            def barrier(group=None):
                return None
        """, rules={"collective-instrumentation"},
            path="paddle_tpu/distributed/communication.py")
        assert rule_ids(fs) == ["collective-instrumentation"] * 2
        assert "all_reduce" in fs[0].message
        assert "barrier" in fs[1].message

    def test_instrumented_collective_clean(self):
        fs = analyze("""
            def all_reduce(tensor, op=0, group=None, sync_op=True):
                rec = _comms.start("all_reduce", "world", 4)
                _comms.finish(rec, tensor)
                return tensor

            def ppermute(x, group, perm):
                _comms.count("ppermute", "world", 4)
                return x

            def axis_index(group):      # no payload: exempt
                return 0

            def _private_helper(sync_op=True):   # private: exempt
                return None
        """, rules={"collective-instrumentation"},
            path="paddle_tpu/distributed/communication.py")
        assert fs == []

    def test_collective_rule_scoped_to_communication_module(self):
        fs = analyze("""
            def all_reduce(tensor, sync_op=True):
                return tensor
        """, rules={"collective-instrumentation"},
            path="paddle_tpu/other/module.py")
        assert fs == []

    def test_stats_rule_scoped_to_engine_stats_modules(self):
        # an unrelated stats dict (HostEmbedding.stats) is NOT audited
        fs = analyze("""
            class Table:
                def touch(self):
                    self.stats["rows_touched"] += 1
        """, rules={"stats-key-naming"}, readme=_README)
        assert fs == []


# ---------------------------------------------------------------------------
# suppression semantics: exactly one line
# ---------------------------------------------------------------------------
class TestSuppression:
    SRC = """
        import numpy as np
        def f(a, b):
            x = np.asarray(a)  # graftlint: disable=host-sync
            y = np.asarray(b)
            return x, y
    """

    def test_suppression_covers_exactly_its_line(self):
        fs = analyze(self.SRC, rules={"host-sync"})
        assert len(fs) == 1
        assert "np.asarray(b)" in fs[0].snippet

    def test_wrong_rule_id_does_not_suppress(self):
        src = self.SRC.replace("disable=host-sync", "disable=span-naming")
        fs = analyze(src, rules={"host-sync"})
        assert len(fs) == 2

    def test_disable_all(self):
        src = self.SRC.replace("disable=host-sync", "disable=all")
        fs = analyze(src, rules={"host-sync"})
        assert len(fs) == 1


# ---------------------------------------------------------------------------
# baseline semantics
# ---------------------------------------------------------------------------
class TestBaseline:
    PATH = "pkg/mod.py"
    SRC = """
        import numpy as np
        def f(a):
            return np.asarray(a)
    """

    def _findings(self, src):
        return analyze(src, rules={"host-sync"}, path=self.PATH)

    def test_entries_survive_line_shifts(self):
        base = gl.Baseline(gl.build_baseline(self._findings(self.SRC)))
        shifted = "# one\n# two\n# three\n" + textwrap.dedent(self.SRC)
        new, old = base.split(analyze(shifted, rules={"host-sync"},
                                      path=self.PATH))
        assert new == [] and len(old) == 1

    def test_new_violation_in_baselined_file_fails(self):
        base = gl.Baseline(gl.build_baseline(self._findings(self.SRC)))
        grown = textwrap.dedent(self.SRC) + "\ndef g(b):\n" \
            "    return np.asarray(b + 1)\n"
        new, old = base.split(analyze(grown, rules={"host-sync"},
                                      path=self.PATH))
        assert len(old) == 1 and len(new) == 1
        assert "b + 1" in new[0].snippet

    def test_extra_copy_of_same_snippet_fails(self):
        # entries carry occurrence counts: one more IDENTICAL line is
        # still a new violation
        base = gl.Baseline(gl.build_baseline(self._findings(self.SRC)))
        doubled = textwrap.dedent(self.SRC) + "\ndef g(b):\n" \
            "    return np.asarray(a)\n"
        fs = analyze(doubled, rules={"host-sync"}, path=self.PATH)
        # normalize: both lines carry the same snippet
        assert len({f.baseline_key() for f in fs}) == 1
        new, old = base.split(fs)
        assert len(old) == 1 and len(new) == 1

    def test_keys_are_rule_file_snippet(self):
        f = self._findings(self.SRC)[0]
        assert f.baseline_key() == ("host-sync", self.PATH,
                                    "return np.asarray(a)")

    def test_update_carries_notes_forward(self):
        fs = self._findings(self.SRC)
        prev = gl.Baseline(gl.build_baseline(fs))
        prev.entries[0]["note"] = "justified: host API"
        entries = gl.build_baseline(fs, previous=gl.Baseline(prev.entries))
        assert entries[0]["note"] == "justified: host API"


# ---------------------------------------------------------------------------
# the repo gate + wiring
# ---------------------------------------------------------------------------
def test_repo_has_zero_new_findings():
    """The acceptance gate: paddle_tpu/ + tools/ against the checked-in
    baseline — every finding is either fixed, suppressed with a reason,
    or baselined with a note."""
    baseline = gl.Baseline.load(gl.default_baseline_path())
    rep = gl.run_paths([os.path.join(ROOT, "paddle_tpu"),
                        os.path.join(ROOT, "tools")],
                       root=ROOT, baseline=baseline)
    assert rep.parse_errors == []
    head = "\n".join(f"{f.path}:{f.line}: {f.rule}: {f.message}"
                     for f in rep.new[:8])
    assert rep.new == [], f"new graftlint findings:\n{head}"
    # the baseline is a burn-down list, not a dumping ground: every
    # entry (at its full count) must still match a live finding, so
    # fixing a site forces `--update-baseline` to shrink the file
    from collections import Counter
    live = Counter(f.baseline_key() for f in rep.findings)
    stale = [e for e in baseline.entries
             if live[(e["rule"], e["path"], e["snippet"])] <
             int(e.get("count", 1))]
    assert stale == [], f"stale baseline entries (burn them down): " \
        f"{stale[:4]}"


def test_cli_json_and_exit_code():
    # a subset scan keeps this wall-clock-cheap; the full-tree gate is
    # test_repo_has_zero_new_findings (in-process, no interpreter tax)
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "tools/graftlint",
         "paddle_tpu/optimizer", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    data = json.loads(out.stdout)
    assert data["counts"]["new"] == 0
    assert data["counts"]["total"] == data["counts"]["baselined"]
    assert data["files"] > 10
    for f in data["findings"]:
        assert f["baselined"] is True


def test_cli_zero_files_is_a_failure():
    # a typo'd path must never read as a green gate
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "no_such_dir_xyz"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "wrong path" in out.stderr


def test_exemption_list_pinned():
    """Per-path analysis exemptions are a reviewed contract: operator
    CLIs under tools/ are exempt from the host-sync inventory ONLY."""
    assert glconfig.PATH_EXEMPTIONS == {
        "tools/obs_top.py": frozenset({"host-sync"}),
        "tools/obs_dump.py": frozenset({"host-sync"}),
        "tools/profile_decode.py": frozenset({"host-sync"}),
        "tools/profile_engine.py": frozenset({"host-sync"}),
        "tools/profile_1p3b.py": frozenset({"host-sync"}),
        "tools/dryfit_6p7b.py": frozenset({"host-sync"}),
        "tools/ablate_engine_step.py": frozenset({"host-sync"}),
        "tools/resnet_traffic.py": frozenset({"host-sync"}),
        "tools/gen_ops_parity.py": frozenset({"host-sync"}),
    }
    for rules_disabled in glconfig.PATH_EXEMPTIONS.values():
        assert rules_disabled == frozenset({"host-sync"})


def test_baseline_entries_carry_notes():
    base = gl.Baseline.load(gl.default_baseline_path())
    assert base.entries, "baseline missing"
    for e in base.entries:
        assert e.get("note"), f"baseline entry without justification: {e}"
        assert e["rule"] in gl.rules()


def test_check_metric_names_shim():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_metric_names as cmn
    finally:
        sys.path.pop(0)
    from tools.graftlint.rules import observability as obs_rules
    # the shim delegates to the graftlint rule module (one canonical
    # implementation), and the repo stays clean through it
    assert cmn.check is obs_rules.check
    assert cmn.collect_series is obs_rules.collect_series
    assert cmn.main(ROOT) == 0


def test_bench_lint_config(tmp_path, monkeypatch, capsys):
    import bench
    monkeypatch.chdir(tmp_path)
    result = bench.bench_lint(on_tpu=False)
    assert result["metric"] == "graftlint_new_findings"
    assert result["value"] == 0 and result["vs_baseline"] == 1.0
    report_path = result["extra"]["report"]
    assert os.path.exists(report_path)
    with open(report_path, encoding="utf-8") as f:
        data = json.load(f)
    assert data["counts"]["new"] == 0
    assert result["extra"]["per_rule"].keys() == \
        data["counts"]["per_rule"].keys()
