"""SOT-style graph-break fallback for @to_static(full_graph=False)
(VERDICT r2 missing #4; ref: python/paddle/jit/sot/translate.py:31 —
compile supported subgraphs, run the rest eagerly under guards)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops
from paddle_tpu.jit import to_static, GraphBreakFunction


@to_static(full_graph=False)
def fn_return_in_branch(x):
    y = ops.sin(x) * 2.0          # region 0 (staged)
    z = y + 1.0
    if float(z.sum().numpy()) > 0:  # eager break (return-in-branch)
        return z * 10.0
    w = ops.tanh(z)               # region 1 (staged)
    w = w - 3.0
    return w


@to_static(full_graph=False)
def fn_tensor_predicate(x):
    s = (x * x).sum()             # region 0
    if s > 3.0:                   # eager tensor-bool per call
        out = s * 2.0
    else:
        out = s - 1.0
    return out


class TestGraphBreak:
    def test_return_in_branch_runs_correctly(self):
        x = pt.to_tensor(np.ones((4,), np.float32))
        out = fn_return_in_branch(x)
        ref = (np.sin(np.ones(4)) * 2 + 1) * 10
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)
        # other branch
        x2 = pt.to_tensor(-2 * np.ones((4,), np.float32))
        z2 = np.sin(-2 * np.ones(4)) * 2 + 1
        assert z2.sum() <= 0
        np.testing.assert_allclose(fn_return_in_branch(x2).numpy(),
                                   np.tanh(z2) - 3, atol=1e-5)

    def test_staged_region_count(self):
        assert isinstance(fn_return_in_branch, GraphBreakFunction)
        # two simple-statement runs around the eager `if`
        assert fn_return_in_branch.region_count == 2
        x = pt.to_tensor(np.ones((4,), np.float32))
        fn_return_in_branch(x)
        r0, r1 = fn_return_in_branch.regions
        assert r0.staged_calls > 0           # region 0 always runs
        fn_return_in_branch(pt.to_tensor(-2 * np.ones((4,), np.float32)))
        assert r1.staged_calls > 0           # region 1 via the else path

    def test_tensor_predicate_branches_per_call(self):
        small = fn_tensor_predicate(pt.to_tensor(np.ones(2, np.float32)))
        big = fn_tensor_predicate(pt.to_tensor(np.ones(8, np.float32)))
        np.testing.assert_allclose(float(small.numpy()), 1.0, atol=1e-5)
        np.testing.assert_allclose(float(big.numpy()), 16.0, atol=1e-5)
        assert fn_tensor_predicate.region_count >= 1

    def test_gradients_flow_through_staged_regions(self):
        x = pt.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
        out = fn_return_in_branch(x)
        out.sum().backward()
        assert x.grad is not None
        np.testing.assert_allclose(x.grad.numpy(),
                                   10 * 2 * np.cos(np.ones(4)), atol=1e-4)

    def test_layer_params_train_through_regions(self):
        class M(pt.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = pt.nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)          # region (self.lin params train)
                h = h * 2.0
                if float(h.sum().numpy()) > 1e9:  # eager break
                    return h
                out = ops.tanh(h)        # region
                return out

        m = M()
        sf = to_static(m.forward, full_graph=False)
        assert sf.region_count == 2
        x = pt.to_tensor(np.random.RandomState(0).randn(2, 4).astype(
            np.float32))
        out = sf(x)
        (out ** 2).sum().backward()
        assert m.lin.weight.grad is not None

    def test_break_inside_helper_degrades_to_eager(self):
        def helper(z):
            # data-dependent python branch INSIDE a call — not stageable
            if float(z.sum().numpy()) > 0:
                return z * 2.0
            return z * 3.0

        def f(x):
            y = x + 1.0
            w = helper(y)       # breaks the region's trace probe
            return w

        sf = to_static(f, full_graph=False)
        x = pt.to_tensor(np.ones((3,), np.float32))
        out = sf(x)
        np.testing.assert_allclose(out.numpy(), 4.0 * np.ones(3),
                                   atol=1e-6)
        # the probe detected the break and fell back to eager execution
        assert all(r.staged_calls == 0 for r in sf.regions) or \
            any(r.eager_calls > 0 for r in sf.regions)

    def test_loops_execute_eagerly(self):
        @to_static(full_graph=False)
        def f(x, n):
            acc = x * 0.0                 # region
            for _ in range(n):            # eager python loop
                acc = acc + x
            out = acc * 2.0               # region
            return out

        x = pt.to_tensor(np.ones((3,), np.float32))
        np.testing.assert_allclose(f(x, 3).numpy(), 6 * np.ones(3),
                                   atol=1e-6)
        assert f.region_count == 2