"""ZeRO stages 1/2/3 (group-sharded) + memory accounting (VERDICT r1
item 5, C20).

The "memory actually drops" criterion uses exact per-device resident
bytes (device.memory.state_bytes_per_device) rather than allocator
telemetry, so it holds on the CPU test mesh too.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.device import memory


@pytest.fixture(autouse=True)
def _reset_groups():
    dist.destroy_process_group()
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group
    set_hybrid_communicate_group(None)
    yield
    dist.destroy_process_group()
    set_hybrid_communicate_group(None)


def _build(level, seed=11):
    pt.seed(seed)
    model = pt.nn.Sequential(
        pt.nn.Linear(64, 256), pt.nn.ReLU(), pt.nn.Linear(256, 64))
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    model, opt = dist.sharding.group_sharded_parallel(model, opt, level)
    return model, opt


def _step(model, opt, seed=0):
    rng = np.random.default_rng(seed)
    x = pt.to_tensor(rng.standard_normal((16, 64)).astype(np.float32))
    inner = getattr(model, "_layers", model)
    loss = pt.ops.mean(inner(x) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


class TestGroupShardedLevels:
    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_trains_finite(self, level):
        model, opt = _build(level)
        l1 = _step(model, opt)
        l2 = _step(model, opt)
        assert np.isfinite(l1) and np.isfinite(l2)
        assert l2 < l1

    def test_levels_agree_numerically(self):
        results = {}
        for level in ["os", "os_g", "p_g_os"]:
            model, opt = _build(level)
            _step(model, opt, seed=3)
            inner = getattr(model, "_layers", model)
            results[level] = {
                n: np.asarray(
                    p._data.astype("float32").numpy()
                    if hasattr(p._data, "numpy") else p.numpy())
                for n, p in inner.named_parameters()}
        base = results["os"]
        for level in ["os_g", "p_g_os"]:
            for k in base:
                got = results[level][k]
                # sharded matmuls change fp reduction order; Adam's
                # g/sqrt(g^2) amplifies that near zero — 1e-4 still
                # catches any semantic error (wrong n-factor etc.)
                np.testing.assert_allclose(got, base[k], rtol=1e-3,
                                           atol=1e-4,
                                           err_msg=f"{level}:{k}")

    def test_bad_level_raises(self):
        model, opt = None, None
        with pytest.raises(ValueError):
            dist.sharding.group_sharded_parallel(model, opt, "zz")

    def test_offload_unsupported(self):
        with pytest.raises(NotImplementedError):
            dist.sharding.group_sharded_parallel(None, None, "p_g_os",
                                                 offload=True)


class TestZeroMemoryProof:
    def test_stage3_per_device_state_drops(self):
        # Replicated baseline: every device stores params + 2 moments.
        pt.seed(5)
        m0 = pt.nn.Linear(256, 256)
        o0 = pt.optimizer.AdamW(learning_rate=1e-3,
                                parameters=m0.parameters())
        rng = np.random.default_rng(0)
        x = pt.to_tensor(rng.standard_normal((8, 256)).astype(np.float32))
        loss = pt.ops.mean(m0(x) ** 2)
        loss.backward()
        o0.step()
        state0 = list(m0.parameters()) + [
            v for st in o0._accumulators.values() for v in st.values()
            if getattr(v, "ndim", 0) > 0]
        base = memory.state_bytes_per_device(state0)

        # Stage 3 on the 8-way sharding mesh.
        m3, o3 = _build("p_g_os")
        _step(m3, o3)
        inner = getattr(m3, "_layers", m3)
        opt = o3._inner_opt
        state3 = list(inner.parameters()) + [
            v for st in opt._accumulators.values() for v in st.values()
            if getattr(v, "ndim", 0) > 0]
        sharded = memory.state_bytes_per_device(state3)

        # per-parameter-byte comparison: bytes per device per model byte
        def density(per_dev, params_bytes):
            return max(per_dev.values()) / params_bytes

        b0 = sum(p._data.size * p._data.dtype.itemsize
                 for p in m0.parameters())
        b3 = sum(p._data.size * p._data.dtype.itemsize
                 for p in inner.parameters())
        d0 = density(base, b0)
        d3 = density(sharded, b3)
        # 8-way sharding: expect ~1/8 of the replicated density; require
        # at least the VERDICT's 0.5x criterion with margin
        assert d3 < 0.5 * d0, (d0, d3)
        assert d3 < 0.2 * d0, (d0, d3)  # actual arithmetic ~0.125

    def test_stage2_grads_sharded_stage1_not(self):
        m1, o1 = _build("os")
        m2, o2 = _build("os_g")
        for model, opt, expect_sharded in ((m1, o1, False),
                                           (m2, o2, True)):
            rng = np.random.default_rng(0)
            x = pt.to_tensor(rng.standard_normal((16, 64))
                             .astype(np.float32))
            inner = getattr(model, "_layers", model)
            loss = pt.ops.mean(inner(x) ** 2)
            loss.backward()
            opt.step()  # stage>=2 commits grads before the update
            g = next(p for p in inner.parameters()
                     if p.grad is not None and p.ndim > 1).grad
            spec = getattr(g._data.sharding, "spec", None)
            if expect_sharded:
                assert "sharding" in str(spec), spec
            else:
                assert "sharding" not in str(spec), spec
            opt.clear_grad()

    def test_memory_stats_api_shape(self):
        # PJRT may not populate stats on every backend; the API must
        # still return well-typed values
        assert isinstance(memory.memory_stats(), dict)
        assert isinstance(memory.memory_allocated(), int)
        assert isinstance(memory.max_memory_allocated(), int)
        memory.reset_max_memory_allocated()
        assert isinstance(memory.max_memory_allocated(), int)
        from paddle_tpu.device import cuda
        assert isinstance(cuda.memory_stats(), dict)
