"""Host-RAM-backed embedding table (VERDICT r4 next-5; ref:
paddle/fluid/distributed/ps/table/memory_sparse_table.h /
ssd_sparse_table.h — beyond-device-memory tables, sparse push/pull)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.ps import HostEmbedding


def _loss_grad(emb, ids, target):
    out = emb(pt.to_tensor(ids))
    loss = ((out - pt.to_tensor(target)) ** 2).mean()
    loss.backward()
    return float(loss.numpy())


def test_forward_matches_table_rows():
    emb = HostEmbedding(100, 8, init_std=0.01, seed=3)
    ids = np.array([[3, 5], [5, 97]], np.int64)
    out = emb(pt.to_tensor(ids)).numpy()
    assert out.shape == (2, 2, 8)
    np.testing.assert_allclose(out[0, 0], emb.table[3], rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], out[1, 0])   # same row 5
    # device footprint is O(unique rows), not O(table)
    assert emb.stats["device_bytes_last"] == 3 * 8 * 4


def test_lazy_init_deterministic_wrt_touch_order():
    a = HostEmbedding(50, 4, init_std=0.1, seed=7)
    b = HostEmbedding(50, 4, init_std=0.1, seed=7)
    a(pt.to_tensor(np.array([1, 2, 3], np.int64)))
    b(pt.to_tensor(np.array([3], np.int64)))
    b(pt.to_tensor(np.array([2, 1], np.int64)))
    np.testing.assert_array_equal(a.table[1:4], b.table[1:4])
    # untouched rows stay zero (virtual pages)
    assert not a.table[10].any()


def test_sgd_update_with_duplicate_ids():
    emb = HostEmbedding(20, 4, optimizer="sgd", learning_rate=0.5,
                        init_std=0.0)
    emb.table[:] = 1.0
    ids = np.array([2, 2, 7], np.int64)
    out = emb(pt.to_tensor(ids))
    # d(sum)/d(row2) accumulates BOTH duplicate occurrences
    out.sum().backward()
    emb.apply_updates()
    np.testing.assert_allclose(emb.table[2], 1.0 - 0.5 * 2.0)
    np.testing.assert_allclose(emb.table[7], 1.0 - 0.5 * 1.0)
    np.testing.assert_allclose(emb.table[3], 1.0)      # untouched


def test_adagrad_matches_reference_math():
    emb = HostEmbedding(10, 2, optimizer="adagrad", learning_rate=0.1,
                        adagrad_epsilon=1e-6, init_std=0.0)
    emb.table[:] = 2.0
    ids = np.array([4], np.int64)
    for _ in range(2):
        out = emb(pt.to_tensor(ids))
        out.sum().backward()
        emb.apply_updates()
    # grad is 1.0 each step: acc=1 -> step 0.1/1; acc=2 -> 0.1/sqrt(2)
    want = 2.0 - 0.1 / (1.0 + 1e-6) - 0.1 / (np.sqrt(2.0) + 1e-6)
    np.testing.assert_allclose(emb.table[4], want, rtol=1e-6)


def test_training_reduces_loss():
    rng = np.random.default_rng(0)
    emb = HostEmbedding(1000, 8, optimizer="adagrad", learning_rate=0.5,
                        init_std=0.01)
    ids = rng.integers(0, 1000, (16,)).astype(np.int64)
    target = rng.standard_normal((16, 8)).astype(np.float32)
    first = _loss_grad(emb, ids, target)
    emb.apply_updates()
    for _ in range(20):
        _loss_grad(emb, ids, target)
        emb.apply_updates()
    last = _loss_grad(emb, ids, target)
    assert last < first * 0.2, (first, last)


def test_prefetch_double_buffer():
    emb = HostEmbedding(100, 4, init_std=0.01)
    ids1 = np.array([1, 2], np.int64)
    ids2 = np.array([3, 4], np.int64)
    emb.prefetch(ids1)
    out1 = emb(pt.to_tensor(ids1))
    emb.prefetch(ids2)
    out2 = emb(pt.to_tensor(ids2))
    assert emb.stats["prefetch_hits"] == 2
    np.testing.assert_allclose(out2.numpy()[0], emb.table[3], rtol=1e-6)
    # a stale prefetch is ignored, not wrongly consumed
    emb.prefetch(ids1)
    out3 = emb(pt.to_tensor(ids2))
    np.testing.assert_allclose(out3.numpy(), out2.numpy())


def test_beyond_hbm_accounting():
    """A table logically larger than this box's device HBM (16 GB)
    trains fine: np.zeros pages are virtual until touched, and the
    device only ever sees the batch's unique rows."""
    emb = HostEmbedding(300_000_000, 16, optimizer="sgd",
                        learning_rate=0.1, init_std=0.0)   # 19.2 GB logical
    assert emb.host_bytes() >= 19_000_000_000
    ids = np.array([0, 123_456_789, 299_999_999], np.int64)
    emb.table[ids] = 1.0
    out = emb(pt.to_tensor(ids))
    out.sum().backward()
    emb.apply_updates()
    np.testing.assert_allclose(emb.table[123_456_789], 0.9, rtol=1e-6)
    assert emb.stats["device_bytes_last"] == 3 * 16 * 4


def test_out_of_range_raises():
    emb = HostEmbedding(10, 2)
    with pytest.raises(IndexError):
        emb(pt.to_tensor(np.array([10], np.int64)))


# ---------------------------------------------------------------------------
# vectorized lazy init (embedding.store.row_init)
# ---------------------------------------------------------------------------
def test_row_init_batched_matches_rowwise():
    """The batched counter-based stream is a pure function of
    (seed, global row id, column): initializing rows one at a time, in
    any order, gives bit-for-bit the same values as one batched call —
    the property the vectorized `_ensure_init` relies on."""
    from paddle_tpu.embedding.store import row_init
    rows = np.array([0, 7, 3, 1_000_003, 42], np.int64)
    batched = row_init(rows, 16, seed=9, std=0.02, dtype=np.float32)
    rowwise = np.concatenate([
        row_init(np.array([r], np.int64), 16, seed=9, std=0.02,
                 dtype=np.float32)
        for r in rows])
    np.testing.assert_array_equal(batched, rowwise)
    # and the stream is keyed on the GLOBAL id: shard (scale, offset)
    # relabeling reproduces the unsharded values exactly
    a = HostEmbedding(100, 8, init_std=0.1, seed=5)
    b = HostEmbedding(50, 8, init_std=0.1, seed=5,
                      init_id_scale=2, init_id_offset=1)   # shard 1 of 2
    a(pt.to_tensor(np.array([3, 7], np.int64)))       # global rows 3, 7
    b(pt.to_tensor(np.array([1, 3], np.int64)))       # local 1,3 -> 3,7
    np.testing.assert_array_equal(a.table[3], b.table[1])
    np.testing.assert_array_equal(a.table[7], b.table[3])


def test_row_init_stats_distribution():
    from paddle_tpu.embedding.store import row_init
    vals = row_init(np.arange(4096), 32, seed=1, std=0.5,
                    dtype=np.float32)
    assert np.isfinite(vals).all()
    assert abs(float(vals.mean())) < 0.01
    assert abs(float(vals.std()) - 0.5) < 0.01


# ---------------------------------------------------------------------------
# prefetch vs apply_updates: the version fence
# ---------------------------------------------------------------------------
def test_prefetch_invalidated_by_update_never_serves_stale_rows():
    """A prefetch issued BEFORE apply_updates gathered pre-update rows;
    the update must invalidate it (counted under
    `prefetch_invalidated`) and the next forward must serve the
    POST-update values."""
    emb = HostEmbedding(50, 4, optimizer="sgd", learning_rate=1.0,
                        init_std=0.0)
    emb.table[:] = 1.0
    ids = np.array([2, 3], np.int64)
    out = emb(pt.to_tensor(ids))
    out.sum().backward()
    emb.prefetch(ids)               # gathers the PRE-update rows
    emb.apply_updates()             # rows 2,3 -> 0.0; invalidates it
    assert emb.stats["prefetch_invalidated"] == 1
    out2 = emb(pt.to_tensor(ids)).numpy()
    np.testing.assert_array_equal(out2, np.zeros((2, 4), np.float32))
    assert emb.stats["prefetch_hits"] == 0


def test_version_fence_rejects_adversarial_schedule():
    """Even if an invalidated in-flight gather REAPPEARS at consume
    time (the worst-case thread schedule the `_inflight` hand-off
    alone can't rule out), the version fence in forward refuses it:
    the gather snapshotted a table version older than the update."""
    emb = HostEmbedding(50, 4, optimizer="sgd", learning_rate=1.0,
                        init_std=0.0)
    emb.table[:] = 1.0
    ids = np.array([5, 6], np.int64)
    emb.prefetch(ids)
    key, t, holder = emb._inflight
    t.join()                        # gather definitely completed (old)
    out = emb(pt.to_tensor(ids))
    out.sum().backward()
    emb.apply_updates()             # bumps the table version
    emb._inflight = (key, t, holder)    # adversarial: stale reappears
    before = emb.stats["prefetch_invalidated"]
    out2 = emb(pt.to_tensor(ids)).numpy()
    np.testing.assert_array_equal(out2, np.zeros((2, 4), np.float32))
    assert emb.stats["prefetch_invalidated"] == before + 1
    assert emb.stats["prefetch_hits"] == 1  # only the pre-update consume


def test_orphaned_prefetch_workers_are_joined():
    """Stale / invalidated prefetch workers are parked and joined by
    the next forward — bounded thread count, no daemon leak."""
    emb = HostEmbedding(50, 4, init_std=0.01)
    ids1 = np.array([1, 2], np.int64)
    ids2 = np.array([3, 4], np.int64)
    emb.prefetch(ids1)
    emb(pt.to_tensor(ids2))         # mismatch: ids1 gather parked
    assert emb.stats["prefetch_stale"] == 1
    assert len(emb._orphans) == 1
    orphan_thread = emb._orphans[0][1]
    emb(pt.to_tensor(ids2))         # next forward drains the park list
    assert emb._orphans == []
    assert not orphan_thread.is_alive()
