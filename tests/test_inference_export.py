"""jit.save -> StableHLO export -> jit.load / Predictor (VERDICT r1
item 7). Round-trip criterion: identical logits without the Python
model class."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.jit import InputSpec, TranslatedLayer


def _model():
    pt.seed(3)
    return pt.nn.Sequential(
        pt.nn.Linear(8, 32), pt.nn.GELU(), pt.nn.Linear(32, 4))


class TestExportRoundTrip:
    def test_save_load_identical_logits(self, tmp_path):
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        x = np.random.default_rng(0).standard_normal((5, 8)) \
            .astype(np.float32)
        ref = m(pt.to_tensor(x)).numpy()

        loaded = pt.jit.load(path)
        assert isinstance(loaded, TranslatedLayer)
        out = loaded(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    def test_symbolic_batch_serves_any_size(self, tmp_path):
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        loaded = pt.jit.load(path)
        for bs in (1, 3, 17):
            x = np.ones((bs, 8), np.float32)
            assert loaded(pt.to_tensor(x)).shape == [bs, 4]

    def test_gpt_logits_roundtrip(self, tmp_path):
        from paddle_tpu.models import gpt_tiny, GPTForCausalLM
        pt.seed(1)
        m = GPTForCausalLM(gpt_tiny(hidden_dropout_prob=0.0,
                                    attention_dropout_prob=0.0))
        m.eval()
        path = str(tmp_path / "gpt")
        pt.jit.save(m, path, input_spec=[InputSpec([1, 16], "int32")])
        ids = np.random.default_rng(0).integers(0, 1000, (1, 16)) \
            .astype(np.int32)
        ref = m(pt.to_tensor(ids)).numpy()
        loaded = pt.jit.load(path)
        out = loaded(pt.to_tensor(ids)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_params_only_save_without_spec(self, tmp_path):
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path)
        state = pt.jit.load(path)
        assert isinstance(state, dict)
        assert any(k.endswith("weight") for k in state)

    def test_state_dict_exposed(self, tmp_path):
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path, input_spec=[InputSpec([2, 8], "float32")])
        loaded = pt.jit.load(path)
        sd = loaded.state_dict()
        assert set(sd) == set(k for k, _ in m.named_parameters())


class TestPredictor:
    def test_handle_api(self, tmp_path):
        from paddle_tpu import inference
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        cfg = inference.Config(path + ".pdmodel", path + ".pdiparams")
        pred = inference.create_predictor(cfg)
        names = pred.get_input_names()
        assert names == ["x0"]
        x = np.random.default_rng(1).standard_normal((4, 8)) \
            .astype(np.float32)
        pred.get_input_handle("x0").copy_from_cpu(x)
        pred.run()
        out_names = pred.get_output_names()
        out = pred.get_output_handle(out_names[0]).copy_to_cpu()
        np.testing.assert_allclose(out, m(pt.to_tensor(x)).numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_direct_run(self, tmp_path):
        from paddle_tpu import inference
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path, input_spec=[InputSpec([2, 8], "float32")])
        pred = inference.create_predictor(inference.Config(path))
        x = np.ones((2, 8), np.float32)
        (out,) = pred.run([x])
        np.testing.assert_allclose(out, m(pt.to_tensor(x)).numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_missing_program_raises(self, tmp_path):
        from paddle_tpu import inference
        m = _model()
        path = str(tmp_path / "m")
        pt.jit.save(m, path)  # params only
        with pytest.raises(ValueError, match="no serialized program"):
            inference.create_predictor(inference.Config(path))


class TestSymbolicDims:
    def test_multiple_dynamic_dims_one_scope(self, tmp_path):
        # regression: two dynamic dims used to land in different
        # symbolic scopes and fail to export
        pt.seed(4)
        m = pt.nn.Sequential(pt.nn.Linear(8, 8))
        path = str(tmp_path / "m")
        pt.jit.save(m, path,
                    input_spec=[InputSpec([None, None, 8], "float32")])
        loaded = pt.jit.load(path)
        for shp in ((2, 3, 8), (5, 7, 8)):
            x = np.ones(shp, np.float32)
            assert loaded(pt.to_tensor(x)).shape == list(shp)

    def test_two_dynamic_inputs_independent_sizes(self, tmp_path):
        pt.seed(4)

        class Cat(pt.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = pt.nn.Linear(8, 2)

            def forward(self, a, b):
                return self.lin(pt.ops.concat([a, b], axis=0))

        m = Cat()
        path = str(tmp_path / "m")
        pt.jit.save(m, path,
                    input_spec=[InputSpec([None, 8], "float32"),
                                InputSpec([None, 8], "float32")])
        loaded = pt.jit.load(path)
        out = loaded(pt.to_tensor(np.ones((2, 8), np.float32)),
                     pt.to_tensor(np.ones((5, 8), np.float32)))
        assert out.shape == [7, 2]
