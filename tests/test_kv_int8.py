"""Int8 KV-cache serving (VERDICT r4 next-3): per-head static scales on
masked/block multihead attention + the LLMEngine int8 pool.

ref: python/paddle/incubate/nn/functional/block_multihead_attention.py:19
(cache_k_quant_scales/... operands)."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.nn.functional import (
    masked_multihead_attention, block_multihead_attention)


def _scales_from(x, axis):
    amax = np.max(np.abs(x), axis=axis)
    return (127.0 / np.maximum(amax, 1e-6)).astype(np.float32)


def test_masked_mha_int8_cache_conformance():
    rng = np.random.default_rng(0)
    B, H, L, D = 3, 4, 32, 16
    t = np.array([5, 9, 0], np.int32)
    cache = rng.standard_normal((2, B, H, L, D)).astype(np.float32) * 0.5
    # only positions < t are ever read; zero the rest for the oracle
    for b in range(B):
        cache[:, b, :, t[b]:, :] = 0.0
    x = (rng.standard_normal((B, 3 * H * D)) * 0.5).astype(np.float32)

    out_fp, _ = masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(t[:, None]))

    kq = _scales_from(cache[0], axis=(0, 2, 3)) / 1.2   # headroom for x
    vq = _scales_from(cache[1], axis=(0, 2, 3)) / 1.2
    cache_i8 = np.stack([
        np.clip(np.round(cache[0] * kq[None, :, None, None]), -127, 127),
        np.clip(np.round(cache[1] * vq[None, :, None, None]), -127, 127),
    ]).astype(np.int8)
    out_q, cache_out = masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache_i8),
        sequence_lengths=pt.to_tensor(t[:, None]),
        cache_k_quant_scales=pt.to_tensor(kq),
        cache_v_quant_scales=pt.to_tensor(vq))
    assert cache_out.numpy().dtype == np.int8
    np.testing.assert_allclose(out_q.numpy(), out_fp.numpy(),
                               atol=2.5e-2, rtol=0)


def test_masked_mha_int8_requires_matching_dtype():
    B, H, L, D = 1, 2, 8, 4
    cache = np.zeros((2, B, H, L, D), np.float32)
    x = np.zeros((B, 3 * H * D), np.float32)
    with pytest.raises(ValueError, match="int8 KV cache"):
        masked_multihead_attention(
            pt.to_tensor(x), pt.to_tensor(cache),
            sequence_lengths=pt.to_tensor(np.zeros((B, 1), np.int32)),
            cache_k_quant_scales=pt.to_tensor(np.ones(H, np.float32)),
            cache_v_quant_scales=pt.to_tensor(np.ones(H, np.float32)))


def _block_args(rng, B, kvH, H, D, bs, npb, lens, dtype, kq=None, vq=None):
    nb = B * npb + 1
    kcache = np.zeros((nb, kvH, bs, D), dtype)
    vcache = np.zeros((nb, kvH, bs, D), dtype)
    tbl = np.arange(B * npb, dtype=np.int32).reshape(B, npb) + 1
    return kcache, vcache, tbl


def test_block_mha_int8_decode_conformance():
    """One decode step against a pre-filled paged cache: int8 pages with
    per-kv-head scales vs fp32 pages."""
    rng = np.random.default_rng(1)
    B, kvH, H, D, bs, npb = 2, 2, 4, 16, 8, 3
    lens = np.array([13, 7], np.int32)
    kcf, vcf, tbl = _block_args(rng, B, kvH, H, D, bs, npb, lens,
                                np.float32)
    # pre-fill the fp cache at each row's positions < len
    kvals = rng.standard_normal((B, kvH, npb * bs, D)).astype(
        np.float32) * 0.7
    vvals = rng.standard_normal((B, kvH, npb * bs, D)).astype(
        np.float32) * 0.7
    for b in range(B):
        for p in range(npb):
            phys = tbl[b, p]
            kcf[phys] = kvals[b, :, p * bs:(p + 1) * bs, :]
            vcf[phys] = vvals[b, :, p * bs:(p + 1) * bs, :]
    qkv = (rng.standard_normal((B, (H + 2 * kvH) * D)) * 0.7).astype(
        np.float32)
    cu = np.arange(B + 1, dtype=np.int32)
    args = dict(
        seq_lens_encoder=pt.to_tensor(np.zeros(B, np.int32)),
        seq_lens_decoder=pt.to_tensor(lens),
        seq_lens_this_time=pt.to_tensor(np.ones(B, np.int32)),
        padding_offsets=None, cum_offsets=None,
        cu_seqlens_q=pt.to_tensor(cu), cu_seqlens_k=pt.to_tensor(cu),
        block_tables=pt.to_tensor(tbl), block_size=bs)

    out_fp, _, _, _ = block_multihead_attention(
        pt.to_tensor(qkv), pt.to_tensor(kcf), pt.to_tensor(vcf), **args)

    kq = _scales_from(kvals, axis=(0, 2, 3)) / 1.2
    vq = _scales_from(vvals, axis=(0, 2, 3)) / 1.2
    k8 = np.clip(np.round(kcf * kq[None, :, None, None]), -127,
                 127).astype(np.int8)
    v8 = np.clip(np.round(vcf * vq[None, :, None, None]), -127,
                 127).astype(np.int8)
    out_q, _, kout, vout = block_multihead_attention(
        pt.to_tensor(qkv), pt.to_tensor(k8), pt.to_tensor(v8),
        cache_k_quant_scales=pt.to_tensor(kq),
        cache_v_quant_scales=pt.to_tensor(vq), **args)
    assert kout.numpy().dtype == np.int8
    np.testing.assert_allclose(out_q.numpy(), out_fp.numpy(),
                               atol=3e-2, rtol=0)


def test_engine_int8_pool():
    """End-to-end: calibrated int8 paged pool halves cache bytes; greedy
    decode stays closely aligned with the fp16 engine (quantisation can
    legitimately flip near-ties, so require strong but not exact
    agreement)."""
    from paddle_tpu.inference import LLMEngine, calibrate_kv_scales
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny())
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
               for n in (8, 12)]
    n_new = 8
    ref = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64)
    ref_out = [r.output_ids for r in ref.generate(prompts, n_new)]

    scales = calibrate_kv_scales(model, prompts[1][None])
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64,
                    kv_quant_scales=scales)
    assert eng.cache.key_caches[0].dtype == jnp.int8
    out = [r.output_ids for r in eng.generate(prompts, n_new)]
    agree = np.mean([np.mean(a == b) for a, b in zip(out, ref_out)])
    assert agree >= 0.5, f"int8 decode diverged too far (agree={agree})"
