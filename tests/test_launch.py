"""Launch CLI + multi-process bootstrap tests (VERDICT r1 item 6).

The real-process test spawns `python -m paddle_tpu.distributed.launch
--backend cpu --nproc_per_node 2 --devices-per-proc 4` — two OS
processes, each with 4 virtual CPU devices, forming one 8-device
jax.distributed job (the reference's test_dist_base subprocess
pattern)."""
import os
import re
import subprocess
import sys

import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PAYLOAD = os.path.join(REPO, "tests", "launch_payload.py")


def _scrubbed_env():
    from paddle_tpu.distributed.launch.main import scrub_backend_env
    env = scrub_backend_env(dict(os.environ))
    # the LAUNCHER process itself must not grab a TPU backend (libtpu is
    # installed even when the axon plugin env is scrubbed)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    return env


class TestLaunchCLI:
    def test_two_process_train_step(self, tmp_path):
        log_dir = str(tmp_path / "logs")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--backend", "cpu", "--nproc_per_node", "2",
             "--devices-per-proc", "4", "--log_dir", log_dir, PAYLOAD],
            env=_scrubbed_env(), cwd=REPO, timeout=600,
            capture_output=True, text=True)
        logs = ""
        for rank in (0, 1):
            with open(os.path.join(log_dir, f"workerlog.{rank}")) as f:
                logs += f.read()
        assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
        losses = re.findall(r"LAUNCH_OK rank=(\d) world=2 "
                            r"loss=([0-9.]+)", logs)
        assert sorted(r for r, _ in losses) == ["0", "1"], logs
        # SPMD: both processes computed the same global loss
        assert losses[0][1] == losses[1][1], logs

    def test_failure_propagates(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import sys; sys.exit(3)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--backend", "cpu", "--nproc_per_node", "2",
             "--devices-per-proc", "2", str(bad)],
            env=_scrubbed_env(), cwd=REPO, timeout=120,
            capture_output=True, text=True)
        assert proc.returncode == 3

    def test_multinode_requires_master(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", PAYLOAD],
            env=_scrubbed_env(), cwd=REPO, timeout=60,
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "--master" in proc.stderr


class TestBootstrapEnv:
    def test_single_process_noop(self):
        import paddle_tpu.distributed as dist
        g = dist.init_parallel_env()
        assert g is not None
        assert dist.get_rank() == 0

    def test_env_parsing_guard(self, monkeypatch):
        from paddle_tpu.distributed import parallel
        monkeypatch.delenv("PADDLE_MASTER", raising=False)
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        assert parallel._maybe_init_jax_distributed() is False
