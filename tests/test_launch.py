"""Launch CLI + multi-process bootstrap tests (VERDICT r1 item 6).

The real-process test spawns `python -m paddle_tpu.distributed.launch
--backend cpu --nproc_per_node 2 --devices-per-proc 4` — two OS
processes, each with 4 virtual CPU devices, forming one 8-device
jax.distributed job (the reference's test_dist_base subprocess
pattern)."""
import os
import re
import subprocess
import sys

import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PAYLOAD = os.path.join(REPO, "tests", "launch_payload.py")


def _scrubbed_env():
    from paddle_tpu.distributed.launch.main import scrub_backend_env
    env = scrub_backend_env(dict(os.environ))
    # the LAUNCHER process itself must not grab a TPU backend (libtpu is
    # installed even when the axon plugin env is scrubbed)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    return env


class TestLaunchCLI:
    def test_two_process_train_step(self, tmp_path):
        log_dir = str(tmp_path / "logs")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--backend", "cpu", "--nproc_per_node", "2",
             "--devices-per-proc", "4", "--log_dir", log_dir, PAYLOAD],
            env=_scrubbed_env(), cwd=REPO, timeout=600,
            capture_output=True, text=True)
        logs = ""
        for rank in (0, 1):
            with open(os.path.join(log_dir, f"workerlog.{rank}")) as f:
                logs += f.read()
        assert proc.returncode == 0, (proc.stdout, proc.stderr, logs)
        losses = re.findall(r"LAUNCH_OK rank=(\d) world=2 "
                            r"loss=([0-9.]+)", logs)
        assert sorted(r for r, _ in losses) == ["0", "1"], logs
        # SPMD: both processes computed the same global loss
        assert losses[0][1] == losses[1][1], logs

    def test_failure_propagates(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import sys; sys.exit(3)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--backend", "cpu", "--nproc_per_node", "2",
             "--devices-per-proc", "2", str(bad)],
            env=_scrubbed_env(), cwd=REPO, timeout=120,
            capture_output=True, text=True)
        assert proc.returncode == 3

    def test_multinode_requires_master(self):
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", PAYLOAD],
            env=_scrubbed_env(), cwd=REPO, timeout=60,
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "--master" in proc.stderr


class TestBootstrapEnv:
    def test_single_process_noop(self):
        import paddle_tpu.distributed as dist
        g = dist.init_parallel_env()
        assert g is not None
        assert dist.get_rank() == 0

    def test_env_parsing_guard(self, monkeypatch):
        from paddle_tpu.distributed import parallel
        monkeypatch.delenv("PADDLE_MASTER", raising=False)
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        assert parallel._maybe_init_jax_distributed() is False


class TestMultiNodeElastic:
    """Coordinated whole-job restart across nodes (VERDICT r2 missing #5;
    ref: fleet/elastic/manager.py:126 ElasticManager). Two node-launchers
    share one elastic rendezvous on localhost; killing one node's worker
    must restart BOTH nodes' workers at epoch 1."""

    def test_two_node_coordinated_restart(self, tmp_path):
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        master = f"127.0.0.1:{port}"
        payload = os.path.join(REPO, "tests", "elastic_payload.py")
        env = _scrubbed_env()

        def node(rank, log_dir):
            return subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--backend", "cpu", "--nnodes", "2",
                 "--node_rank", str(rank), "--nproc_per_node", "1",
                 "--master", master, "--max_restarts", "1",
                 "--log_dir", log_dir, payload],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)

        d0, d1 = str(tmp_path / "n0"), str(tmp_path / "n1")
        p0 = node(0, d0)
        p1 = node(1, d1)
        out0, _ = p0.communicate(timeout=180)
        out1, _ = p1.communicate(timeout=180)
        logs = ""
        for d, rank in ((d0, 0), (d1, 1)):
            with open(os.path.join(d, f"workerlog.{rank}")) as f:
                logs += f.read()
        assert p0.returncode == 0, (out0, out1, logs)
        assert p1.returncode == 0, (out0, out1, logs)
        # epoch 0: both ranks started, rank 1 crashed
        assert "ELASTIC_START rank=0 epoch=0" in logs
        assert "ELASTIC_CRASH rank=1 epoch=0" in logs
        # the COORDINATED restart: rank 0's healthy 300s sleeper was
        # killed and BOTH ranks completed epoch 1
        assert "ELASTIC_OK rank=0 epoch=1" in logs
        assert "ELASTIC_OK rank=1 epoch=1" in logs
        # launcher announced the coordinated restart
        assert "coordinated restart" in out0 + out1
