"""Continuous-batching LLMEngine (inference/llm_engine.py): the paged
KV cache as THE serving path.

Oracle: models.generation.generate() (dense max-length cache) run
per-prompt — the engine's paged, mixed-length, preemptible runtime must
produce exactly the same greedy tokens.
ref: python/paddle/incubate/nn/functional/block_multihead_attention.py:19
(the runtime those operands exist for)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


def _seeded(model_cls, cfg):
    pt.seed(0)
    return model_cls(cfg)


def _oracle(model, prompt, n_new):
    out = generate(model, pt.to_tensor(np.asarray(prompt, np.int32)[None]),
                   max_new_tokens=n_new).numpy()[0]
    return out[len(prompt):]


@pytest.fixture(scope="module")
def tiny_gpt():
    return _seeded(GPTForCausalLM, gpt_tiny())


@pytest.fixture(scope="module")
def tiny_llama():
    return _seeded(LlamaForCausalLM, llama_tiny())


def test_engine_greedy_matches_generate(tiny_gpt):
    model = tiny_gpt
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
               for n in (5, 9, 13, 21)]
    n_new = 8
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64)
    results = eng.generate(prompts, max_new_tokens=n_new)
    assert len(results) == len(prompts)
    for p, r in zip(prompts, results):
        want = _oracle(model, p, n_new)
        np.testing.assert_array_equal(r.output_ids, want)
        assert r.finish_reason == "length"
    # max_batch=2 with 4 prompts forces queueing + slot reuse
    assert eng.stats["prefills"] >= 4
    # every page is back in circulation — free, or parked reusable in
    # the prefix-cache LRU (only the trash page stays leased)
    assert eng.cache.available_blocks == eng.cache.allocator.num_blocks - 1


def test_engine_llama_family(tiny_llama):
    model = tiny_llama
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
               for n in (6, 11)]
    n_new = 6
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64)
    results = eng.generate(prompts, max_new_tokens=n_new)
    for p, r in zip(prompts, results):
        np.testing.assert_array_equal(r.output_ids,
                                      _oracle(model, p, n_new))


def test_engine_preemption_recovers(tiny_gpt):
    """A pool too small for every admitted sequence forces preemption;
    outputs must still match the oracle exactly (recompute preemption
    rebuilds the evicted context bit-for-bit)."""
    model = tiny_gpt
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 1024, (n,)).astype(np.int32)
               for n in (17, 18)]
    n_new = 20
    # both admit at 3 pages each (8 usable), but each needs 5 pages at
    # peak (ceil(37/8)) — the pool can't hold 2x5, so one sequence MUST
    # be preempted mid-decode and resumed later
    eng = LLMEngine(model, max_batch=2, block_size=8, num_blocks=9,
                    decode_chunk=4, prompt_quantum=16, max_model_len=64)
    results = eng.generate(prompts, max_new_tokens=n_new)
    for p, r in zip(prompts, results):
        np.testing.assert_array_equal(r.output_ids,
                                      _oracle(model, p, n_new))
    assert eng.stats["preemptions"] >= 1
    assert eng.cache.available_blocks == eng.cache.allocator.num_blocks - 1


def test_engine_admission_control(tiny_gpt):
    model = tiny_gpt
    eng = LLMEngine(model, max_batch=2, block_size=8, num_blocks=5,
                    max_model_len=64)
    # needs ceil((20+20)/8) = 5 pages > 4 usable -> rejected up front
    with pytest.raises(MemoryError):
        eng.add_request("big", np.zeros(20, np.int32), max_new_tokens=20)
    with pytest.raises(ValueError):
        eng.add_request("long", np.zeros(60, np.int32), max_new_tokens=10)


def test_engine_eos_stops_early(tiny_gpt):
    model = tiny_gpt
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 1024, (7,)).astype(np.int32)
    full = _oracle(model, prompt, 10)
    eos = int(full[3])
    stop = int(np.argmax(full == eos))      # first occurrence
    eng = LLMEngine(model, max_batch=1, block_size=16, decode_chunk=2,
                    prompt_quantum=16, max_model_len=64,
                    eos_token_id=eos)
    (r,) = eng.generate([prompt], max_new_tokens=10)
    assert r.finish_reason == "eos"
    np.testing.assert_array_equal(r.output_ids, full[:stop + 1])


def test_engine_streaming_steps(tiny_gpt):
    """step()-level API: requests added while others are mid-decode
    join the running batch (continuous batching, not static batching)."""
    model = tiny_gpt
    rng = np.random.default_rng(4)
    p1 = rng.integers(0, 1024, (9,)).astype(np.int32)
    p2 = rng.integers(0, 1024, (12,)).astype(np.int32)
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=2,
                    prompt_quantum=16, max_model_len=64)
    eng.add_request("a", p1, max_new_tokens=9)
    eng.step()                          # "a" starts decoding
    eng.add_request("b", p2, max_new_tokens=5)
    done = {}
    while eng.has_unfinished:
        for r in eng.step():
            done[r.request_id] = r
    np.testing.assert_array_equal(done["a"].output_ids,
                                  _oracle(model, p1, 9))
    np.testing.assert_array_equal(done["b"].output_ids,
                                  _oracle(model, p2, 5))
