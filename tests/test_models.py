"""Model-family tests (GPT/LLaMA/BERT) + sharded TrainStep."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    LlamaForCausalLM, llama_tiny, BertForMaskedLM, bert_tiny,
)


def _ids(cfg_vocab, shape):
    return pt.to_tensor(
        np.random.randint(0, cfg_vocab, shape).astype(np.int32))


class TestGPT:
    def test_forward_shape(self):
        cfg = gpt_tiny()
        m = GPTForCausalLM(cfg)
        m.eval()
        logits = m(_ids(cfg.vocab_size, (2, 16)))
        assert logits.shape == [2, 16, cfg.vocab_size]

    def test_backward(self):
        cfg = gpt_tiny()
        m = GPTForCausalLM(cfg)
        m.train()
        ids = _ids(cfg.vocab_size, (2, 8))
        loss = GPTPretrainingCriterion()(m(ids)[:, :-1], ids[:, 1:])
        loss.backward()
        g = m.gpt.layers[0].attn.qkv_proj.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()

    def test_kv_cache_decode_matches_full(self):
        cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = _ids(cfg.vocab_size, (1, 8))
        full = m(ids).numpy()
        caches = [(pt.zeros([1, 0, 4, 32]), pt.zeros([1, 0, 4, 32]))
                  for _ in range(cfg.num_layers)]
        outs = []
        for t in range(8):
            pos = pt.to_tensor(np.array([t], np.int32))
            logits, caches = m(ids[:, t:t + 1], position_ids=pos,
                               caches=caches)
            outs.append(logits.numpy())
        step = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(full, step, rtol=2e-4, atol=2e-4)

    def test_cached_prefill_is_causal(self):
        # multi-token prefill THROUGH the cache API must match the
        # plain causal forward (regression: bidirectional prefill bug)
        cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = _ids(cfg.vocab_size, (1, 8))
        full = m(ids).numpy()
        caches = [(pt.zeros([1, 0, 4, 32]), pt.zeros([1, 0, 4, 32]))
                  for _ in range(cfg.num_layers)]
        prefill, caches = m(ids, caches=caches)  # default position_ids
        np.testing.assert_allclose(full, prefill.numpy(), rtol=2e-4,
                                   atol=2e-4)
        # decode one more token with default (cache-derived) positions
        nxt = _ids(cfg.vocab_size, (1, 1))
        logits, caches = m(nxt, caches=caches)
        full2 = m(pt.concat([ids, nxt], axis=1)).numpy()[:, -1:]
        np.testing.assert_allclose(full2, logits.numpy(), rtol=2e-4,
                                   atol=2e-4)

    def test_train_step_reduces_loss(self):
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer import AdamW
        cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.train()
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        crit = GPTPretrainingCriterion()
        step = TrainStep(m, opt, lambda mm, x, y: crit(mm(x), y))
        ids = np.random.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1)
        first = float(step(ids, labels).numpy())
        for _ in range(10):
            last = float(step(ids, labels).numpy())
        assert last < first


class TestLlama:
    def test_forward_backward(self):
        cfg = llama_tiny()
        m = LlamaForCausalLM(cfg)
        m.train()
        ids = _ids(cfg.vocab_size, (2, 16))
        logits = m(ids)
        assert logits.shape == [2, 16, cfg.vocab_size]
        loss = pt.ops.cross_entropy(logits[:, :-1], ids[:, 1:])
        loss.backward()
        g = m.llama.layers[0].self_attn.q_proj.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()

    def test_gqa_heads(self):
        cfg = llama_tiny()
        assert cfg.num_kv_heads == 2 and cfg.num_heads == 4


class TestBert:
    def test_mlm_loss(self):
        cfg = bert_tiny()
        m = BertForMaskedLM(cfg)
        m.train()
        ids = _ids(cfg.vocab_size, (2, 16))
        loss, logits = m(ids, labels=ids,
                         attention_mask=pt.ones([2, 16]))
        assert logits.shape == [2, 16, cfg.vocab_size]
        loss.backward()
        assert np.isfinite(float(loss.numpy()))


class TestShardedTrainStep:
    def test_tp_dp_mesh_step(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer import AdamW
        from paddle_tpu.models.shard_plans import gpt_tp_rules
        devices = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devices, ("dp", "mp"))
        cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        m = GPTForCausalLM(cfg)
        m.train()
        opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
        crit = GPTPretrainingCriterion()
        step = TrainStep(m, opt, lambda mm, x, y: crit(mm(x), y),
                         mesh=mesh, shard_param=gpt_tp_rules,
                         shard_data=P("dp", None))
        ids = np.random.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1)
        first = float(step(ids, labels).numpy())
        for _ in range(5):
            last = float(step(ids, labels).numpy())
        assert np.isfinite(last) and last < first
        # params must actually be sharded over mp
        qkv = [p for n, p in zip(step._pnames, step.params)
               if "qkv_proj.weight" in n][0]
        assert qkv.sharding.spec == P(None, "mp")

    def test_sharded_matches_single_chip(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.optimizer import SGD
        from paddle_tpu.models.shard_plans import gpt_tp_rules
        cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        pt.seed(7)
        m1 = GPTForCausalLM(cfg)
        pt.seed(7)
        m2 = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        ids = np.random.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1)
        s1 = TrainStep(m1, SGD(learning_rate=0.1, parameters=m1.parameters()),
                       lambda mm, x, y: crit(mm(x), y))
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "mp"))
        s2 = TrainStep(m2, SGD(learning_rate=0.1, parameters=m2.parameters()),
                       lambda mm, x, y: crit(mm(x), y), mesh=mesh,
                       shard_param=gpt_tp_rules, shard_data=P("dp", None))
        for _ in range(3):
            l1 = float(s1(ids, labels).numpy())
            l2 = float(s2(ids, labels).numpy())
        np.testing.assert_allclose(l1, l2, rtol=1e-4, atol=1e-5)
