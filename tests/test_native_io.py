"""Native data-loader core (C++ blocking queue + parallel collation,
ref operators/reader/blocking_queue.h) and the worker-threaded
DataLoader path."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.io.native import (NativeQueue, collate_stack, available)


pytestmark = pytest.mark.skipif(not available(),
                                reason="native io library unavailable")


class TestNativeQueue:
    def test_fifo_through_threads(self):
        q = NativeQueue(4)
        got = []

        def consumer():
            while True:
                try:
                    got.append(q.pop(timeout_ms=5000))
                except StopIteration:
                    return

        t = threading.Thread(target=consumer)
        t.start()
        for i in range(32):
            q.push(i)
        q.close()
        t.join()
        assert got == list(range(32))

    def test_capacity_blocks_and_timeout(self):
        q = NativeQueue(2)
        assert q.push(1, timeout_ms=100)
        assert q.push(2, timeout_ms=100)
        assert not q.push(3, timeout_ms=50)   # full -> timeout
        assert q.pop() == 1
        assert q.push(3, timeout_ms=100)

    def test_pop_timeout_raises(self):
        q = NativeQueue(2)
        with pytest.raises(TimeoutError):
            q.pop(timeout_ms=50)

    def test_close_drains_then_stops(self):
        q = NativeQueue(4)
        q.push("a")
        q.close()
        assert q.pop(timeout_ms=100) == "a"
        with pytest.raises(StopIteration):
            q.pop(timeout_ms=100)


class TestNativeCollate:
    def test_matches_np_stack(self):
        arrs = [np.random.default_rng(i).standard_normal(
            (64, 257)).astype(np.float32) for i in range(7)]
        np.testing.assert_array_equal(collate_stack(arrs),
                                      np.stack(arrs))

    def test_mixed_shapes_fall_back(self):
        arrs = [np.zeros((4, 4), np.float32), np.zeros((4,), np.float32)]
        with pytest.raises(Exception):
            collate_stack(arrs)  # np.stack raises identically


class TestWorkerDataLoader:
    class _DS:
        def __init__(self, n=64, d=128):
            self.data = np.arange(n * d, dtype=np.float32).reshape(n, d)

        def __len__(self):
            return len(self.data)

        def __getitem__(self, i):
            time.sleep(0.001)  # simulated decode cost
            return self.data[i]

    def test_worker_loader_matches_serial(self):
        ds = self._DS()
        serial = [b.numpy() for b in pt.io.DataLoader(
            ds, batch_size=8, num_workers=0, shuffle=False)]
        parallel = [b.numpy() for b in pt.io.DataLoader(
            ds, batch_size=8, num_workers=4, shuffle=False)]
        assert len(serial) == len(parallel) == 8
        for a, b in zip(serial, parallel):
            np.testing.assert_array_equal(a, b)

    def test_worker_error_propagates(self):
        class Bad(self._DS):
            def __getitem__(self, i):
                if i == 19:
                    raise ValueError("corrupt sample")
                return super().__getitem__(i)

        loader = pt.io.DataLoader(Bad(), batch_size=8, num_workers=2)
        with pytest.raises(ValueError, match="corrupt sample"):
            list(loader)

    def test_workers_actually_concurrent(self):
        # structural overlap check (wall-clock ratios flake on loaded
        # CI boxes): observe >1 __getitem__ in flight at once
        lock = threading.Lock()
        live = {"now": 0, "peak": 0}

        outer = self

        class Probe(self._DS):
            def __getitem__(self, i):
                with lock:
                    live["now"] += 1
                    live["peak"] = max(live["peak"], live["now"])
                try:
                    time.sleep(0.002)
                    return outer._DS.__getitem__(self, i)
                finally:
                    with lock:
                        live["now"] -= 1

        list(pt.io.DataLoader(Probe(n=96), batch_size=8, num_workers=4))
        assert live["peak"] > 1, live

    def test_early_break_no_thread_spew(self):
        loader = pt.io.DataLoader(self._DS(n=64), batch_size=8,
                                  num_workers=3)
        it = iter(loader)
        next(it)
        it.close()  # consumer abandons mid-epoch; workers must exit

    def test_object_dtype_collate_safe(self):
        import gc
        from paddle_tpu.io.native import collate_stack
        objs = [np.array([{"k": i}] * 9000, dtype=object)
                for i in range(3)]
        out = collate_stack(objs)
        del objs
        gc.collect()
        assert out[0][0]["k"] == 0  # no dangling PyObject pointers
