"""nn.utils + new layer surface (ref: python/paddle/nn/utils/,
nn/layer/{rnn,pooling,conv,common}.py parity additions)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import ops


def t(x):
    return pt.to_tensor(np.asarray(x, np.float32))


class TestNnUtils:
    def test_weight_norm_preserves_function_and_reparam(self):
        lin = nn.Linear(4, 3)
        w0 = np.array(lin.weight.numpy())
        nn.utils.weight_norm(lin)
        assert sorted(lin._parameters) == ["bias", "weight_g", "weight_v"]
        x = t(np.random.default_rng(0).standard_normal((2, 4)))
        y = lin(x).numpy()
        ref = x.numpy() @ w0 + lin.bias.numpy()
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)
        nn.utils.remove_weight_norm(lin)

    def test_clip_grad_norm_scales_to_max(self):
        lin = nn.Linear(3, 3)
        x = t(np.ones((2, 3)))
        loss = ops.sum(lin(x) ** 2.0)
        loss.backward()
        nn.utils.clip_grad_norm_(lin.parameters(), 0.5)
        total = np.sqrt(sum(
            float((np.asarray(p.grad.numpy(), np.float64) ** 2).sum())
            for p in lin.parameters()))
        assert total <= 0.5 + 1e-4

    def test_clip_grad_value(self):
        lin = nn.Linear(3, 3)
        loss = ops.sum(lin(t(np.ones((2, 3)))) * 10.0)
        loss.backward()
        nn.utils.clip_grad_value_(lin.parameters(), 0.1)
        for p in lin.parameters():
            assert float(np.abs(p.grad.numpy()).max()) <= 0.1 + 1e-6

    def test_parameter_vector_roundtrip(self):
        lin = nn.Linear(4, 2)
        vec = nn.utils.parameters_to_vector(lin.parameters())
        before = [np.array(p.numpy()) for p in lin.parameters()]
        nn.utils.vector_to_parameters(vec * 0.0 + 1.0, lin.parameters())
        for p in lin.parameters():
            np.testing.assert_allclose(p.numpy(), np.ones(p.shape))
        assert vec.shape[0] == sum(b.size for b in before)

    def test_spectral_norm_bounds_sigma(self):
        lin = nn.Linear(8, 4)
        nn.utils.spectral_norm(lin, n_power_iterations=30)
        lin(t(np.ones((1, 8))))
        s = np.linalg.svd(np.asarray(lin.weight.numpy()),
                          compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, atol=5e-2)


class TestNewLayers:
    def test_rnn_over_cell_matches_manual(self):
        pt.seed(0)
        cell = nn.SimpleRNNCell(3, 5)
        rnn = nn.RNN(cell)
        x = t(np.random.default_rng(1).standard_normal((2, 4, 3)))
        out, last = rnn(x)
        h = None
        for step in range(4):
            o, h = cell(pt.to_tensor(x.numpy()[:, step]), h)
        np.testing.assert_allclose(out.numpy()[:, -1], o.numpy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(last.numpy(), h.numpy(), rtol=1e-5)

    def test_birnn_concat_dims(self):
        bi = nn.BiRNN(nn.SimpleRNNCell(3, 5), nn.SimpleRNNCell(3, 5))
        out, _ = bi(t(np.ones((2, 4, 3))))
        assert list(out.shape) == [2, 4, 10]

    def test_conv3d_transpose_layer(self):
        layer = nn.Conv3DTranspose(2, 3, 3, stride=2, padding=1)
        out = layer(t(np.ones((1, 2, 4, 4, 4))))
        ref = TF.conv_transpose3d(
            torch.ones(1, 2, 4, 4, 4),
            torch.tensor(np.asarray(layer.weight.numpy())),
            torch.tensor(np.asarray(layer.bias.numpy())),
            stride=2, padding=1)
        np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_adaptive_pools(self):
        x = t(np.arange(2 * 3 * 8, dtype=np.float32).reshape(2, 3, 8))
        o = F.adaptive_max_pool1d(x, 4)
        ref = TF.adaptive_max_pool1d(torch.tensor(x.numpy()), 4)
        np.testing.assert_allclose(o.numpy(), ref.numpy())
        x3 = t(np.random.default_rng(2).standard_normal((1, 2, 4, 6, 8)))
        o3 = nn.AdaptiveAvgPool3D((2, 3, 4))(x3)
        ref3 = TF.adaptive_avg_pool3d(torch.tensor(x3.numpy()), (2, 3, 4))
        np.testing.assert_allclose(o3.numpy(), ref3.numpy(), rtol=1e-5)
        om = nn.AdaptiveMaxPool3D(2)(x3)
        refm = TF.adaptive_max_pool3d(torch.tensor(x3.numpy()), 2)
        np.testing.assert_allclose(om.numpy(), refm.numpy(), rtol=1e-5)

    def test_softmax2d(self):
        x = t(np.random.default_rng(3).standard_normal((2, 3, 4, 4)))
        out = nn.Softmax2D()(x)
        np.testing.assert_allclose(out.numpy().sum(axis=1),
                                   np.ones((2, 4, 4)), rtol=1e-5)

    def test_fold_layer_and_get_worker_info(self):
        import paddle_tpu.io as io
        assert io.get_worker_info() is None
        f = nn.Fold(output_sizes=(4, 4), kernel_sizes=2)
        assert list(f(t(np.ones((1, 12, 9)))).shape) == [1, 3, 4, 4]


class TestReviewFixes:
    def test_remove_weight_norm_restores_trainable_weight(self):
        lin = nn.Linear(4, 3)
        nn.utils.weight_norm(lin)
        lin(t(np.ones((1, 4))))
        nn.utils.remove_weight_norm(lin)
        assert "weight" in lin._parameters
        assert "weight_g" not in lin._parameters
        y = ops.sum(lin(t(np.ones((2, 4)))) ** 2.0)
        y.backward()
        assert lin.weight.grad is not None

    def test_spectral_norm_u_persists_and_converges(self):
        lin = nn.Linear(8, 4)
        nn.utils.spectral_norm(lin)  # default 1 power iteration
        x = t(np.ones((1, 8)))
        for _ in range(40):          # u converges across calls
            lin(x)
        s = np.linalg.svd(np.asarray(lin.weight.numpy()),
                          compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, atol=5e-2)

    def test_rnn_sequence_length_masks(self):
        pt.seed(1)
        cell = nn.SimpleRNNCell(3, 5)
        rnn = nn.RNN(cell)
        x = t(np.random.default_rng(4).standard_normal((2, 6, 3)))
        lens = pt.to_tensor(np.array([6, 3], np.int32))
        out, last = rnn(x, sequence_length=lens)
        # row 1: outputs past step 3 are zero, final state = state@step3
        assert np.abs(out.numpy()[1, 3:]).max() == 0.0
        short, short_last = rnn(t(x.numpy()[1:2, :3]))
        np.testing.assert_allclose(last.numpy()[1], short_last.numpy()[0],
                                   rtol=1e-5)

    def test_conv_transpose_output_size(self):
        layer = nn.Conv3DTranspose(2, 3, 3, stride=2, padding=1)
        x = t(np.ones((1, 2, 4, 4, 4)))
        assert list(layer(x).shape)[2:] == [7, 7, 7]
        assert list(layer(x, output_size=(8, 8, 8)).shape)[2:] == [8, 8, 8]
        with pytest.raises(ValueError, match="unreachable"):
            layer(x, output_size=(20, 20, 20))

    def test_adaptive_max_pool1d_return_mask(self):
        """Oracle: torch return_indices — indices are positions along
        the unpadded L axis (the unpool contract)."""
        x = t(np.random.default_rng(7).standard_normal((2, 3, 10))
              .astype(np.float32))
        out, idx = nn.AdaptiveMaxPool1D(4, return_mask=True)(x)
        ref, ridx = TF.adaptive_max_pool1d(torch.tensor(x.numpy()), 4,
                                           return_indices=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
        np.testing.assert_array_equal(idx.numpy(), ridx.numpy())
        # mask actually addresses the max: gather reproduces the output
        g = np.take_along_axis(x.numpy(), idx.numpy(), axis=-1)
        np.testing.assert_allclose(g, out.numpy())

    def test_adaptive_max_pool3d_return_mask(self):
        """Oracle: torch return_indices — indices flat into D*H*W."""
        x = t(np.random.default_rng(8).standard_normal((1, 2, 4, 6, 8))
              .astype(np.float32))
        out, idx = nn.AdaptiveMaxPool3D((2, 3, 4),
                                        return_mask=True)(x)
        ref, ridx = TF.adaptive_max_pool3d(torch.tensor(x.numpy()),
                                           (2, 3, 4),
                                           return_indices=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
        np.testing.assert_array_equal(idx.numpy(), ridx.numpy())
        flat = x.numpy().reshape(1, 2, -1)
        g = np.take_along_axis(flat, idx.numpy().reshape(1, 2, -1),
                               axis=-1)
        np.testing.assert_allclose(
            g.reshape(out.numpy().shape), out.numpy())

    def test_max_unpool2d_nhwc(self):
        """NHWC MaxUnPool2D: same flat-H*W index contract as NCHW,
        scatter transposed around the same op; oracle = the NCHW
        path on the transposed tensors (itself torch-oracled via
        max_pool2d_with_index round-trip tests)."""
        rng = np.random.default_rng(9)
        xc = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
        out_c, idx_c = ops.max_pool2d_with_index(t(xc), 2, 2)
        up_c = nn.MaxUnPool2D(2, 2)(out_c, idx_c)
        up_h = nn.MaxUnPool2D(2, 2, data_format="NHWC")(
            ops.transpose(out_c, [0, 2, 3, 1]),
            ops.transpose(idx_c, [0, 2, 3, 1]))
        np.testing.assert_allclose(
            up_h.numpy(), np.transpose(up_c.numpy(), (0, 2, 3, 1)))
        tref = TF.max_unpool2d(torch.tensor(out_c.numpy()),
                               torch.tensor(idx_c.numpy().astype(
                                   np.int64)), 2, 2)
        np.testing.assert_allclose(up_c.numpy(), tref.numpy())
        with pytest.raises(ValueError):
            nn.MaxUnPool2D(2, 2, data_format="NDHWC")

    def test_clip_delegation_single_impl(self):
        import paddle_tpu.nn.clip as clipmod
        lin = nn.Linear(3, 3)
        loss = ops.sum(lin(t(np.ones((2, 3)))) ** 2.0)
        loss.backward()
        n1 = nn.utils.clip_grad_norm_(lin.parameters(), 1e9)
        n2 = clipmod.clip_grad_norm_(lin.parameters(), 1e9)
        np.testing.assert_allclose(n1.numpy(), n2.numpy(), rtol=1e-6)
