"""Training numerics & model-health plane (ISSUE 15).

Pins: in-trace stats correctness (fused optimizer / whole-graph
backward tap / TrainStep / eager fallback against numpy references),
bit-identical gradients+optimizer states with the plane on vs off
across all three backward dispatch modes, the ≤1-async-pull-per-step
budget, the stats-on executable-variant family budget, disabled-mode
zero-allocation, the NaN/Inf sentinel chaos acceptance
(PoisonGradient → exactly one numerics_divergence bundle naming the
first nonfinite parameter), AMP dynamic-loss-scaling under injected
overflow, the fused unscale's one-dispatch/one-sync contract and
trajectory parity, the GradScaler state-dict round trip, the
flight-reason-documented graftlint rule, and the obs_top panel.
"""
import math
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.amp import GradScaler
from paddle_tpu.autograd import dispatch_queue as dq
from paddle_tpu.observability import flight
from paddle_tpu.observability import metrics
from paddle_tpu.observability import numerics as num
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_plane():
    yield
    num.disable()
    num.reset_window()
    faults.clear_all()
    flight.disarm()
    obs.disable()
    obs.reset()
    dq.set_dispatch_mode("whole_graph")


def _mlp(rng, n=3, width=8):
    layers = [pt.nn.Linear(width, width) for _ in range(n)]
    for lyr in layers:
        for p in lyr.parameters():
            p.set_value(pt.to_tensor(
                rng.standard_normal(p.shape).astype(np.float32)))
    return layers


def _step_fn(layers, x, opt):
    def step():
        h = x
        for lyr in layers[:-1]:
            h = pt.ops.tanh(lyr(h))
        loss = (layers[-1](h) ** 2).mean()
        loss.backward()
        grads = [np.asarray(p._grad._data) for lyr in layers
                 for p in lyr.parameters()]
        opt.step()
        opt.clear_grad()
        return grads
    return step


# ---------------------------------------------------------------------------
# in-trace stats correctness
# ---------------------------------------------------------------------------
class TestInTraceStats:
    def test_fused_optimizer_stats_match_numpy(self):
        rng = np.random.default_rng(0)
        layers = _mlp(rng)
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        step = _step_fn(layers, x, opt)
        obs.enable()
        num.enable(interval=1)
        olds = [np.asarray(p._data, np.float64) for p in params]
        grads = step()
        news = [np.asarray(p._data, np.float64) for p in params]
        rec = num.flush()
        assert rec["source"] == "optimizer_fused"
        gn_ref = math.sqrt(sum(float(np.sum(np.asarray(g, np.float64)
                                            ** 2)) for g in grads))
        assert rec["grad_norm"] == pytest.approx(gn_ref, rel=1e-4)
        pn_ref = math.sqrt(sum(float(np.sum(w ** 2)) for w in olds))
        assert rec["param_norm"] == pytest.approx(pn_ref, rel=1e-4)
        d_ref = math.sqrt(sum(float(np.sum((n - w) ** 2))
                              for n, w in zip(news, olds)))
        assert rec["update_ratio"] == pytest.approx(d_ref / pn_ref,
                                                    rel=1e-3)
        assert rec["nonfinite"] == {"grad": 0, "param": 0, "loss": 0}
        # gauges published (group=all + the single default group g0)
        snap = obs.snapshot()
        rows = snap["paddle_tpu_train_grad_norm"]["series"]
        assert rows[("all",)] == pytest.approx(gn_ref, rel=1e-4)
        assert rows[("g0",)] == pytest.approx(gn_ref, rel=1e-4)
        assert snap["paddle_tpu_train_param_norm"]["series"][()] == \
            pytest.approx(pn_ref, rel=1e-4)

    def test_whole_graph_backward_tap(self):
        """Backward-only loop (no optimizer submit): the in-trace
        whole-graph tap alone provides grad norm + nonfinite count,
        published by flush() as a backward-sourced record."""
        rng = np.random.default_rng(1)
        layers = _mlp(rng)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        num.enable(interval=1)
        with dq.backward_dispatch_mode("whole_graph"):
            h = pt.ops.tanh(layers[0](x))
            h = pt.ops.tanh(layers[1](h))
            loss = (layers[2](h) ** 2).mean()
            loss.backward()
        grads = [np.asarray(p._grad._data, np.float64)
                 for lyr in layers for p in lyr.parameters()]
        rec = num.flush()
        assert rec["source"] == "backward"
        gn_ref = math.sqrt(sum(float(np.sum(g ** 2)) for g in grads))
        assert rec["backward"]["grad_norm"] == pytest.approx(
            gn_ref, rel=1e-4)
        assert rec["backward"]["nonfinite"] == 0
        assert rec["grad_norm"] == pytest.approx(gn_ref, rel=1e-4)

    def test_eager_fallback_same_series(self, monkeypatch):
        """PADDLE_TPU_FUSED_OPT=0 forces the per-param eager optimizer
        path: the host-side fallback publishes the same record shape
        with the same numbers."""
        monkeypatch.setenv("PADDLE_TPU_FUSED_OPT", "0")
        rng = np.random.default_rng(2)
        layers = _mlp(rng)
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        step = _step_fn(layers, x, opt)
        obs.enable()
        num.enable(interval=1)
        grads = step()
        rec = num.flush()
        assert rec["source"] == "optimizer_eager"
        gn_ref = math.sqrt(sum(float(np.sum(np.asarray(g, np.float64)
                                            ** 2)) for g in grads))
        assert rec["grad_norm"] == pytest.approx(gn_ref, rel=1e-4)

    def test_trainstep_stats_and_loss(self):
        from paddle_tpu.jit import TrainStep
        rng = np.random.default_rng(3)
        layers = _mlp(rng)

        class M(pt.nn.Layer):
            def __init__(self):
                super().__init__()
                self.ls = pt.nn.LayerList(layers)

            def forward(self, x):
                h = x
                for lyr in self.ls[:-1]:
                    h = pt.ops.tanh(lyr(h))
                return (self.ls[-1](h) ** 2).mean()

        m = M()
        opt = pt.optimizer.SGD(learning_rate=1e-2,
                               parameters=m.parameters())
        obs.enable()
        num.enable(interval=1)
        ts = TrainStep(m, opt)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        losses = [float(ts(x).numpy()) for _ in range(3)]
        rec = num.flush()
        assert rec["source"] == "train_step"
        assert rec["loss"] == pytest.approx(losses[-1], rel=1e-5)
        assert rec["grad_norm"] and math.isfinite(rec["grad_norm"])
        assert rec["update_ratio"] and rec["update_ratio"] > 0

    @pytest.mark.parametrize("fused", [True, False])
    def test_per_group_rows(self, fused, monkeypatch):
        """Both the fused path and the eager fallback label the
        per-group rows identically (the fallback once extracted the
        GRAD from the (p, g, group) tuples and collapsed everything
        to g0 — ISSUE 15 review finding)."""
        if not fused:
            monkeypatch.setenv("PADDLE_TPU_FUSED_OPT", "0")
        rng = np.random.default_rng(4)
        l1, l2 = pt.nn.Linear(8, 8), pt.nn.Linear(8, 8)
        opt = pt.optimizer.SGD(
            learning_rate=1e-2,
            parameters=[{"params": l1.parameters()},
                        {"params": l2.parameters(),
                         "learning_rate": 0.5}])
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        obs.enable()
        num.enable(interval=1)
        loss = (l2(pt.ops.tanh(l1(x))) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        rec = num.flush()
        assert rec["source"] == ("optimizer_fused" if fused
                                 else "optimizer_eager")
        assert set(rec["group_norms"]) == {"g0", "g1"}
        rows = obs.snapshot()["paddle_tpu_train_grad_norm"]["series"]
        assert ("g0",) in rows and ("g1",) in rows and ("all",) in rows

    def test_sampling_cadence(self):
        """interval=k publishes every k-th step only (the default-
        cadence overhead contract), and the stats-off steps keep
        hitting the stats-off executables."""
        rng = np.random.default_rng(5)
        layers = _mlp(rng)
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        step = _step_fn(layers, x, opt)
        num.enable(interval=4)
        base = num.pulls()
        for _ in range(9):              # samples at ticks 0, 4, 8
            step()
        num.flush()
        assert num.pulls() - base == 3


# ---------------------------------------------------------------------------
# read-only taps: bit-identical training with the plane on vs off
# ---------------------------------------------------------------------------
class TestBitIdentical:
    @pytest.mark.parametrize("mode", ["whole_graph", "batched",
                                      "per_node"])
    def test_grads_and_states_bit_identical(self, mode):
        rng = np.random.default_rng(7)
        W = [rng.standard_normal((8, 8)).astype(np.float32)
             for _ in range(3)]
        x_np = rng.standard_normal((4, 8)).astype(np.float32)

        def run(plane_on):
            dq.clear_chain_cache()
            layers = [pt.nn.Linear(8, 8) for _ in range(3)]
            for lyr, w in zip(layers, W):
                lyr.weight.set_value(pt.to_tensor(w))
            params = [p for lyr in layers for p in lyr.parameters()]
            opt = pt.optimizer.Adam(learning_rate=1e-2,
                                    parameters=params)
            x = pt.to_tensor(x_np)
            if plane_on:
                num.enable(interval=1)
            else:
                num.disable()
            with dq.backward_dispatch_mode(mode):
                for _ in range(4):
                    h = pt.ops.tanh(layers[0](x))
                    h = pt.ops.tanh(layers[1](h))
                    loss = (layers[2](h) ** 2).mean()
                    loss.backward()
                    opt.step()
                    opt.clear_grad()
            num.disable()
            ps = [np.asarray(p._data).tobytes() for p in params]
            sts = [{k: np.asarray(v).tobytes() for k, v in
                    opt._accumulators[id(p)].items()} for p in params]
            return ps, sts

        assert run(False) == run(True)


# ---------------------------------------------------------------------------
# the async-pull budget and the executable family budget
# ---------------------------------------------------------------------------
class TestBudgets:
    def test_at_most_one_pull_per_step(self):
        rng = np.random.default_rng(8)
        layers = _mlp(rng)
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        step = _step_fn(layers, x, opt)
        num.enable(interval=1)
        base = num.pulls()
        n = 6
        for _ in range(n):
            step()
        # each submit publishes the PREVIOUS step's bundle: n-1 pulls
        assert num.pulls() - base == n - 1
        num.flush()
        assert num.pulls() - base == n

    def test_stats_on_variant_family_budget(self):
        """Toggling the plane on adds AT MOST one extra executable per
        family (the stats-on variant) and the steady state compiles
        nothing further — the TestCompileFamilyBudget convention."""
        rng = np.random.default_rng(9)
        layers = _mlp(rng, width=16)
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 16)).astype(np.float32))
        step = _step_fn(layers, x, opt)
        dq.clear_chain_cache()
        obs.enable()
        obs.reset()
        with dq.backward_dispatch_mode("whole_graph"):
            for _ in range(2):
                step()              # stats-off variants compile
            num.enable(interval=1)
            for _ in range(2):
                step()              # stats-on variants compile
            snap1 = obs.snapshot()["paddle_tpu_compile_total"]["series"]
            for _ in range(3):
                step()              # steady state: no new compiles
            snap2 = obs.snapshot()["paddle_tpu_compile_total"]["series"]
        per_family = {k[0]: int(v) for k, v in snap2.items() if v}
        assert per_family.get("backward_fused", 0) <= 2
        assert per_family.get("optimizer_fused", 0) <= 2
        assert snap1 == snap2, "steady state recompiled"

    def test_disabled_mode_zero_alloc_and_zero_pulls(self):
        """The instrumentation entry points with the plane off are one
        flag check: no allocation growth, no pulls, no pending state
        (the PR 2/8/14 tracemalloc convention, applied to the layer
        directly so a per-op leak can't hide in loop noise)."""
        import tracemalloc
        assert not obs.enabled() and not num.enabled()
        for _ in range(16):
            num.note_backward_tap(None)
            num.submit(None, (), ())
            num.note_loss_scale(1.0)
            num.note_found_inf()
            num.want_stats()

        def window(iters):
            tracemalloc.start()
            base = tracemalloc.get_traced_memory()[0]
            for _ in range(iters):
                num.note_backward_tap(None)
                num.submit(None, (), ())
                num.note_loss_scale(1.0)
                num.note_found_inf()
                num.want_stats()
            grown = tracemalloc.get_traced_memory()[0] - base
            tracemalloc.stop()
            return grown

        window(4000)
        g1, g2 = window(4000), window(4000)
        assert g2 < 1024, (g1, g2)
        assert num.pulls() == 0 or num._PENDING is None
        assert num._PENDING is None and not num._STEP_TAPS


# ---------------------------------------------------------------------------
# chaos acceptance: sentinel + forensics
# ---------------------------------------------------------------------------
class TestChaosDivergence:
    def _setup(self, seed=10):
        rng = np.random.default_rng(seed)
        layers = _mlp(rng)
        params = [p for lyr in layers for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        return layers, params, opt, _step_fn(layers, x, opt)

    def test_poisoned_gradient_exactly_one_bundle(self, tmp_path):
        layers, params, opt, step = self._setup()
        obs.enable()
        num.enable(interval=1)
        flight.arm(str(tmp_path))
        for _ in range(3):
            step()
        target = params[2].name
        with faults.inject("numerics.check",
                           exc=num.PoisonGradient(param=target),
                           times=1, match={"where": "step"}):
            step()
        # the poisoned update NaNs the params: every later step stays
        # nonfinite — one EPISODE, so still exactly one bundle
        for _ in range(3):
            step()
        num.flush()
        bundles = flight.bundles(str(tmp_path))
        assert len(bundles) == 1
        b = flight.load_bundle(bundles[0])
        assert b["meta"]["reason"] == "numerics_divergence"
        det = b["meta"]["detail"]
        assert det["first_nonfinite_param"] == target
        assert "nonfinite" in det["reasons"]
        assert det["loss_history"] == []        # no loss noted (eager)
        # the bundle's metrics snapshot shows the counter increment
        rows = b["metrics"]["paddle_tpu_train_nonfinite_total"]["series"]
        assert any(s["labels"]["where"] == "grad" and s["value"] > 0
                   for s in rows)
        # and its trace holds the triggering numerics.check span,
        # whose ids the meta names
        spans = [e for e in b["trace"] if e["name"] == "numerics.check"]
        assert spans
        assert det["trace_id"] in {e.get("trace_id") for e in spans}

    def test_clean_run_zero_bundles_zero_counts(self, tmp_path):
        _, _, _, step = self._setup(seed=11)
        obs.enable()
        num.enable(interval=1)
        flight.arm(str(tmp_path))
        for _ in range(4):
            step()
        num.flush()
        assert flight.bundles(str(tmp_path)) == []
        rows = obs.snapshot().get("paddle_tpu_train_nonfinite_total",
                                  {}).get("series", {})
        assert not any(v for v in rows.values()), rows

    def test_latch_rearms_after_clean_step(self, tmp_path):
        """Two separate poison episodes with clean steps between =
        two bundles; consecutive poisoned steps inside one episode
        do not double-fire. Poison value 0 keeps params finite so the
        episode actually ENDS (NaN would be absorbing)."""
        layers, params, opt, step = self._setup(seed=12)
        obs.enable()
        num.enable(interval=1)
        flight.arm(str(tmp_path))
        step()
        for _ in range(2):      # episode 1: two consecutive poisons
            with faults.inject("numerics.check",
                               exc=num.PoisonGradient(
                                   value=float("inf")),
                               times=1, match={"where": "step"}):
                step()
        # params went nonfinite? inf*lr subtracted — rebuild weights
        rng = np.random.default_rng(13)
        for p in params:
            p.set_value(pt.to_tensor(
                rng.standard_normal(p.shape).astype(np.float32)))
        for _ in range(3):      # clean steps re-arm the latch
            step()
        with faults.inject("numerics.check",
                           exc=num.PoisonGradient(value=float("inf")),
                           times=1, match={"where": "step"}):
            step()              # episode 2
        for p in params:
            p.set_value(pt.to_tensor(
                rng.standard_normal(p.shape).astype(np.float32)))
        step()
        num.flush()
        assert len(flight.bundles(str(tmp_path))) == 2

    def test_grad_spike_detection(self, tmp_path):
        num.enable(interval=1, spike_factor=5.0, min_window=4)
        obs.enable()
        flight.arm(str(tmp_path))
        names = ("w",)
        groups = ("g0",)
        import jax.numpy as jnp

        def fake_step(scale):
            g = jnp.full((16,), scale, jnp.float32)
            w = jnp.ones((16,), jnp.float32)
            num.submit(num.pack_stats([w], [g], [w - 0.01 * g]),
                       names=names, groups=groups, lr=0.01)
        for _ in range(6):
            fake_step(1.0)
        fake_step(100.0)        # 100x the window median
        num.flush()
        bundles = flight.bundles(str(tmp_path))
        assert len(bundles) == 1
        det = flight.load_bundle(bundles[0])["meta"]["detail"]
        assert det["reasons"] == ["grad_spike"]

    def test_sustained_regime_change_releases_latch(self, tmp_path):
        """A legitimate persistent grad-norm jump fires grad_spike
        ONCE, then the window median adapts, the latch re-arms, and a
        later REAL nonfinite event still gets its bundle — spiked
        norms excluded from the window would hold the latch forever
        and swallow the NaN bundle (review finding)."""
        num.enable(interval=1, spike_factor=5.0, min_window=4,
                   window=8)
        obs.enable()
        flight.arm(str(tmp_path))
        import jax.numpy as jnp

        def fake_step(scale):
            g = jnp.full((16,), scale, jnp.float32)
            w = jnp.ones((16,), jnp.float32)
            num.submit(num.pack_stats([w], [g], [w - 0.01 * g]),
                       names=("w",), groups=("g0",), lr=0.01)
        for _ in range(6):
            fake_step(1.0)
        for _ in range(12):     # new PERMANENT regime, 20x the median
            fake_step(20.0)
        num.flush()
        assert len(flight.bundles(str(tmp_path))) == 1  # one episode
        fake_step(float("nan"))     # the real event must still fire
        num.flush()
        bundles = flight.bundles(str(tmp_path))
        assert len(bundles) == 2
        det = flight.load_bundle(bundles[-1])["meta"]["detail"]
        assert "nonfinite" in det["reasons"]

    def test_tap_variant_key_folds_leaf_classification(self):
        """The whole-graph tap executable keys include each node's
        leaf-vs-boundary edge flags (base keys encode both as emitted
        ('o',) — right for routing, wrong for the tap, which reduces
        only LEAF emissions; review finding)."""
        rng = np.random.default_rng(30)
        layers = _mlp(rng)
        x = pt.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        num.enable(interval=1)
        dq.clear_chain_cache()
        with dq.backward_dispatch_mode("whole_graph"):
            h = pt.ops.tanh(layers[0](x))
            loss = (layers[1](h) ** 2).mean()
            loss.backward()
        tap_keys = [k for k in dq._CHAIN_CACHE
                    if k and isinstance(k[-1], tuple)
                    and k[-1][:1] == ("numtap",)]
        assert tap_keys
        for k in tap_keys:
            # marker + one leaf-flag tuple per segment node
            assert len(k[-1]) == 1 + len(k) - 1
            assert all(isinstance(f, tuple) for f in k[-1][1:])

    def test_loss_scale_floor_fires(self, tmp_path):
        num.enable(interval=1, loss_scale_floor=4.0)
        flight.arm(str(tmp_path))
        num.note_loss_scale(32.0, decreased=True)
        assert flight.bundles(str(tmp_path)) == []
        num.note_loss_scale(4.0, decreased=True)
        bundles = flight.bundles(str(tmp_path))
        assert len(bundles) == 1
        det = flight.load_bundle(bundles[0])["meta"]["detail"]
        assert det["reasons"] == ["loss_scale_floor"]
        assert det["loss_scale_history"][-2:] == [32.0, 4.0]


# ---------------------------------------------------------------------------
# AMP: fused unscale + dynamic-loss-scaling forensics
# ---------------------------------------------------------------------------
class TestAMP:
    def _scaler_loop(self, scaler, n=1, width=6, seed=20):
        rng = np.random.default_rng(seed)
        lin = [pt.nn.Linear(width, width) for _ in range(2)]
        params = [p for lyr in lin for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        x = pt.to_tensor(rng.standard_normal((4, width))
                         .astype(np.float32))

        def step():
            h = pt.ops.tanh(lin[0](x))
            loss = (lin[1](h) ** 2).mean()
            scaler.scale(loss).backward()
            scaler.step(opt)
            opt.clear_grad()
        for _ in range(n):
            step()
        return params, opt, step

    def test_fused_unscale_one_dispatch_one_sync(self):
        scaler = GradScaler(init_loss_scaling=2.0 ** 10)
        self._scaler_loop(scaler, n=5)
        st = scaler._unscale_stats
        assert st["dispatches"] == 5        # ONE fused call per step
        assert st["syncs"] == 5             # ONE host sync per step
        assert st["fallbacks"] == 0
        assert len(scaler._unscale_cache) == 1

    def test_fused_unscale_trajectory_matches_eager_loop(self):
        """The fused rewrite is bit-identical to the original
        per-parameter loop — same unscaled grads, same found_inf —
        including across an injected overflow."""
        rng = np.random.default_rng(21)
        W = [rng.standard_normal((6, 6)).astype(np.float32)
             for _ in range(2)]
        x_np = rng.standard_normal((4, 6)).astype(np.float32)

        def run(force_eager):
            lin = [pt.nn.Linear(6, 6) for _ in range(2)]
            for lyr, w in zip(lin, W):
                lyr.weight.set_value(pt.to_tensor(w))
            params = [p for lyr in lin for p in lyr.parameters()]
            opt = pt.optimizer.SGD(learning_rate=1e-2,
                                   parameters=params)
            scaler = GradScaler(init_loss_scaling=2.0 ** 8,
                                decr_every_n_nan_or_inf=1)
            if force_eager:
                scaler._unscale_fn = lambda garrs: None
            x = pt.to_tensor(x_np)
            for i in range(4):
                h = pt.ops.tanh(lin[0](x))
                loss = (lin[1](h) ** 2).mean()
                scaler.scale(loss).backward()
                if i == 1:      # poison one step's grads directly
                    g = params[0]._grad
                    g._set_data(g._data.at[0, 0].set(float("nan")))
                scaler.step(opt)
                opt.clear_grad()
            return ([np.asarray(p._data).tobytes() for p in params],
                    scaler._scale, scaler._good_steps,
                    scaler._bad_steps)

        assert run(False) == run(True)

    def test_dynamic_scaling_under_injected_overflow(self):
        obs.enable()
        scaler = GradScaler(init_loss_scaling=2.0 ** 10,
                            decr_every_n_nan_or_inf=2,
                            incr_every_n_steps=3)
        params, opt, step = self._scaler_loop(scaler, n=0)
        for _ in range(2):
            step()
        assert scaler._scale == 2.0 ** 10
        # nonfinite grads for decr_every_n_nan_or_inf consecutive
        # steps: both skipped, then the scale halves exactly once
        with faults.inject("numerics.check", exc=num.PoisonGradient(),
                           times=2, match={"where": "amp"}):
            step()
            step()
        assert scaler._scale == 2.0 ** 9
        snap = obs.snapshot()
        assert snap["paddle_tpu_amp_steps_total"]["series"][
            ("skipped",)] == 2
        assert snap["paddle_tpu_amp_steps_total"]["series"][("ok",)] == 2
        assert snap["paddle_tpu_amp_scale_decreases_total"][
            "series"][()] == 1
        assert snap["paddle_tpu_amp_loss_scale"]["series"][()] == \
            2.0 ** 9
        # recovery: incr_every_n_steps clean steps grow the scale back
        for _ in range(3):
            step()
        assert scaler._scale == 2.0 ** 10

    def test_skipped_step_counts_nonfinite_once(self):
        obs.enable()
        num.enable(interval=1)
        scaler = GradScaler(init_loss_scaling=2.0 ** 10)
        params, opt, step = self._scaler_loop(scaler, n=1)
        with faults.inject("numerics.check", exc=num.PoisonGradient(),
                           times=1, match={"where": "amp"}):
            step()
        rows = obs.snapshot()["paddle_tpu_train_nonfinite_total"][
            "series"]
        assert rows[("grad",)] == 1

    def test_explicit_unscale_not_applied_twice(self):
        """scaler.unscale_(opt) then scaler.step(opt) — the grad-
        clipping pattern — unscales exactly once: the original loop
        checked an `_unscaled` guard nothing ever set, so the step
        silently divided the update by the loss scale again (ISSUE 15
        review finding)."""
        rng = np.random.default_rng(22)
        lin = pt.nn.Linear(6, 6)
        params = lin.parameters()
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        scaler = GradScaler(init_loss_scaling=2.0 ** 8)
        x = pt.to_tensor(rng.standard_normal((4, 6)).astype(np.float32))
        loss = (lin(x) ** 2).mean()
        scaler.scale(loss).backward()
        scaler.unscale_(opt)
        g_once = np.asarray(params[0]._grad._data).copy()
        w_before = np.asarray(params[0]._data).copy()
        scaler.step(opt)        # must NOT unscale a second time
        assert scaler._unscale_stats["dispatches"] == 1
        np.testing.assert_allclose(
            np.asarray(params[0]._data), w_before - 1e-2 * g_once,
            rtol=1e-6)
        # and the next step's internal unscale runs again (flag reset)
        opt.clear_grad()
        loss = (lin(x) ** 2).mean()
        scaler.scale(loss).backward()
        scaler.step(opt)
        assert scaler._unscale_stats["dispatches"] == 2

    def test_skipped_step_taps_do_not_leak(self, tmp_path):
        """A whole-graph backward tap recorded for a step AMP then
        skips must not ride the NEXT clean step's bundle — stale
        nonfinite counts would fire a false divergence (ISSUE 15
        review finding)."""
        obs.enable()
        num.enable(interval=1)
        flight.arm(str(tmp_path))
        rng = np.random.default_rng(23)
        lin = [pt.nn.Linear(6, 6) for _ in range(2)]
        params = [p for lyr in lin for p in lyr.parameters()]
        opt = pt.optimizer.SGD(learning_rate=1e-2, parameters=params)
        scaler = GradScaler(init_loss_scaling=2.0 ** 8,
                            decr_every_n_nan_or_inf=10)
        x = pt.to_tensor(rng.standard_normal((4, 6)).astype(np.float32))

        def step(poison=False):
            with dq.backward_dispatch_mode("whole_graph"):
                h = pt.ops.tanh(lin[0](x))
                loss = (lin[1](h) ** 2).mean()
                scaler.scale(loss).backward()
                if poison:
                    g = params[0]._grad
                    g._set_data(g._data.at[0, 0].set(float("nan")))
                scaler.step(opt)
                opt.clear_grad()
        step()
        step(poison=True)       # skipped: taps recorded then drained
        assert not num._STEP_TAPS
        step()                  # clean step publishes clean taps only
        step()
        rec = num.flush()
        assert rec["nonfinite"]["grad"] == 0
        assert rec["backward"] is None or \
            rec["backward"]["nonfinite"] == 0
        assert flight.bundles(str(tmp_path)) == []

    def test_state_dict_roundtrip_mid_decay(self):
        scaler = GradScaler(init_loss_scaling=2.0 ** 10, incr_ratio=4.0,
                            decr_ratio=0.25, incr_every_n_steps=7,
                            decr_every_n_nan_or_inf=3)
        params, opt, step = self._scaler_loop(scaler, n=2)
        # advance INTO a decay run: one bad step of the three needed
        with faults.inject("numerics.check", exc=num.PoisonGradient(),
                           times=1, match={"where": "amp"}):
            step()
        assert scaler._bad_steps == 1 and scaler._scale == 2.0 ** 10
        sd = scaler.state_dict()
        # restore into a scaler built with DIFFERENT ctor args: every
        # field must come from the checkpoint, not the ctor
        s2 = GradScaler()
        s2.load_state_dict(sd)
        for attr in ("_scale", "_incr_ratio", "_decr_ratio",
                     "_incr_every", "_decr_every", "_good_steps",
                     "_bad_steps", "_found_inf", "_dynamic"):
            assert getattr(s2, attr) == getattr(scaler, attr), attr
        # the restored scaler finishes the decay exactly where the
        # original would: 2 more bad steps halve... decr_ratio=0.25
        s2._found_inf = True
        s2.update()
        assert s2._bad_steps == 2
        s2._found_inf = True
        s2.update()
        assert s2._scale == 2.0 ** 10 * 0.25 and s2._bad_steps == 0


# ---------------------------------------------------------------------------
# plumbing: obs.reset window semantics + fleet ride-along
# ---------------------------------------------------------------------------
class TestPlumbing:
    def test_reset_clears_numerics_window(self):
        num.enable(interval=1)
        import jax.numpy as jnp
        num.submit(num.pack_stats([jnp.ones((4,))], [jnp.ones((4,))],
                                  [jnp.ones((4,))]),
                   names=("w",), groups=("g0",))
        assert num._PENDING is not None
        obs.reset()
        assert num._PENDING is None and num.last() is None
        assert num.enabled()            # the flag survives

    def test_series_ride_fleet_farewell(self):
        """The numerics gauges are ordinary registry series, so they
        ship in fleet bundles (the worker-farewell wire format) like
        every other series — the aggregator sees per-process grad
        norms."""
        from paddle_tpu.observability import fleet
        obs.enable()
        num.enable(interval=1)
        import jax.numpy as jnp
        num.submit(num.pack_stats([jnp.ones((4,))], [jnp.ones((4,))],
                                  [jnp.ones((4,))]),
                   names=("w",), groups=("g0",))
        num.flush()
        bundle = fleet.worker_farewell()
        snap = bundle["metrics"]
        assert "paddle_tpu_train_grad_norm" in snap
        assert snap["paddle_tpu_train_grad_norm"]["series"]

    def test_flight_reason_registered(self):
        assert "numerics_divergence" in flight.TRIGGER_REASONS


# ---------------------------------------------------------------------------
# graftlint: the flight-reason-documented rule (fixture, both ways)
# ---------------------------------------------------------------------------
class TestFlightReasonRule:
    SRC = (
        'TRIGGER_REASONS = ("step_latency", "strange_reason")\n'
        "def f():\n"
        '    flight.trigger("other_reason", detail={})\n'
    )

    def _run(self, readme):
        from tools.graftlint.core import analyze_source
        return analyze_source(
            self.SRC, path="paddle_tpu/observability/fixture.py",
            rule_ids={"flight-reason-documented"}, readme_text=readme)

    def test_undocumented_reasons_flagged(self):
        found = self._run("step_latency is documented")
        assert sorted(f.line for f in found) == [1, 3]
        assert all(f.rule == "flight-reason-documented" for f in found)

    def test_documented_reasons_clean(self):
        assert self._run("step_latency strange_reason other_reason") \
            == []

    def test_out_of_scope_paths_ignored(self):
        from tools.graftlint.core import analyze_source
        assert analyze_source(
            self.SRC, path="paddle_tpu/inference/fixture.py",
            rule_ids={"flight-reason-documented"}, readme_text="") == []

    def test_repo_is_clean(self):
        """Every live trigger reason in the repo is documented — the
        rule holds on the actual tree (0 new findings is also pinned
        by the repo gate in test_graftlint, but the rule-scoped run
        keeps the failure message readable)."""
        from tools.graftlint.core import run_paths, repo_root
        rep = run_paths(["paddle_tpu"], root=repo_root(),
                        rule_ids={"flight-reason-documented"})
        assert rep.new == []


# ---------------------------------------------------------------------------
# obs_top: the numerics panel
# ---------------------------------------------------------------------------
@pytest.mark.obs
class TestObsTopPanel:
    def test_numerics_panel_renders(self):
        import json
        import importlib
        obs.enable()
        num.enable(interval=1)
        import jax.numpy as jnp
        num.submit(num.pack_stats([jnp.ones((4,))],
                                  [jnp.full((4,), 2.0)],
                                  [jnp.ones((4,)) * 0.9]),
                   names=("w",), groups=("g0",))
        num.flush()
        _amp = importlib.import_module("paddle_tpu.amp")
        m = _amp._amp_metrics()
        m["scale"].set(1024.0)
        m["steps"].labels(outcome="ok").inc(3)
        m["steps"].labels(outcome="skipped").inc()
        doc = json.loads(obs.to_json())
        import tools.obs_top as obs_top
        frame = obs_top.render(doc)
        assert "== numerics ==" in frame
        assert "grad norm" in frame and "all=" in frame
        assert "loss scale   1024" in frame
        assert "ok=3 skipped=1" in frame

    def test_no_panel_when_silent(self):
        import json
        obs.enable()
        doc = json.loads(obs.to_json())
        import tools.obs_top as obs_top
        assert "== numerics ==" not in obs_top.render(doc)
