"""Unified observability subsystem (paddle_tpu/observability/):
metrics registry semantics, Prometheus/JSON/Chrome-trace exports, the
disabled-mode overhead guard, profiler unification, and the runtime
instrumentation wired into LLMEngine / DataLoader (incl. across the
spawn boundary) / distributed checkpoint / fused optimizer step."""
import json
import os
import pickle
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.observability import MetricsRegistry, metrics, tracing
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts and ends disabled with empty series/ring (the
    registry is process-global)."""
    obs.disable()
    obs.reset()
    cap = tracing.capacity()
    yield
    obs.disable()
    obs.reset()
    tracing.set_capacity(cap)
    faults.clear_all()


def _series(name):
    return obs.snapshot()[name]["series"]


# ---------------------------------------------------------------------------
# metrics registry semantics
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counter_inc_and_snapshot(self):
        obs.enable()
        c = obs.registry().counter("t_reg_total", "help")
        c.inc()
        c.inc(2.5)
        assert _series("t_reg_total")[()] == 3.5

    def test_counter_rejects_negative(self):
        obs.enable()
        c = obs.registry().counter("t_neg_total", "")
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1)

    def test_labels_are_independent_series(self):
        obs.enable()
        c = obs.registry().counter("t_lbl_total", "", ("op", "ok"))
        c.labels(op="read", ok="true").inc(2)
        c.labels(op="write", ok="false").inc(5)
        s = _series("t_lbl_total")
        assert s[("read", "true")] == 2
        assert s[("write", "false")] == 5
        # cached child: same label values -> same object
        assert c.labels(op="read", ok="true") is \
            c.labels(op="read", ok="true")
        with pytest.raises(ValueError, match="expected labels"):
            c.labels(op="read")

    def test_gauge_set_inc_dec(self):
        obs.enable()
        g = obs.registry().gauge("t_gauge", "")
        g.set(10)
        g.inc(2)
        g.dec(5)
        assert _series("t_gauge")[()] == 7

    def test_histogram_bucket_correctness(self):
        obs.enable()
        h = obs.registry().histogram("t_hist_seconds", "",
                                     buckets=(1.0, 2.0))
        for v in (0.5, 1.0, 1.5, 3.0):      # le semantics: 1.0 -> le=1
            h.observe(v)
        val = _series("t_hist_seconds")[()]
        assert val["buckets"] == [2, 1, 1]   # (le 1, le 2, +Inf)
        assert val["count"] == 4
        assert val["sum"] == pytest.approx(6.0)
        assert val["min"] == 0.5 and val["max"] == 3.0

    def test_get_or_create_idempotent_and_conflict(self):
        r = obs.registry()
        a = r.counter("t_same_total", "h")
        assert r.counter("t_same_total", "h") is a
        with pytest.raises(ValueError, match="conflicting"):
            r.gauge("t_same_total")
        with pytest.raises(ValueError, match="conflicting"):
            r.counter("t_same_total", "h", labelnames=("x",))

    def test_reset_zeroes_but_keeps_registrations(self):
        obs.enable()
        c = obs.registry().counter("t_reset_total", "")
        c.inc(4)
        obs.reset()
        assert _series("t_reset_total")[()] == 0
        c.inc()                       # handed-out handle still works
        assert _series("t_reset_total")[()] == 1

    def test_disabled_records_nothing(self):
        c = obs.registry().counter("t_off_total", "")
        h = obs.registry().histogram("t_off_seconds", "")
        c.inc(5)
        h.observe(1.0)
        assert _series("t_off_total")[()] == 0
        assert _series("t_off_seconds")[()]["count"] == 0

    def test_disabled_mode_no_allocation_growth(self):
        """The acceptance guard: registry off => no net allocation per
        op (one flag check and out; span() returns a shared null)."""
        import tracemalloc
        c = obs.registry().counter("t_ov_total", "")
        h = obs.registry().histogram("t_ov_seconds", "")
        g = obs.registry().gauge("t_ov_gauge", "")
        for _ in range(16):           # warm any lazy state
            c.inc()
            h.observe(1.0)
            g.set(1.0)
            with obs.span("t.ov", k=1):
                pass
        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        for _ in range(5000):
            c.inc()
            h.observe(1.0)
            g.set(1.0)
            with obs.span("t.ov", k=1):
                pass
        grown = tracemalloc.get_traced_memory()[0] - base
        tracemalloc.stop()
        assert grown < 2048, f"disabled-mode ops leaked {grown}B"
        assert _series("t_ov_total")[()] == 0
        assert tracing.events() == []

    def test_snapshot_pickles_and_merges(self):
        obs.enable()
        src = MetricsRegistry()
        src.counter("t_m_total", "", ("k",)).labels(k="a").inc(2)
        hsrc = src.histogram("t_m_seconds", "", buckets=(1.0,))
        hsrc.observe(0.5)
        hsrc.observe(2.0)
        snap = pickle.loads(pickle.dumps(src.snapshot()))
        dst = MetricsRegistry()
        dst.merge(snap)
        dst.merge(snap)               # additive
        assert dst.counter("t_m_total", "", ("k",)) \
            .labels(k="a").value == 4
        out = dst.snapshot()["t_m_seconds"]["series"][()]
        assert out["count"] == 4
        assert out["buckets"] == [2, 2]
        assert out["sum"] == pytest.approx(5.0)
        assert out["min"] == 0.5 and out["max"] == 2.0

    def test_merge_bucket_skew_raises_not_corrupts(self):
        """A snapshot whose histogram bucket boundaries differ from
        the local registration must raise MergeSkewError (bucket-wise
        addition into the wrong bins is silent corruption), and the
        local series must be untouched afterwards."""
        obs.enable()
        src = MetricsRegistry()
        src.histogram("t_skw_seconds", "", buckets=(0.1, 1.0)) \
            .observe(0.5)
        dst = MetricsRegistry()
        dst.histogram("t_skw_seconds", "", buckets=(0.5, 2.0)) \
            .observe(0.2)
        with pytest.raises(obs.MergeSkewError, match="merge skew"):
            dst.merge(src.snapshot())
        out = dst.snapshot()["t_skw_seconds"]["series"][()]
        assert out["count"] == 1 and out["buckets"] == [1, 0, 0]

    def test_merge_label_schema_skew_raises_not_corrupts(self):
        obs.enable()
        src = MetricsRegistry()
        src.counter("t_skw_total", "", ("old_label",)) \
            .labels(old_label="x").inc(3)
        dst = MetricsRegistry()
        dst.counter("t_skw_total", "", ("new_label",)) \
            .labels(new_label="y").inc(1)
        with pytest.raises(obs.MergeSkewError, match="merge skew"):
            dst.merge(src.snapshot())
        assert dst.snapshot()["t_skw_total"]["series"] == {("y",): 1}

    def test_merge_skew_quarantine_mode(self):
        """on_skew="quarantine": both skew directions merge under the
        convention-preserving *_skew name, local series untouched —
        the fleet aggregator's stance (one stale peer must not stall
        the plane)."""
        obs.enable()
        src = MetricsRegistry()
        src.histogram("t_skwq_seconds", "", buckets=(0.1,)).observe(0.05)
        src.counter("t_skwq_total", "", ("lbl",)).labels(lbl="a").inc(2)
        dst = MetricsRegistry()
        dst.histogram("t_skwq_seconds", "", buckets=(0.5,)).observe(0.2)
        dst.counter("t_skwq_total", "").inc(7)
        q = dst.merge(src.snapshot(), on_skew="quarantine")
        assert sorted(q) == ["t_skwq_skew_seconds", "t_skwq_skew_total"]
        snap = dst.snapshot()
        # local series untouched
        assert snap["t_skwq_seconds"]["series"][()]["count"] == 1
        assert snap["t_skwq_total"]["series"][()] == 7
        # quarantined series carry the INCOMING schema + values
        assert snap["t_skwq_skew_seconds"]["series"][()]["count"] == 1
        assert snap["t_skwq_skew_total"]["series"][("a",)] == 2
        # clean merges still return no quarantines
        clean = MetricsRegistry()
        clean.counter("t_skwq_clean_total", "").inc()
        assert dst.merge(clean.snapshot(), on_skew="quarantine") == []

    def test_merge_malformed_series_shape_raises(self):
        obs.enable()
        src = MetricsRegistry()
        src.histogram("t_skwm_seconds", "").observe(0.5)
        snap = src.snapshot()
        snap["t_skwm_seconds"]["series"][()]["buckets"].append(1)
        dst = MetricsRegistry()
        with pytest.raises(obs.MergeSkewError, match="bucket count"):
            dst.merge(snap)

    def test_merge_applies_while_disabled(self):
        # the parent may have turned recording off by the time a worker
        # farewell arrives; the shipped history still counts
        src = MetricsRegistry()
        obs.enable()
        src.counter("t_md_total", "").inc(3)
        snap = src.snapshot()
        obs.disable()
        obs.registry().merge(snap)
        assert _series("t_md_total")[()] == 3


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------
class TestExports:
    def test_prometheus_exposition_golden(self):
        obs.enable()
        reg = MetricsRegistry()
        reg.counter("demo_total", "a counter", ("method",)) \
            .labels(method="get").inc(3)
        reg.gauge("demo_gauge", "a gauge").set(2.5)
        h = reg.histogram("demo_seconds", "a histogram",
                          buckets=(0.25, 1.0))
        for v in (0.25, 0.5, 2.0):
            h.observe(v)
        assert reg.to_prometheus() == (
            "# HELP demo_gauge a gauge\n"
            "# TYPE demo_gauge gauge\n"
            "demo_gauge 2.5\n"
            "# HELP demo_seconds a histogram\n"
            "# TYPE demo_seconds histogram\n"
            'demo_seconds_bucket{le="0.25"} 1\n'
            'demo_seconds_bucket{le="1"} 2\n'
            'demo_seconds_bucket{le="+Inf"} 3\n'
            "demo_seconds_sum 2.75\n"
            "demo_seconds_count 3\n"
            "# HELP demo_total a counter\n"
            "# TYPE demo_total counter\n"
            'demo_total{method="get"} 3\n')

    def test_prometheus_label_escaping(self):
        obs.enable()
        reg = MetricsRegistry()
        reg.counter("esc_total", "", ("p",)) \
            .labels(p='a"b\\c\nd').inc()
        assert 'esc_total{p="a\\"b\\\\c\\nd"} 1' in reg.to_prometheus()

    def test_json_export_roundtrip(self):
        obs.enable()
        reg = MetricsRegistry()
        reg.counter("j_total", "", ("k",)).labels(k="v").inc(7)
        reg.histogram("j_seconds", "", buckets=(1.0,)).observe(0.5)
        doc = json.loads(reg.to_json())
        assert doc["j_total"]["series"] == [
            {"labels": {"k": "v"}, "value": 7.0}]
        hs = doc["j_seconds"]
        assert hs["buckets"] == [1.0]
        assert hs["series"][0]["value"]["count"] == 1

    @pytest.mark.obs
    def test_chrome_trace_export(self, tmp_path):
        obs.enable()
        with obs.span("outer", kind="test"):
            with obs.span("inner"):
                pass
        path = obs.export_chrome_trace(str(tmp_path / "t.json"))
        with open(path) as f:
            doc = json.load(f)
        names = [e["name"] for e in doc["traceEvents"]]
        assert names == ["inner", "outer"]    # inner span ends first
        for e in doc["traceEvents"]:
            assert {"ph", "pid", "tid", "ts", "dur"} <= set(e)
            assert e["ph"] == "X" and e["dur"] >= 0
        outer = doc["traceEvents"][1]
        assert outer["args"] == {"kind": "test"}

    @pytest.mark.obs
    def test_jsonl_export(self, tmp_path):
        obs.enable()
        for i in range(3):
            with obs.span(f"s{i}"):
                pass
        path = obs.export_jsonl(str(tmp_path / "t.jsonl"))
        lines = [json.loads(l) for l in open(path)]
        assert [e["name"] for e in lines] == ["s0", "s1", "s2"]


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------
class TestTracing:
    def test_span_nesting_monotonic(self):
        obs.enable()
        with obs.span("a"):
            with obs.span("b"):
                pass
        evs = tracing.events()
        b, a = evs[0], evs[1]
        assert (b["name"], a["name"]) == ("b", "a")
        # the inner span is contained in the outer one
        assert a["ts"] <= b["ts"]
        assert b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 1e-3

    def test_ring_buffer_bounded(self):
        obs.enable()
        tracing.set_capacity(8)
        for i in range(20):
            with obs.span(f"e{i}"):
                pass
        evs = tracing.events()
        assert len(evs) == 8
        assert evs[0]["name"] == "e12"     # oldest dropped

    def test_disabled_span_is_shared_noop(self):
        s1 = obs.span("x", a=1)
        s2 = obs.span("y")
        assert s1 is s2                    # no allocation when off
        with s1:
            pass
        assert tracing.events() == []

    def test_span_end_idempotent(self):
        obs.enable()
        s = obs.span("once")
        with s:
            pass
        s.end()
        s.__exit__(None, None, None)
        assert len(tracing.events()) == 1


# ---------------------------------------------------------------------------
# profiler unification
# ---------------------------------------------------------------------------
class TestProfilerUnification:
    def test_record_event_double_end_idempotent(self):
        obs.enable()
        ev = profiler.RecordEvent("re")
        ev.begin()
        ev.end()
        ev.end()
        with profiler.RecordEvent("re2") as ev2:
            ev2.end()                      # explicit end inside with
        evs = [e["name"] for e in tracing.events()]
        assert evs == ["re", "re2"]

    @pytest.mark.obs
    def test_one_event_stream(self, tmp_path):
        """RecordEvent and observability spans land in ONE buffer;
        profiler export and the obs exporter see the same events."""
        p = profiler.Profiler(timer_only=True)
        p.start()
        try:
            with profiler.RecordEvent("via_profiler"):
                pass
            with obs.span("via_obs"):
                pass
        finally:
            p.stop()
        names = {e["name"] for e in p.events()}
        assert {"via_profiler", "via_obs"} <= names
        handler = profiler.export_chrome_tracing(str(tmp_path), "w")
        with open(handler(p)) as f:
            doc = json.load(f)
        assert {e["name"] for e in doc["traceEvents"]} >= names
        # profiler session over, obs was off before -> tracing off again
        assert not tracing.enabled()

    def test_profiler_restores_obs_tracing(self):
        obs.enable()
        p = profiler.Profiler(timer_only=True)
        p.start()
        p.stop()
        assert tracing.enabled()       # obs had it on before the session


# ---------------------------------------------------------------------------
# engine instrumentation (real LLMEngine run)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_gpt():
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


def _run_engine(model, n_prompts=3, n_new=6):
    from paddle_tpu.inference import LLMEngine
    rng = np.random.default_rng(0)
    eng = LLMEngine(model, max_batch=2, block_size=16, decode_chunk=4,
                    prompt_quantum=16, max_model_len=64)
    prompts = [rng.integers(0, 1024, (int(n),)).astype(np.int32)
               for n in (5, 9, 13, 7, 11)[:n_prompts]]
    return eng, eng.generate(prompts, max_new_tokens=n_new)


class TestEngineInstrumentation:
    def test_engine_emits_expected_series(self, tiny_gpt):
        obs.enable()
        eng, results = _run_engine(tiny_gpt)
        assert all(r.ok for r in results)
        snap = obs.snapshot()
        assert snap["paddle_tpu_engine_step_seconds"]["series"][()][
            "count"] >= 2
        # 3 prompts through max_batch=2 -> at least 2 admission waves
        assert snap["paddle_tpu_engine_prefill_seconds"]["series"][()][
            "count"] >= 2
        assert snap["paddle_tpu_engine_decode_chunk_seconds"]["series"][
            ()]["count"] >= 1
        ev = snap["paddle_tpu_engine_events_total"]["series"]
        assert ev[("prefills",)] == eng.stats["prefills"] == 3
        assert ev[("decode_tokens",)] == eng.stats["decode_tokens"]
        pool = snap["paddle_tpu_engine_page_pool_blocks"]["series"]
        assert pool[("free",)] + pool[("used",)] == \
            eng.cache.allocator.num_blocks
        q = snap["paddle_tpu_engine_queue_depth"]["series"]
        assert q[("waiting",)] == 0 and q[("running",)] == 0  # drained

    def test_engine_trace_spans(self, tiny_gpt):
        obs.enable()
        _run_engine(tiny_gpt, n_prompts=1, n_new=4)
        names = {e["name"] for e in tracing.events()}
        assert {"engine.step", "engine.prefill",
                "engine.decode_chunk"} <= names

    def test_engine_stats_backward_compatible_when_disabled(self,
                                                            tiny_gpt):
        """engine.stats stays a plain per-engine dict whether or not
        observability records — the pre-existing contract."""
        eng, results = _run_engine(tiny_gpt, n_prompts=2, n_new=4)
        assert isinstance(eng.stats, dict)
        assert dict(eng.stats) == eng.stats
        assert eng.stats["prefills"] == 2
        assert eng.stats["decode_tokens"] >= 2
        assert sorted(eng.stats) == [
            "aborted_requests",
            "deadline_expired", "decode_chunks", "decode_tokens",
            "failed_requests", "preemptions", "prefills",
            "prefix_cache_hit_tokens", "prefix_cache_miss_tokens",
            "ragged_launches", "rejected_requests",
            "spec_accepted_tokens", "spec_drafted_tokens",
            "spec_proposer_errors", "spec_step_errors", "spec_steps"]
        # nothing leaked into the (disabled) registry
        ev = _series("paddle_tpu_engine_events_total")
        assert sum(ev.values()) == 0
        assert tracing.events() == []

    def test_engine_failure_counters_mirror(self, tiny_gpt):
        obs.enable()
        from paddle_tpu.inference import LLMEngine
        eng = LLMEngine(tiny_gpt, max_batch=2, block_size=8,
                        num_blocks=5, max_model_len=64, shed_load=True)
        # infeasible: needs more blocks than the pool owns -> rejected
        eng.add_request("big", np.arange(30, dtype=np.int32),
                        max_new_tokens=30)
        res = eng.step()
        assert res and res[0].finish_reason == "rejected"
        ev = _series("paddle_tpu_engine_events_total")
        assert ev[("rejected_requests",)] == 1


# ---------------------------------------------------------------------------
# DataLoader instrumentation (incl. spawn-boundary aggregation)
# ---------------------------------------------------------------------------
class ObsShmDs(Dataset):
    """Module-level (spawn-picklable); 256 KiB samples force the
    SharedMemory transport."""

    def __init__(self, n=12):
        self.n = n

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return rng.standard_normal(64 * 1024).astype(np.float32), \
            np.int64(i)

    def __len__(self):
        return self.n


class ObsSmallDs(Dataset):
    """Tiny samples: rides the queue pickle, no SharedMemory."""

    def __init__(self, n=12):
        self.n = n

    def __getitem__(self, i):
        return np.full((4,), i, np.float32)

    def __len__(self):
        return self.n


class TestDataLoaderInstrumentation:
    def test_buffered_tier_wait_histogram(self):
        obs.enable()
        ds = ObsSmallDs(n=8)
        out = list(DataLoader(ds, batch_size=2, num_workers=0))
        assert len(out) == 4
        wait = _series("paddle_tpu_dataloader_batch_wait_seconds")[()]
        # one wait per batch + one for the end-of-epoch sentinel
        assert wait["count"] >= 4

    def test_spawn_worker_metrics_survive_aggregation(self):
        """Worker-side series are recorded IN the spawned processes and
        merged into the parent registry via the workers' farewell
        messages (the faults snapshot/install pattern, reversed)."""
        obs.enable()
        ds = ObsShmDs(n=12)
        out = list(DataLoader(ds, batch_size=4, num_workers=2))
        assert len(out) == 3
        snap = obs.snapshot()
        produced = snap["paddle_tpu_dataloader_worker_batches_total"][
            "series"][()]
        assert produced == 3
        lat = snap["paddle_tpu_dataloader_worker_batch_seconds"][
            "series"][()]
        assert lat["count"] == 3 and lat["sum"] > 0
        # parent-side series from the same epoch
        wait = snap["paddle_tpu_dataloader_batch_wait_seconds"][
            "series"][()]
        assert wait["count"] == 3
        shm = snap["paddle_tpu_dataloader_shm_bytes_total"]["series"][()]
        assert shm == 3 * 4 * 64 * 1024 * 4    # 3 batches x [4, 64Ki] f32
        assert snap["paddle_tpu_dataloader_shm_bytes_in_flight"][
            "series"][()] == 0                 # all unpacked

    def test_worker_restart_counter(self):
        obs.enable()
        ds = ObsSmallDs(n=12)
        with faults.inject("io.worker.batch", exit_code=1, times=1,
                           match={"bi": 2, "attempt": 0}):
            # the hard exit can land before the queue feeder flushes
            # earlier batches, so the respawn batch number varies — the
            # restart COUNT is the contract here
            with pytest.warns(UserWarning, match="respawning at batch"):
                out = list(DataLoader(ds, batch_size=2, num_workers=2))
        assert len(out) == 6
        assert _series(
            "paddle_tpu_dataloader_worker_restarts_total")[()] == 1


# ---------------------------------------------------------------------------
# checkpoint instrumentation
# ---------------------------------------------------------------------------
class TestCheckpointInstrumentation:
    def test_save_restore_metrics(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        obs.enable()
        sd = {"w": pt.to_tensor(np.arange(32, dtype=np.float32)),
              "b": pt.to_tensor(np.ones((4,), np.float32))}
        ckpt.save_state_dict(sd, str(tmp_path / "step_1"))
        dst = {"w": pt.to_tensor(np.zeros(32, np.float32)),
               "b": pt.to_tensor(np.zeros(4, np.float32))}
        ckpt.load_state_dict(dst, str(tmp_path / "step_1"))
        np.testing.assert_array_equal(dst["w"].numpy(), sd["w"].numpy())
        snap = obs.snapshot()
        assert snap["paddle_tpu_checkpoint_save_seconds"]["series"][()][
            "count"] == 1
        assert snap["paddle_tpu_checkpoint_restore_seconds"]["series"][
            ()]["count"] == 1
        by = snap["paddle_tpu_checkpoint_shard_bytes_total"]["series"]
        assert by[("save",)] > 0
        assert by[("save",)] == by[("restore",)]
        names = {e["name"] for e in tracing.events()}
        assert {"checkpoint.save", "checkpoint.restore"} <= names

    def test_torn_quarantine_counters(self, tmp_path):
        from paddle_tpu.distributed import checkpoint as ckpt
        obs.enable()
        sd = {"w": pt.to_tensor(np.arange(8, dtype=np.float32))}
        ckpt.save_state_dict(sd, str(tmp_path / "step_1"))
        ckpt.save_state_dict(
            {"w": pt.to_tensor(np.arange(8, 16).astype(np.float32))},
            str(tmp_path / "step_2"))
        # tear the newer checkpoint: drop a manifest-listed shard file
        shard = next(f for f in os.listdir(tmp_path / "step_2")
                     if f.endswith(".npy"))
        os.remove(tmp_path / "step_2" / shard)
        dst = {"w": pt.to_tensor(np.zeros(8, np.float32))}
        with pytest.warns(UserWarning, match="skipping torn"):
            loaded = ckpt.resume_latest(dst, str(tmp_path),
                                        cleanup=True)
        assert loaded and loaded.endswith("step_1")
        torn = _series("paddle_tpu_checkpoint_torn_total")
        assert torn[("skipped",)] == 1
        assert torn[("quarantined",)] == 1


# ---------------------------------------------------------------------------
# fused optimizer step instrumentation
# ---------------------------------------------------------------------------
class TestOptimizerInstrumentation:
    def test_fused_cache_hit_miss_counters(self):
        obs.enable()
        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=lin.parameters())
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        for _ in range(3):
            (lin(x) ** 2).mean().backward()
            opt.step()
            opt.clear_grad()
        s = _series("paddle_tpu_optimizer_fused_step_total")
        assert s[("compile",)] == 1          # first signature compiles
        assert s[("hit",)] == 2              # then the executable reuses

    def test_hyper_mutation_counts_recompile(self):
        obs.enable()
        lin = pt.nn.Linear(4, 4)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=lin.parameters())
        x = pt.to_tensor(np.ones((2, 4), np.float32))

        def one_step():
            (lin(x) ** 2).mean().backward()
            opt.step()
            opt.clear_grad()

        one_step()
        opt.beta1 = 0.5          # instance-hyper mutation -> new key
        one_step()
        s = _series("paddle_tpu_optimizer_fused_step_total")
        assert s[("compile",)] == 2
