"""Registry-wide op conformance matrix (VERDICT r1 item 9).

Family-driven: unary/binary/comparison/reduction ops are checked
against their numpy equivalents in eager AND jit modes via op_test;
gradient checks use the vectorized jacfwd path. A coverage gate keeps
the matrix honest: every newly registered op must either join a family
table or the documented exemption list.
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import ops
from tests.op_test import check_output, check_grad

RNG = np.random.default_rng(7)


def _x(shape=(3, 4), lo=-2.0, hi=2.0):
    return (RNG.uniform(lo, hi, shape)).astype(np.float32)


# op -> numpy reference; input domain (-2,2) unless listed in _POS/_UNIT
UNARY = {
    "abs": np.abs, "acos": None, "acosh": None, "asin": None,
    "asinh": np.arcsinh, "atan": np.arctan, "atanh": None,
    "ceil": np.ceil, "cos": np.cos, "cosh": np.cosh,
    "deg2rad": np.deg2rad, "digamma": None, "erf": None, "erfinv": None,
    "exp": np.exp, "expm1": np.expm1, "floor": np.floor, "frac": None,
    "i0": None, "i0e": None, "i1": None, "i1e": None, "imag": None,
    "isfinite": np.isfinite, "isinf": np.isinf, "isnan": np.isnan,
    "lgamma": None, "log": np.log, "log10": np.log10, "log1p": np.log1p,
    "log2": np.log2, "neg": np.negative, "rad2deg": np.rad2deg,
    "real": None, "reciprocal": np.reciprocal, "round": np.round,
    "rsqrt": None, "sigmoid": None, "sign": np.sign, "sin": np.sin,
    "sinh": np.sinh, "sqrt": np.sqrt, "square": np.square,
    "tan": np.tan, "tanh": np.tanh, "trunc": np.trunc,
}
_POS = {"log", "log10", "log1p", "log2", "sqrt", "rsqrt", "digamma",
        "lgamma", "reciprocal"}          # domain (0.1, 3)
_UNIT = {"acos", "asin", "atanh", "erfinv"}   # domain (-0.9, 0.9)
_GE1 = {"acosh"}                              # domain (1.1, 3)
_NO_GRAD = {"ceil", "floor", "round", "sign", "trunc", "isfinite",
            "isinf", "isnan", "frac", "i0", "i0e", "i1", "i1e",
            "erfinv", "digamma", "real", "imag"}

_NP_FALLBACK = {
    "acos": np.arccos, "acosh": np.arccosh, "asin": np.arcsin,
    "atanh": np.arctanh, "frac": lambda x: x - np.trunc(x),
    "rsqrt": lambda x: 1.0 / np.sqrt(x),
    "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
    "real": np.real, "imag": np.imag,
}
try:
    import scipy.special as _sps
    _NP_FALLBACK.update({
        "digamma": _sps.digamma, "erf": _sps.erf, "erfinv": _sps.erfinv,
        "lgamma": _sps.gammaln, "i0": _sps.i0, "i0e": _sps.i0e,
        "i1": _sps.i1, "i1e": _sps.i1e})
except ImportError:
    pass

BINARY = {
    "add": np.add, "subtract": np.subtract, "multiply": np.multiply,
    "divide": np.divide, "maximum": np.maximum, "minimum": np.minimum,
    "fmax": np.fmax, "fmin": np.fmin, "pow": np.power,
    "atan2": np.arctan2, "hypot": np.hypot, "logaddexp": np.logaddexp,
    "copysign": np.copysign, "nextafter": np.nextafter,
    "heaviside": np.heaviside, "mod": np.mod,
    "floor_divide": np.floor_divide,
}
_BIN_NO_GRAD = {"nextafter", "heaviside", "mod", "floor_divide",
                "copysign"}

COMPARE = {
    "equal": np.equal, "not_equal": np.not_equal,
    "greater_than": np.greater, "greater_equal": np.greater_equal,
    "less_than": np.less, "less_equal": np.less_equal,
    "logical_and": np.logical_and, "logical_or": np.logical_or,
    "logical_xor": np.logical_xor,
}

REDUCE = {
    "sum": np.sum, "mean": np.mean, "max": np.max, "min": np.min,
    "prod": np.prod, "amax": np.amax, "amin": np.amin,
    "std": lambda x: np.std(x, ddof=1), "var": lambda x: np.var(x, ddof=1),
    "median": np.median, "nansum": np.nansum, "nanmean": np.nanmean,
    "logsumexp": None, "all": np.all, "any": np.any,
    "count_nonzero": np.count_nonzero,
}


def _domain(name):
    if name in _POS:
        return _x(lo=0.1, hi=3.0)
    if name in _UNIT:
        return _x(lo=-0.9, hi=0.9)
    if name in _GE1:
        return _x(lo=1.1, hi=3.0)
    return _x()


class TestUnaryFamily:
    @pytest.mark.parametrize("name", sorted(UNARY))
    def test_output(self, name):
        ref = UNARY[name] or _NP_FALLBACK.get(name)
        if ref is None:
            pytest.skip(f"no numpy reference for {name}")
        x = _domain(name)
        if name in ("real", "imag"):
            x = x.astype(np.complex64)
        check_output(getattr(ops, name), ref, {"x": x})

    @pytest.mark.parametrize(
        "name", sorted(set(UNARY) - _NO_GRAD))
    def test_grad_jacfwd(self, name):
        x = _domain(name)
        check_grad(getattr(ops, name), {"x": x}, method="jacfwd")


class TestBinaryFamily:
    @pytest.mark.parametrize("name", sorted(BINARY))
    def test_output(self, name):
        a, b = _x(), _x(lo=0.2, hi=2.0)
        check_output(getattr(ops, name), BINARY[name],
                     {"a": a, "b": b})

    @pytest.mark.parametrize(
        "name", sorted(set(BINARY) - _BIN_NO_GRAD))
    def test_grad_jacfwd(self, name):
        a, b = _x(lo=0.2, hi=2.0), _x(lo=0.2, hi=2.0)
        check_grad(getattr(ops, name), {"a": a, "b": b},
                   method="jacfwd")


class TestCompareFamily:
    @pytest.mark.parametrize("name", sorted(COMPARE))
    def test_output(self, name):
        if name.startswith("logical"):
            a = RNG.integers(0, 2, (3, 4)).astype(bool)
            b = RNG.integers(0, 2, (3, 4)).astype(bool)
        else:
            a, b = _x(), _x()
        check_output(getattr(ops, name), COMPARE[name],
                     {"a": a, "b": b})


class TestReduceFamily:
    @pytest.mark.parametrize("name", sorted(REDUCE))
    def test_output(self, name):
        ref = REDUCE[name]
        if ref is None:
            from scipy.special import logsumexp as ref  # noqa: F811
        x = _x()
        if name in ("all", "any"):
            x = x > 0
        check_output(getattr(ops, name), ref, {"x": x})

    @pytest.mark.parametrize("name", ["sum", "mean", "logsumexp",
                                      "std", "var"])
    def test_grad_jacfwd(self, name):
        check_grad(getattr(ops, name), {"x": _x()}, method="jacfwd")


ACTIVATIONS = {
    # name -> numpy reference
    "relu": lambda x: np.maximum(x, 0),
    "relu6": lambda x: np.clip(x, 0, 6),
    "leaky_relu": lambda x: np.where(x > 0, x, 0.01 * x),
    "elu": lambda x: np.where(x > 0, x, np.expm1(x)),
    "celu": lambda x: np.maximum(x, 0) + np.minimum(0, np.expm1(x)),
    "selu": lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * np.expm1(x)),
    "softplus": lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0),
    "softsign": lambda x: x / (1 + np.abs(x)),
    "hardtanh": lambda x: np.clip(x, -1, 1),
    "hardsigmoid": lambda x: np.clip(x / 6 + 0.5, 0, 1),
    "hardswish": lambda x: x * np.clip(x + 3, 0, 6) / 6,
    "hardshrink": lambda x: np.where(np.abs(x) > 0.5, x, 0),
    "softshrink": lambda x: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0),
    "tanhshrink": lambda x: x - np.tanh(x),
    "mish": lambda x: x * np.tanh(np.log1p(np.exp(-np.abs(x)))
                                  + np.maximum(x, 0)),
    "logsigmoid": lambda x: -(np.log1p(np.exp(-np.abs(x)))
                              + np.maximum(-x, 0)),
    "logit": None,
    "stanh": lambda x: 1.7159 * np.tanh(0.67 * x),
    "thresholded_relu": lambda x: np.where(x > 1.0, x, 0),
    "silu": lambda x: x / (1 + np.exp(-x)),
    "gelu": None,
}
_ACT_NO_GRAD = {"hardshrink", "softshrink", "thresholded_relu", "logit",
                "gelu"}


class TestActivationFamily:
    @pytest.mark.parametrize("name", sorted(ACTIVATIONS))
    def test_output(self, name):
        ref = ACTIVATIONS[name]
        if ref is None:
            pytest.skip(f"no closed numpy reference for {name}")
        check_output(getattr(ops, name), ref, {"x": _x()}, rtol=1e-3,
                     atol=1e-4)

    @pytest.mark.parametrize("name",
                             sorted(set(ACTIVATIONS) - _ACT_NO_GRAD))
    def test_grad_jacfwd(self, name):
        check_grad(getattr(ops, name), {"x": _x()}, method="jacfwd",
                   rtol=2e-2)


INT_BINARY = {
    "bitwise_and": np.bitwise_and, "bitwise_or": np.bitwise_or,
    "bitwise_xor": np.bitwise_xor,
    "bitwise_left_shift": np.left_shift,
    "bitwise_right_shift": np.right_shift,
    "gcd": np.gcd, "lcm": np.lcm,
}


class TestIntFamily:
    @pytest.mark.parametrize("name", sorted(INT_BINARY))
    def test_output(self, name):
        a = RNG.integers(0, 8, (3, 4)).astype(np.int32)
        b = RNG.integers(1, 4, (3, 4)).astype(np.int32)
        check_output(getattr(ops, name), INT_BINARY[name],
                     {"a": a, "b": b})

    def test_bitwise_not(self):
        a = RNG.integers(0, 8, (3, 4)).astype(np.int32)
        check_output(ops.bitwise_not, np.bitwise_not, {"a": a})

    def test_logical_not(self):
        a = RNG.integers(0, 2, (3, 4)).astype(bool)
        check_output(ops.logical_not, np.logical_not, {"a": a})


class TestShapeFamily:
    """Manipulation ops: eager == jit == numpy."""

    CASES = {
        "reshape": (lambda x: ops.reshape(x, (4, 3)),
                    lambda x: np.reshape(x, (4, 3))),
        "transpose": (lambda x: ops.transpose(x, (1, 0)),
                      lambda x: np.transpose(x)),
        "flip": (lambda x: ops.flip(x, axis=0),
                 lambda x: np.flip(x, 0)),
        "roll": (lambda x: ops.roll(x, 1, axis=1),
                 lambda x: np.roll(x, 1, 1)),
        "squeeze": (lambda x: ops.squeeze(ops.unsqueeze(x, 0), 0),
                    lambda x: x),
        "tile": (lambda x: ops.tile(x, (2, 1)),
                 lambda x: np.tile(x, (2, 1))),
        "rot90": (lambda x: ops.rot90(x), lambda x: np.rot90(x)),
        "tril": (lambda x: ops.tril(x), np.tril),
        "triu": (lambda x: ops.triu(x), np.triu),
        "diag": (lambda x: ops.diag(x), np.diag),
        "cumsum": (lambda x: ops.cumsum(x, axis=1),
                   lambda x: np.cumsum(x, 1)),
        "cumprod": (lambda x: ops.cumprod(x, dim=1),
                    lambda x: np.cumprod(x, 1)),
        "sort": (lambda x: ops.sort(x, axis=1),
                 lambda x: np.sort(x, 1)),
        "argsort": (lambda x: ops.argsort(x, axis=1),
                    lambda x: np.argsort(x, 1)),
        "flatten": (lambda x: ops.flatten(x),
                    lambda x: x.reshape(-1)),
        "swapaxes": (lambda x: ops.swapaxes(x, 0, 1),
                     lambda x: np.swapaxes(x, 0, 1)),
        "moveaxis": (lambda x: ops.moveaxis(x, 0, 1),
                     lambda x: np.moveaxis(x, 0, 1)),
        "t": (lambda x: ops.t(x), lambda x: x.T),
        "unsqueeze": (lambda x: ops.unsqueeze(x, 1),
                      lambda x: x[:, None, :]),
        "diagonal": (lambda x: ops.diagonal(x), np.diagonal),
        "trace": (lambda x: ops.trace(x), np.trace),
        "diff": (lambda x: ops.diff(x, axis=1),
                 lambda x: np.diff(x, axis=1)),
        "nan_to_num": (lambda x: ops.nan_to_num(x), np.nan_to_num),
        "cummax": (lambda x: ops.cummax(x, axis=1)[0],
                   lambda x: np.maximum.accumulate(x, 1)),
        "cummin": (lambda x: ops.cummin(x, axis=1)[0],
                   lambda x: np.minimum.accumulate(x, 1)),
        # paddle full-rank pad orders dims FIRST->last (functional.pad
        # docs), unlike torch's partial spec
        "pad_op": (lambda x: ops.pad(x, [1, 1, 0, 0]),
                   lambda x: np.pad(x, ((1, 1), (0, 0)))),
        "atleast_2d_op": (lambda x: ops.atleast_2d(x),
                          np.atleast_2d),
        "as_strided": (lambda x: ops.as_strided(x, (2, 3), (4, 1)),
                       lambda x: np.lib.stride_tricks.as_strided(
                           x, (2, 3), (16, 4))),
    }

    @pytest.mark.parametrize("name", sorted(CASES))
    def test_output(self, name):
        fn, ref = self.CASES[name]
        check_output(fn, ref, {"x": _x()})


class TestLinalgFamily:
    def test_matmul(self):
        check_output(ops.matmul, np.matmul,
                     {"a": _x((3, 4)), "b": _x((4, 5))})
        check_grad(ops.matmul, {"a": _x((3, 4)), "b": _x((4, 5))},
                   method="jacfwd")

    def test_einsum_like(self):
        for name, ref in [
            ("dot", np.dot), ("inner", np.inner), ("outer", np.outer),
            ("kron", np.kron),
        ]:
            check_output(getattr(ops, name), ref,
                         {"a": _x((4,)), "b": _x((4,))})

    def test_mat_products(self):
        check_output(ops.mm, np.matmul, {"a": _x((3, 4)), "b": _x((4, 5))})
        check_output(ops.bmm, np.matmul,
                     {"a": _x((2, 3, 4)), "b": _x((2, 4, 5))})
        check_output(ops.mv, np.matmul, {"a": _x((3, 4)), "b": _x((4,))})
        check_output(lambda i, a, b: ops.addmm(i, a, b),
                     lambda i, a, b: i + a @ b,
                     {"i": _x((3, 5)), "a": _x((3, 4)), "b": _x((4, 5))})
        check_output(ops.cross, np.cross, {"a": _x((3, 3)), "b": _x((3, 3))})
        check_output(lambda a, b: ops.tensordot(a, b, axes=1), np.dot,
                     {"a": _x((3, 4)), "b": _x((4, 5))})

    def test_determinants(self):
        a = _x((4, 4)) + 4 * np.eye(4, dtype=np.float32)
        check_output(ops.det, np.linalg.det, {"a": a}, rtol=1e-3)
        sign, logdet = ops.slogdet(pt.to_tensor(a))
        rs, rl = np.linalg.slogdet(a)
        np.testing.assert_allclose(float(sign.numpy()), rs)
        np.testing.assert_allclose(float(logdet.numpy()), rl, rtol=1e-4)
        spd = a @ a.T + np.eye(4, dtype=np.float32)
        check_output(ops.cholesky, np.linalg.cholesky, {"a": spd},
                     rtol=1e-3, atol=1e-4)

    def test_solve_inverse(self):
        a = _x((4, 4)) + 4 * np.eye(4, dtype=np.float32)
        b = _x((4, 2))
        check_output(ops.solve, np.linalg.solve, {"a": a, "b": b},
                     rtol=1e-3)
        check_output(ops.inverse, np.linalg.inv, {"a": a}, rtol=1e-3)
        check_output(lambda x: ops.norm(x), np.linalg.norm,
                     {"x": _x((4, 4))}, rtol=1e-4)

    def test_decompositions_reconstruct(self):
        a = _x((5, 4))
        u, s, vh = ops.svd(pt.to_tensor(a), full_matrices=False)
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)
        q, r = ops.qr(pt.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a,
                                   rtol=1e-3, atol=1e-4)


class TestRegistryCoverage:
    """Every registered op is either exercised by a test family above /
    a dedicated test module, or carries a documented exemption."""

    # ops covered by dedicated test modules (grep the name to find it)
    DEDICATED = {
        "scaled_dot_product_attention", "fused_flash_attention",
        "softmax", "log_softmax", "cross_entropy", "layer_norm",
        "rms_norm", "batch_norm", "group_norm", "instance_norm",
        "linear", "embedding", "conv1d", "conv2d", "conv3d",
        "conv2d_transpose", "dropout", "gelu", "relu", "silu",
        "matmul", "one_hot", "gather", "concat", "split_op", "stack",
        "where", "clip", "cast", "topk", "argmax", "argmin",
        "max_pool2d", "avg_pool2d", "mse_loss", "l1_loss", "nll_loss",
        "binary_cross_entropy", "binary_cross_entropy_with_logits",
        "softmax_with_cross_entropy", "kl_div", "smooth_l1_loss",
        "swiglu", "unbind",
        "fused_rms_norm", "fused_layer_norm", "fused_linear",
        "fused_rotary_position_embedding", "expand", "broadcast_to",
        "slice_op", "getitem", "setitem", "full_like", "ones_like",
        "zeros_like", "assign",
        # covered by tests/test_ops_vision_seq.py
        "depthwise_conv2d", "conv3d_transpose", "deformable_conv", "fold",
        "max_pool2d_with_index", "unpool", "roi_pool", "psroi_pool",
        "prior_box", "yolo_box", "matrix_nms", "multiclass_nms",
        "ctc_loss", "viterbi_decode", "gather_tree", "top_p_sampling",
        "edit_distance", "class_center_sample", "huber_loss",
        "hsigmoid_loss", "margin_cross_entropy", "logcumsumexp", "renorm",
        "clip_by_norm", "p_norm", "add_n", "unstack", "fill_diagonal",
        "lu", "lu_unpack", "spectral_norm", "rrelu", "bilinear",
        "send_u_recv", "send_ue_recv", "send_uv", "segment_pool",
        # covered by tests/test_nn_utils_extra.py
        "adaptive_max_pool1d", "adaptive_avg_pool3d", "adaptive_max_pool3d",
        # covered by tests/test_ops_torch_oracle.py
        "lerp", "ldexp", "histogram", "bincount", "kthvalue", "mode",
        "quantile", "nanquantile", "nanmedian", "polygamma",
        "searchsorted", "put_along_axis", "take_along_axis",
        "index_select", "index_add", "masked_fill", "masked_select",
        "cholesky_solve", "matrix_power", "svdvals", "pinv",
        "householder_product", "dist", "cov", "corrcoef", "glu", "prelu",
        "cosine_similarity", "triplet_margin_loss",
        "hinge_embedding_loss", "cosine_embedding_loss",
        "margin_ranking_loss", "sigmoid_cross_entropy_with_logits",
        "log_loss", "isclose", "equal_all", "allclose", "diag_embed",
        "diagflat", "trapezoid", "cumulative_trapezoid", "unfold",
        "repeat_interleave", "nonzero", "increment", "gather_nd",
        "strided_slice", "expand_as", "angle", "conj",
        # covered by tests/test_ops_oracle_r3.py (round-3 long-tail +
        # previously-exempt tail; see its case tables)
        "column_stack", "row_stack", "hstack", "vstack", "dstack",
        "unflatten", "take", "block_diag", "cartesian_prod",
        "combinations", "diagonal_scatter", "select_scatter",
        "slice_scatter", "sinc", "signbit", "isposinf", "isneginf",
        "isreal", "positive", "negative", "sgn", "float_power", "vander",
        "gammaln", "gammainc", "gammaincc", "multigammaln",
        "histogram_bin_edges", "histogramdd", "pdist", "cdist", "polar",
        "linalg_cond", "matrix_exp", "addbmm", "baddbmm",
        "cholesky_inverse", "geqrf", "reverse",
        "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
        "avg_pool1d", "avg_pool3d", "max_pool1d", "max_pool3d",
        "bucketize", "channel_shuffle", "pixel_shuffle", "pixel_unshuffle",
        "index_sample", "index_fill", "index_put", "masked_scatter",
        "local_response_norm", "normalize", "multi_dot", "matrix_norm",
        "vector_norm", "matrix_rank", "maxout", "triangular_solve",
        "unique_consecutive", "unique_op", "label_smooth",
        "square_error_cost", "scale", "crop", "multiplex", "is_empty",
        "shard_index", "einsum_op", "view", "as_complex", "as_real",
        "complex", "atleast_1d_op", "atleast_3d_op", "unfold_im2col",
        "scatter", "scatter_nd", "scatter_nd_add", "eig", "eigh",
        "eigvals", "eigvalsh", "lstsq", "interpolate", "upsample",
        "affine_grid", "grid_sample", "alpha_dropout", "dropout2d",
        "gumbel_softmax", "temporal_shift", "nms", "sequence_mask",
        "roi_align", "box_coder", "fused_dropout_add",
        "fused_bias_dropout_residual_layer_norm",
        "fused_linear_activation", "npair_loss",
        "mean_all", "numel", "shape_op", "fill", "fill_diagonal_tensor",
        "accuracy_op", "auc_op", "weight_quantize", "weight_dequantize",
        "weight_only_linear", "llm_int8_linear", "warprnnt",
        "fused_softmax_mask", "fused_softmax_mask_upper_triangle",
        "generate_proposals", "distribute_fpn_proposals",
        "max_pool3d_with_index", "unpool3d", "assign_value",
        "check_numerics", "full_batch_size_like", "index_select_strided",
        "trans_layout",
        # covered by tests/test_parity_gaps_r4.py (round-4 gap closures)
        "squared_l2_norm", "frexp", "yolo_loss",
        # covered by tests/test_rnn_scan_conformance.py (torch oracle)
        "lstm_scan", "gru_scan", "simple_rnn_scan",
        "fused_bias_act",  # covered by tests/test_parity_gaps_r4.py
        # covered by tests/test_serving.py TestIncubateFunctionalBatch
        "fused_matmul_bias", "fused_dot_product_attention",
        "fused_ec_moe", "fused_gate_attention",
    }

    def test_coverage_accounting(self):
        import paddle_tpu.ops.registry as r
        covered = (set(UNARY) | set(BINARY) | set(COMPARE) | set(REDUCE)
                   | set(ACTIVATIONS) | set(INT_BINARY)
                   | {"bitwise_not", "logical_not"}
                   | set(TestShapeFamily.CASES) | self.DEDICATED
                   | {"dot", "inner", "outer", "kron", "solve",
                      "inverse", "norm", "svd", "qr", "mm", "bmm", "mv",
                      "addmm", "cross", "tensordot", "det", "slogdet",
                      "cholesky"})
        registered = set(r.OPS)
        uncovered = sorted(registered - covered)
        # fft/signal/quant ops have their own conformance modules
        # fft/signal/quant have dedicated modules; dist_reshard /
        # moe_gshard_dispatch / pp_xfer are runtime-internal ops
        # exercised by the distributed suites
        uncovered = [n for n in uncovered
                     if not n.startswith(("fft_", "signal_", "fake_",
                                          "dist_", "moe_", "pp_xfer",
                                          "ring_", "to_static_"))]
        # identity placeholder ops carry the "internal" tag (they keep a
        # YAML name importable while the real API lives elsewhere) — not
        # computational surface
        uncovered = [n for n in uncovered
                     if "internal" not in getattr(r.OPS[n], "tags", ())]
        # Gate: breadth may grow, but the uncovered tail must not.
        # (r1: 120, r2: 70, r3: 5, r4: 0 — the rnn/gru/lstm scan bodies
        # now have direct torch-oracle tests)
        assert len(uncovered) == 0, (
            f"{len(uncovered)} registered ops lack conformance coverage; "
            f"add them to a family table or a dedicated module: "
            f"{uncovered}")
