"""Op conformance: math/reduction/linalg/manipulation vs numpy
(OpTest analog, SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_output, check_grad


def _rand(*shape):
    return np.random.randn(*shape).astype(np.float32)


class TestBinaryOps:
    def test_add(self):
        check_output(paddle.add, np.add,
                     {"x": _rand(3, 4), "y": _rand(3, 4)})

    def test_broadcast_add(self):
        check_output(paddle.add, np.add, {"x": _rand(3, 4), "y": _rand(4)})

    def test_subtract(self):
        check_output(paddle.subtract, np.subtract,
                     {"x": _rand(3, 4), "y": _rand(3, 4)})

    def test_multiply(self):
        check_output(paddle.multiply, np.multiply,
                     {"x": _rand(3, 4), "y": _rand(3, 4)})

    def test_divide(self):
        check_output(paddle.divide, np.true_divide,
                     {"x": _rand(3, 4), "y": np.abs(_rand(3, 4)) + 1})

    def test_pow(self):
        check_output(paddle.pow, np.power,
                     {"x": np.abs(_rand(3, 4)) + 0.5, "y": _rand(3, 4)})

    def test_maximum(self):
        check_output(paddle.maximum, np.maximum,
                     {"x": _rand(3, 4), "y": _rand(3, 4)})

    def test_mod(self):
        check_output(paddle.mod, np.mod,
                     {"x": np.abs(_rand(3, 4)) * 10,
                      "y": np.abs(_rand(3, 4)) + 1})

    def test_add_grad(self):
        check_grad(paddle.multiply, {"x": _rand(3, 4), "y": _rand(3, 4)})


class TestUnaryOps:
    @pytest.mark.parametrize("op,ref", [
        ("exp", np.exp), ("log", None), ("sqrt", None), ("tanh", np.tanh),
        ("sin", np.sin), ("cos", np.cos), ("abs", np.abs),
        ("floor", np.floor), ("ceil", np.ceil), ("square", np.square),
        ("sign", np.sign),
    ])
    def test_unary(self, op, ref):
        x = np.abs(_rand(3, 4)) + 0.5 if op in ("log", "sqrt") else _rand(3, 4)
        ref = ref or getattr(np, op)
        # XLA CPU uses fast vectorized transcendentals: tolerate ~1e-4 abs
        check_output(getattr(paddle, op), ref, {"x": x}, atol=1e-4,
                     rtol=1e-3)

    def test_sigmoid(self):
        check_output(paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x)),
                     {"x": _rand(3, 4)})

    def test_clip(self):
        check_output(paddle.clip, lambda x, min, max: np.clip(x, min, max),
                     {"x": _rand(3, 4)}, {"min": -0.5, "max": 0.5})

    def test_rsqrt_grad(self):
        check_grad(paddle.rsqrt, {"x": np.abs(_rand(3, 3)) + 0.5})

    def test_tanh_grad(self):
        check_grad(paddle.tanh, {"x": _rand(3, 3)})


class TestReductions:
    def test_sum(self):
        check_output(paddle.sum, lambda x: np.sum(x), {"x": _rand(3, 4)})

    def test_sum_axis(self):
        check_output(paddle.sum,
                     lambda x, axis, keepdim: np.sum(x, axis,
                                                     keepdims=keepdim),
                     {"x": _rand(3, 4, 5)}, {"axis": 1, "keepdim": True})

    def test_mean(self):
        check_output(paddle.mean,
                     lambda x, axis: np.mean(x, axis),
                     {"x": _rand(3, 4)}, {"axis": 0})

    def test_max_min(self):
        check_output(paddle.max, lambda x, axis: np.max(x, axis),
                     {"x": _rand(3, 4)}, {"axis": 1})
        check_output(paddle.min, lambda x: np.min(x), {"x": _rand(3, 4)})

    def test_prod(self):
        check_output(paddle.prod, lambda x: np.prod(x), {"x": _rand(2, 3)})

    def test_logsumexp(self):
        from scipy.special import logsumexp as np_lse
        check_output(paddle.logsumexp, lambda x: np_lse(x),
                     {"x": _rand(3, 4)})

    def test_var_std(self):
        check_output(paddle.var, lambda x: np.var(x, ddof=1),
                     {"x": _rand(4, 5)})
        check_output(paddle.std, lambda x: np.std(x, ddof=1),
                     {"x": _rand(4, 5)})

    def test_cumsum(self):
        check_output(paddle.cumsum, lambda x, axis: np.cumsum(x, axis),
                     {"x": _rand(3, 4)}, {"axis": 1})

    def test_mean_grad(self):
        check_grad(paddle.mean, {"x": _rand(3, 4)})


class TestLinalg:
    def test_matmul(self):
        check_output(paddle.matmul, np.matmul,
                     {"x": _rand(3, 4), "y": _rand(4, 5)}, rtol=1e-4,
                     atol=1e-4)

    def test_matmul_transpose(self):
        check_output(paddle.matmul,
                     lambda x, y, transpose_y: x @ y.T,
                     {"x": _rand(3, 4), "y": _rand(5, 4)},
                     {"transpose_y": True}, rtol=1e-4, atol=1e-4)

    def test_batched_matmul(self):
        check_output(paddle.matmul, np.matmul,
                     {"x": _rand(2, 3, 4), "y": _rand(2, 4, 5)}, rtol=1e-4,
                     atol=1e-4)

    def test_matmul_grad(self):
        check_grad(paddle.matmul, {"x": _rand(3, 4), "y": _rand(4, 2)})

    def test_norm(self):
        check_output(paddle.norm, lambda x: np.linalg.norm(x.ravel()),
                     {"x": _rand(3, 4)}, rtol=1e-4)

    def test_einsum(self):
        x, y = _rand(3, 4), _rand(4, 5)
        out = paddle.einsum("ij,jk->ik", paddle.to_tensor(x),
                            paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), x @ y, rtol=1e-4, atol=1e-4)

    def test_svd_solve(self):
        a = _rand(4, 4) + 4 * np.eye(4, dtype=np.float32)
        b = _rand(4, 2)
        out = paddle.solve(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), np.linalg.solve(a, b),
                                   rtol=1e-3, atol=1e-3)

    def test_cholesky(self):
        a = _rand(3, 3)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        out = paddle.cholesky(paddle.to_tensor(spd))
        np.testing.assert_allclose(out.numpy(), np.linalg.cholesky(spd),
                                   rtol=1e-4, atol=1e-4)


class TestManipulation:
    def test_reshape(self):
        check_output(paddle.reshape, lambda x, shape: x.reshape(shape),
                     {"x": _rand(3, 4)}, {"shape": [2, 6]})

    def test_transpose(self):
        check_output(paddle.transpose,
                     lambda x, perm: np.transpose(x, perm),
                     {"x": _rand(2, 3, 4)}, {"perm": [2, 0, 1]})

    def test_concat(self):
        x, y = _rand(2, 3), _rand(2, 3)
        out = paddle.concat([paddle.to_tensor(x), paddle.to_tensor(y)],
                            axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([x, y], 0))

    def test_stack_split(self):
        x, y = _rand(2, 3), _rand(2, 3)
        st = paddle.stack([paddle.to_tensor(x), paddle.to_tensor(y)], axis=0)
        np.testing.assert_allclose(st.numpy(), np.stack([x, y]))
        parts = paddle.split(st, 2, axis=0)
        assert len(parts) == 2
        np.testing.assert_allclose(parts[0].numpy()[0], x)

    def test_squeeze_unsqueeze(self):
        check_output(paddle.unsqueeze,
                     lambda x, axis: np.expand_dims(x, axis),
                     {"x": _rand(3, 4)}, {"axis": 1})
        check_output(paddle.squeeze, lambda x, axis: np.squeeze(x, axis),
                     {"x": _rand(3, 1, 4)}, {"axis": 1})

    def test_gather(self):
        x = _rand(5, 4)
        idx = np.array([0, 2, 4])
        out = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(out.numpy(), x[idx])

    def test_where(self):
        c = np.array([[True, False], [False, True]])
        x, y = _rand(2, 2), _rand(2, 2)
        out = paddle.where(paddle.to_tensor(c), paddle.to_tensor(x),
                           paddle.to_tensor(y))
        np.testing.assert_allclose(out.numpy(), np.where(c, x, y))

    def test_tile_expand(self):
        check_output(paddle.tile, lambda x, repeat_times: np.tile(
            x, repeat_times), {"x": _rand(2, 3)}, {"repeat_times": (2, 1)})

    def test_pad(self):
        x = _rand(2, 3)
        out = paddle.pad(paddle.to_tensor(x), [1, 1, 2, 2], value=1.0)
        np.testing.assert_allclose(
            out.numpy(), np.pad(x, [(1, 1), (2, 2)], constant_values=1.0))

    def test_getitem(self):
        x = _rand(4, 5, 6)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t[1].numpy(), x[1])
        np.testing.assert_allclose(t[1:3, ::2].numpy(), x[1:3, ::2])
        np.testing.assert_allclose(t[:, None, 0].numpy(), x[:, None, 0])

    def test_setitem(self):
        x = _rand(4, 5)
        t = paddle.to_tensor(x)
        t[1] = 0.0
        x[1] = 0.0
        np.testing.assert_allclose(t.numpy(), x)

    def test_cast(self):
        x = _rand(3, 3)
        out = paddle.cast(paddle.to_tensor(x), "int32")
        assert out.dtype == paddle.int32


class TestSearchSort:
    def test_argmax(self):
        check_output(paddle.argmax, lambda x, axis: np.argmax(x, axis),
                     {"x": _rand(3, 4)}, {"axis": 1})

    def test_sort_argsort(self):
        check_output(paddle.sort, lambda x, axis: np.sort(x, axis),
                     {"x": _rand(3, 4)}, {"axis": 1})

    def test_topk(self):
        x = _rand(3, 10)
        vals, idx = paddle.topk(paddle.to_tensor(x), k=3)
        ref = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_unique(self):
        x = np.array([1, 3, 1, 2, 3], np.int64)
        out = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])

    def test_nonzero(self):
        x = np.array([[1, 0], [0, 2]], np.float32)
        out = paddle.nonzero(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [[0, 0], [1, 1]])


class TestLogic:
    def test_compare(self):
        x, y = _rand(3, 3), _rand(3, 3)
        out = paddle.to_tensor(x) > paddle.to_tensor(y)
        np.testing.assert_array_equal(out.numpy(), x > y)

    def test_allclose_isclose(self):
        x = _rand(3, 3)
        assert bool(paddle.allclose(paddle.to_tensor(x),
                                    paddle.to_tensor(x)))

    def test_logical(self):
        a = np.array([True, False, True])
        b = np.array([True, True, False])
        out = paddle.logical_and(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_array_equal(out.numpy(), a & b)
