"""Round-3 oracle conformance: the new long-tail ops (ops/longtail.py,
sparse/nn) AND the previously conformance-exempt registry tail
(VERDICT r2 weak #2 — drive exemptions 70 -> <=25).

Torch CPU (or numpy/scipy) is the oracle, same style as
test_ops_torch_oracle.py; case tables keep it vectorized.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
from paddle_tpu import ops
import paddle_tpu.nn.functional as F

rng = np.random.default_rng(7)


def t(x):
    return pt.to_tensor(np.asarray(x))


def npy(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


A23 = rng.standard_normal((2, 3)).astype(np.float32)
A46 = rng.standard_normal((4, 6)).astype(np.float32)
A345 = rng.standard_normal((3, 4, 5)).astype(np.float32)
V6 = rng.standard_normal(6).astype(np.float32)
IMG = rng.standard_normal((2, 4, 8, 8)).astype(np.float32)   # NCHW
IMG3 = rng.standard_normal((1, 3, 4, 6, 6)).astype(np.float32)  # NCDHW
SEQ = rng.standard_normal((2, 4, 16)).astype(np.float32)     # NCL


# ---------------------------------------------------------------------
# new long-tail ops (ops/longtail.py)
# ---------------------------------------------------------------------
LONGTAIL_CASES = [
    ("tensor_split",
     lambda: ops.tensor_split(t(A46), 4, axis=1)[0],
     lambda: torch.tensor_split(torch.tensor(A46), 4, dim=1)[0], 0),
    ("hsplit", lambda: ops.hsplit(t(A46), 2)[1],
     lambda: torch.hsplit(torch.tensor(A46), 2)[1], 0),
    ("vsplit", lambda: ops.vsplit(t(A46), 2)[1],
     lambda: torch.vsplit(torch.tensor(A46), 2)[1], 0),
    ("dsplit", lambda: ops.dsplit(t(A345.reshape(3, 4, 5)), [2])[0],
     lambda: torch.dsplit(torch.tensor(A345), [2])[0], 0),
    ("column_stack", lambda: ops.column_stack([t(V6), t(V6 * 2)]),
     lambda: torch.column_stack([torch.tensor(V6),
                                 torch.tensor(V6 * 2)]), 0),
    ("row_stack", lambda: ops.row_stack([t(A23), t(A23)]),
     lambda: torch.vstack([torch.tensor(A23), torch.tensor(A23)]), 0),
    ("hstack", lambda: ops.hstack([t(A23), t(A23)]),
     lambda: torch.hstack([torch.tensor(A23), torch.tensor(A23)]), 0),
    ("vstack", lambda: ops.vstack([t(A23), t(A23)]),
     lambda: torch.vstack([torch.tensor(A23), torch.tensor(A23)]), 0),
    ("dstack", lambda: ops.dstack([t(A23), t(A23)]),
     lambda: torch.dstack([torch.tensor(A23), torch.tensor(A23)]), 0),
    ("unflatten", lambda: ops.unflatten(t(A46), 1, [2, 3]),
     lambda: torch.tensor(A46).unflatten(1, (2, 3)), 0),
    ("take", lambda: ops.take(t(A46), t(np.array([0, 7, 23]))),
     lambda: torch.take(torch.tensor(A46), torch.tensor([0, 7, 23])), 0),
    ("block_diag",
     lambda: ops.block_diag([t(A23), t(np.eye(2, dtype=np.float32))]),
     lambda: torch.block_diag(torch.tensor(A23),
                              torch.eye(2)), 0),
    ("cartesian_prod",
     lambda: ops.cartesian_prod([t(V6[:2]), t(V6[2:5])]),
     lambda: torch.cartesian_prod(torch.tensor(V6[:2]),
                                  torch.tensor(V6[2:5])), 0),
    ("combinations", lambda: ops.combinations(t(V6), 2),
     lambda: torch.combinations(torch.tensor(V6), 2), 0),
    ("combinations_wr",
     lambda: ops.combinations(t(V6[:3]), 2, with_replacement=True),
     lambda: torch.combinations(torch.tensor(V6[:3]), 2,
                                with_replacement=True), 0),
    ("diagonal_scatter",
     lambda: ops.diagonal_scatter(t(A46), t(np.ones(4, np.float32))),
     lambda: torch.diagonal_scatter(torch.tensor(A46),
                                    torch.ones(4)), 0),
    ("diagonal_scatter_off",
     lambda: ops.diagonal_scatter(t(A46), t(np.ones(4, np.float32)),
                                  offset=2),
     lambda: torch.diagonal_scatter(torch.tensor(A46), torch.ones(4),
                                    offset=2), 0),
    ("select_scatter",
     lambda: ops.select_scatter(t(A46), t(np.zeros(6, np.float32)), 0, 2),
     lambda: torch.select_scatter(torch.tensor(A46), torch.zeros(6),
                                  0, 2), 0),
    ("slice_scatter",
     lambda: ops.slice_scatter(t(A46), t(np.zeros((4, 2), np.float32)),
                               [1], [1], [3], [1]),
     lambda: torch.slice_scatter(torch.tensor(A46), torch.zeros(4, 2),
                                 1, 1, 3, 1), 0),
    ("sinc", lambda: ops.sinc(t(A23)),
     lambda: torch.sinc(torch.tensor(A23)), 1e-5),
    ("signbit", lambda: ops.signbit(t(A23)),
     lambda: torch.signbit(torch.tensor(A23)), 0),
    ("isposinf",
     lambda: ops.isposinf(t(np.array([1.0, np.inf, -np.inf]))),
     lambda: torch.isposinf(torch.tensor([1.0, np.inf, -np.inf])), 0),
    ("isneginf",
     lambda: ops.isneginf(t(np.array([1.0, np.inf, -np.inf]))),
     lambda: torch.isneginf(torch.tensor([1.0, np.inf, -np.inf])), 0),
    ("positive", lambda: ops.positive(t(A23)),
     lambda: torch.positive(torch.tensor(A23)), 0),
    ("negative", lambda: ops.negative(t(A23)),
     lambda: torch.negative(torch.tensor(A23)), 0),
    ("sgn_complex",
     lambda: ops.sgn(t((A23 + 1j * A23).astype(np.complex64))),
     lambda: torch.sgn(torch.tensor((A23 + 1j * A23).astype(
         np.complex64))), 1e-5),
    ("float_power", lambda: ops.float_power(t(np.abs(A23) + 0.1), 2.5),
     lambda: torch.float_power(torch.tensor(np.abs(A23) + 0.1), 2.5),
     1e-6),
    ("vander", lambda: ops.vander(t(V6), 4),
     lambda: torch.vander(torch.tensor(V6), 4), 1e-4),
    ("gammaln", lambda: ops.gammaln(t(np.abs(A23) + 0.5)),
     lambda: torch.lgamma(torch.tensor(np.abs(A23) + 0.5)), 1e-5),
    ("gammainc", lambda: ops.gammainc(t(np.abs(A23) + 1),
                                      t(np.abs(A23) + 0.5)),
     lambda: torch.special.gammainc(torch.tensor(np.abs(A23) + 1),
                                    torch.tensor(np.abs(A23) + 0.5)),
     1e-5),
    ("gammaincc", lambda: ops.gammaincc(t(np.abs(A23) + 1),
                                        t(np.abs(A23) + 0.5)),
     lambda: torch.special.gammaincc(torch.tensor(np.abs(A23) + 1),
                                     torch.tensor(np.abs(A23) + 0.5)),
     1e-5),
    ("multigammaln", lambda: ops.multigammaln(t(np.abs(A23) + 3), 2),
     lambda: torch.special.multigammaln(torch.tensor(np.abs(A23) + 3),
                                        2), 1e-4),
    ("histogram_bin_edges",
     lambda: ops.histogram_bin_edges(t(V6), 4, -2, 2),
     lambda: np.histogram_bin_edges(V6, 4, range=(-2, 2)), 1e-6),
    ("pdist", lambda: ops.pdist(t(A46)),
     lambda: torch.pdist(torch.tensor(A46)), 1e-4),
    ("pdist_p1", lambda: ops.pdist(t(A46), p=1.0),
     lambda: torch.pdist(torch.tensor(A46), p=1.0), 1e-4),
    ("cdist", lambda: ops.cdist(t(A46), t(A46[:3])),
     lambda: torch.cdist(torch.tensor(A46), torch.tensor(A46[:3])),
     1e-3),
    ("cdist_p1", lambda: ops.cdist(t(A46), t(A46[:3]), p=1.0),
     lambda: torch.cdist(torch.tensor(A46), torch.tensor(A46[:3]),
                         p=1.0), 1e-4),
    ("polar", lambda: ops.polar(t(np.abs(A23)), t(A23)),
     lambda: torch.polar(torch.tensor(np.abs(A23)),
                         torch.tensor(A23)), 1e-5),
    ("view_as_complex",
     lambda: ops.view_as_complex(t(A46.reshape(4, 3, 2))),
     lambda: torch.view_as_complex(torch.tensor(
         A46.reshape(4, 3, 2))), 0),
    ("view_as_real",
     lambda: ops.view_as_real(t((A23 + 1j * A23).astype(np.complex64))),
     lambda: torch.view_as_real(torch.tensor(
         (A23 + 1j * A23).astype(np.complex64))), 0),
    ("cond_2", lambda: ops.cond(t(A46[:4, :4] + 4 * np.eye(4, dtype=np.float32))),
     lambda: torch.linalg.cond(torch.tensor(
         A46[:4, :4] + 4 * np.eye(4, dtype=np.float32))), 1e-3),
    ("cond_fro",
     lambda: ops.cond(t(A46[:4, :4] + 4 * np.eye(4, dtype=np.float32)),
                      p="fro"),
     lambda: torch.linalg.cond(torch.tensor(
         A46[:4, :4] + 4 * np.eye(4, dtype=np.float32)), p="fro"), 1e-3),
    ("matrix_exp", lambda: ops.matrix_exp(t(A46[:3, :3] * 0.3)),
     lambda: torch.matrix_exp(torch.tensor(A46[:3, :3] * 0.3)), 1e-4),
    ("addbmm",
     lambda: ops.addbmm(t(A23), t(A345[:, :2, :]),
                        t(np.swapaxes(A345, 1, 2)[:, :, :3][:, :, :3]
                          .copy())[:, :, :3][:, :, :3],
                        beta=0.5, alpha=2.0),
     lambda: torch.addbmm(torch.tensor(A23),
                          torch.tensor(A345[:, :2, :]),
                          torch.tensor(np.swapaxes(A345, 1, 2)
                                       [:, :, :3].copy()),
                          beta=0.5, alpha=2.0), 1e-4),
    ("baddbmm",
     lambda: ops.baddbmm(t(np.zeros((3, 2, 3), np.float32)),
                         t(A345[:, :2, :]),
                         t(np.swapaxes(A345, 1, 2)[:, :, :3].copy()),
                         beta=0.0, alpha=1.0),
     lambda: torch.baddbmm(torch.zeros(3, 2, 3),
                           torch.tensor(A345[:, :2, :]),
                           torch.tensor(np.swapaxes(A345, 1, 2)
                                        [:, :, :3].copy()),
                           beta=0.0, alpha=1.0), 1e-4),
    ("reverse", lambda: ops.reverse(t(A345), [0, 2]),
     lambda: torch.flip(torch.tensor(A345), [0, 2]), 0),
]

# wrong-shaped lambda above for addbmm second operand; rebuild simply
B34 = rng.standard_normal((3, 4)).astype(np.float32)
B_ADD = rng.standard_normal((3, 2, 4)).astype(np.float32)
C_ADD = rng.standard_normal((3, 4, 3)).astype(np.float32)
LONGTAIL_CASES = [c for c in LONGTAIL_CASES if c[0] != "addbmm"] + [
    ("addbmm",
     lambda: ops.addbmm(t(A23), t(B_ADD), t(C_ADD), beta=0.5, alpha=2.0),
     lambda: torch.addbmm(torch.tensor(A23), torch.tensor(B_ADD),
                          torch.tensor(C_ADD), beta=0.5, alpha=2.0),
     1e-4),
]


# ---------------------------------------------------------------------
# previously conformance-exempt registry tail
# ---------------------------------------------------------------------
IDX23 = np.array([[0, 2], [1, 0]], np.int64)
TAIL_CASES = [
    ("adaptive_avg_pool1d", lambda: ops.adaptive_avg_pool1d(t(SEQ), 4),
     lambda: TF.adaptive_avg_pool1d(torch.tensor(SEQ), 4), 1e-5),
    ("adaptive_avg_pool2d", lambda: ops.adaptive_avg_pool2d(t(IMG), 3),
     lambda: TF.adaptive_avg_pool2d(torch.tensor(IMG), 3), 1e-5),
    ("adaptive_max_pool2d", lambda: ops.adaptive_max_pool2d(t(IMG), 3),
     lambda: TF.adaptive_max_pool2d(torch.tensor(IMG), 3), 1e-5),
    ("avg_pool1d", lambda: ops.avg_pool1d(t(SEQ), 4, 2, 0),
     lambda: TF.avg_pool1d(torch.tensor(SEQ), 4, 2, 0), 1e-5),
    ("avg_pool3d", lambda: ops.avg_pool3d(t(IMG3), 2, 2, 0),
     lambda: TF.avg_pool3d(torch.tensor(IMG3), 2, 2, 0), 1e-5),
    ("max_pool1d", lambda: ops.max_pool1d(t(SEQ), 4, 2, 0),
     lambda: TF.max_pool1d(torch.tensor(SEQ), 4, 2, 0), 1e-5),
    ("max_pool3d", lambda: ops.max_pool3d(t(IMG3), 2, 2, 0),
     lambda: TF.max_pool3d(torch.tensor(IMG3), 2, 2, 0), 1e-5),
    ("bucketize",
     lambda: ops.bucketize(t(A23), t(np.sort(V6))),
     lambda: torch.bucketize(torch.tensor(A23),
                             torch.tensor(np.sort(V6))), 0),
    ("channel_shuffle", lambda: ops.channel_shuffle(t(IMG), 2),
     lambda: TF.channel_shuffle(torch.tensor(IMG), 2), 0),
    ("pixel_shuffle", lambda: ops.pixel_shuffle(t(IMG), 2),
     lambda: TF.pixel_shuffle(torch.tensor(IMG), 2), 0),
    ("pixel_unshuffle", lambda: ops.pixel_unshuffle(t(IMG), 2),
     lambda: TF.pixel_unshuffle(torch.tensor(IMG), 2), 0),
    ("index_sample",
     lambda: ops.index_sample(t(A23), t(IDX23)),
     lambda: torch.gather(torch.tensor(A23), 1, torch.tensor(IDX23)), 0),
    ("index_fill",
     lambda: ops.index_fill(t(A46), t(np.array([0, 2])), 0, -1.0),
     lambda: torch.tensor(A46).index_fill(
         0, torch.tensor([0, 2]), -1.0), 0),
    ("masked_scatter",
     lambda: ops.masked_scatter(t(A23), t(A23 > 0),
                                t(np.ones(6, np.float32))),
     lambda: torch.tensor(A23).masked_scatter(
         torch.tensor(A23 > 0), torch.ones(6)), 0),
    ("local_response_norm",
     lambda: F.local_response_norm(t(IMG), 3, alpha=1e-4, beta=0.75, k=1.0),
     lambda: TF.local_response_norm(torch.tensor(IMG), 3, alpha=1e-4,
                                    beta=0.75, k=1.0), 2e-3),
    ("normalize", lambda: F.normalize(t(A23), p=2, axis=1),
     lambda: TF.normalize(torch.tensor(A23), p=2, dim=1), 1e-5),
    ("multi_dot",
     lambda: ops.multi_dot([t(A23), t(B34), t(C_ADD[0][:, :2].copy())]),
     lambda: torch.linalg.multi_dot(
         [torch.tensor(A23), torch.tensor(B34),
          torch.tensor(C_ADD[0][:, :2].copy())]), 1e-4),
    ("matrix_norm", lambda: ops.matrix_norm(t(A46), "fro"),
     lambda: torch.linalg.matrix_norm(torch.tensor(A46), "fro"), 1e-5),
    ("vector_norm", lambda: ops.vector_norm(t(A46), 3.0),
     lambda: torch.linalg.vector_norm(torch.tensor(A46), 3.0), 1e-5),
    ("matrix_rank",
     lambda: ops.matrix_rank(t(np.outer(V6, V6).astype(np.float32))),
     lambda: torch.linalg.matrix_rank(torch.tensor(
         np.outer(V6, V6).astype(np.float32))), 0),
    ("maxout", lambda: ops.maxout(t(IMG), groups=2),
     lambda: torch.tensor(IMG).reshape(2, 2, 2, 8, 8).max(2)[0], 0),
    ("triangular_solve",
     lambda: ops.triangular_solve(
         t(np.tril(A46[:4, :4]) + 3 * np.eye(4, dtype=np.float32)),
         t(A46[:4, :2].copy()), upper=False),
     lambda: torch.linalg.solve_triangular(
         torch.tensor(np.tril(A46[:4, :4])
                      + 3 * np.eye(4, dtype=np.float32)),
         torch.tensor(A46[:4, :2].copy()), upper=False), 1e-4),
    ("unique_consecutive",
     lambda: ops.unique_consecutive(t(np.array([1., 1., 2., 2., 3., 1.]))),
     lambda: torch.unique_consecutive(
         torch.tensor([1., 1., 2., 2., 3., 1.])), 0),
    ("label_smooth",
     lambda: ops.label_smooth(t(np.eye(3, dtype=np.float32)), epsilon=0.1),
     lambda: torch.tensor(np.eye(3, dtype=np.float32)) * 0.9 + 0.1 / 3, 1e-6),
    ("square_error_cost",
     lambda: ops.square_error_cost(t(A23), t(A23 * 2)),
     lambda: (torch.tensor(A23) - torch.tensor(A23 * 2)) ** 2, 1e-6),
    ("scale", lambda: ops.scale(t(A23), 2.0, 1.0),
     lambda: torch.tensor(A23) * 2.0 + 1.0, 1e-6),
    ("scale_after",
     lambda: ops.scale(t(A23), 2.0, 1.0, bias_after_scale=False),
     lambda: (torch.tensor(A23) + 1.0) * 2.0, 1e-6),
    ("crop", lambda: ops.crop(t(A46), shape=[2, 3], offsets=[1, 2]),
     lambda: torch.tensor(A46)[1:3, 2:5], 0),
    ("multiplex",
     lambda: ops.multiplex([t(A23), t(A23 * 2)],
                           t(np.array([[0], [1]], np.int32))),
     lambda: torch.stack([torch.tensor(A23)[0],
                          torch.tensor(A23 * 2)[1]]), 1e-6),
    ("is_empty", lambda: ops.is_empty(t(np.zeros((0, 3), np.float32))),
     lambda: np.array(True), 0),
    ("shard_index",
     lambda: ops.shard_index(t(np.array([[1], [6], [11]], np.int64)),
                             index_num=12, nshards=2, shard_id=0),
     lambda: np.array([[1], [-1], [-1]], np.int64), 0),
    ("einsum_op", lambda: ops.einsum("ij,jk->ik", t(A23), t(B34)),
     lambda: np.einsum("ij,jk->ik", A23, B34), 1e-5),
    ("view", lambda: ops.view(t(A46), [2, 12]),
     lambda: torch.tensor(A46).view(2, 12), 0),
    ("as_complex", lambda: ops.as_complex(t(A46.reshape(4, 3, 2))),
     lambda: torch.view_as_complex(torch.tensor(A46.reshape(4, 3, 2))), 0),
    ("as_real",
     lambda: ops.as_real(t((A23 + 1j * A23).astype(np.complex64))),
     lambda: torch.view_as_real(torch.tensor(
         (A23 + 1j * A23).astype(np.complex64))), 0),
    ("complex", lambda: ops.complex(t(A23), t(A23 * 2)),
     lambda: torch.complex(torch.tensor(A23), torch.tensor(A23 * 2)), 0),
    ("atleast_1d", lambda: ops.atleast_1d(t(np.float32(3.0))),
     lambda: torch.atleast_1d(torch.tensor(3.0)), 0),
    ("atleast_3d", lambda: ops.atleast_3d(t(A23)),
     lambda: torch.atleast_3d(torch.tensor(A23)), 0),
    ("unfold_im2col", lambda: ops.unfold_im2col(t(IMG), 3, 1, 1, 1),
     lambda: TF.unfold(torch.tensor(IMG), 3, dilation=1, padding=1,
                       stride=1), 1e-5),
    ("tensor_unfold", lambda: ops.unfold(t(V6), 0, 3, 1),
     lambda: torch.tensor(V6).unfold(0, 3, 1), 0),
    ("gather_tree_like_scatter",  # scatter overwrite semantics
     lambda: ops.scatter(t(A46), t(np.array([1, 3])),
                         t(np.zeros((2, 6), np.float32))),
     lambda: torch.tensor(A46).index_copy(
         0, torch.tensor([1, 3]), torch.zeros(2, 6)), 0),
    ("scatter_nd",
     lambda: ops.scatter_nd(t(np.array([[1], [3]], np.int64)),
                            t(np.ones((2, 6), np.float32)), [4, 6]),
     lambda: torch.zeros(4, 6).index_add(
         0, torch.tensor([1, 3]), torch.ones(2, 6)), 0),
    ("scatter_nd_add",
     lambda: ops.scatter_nd_add(t(A46), t(np.array([[1], [1]], np.int64)),
                                t(np.ones((2, 6), np.float32))),
     lambda: torch.tensor(A46).index_add(
         0, torch.tensor([1, 1]), torch.ones(2, 6)), 1e-6),
]


@pytest.mark.parametrize("name,ours,ref,rtol",
                         LONGTAIL_CASES + TAIL_CASES,
                         ids=[c[0] for c in LONGTAIL_CASES + TAIL_CASES])
def test_matches_oracle(name, ours, ref, rtol):
    got = npy(ours())
    want = ref()
    want = want.numpy() if hasattr(want, "numpy") else np.asarray(want)
    if rtol == 0:
        np.testing.assert_array_equal(got, np.asarray(want))
    else:
        np.testing.assert_allclose(got, np.asarray(want), rtol=rtol,
                                   atol=rtol)


# ---------------------------------------------------------------------
# cases that need structure beyond allclose
# ---------------------------------------------------------------------
def test_eigh_eigvalsh():
    S = (A46[:4, :4] + A46[:4, :4].T).astype(np.float32)
    w, v = ops.eigh(t(S))
    wr = np.linalg.eigvalsh(S)
    np.testing.assert_allclose(npy(w), wr, atol=1e-4)
    np.testing.assert_allclose(npy(ops.eigvalsh(t(S))), wr, atol=1e-4)
    # eigenvector property: S v = w v
    np.testing.assert_allclose(S @ npy(v), npy(v) * npy(w)[None, :],
                               atol=1e-3)


def test_eig_eigvals():
    M = A46[:4, :4]
    w = npy(ops.eigvals(t(M)))
    wr = np.linalg.eigvals(M)
    np.testing.assert_allclose(sorted(w.real), sorted(wr.real), atol=1e-4)
    w2, v2 = ops.eig(t(M))
    np.testing.assert_allclose(sorted(npy(w2).real), sorted(wr.real),
                               atol=1e-4)


def test_lstsq():
    a = A46[:4, :3]
    b = A46[:4, :2].copy()
    sol = ops.lstsq(t(a), t(b))
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(npy(sol[0]), ref, atol=1e-4)


def test_interpolate_upsample_match_torch():
    for mode, tm in (("nearest", "nearest"), ("bilinear", "bilinear")):
        got = npy(F.interpolate(t(IMG), size=[16, 16], mode=mode,
                                align_corners=False if mode != "nearest"
                                else None))
        want = TF.interpolate(torch.tensor(IMG), size=(16, 16), mode=tm,
                              align_corners=(False if mode != "nearest"
                                             else None)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)
    got = npy(ops.upsample(t(IMG), scale_factor=2, mode="nearest"))
    want = TF.interpolate(torch.tensor(IMG), scale_factor=2,
                          mode="nearest").numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_grid_sample_affine_grid():
    theta = np.tile(np.array([[[1., 0., 0.], [0., 1., 0.]]], np.float32),
                    (2, 1, 1))
    grid = F.affine_grid(t(theta), [2, 4, 8, 8], align_corners=False)
    gref = TF.affine_grid(torch.tensor(theta), [2, 4, 8, 8],
                          align_corners=False)
    np.testing.assert_allclose(npy(grid), gref.numpy(), atol=1e-5)
    out = F.grid_sample(t(IMG), grid, align_corners=False)
    oref = TF.grid_sample(torch.tensor(IMG), gref, align_corners=False)
    np.testing.assert_allclose(npy(out), oref.numpy(), atol=1e-4)


def test_dropout_family_identity_and_structure():
    # p=0 -> identity for all dropout variants
    np.testing.assert_array_equal(npy(F.alpha_dropout(t(A23), 0.0)), A23)
    np.testing.assert_array_equal(npy(F.dropout2d(t(IMG), 0.0)), IMG)
    # dropout2d zeroes whole channels
    out = npy(F.dropout2d(t(np.ones_like(IMG)), 0.5, training=True))
    per_chan = out.reshape(2, 4, -1)
    is_zero = (per_chan == 0).all(-1)
    is_kept = (per_chan == 2.0).all(-1)
    assert np.all(is_zero | is_kept)


def test_gumbel_softmax():
    logits = t(rng.standard_normal((4, 5)).astype(np.float32))
    soft = npy(F.gumbel_softmax(logits, temperature=1.0))
    np.testing.assert_allclose(soft.sum(-1), np.ones(4), atol=1e-5)
    hard = npy(F.gumbel_softmax(logits, temperature=1.0, hard=True))
    assert np.all(np.isclose(hard, 0.0, atol=1e-6)
                  | np.isclose(hard, 1.0, atol=1e-6))
    np.testing.assert_allclose(hard.sum(-1), np.ones(4), atol=1e-5)


def test_unique_op_full():
    x = np.array([3., 1., 2., 1., 3.], np.float32)
    out = ops.unique(t(x), return_index=True, return_inverse=True,
                     return_counts=True)
    ur, ui, uinv, uc = [npy(o) for o in out]
    ref = np.unique(x, return_index=True, return_inverse=True,
                    return_counts=True)
    np.testing.assert_array_equal(ur, ref[0])
    np.testing.assert_array_equal(uinv.reshape(-1), ref[2])
    np.testing.assert_array_equal(uc, ref[3])


def test_index_put():
    x = A46.copy()
    idx = (np.array([0, 2]), np.array([1, 3]))
    got = npy(ops.index_put(t(x), (t(idx[0]), t(idx[1])),
                            t(np.array([9., 8.], np.float32))))
    want = x.copy()
    want[idx] = [9., 8.]
    np.testing.assert_array_equal(got, want)


def test_temporal_shift():
    # ref semantics (phi temporal_shift_kernel): batch-major [B*T, C, H, W];
    # first fold of channels pulls from t+1, second fold from t-1, rest
    # untouched
    x = rng.standard_normal((4, 4, 2, 2)).astype(np.float32)  # B=2,T=2
    got = npy(ops.temporal_shift(t(x), seg_num=2, shift_ratio=0.25))
    xt = x.reshape(2, 2, 4, 2, 2)                 # [B, T, C, H, W]
    want = np.zeros_like(xt)
    want[:, :-1, :1] = xt[:, 1:, :1]              # from the future
    want[:, 1:, 1:2] = xt[:, :-1, 1:2]            # from the past
    want[:, :, 2:] = xt[:, :, 2:]
    np.testing.assert_allclose(got, want.reshape(4, 4, 2, 2), atol=1e-6)


def test_nms_oracle():
    from paddle_tpu.vision.ops import nms
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = npy(nms(t(boxes), iou_threshold=0.5, scores=t(scores)))
    kept = set(np.asarray(keep).reshape(-1).tolist())
    assert 0 in kept and 2 in kept and 1 not in kept


def test_fused_functional_identity_paths():
    # dropout=0 renderings against their compositional definitions
    x, r = A23, A23 * 0.5
    bias = np.float32(0.1) * np.ones(3, np.float32)
    from paddle_tpu.incubate.nn.functional import fused_dropout_add
    got = npy(fused_dropout_add(t(x), t(r), p=0.0))
    np.testing.assert_allclose(got, x + r, atol=1e-6)
    from paddle_tpu.incubate.nn.functional import (
        fused_bias_dropout_residual_layer_norm, fused_linear_activation)
    got = npy(fused_bias_dropout_residual_layer_norm(
        t(x), t(r), bias=t(bias), dropout_rate=0.0))
    ref = TF.layer_norm(torch.tensor(x + bias + r), (3,)).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)
    got = npy(fused_linear_activation(t(A23), t(B34),
                                      activation="gelu"))
    # jax.nn.gelu defaults to the tanh approximation
    ref = TF.gelu(torch.tensor(A23) @ torch.tensor(B34),
                  approximate="tanh").numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


# ---------------------------------------------------------------------
# sparse conv3d / subm / pool / attention (VERDICT r2 missing #3 tail)
# ---------------------------------------------------------------------
class TestSparseNN:
    def _coo_input(self):
        import paddle_tpu.sparse as sp
        dense = np.zeros((1, 4, 4, 4, 2), np.float32)
        sites = [(0, 0, 1, 1), (0, 2, 2, 3), (0, 3, 0, 2)]
        for s in sites:
            dense[s] = rng.standard_normal(2)
        idx = np.array(sites).T
        vals = np.stack([dense[s] for s in sites])
        return sp.sparse_coo_tensor(idx, vals, shape=dense.shape), dense

    def test_conv3d_matches_dense_at_active_sites(self):
        import jax
        import paddle_tpu.sparse as sp
        x, dense = self._coo_input()
        w = rng.standard_normal((3, 3, 3, 2, 3)).astype(np.float32)
        out = sp.nn.functional.conv3d(x, t(w), padding=1)
        dn = jax.lax.conv_dimension_numbers(
            dense.shape, w.shape, ("NDHWC", "DHWIO", "NDHWC"))
        ref = np.asarray(jax.lax.conv_general_dilated(
            dense, w, (1, 1, 1), [(1, 1)] * 3, dimension_numbers=dn))
        od = npy(out.to_dense())
        mask = np.any(od != 0, -1)
        np.testing.assert_allclose(od[mask], ref[mask], atol=1e-5)

    def test_subm_conv3d_preserves_site_pattern(self):
        import paddle_tpu.sparse as sp
        x, dense = self._coo_input()
        w = rng.standard_normal((3, 3, 3, 2, 3)).astype(np.float32)
        out = sp.nn.functional.subm_conv3d(x, t(w), padding=1)
        out_sites = set(map(tuple, np.argwhere(
            np.any(npy(out.to_dense()) != 0, -1))))
        in_sites = set(map(tuple, np.argwhere(np.any(dense != 0, -1))))
        assert out_sites <= in_sites

    def test_max_pool3d_active_window_semantics(self):
        import paddle_tpu.sparse as sp
        x, dense = self._coo_input()
        out = sp.nn.functional.max_pool3d(x, 2, 2)
        od = npy(out.to_dense())
        assert list(od.shape) == [1, 2, 2, 2, 2]
        # windows with no active input site stay inactive
        act = np.any(dense != 0, -1)[0]
        win_act = act.reshape(2, 2, 2, 2, 2, 2).transpose(
            0, 2, 4, 1, 3, 5).reshape(2, 2, 2, -1).any(-1)
        np.testing.assert_array_equal(np.any(od[0] != 0, -1), win_act)

    def test_sparse_attention_matches_masked_dense(self):
        import paddle_tpu.sparse as sp
        b, h, s, d = 1, 2, 4, 8
        q, k, v = [rng.standard_normal((b, h, s, d)).astype(np.float32)
                   for _ in range(3)]
        cols, crow = [], [0]
        for r in range(s):
            cr = [max(0, r - 1), r] if r > 0 else [0]
            cols += cr
            crow.append(len(cols))
        nnz = len(cols)
        crows_b = np.tile(np.array(crow), (b * h, 1))
        cols_b = np.tile(np.array(cols), (b * h, 1))
        sm = sp.sparse_csr_tensor(
            crows_b.reshape(-1), cols_b.reshape(-1),
            np.ones((b * h * nnz,), np.float32), shape=(b * h, s, s))
        out = npy(sp.nn.functional.attention(t(q), t(k), t(v), sm))
        allow = np.zeros((s, s), bool)
        for r in range(s):
            allow[r, max(0, r - 1)] = True
            allow[r, r] = True
        sc = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        sc = np.where(allow, sc, -1e30)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)


def test_isreal_histogramdd_choleskyinv_geqrf():
    z = (A23 + 1j * np.where(A23 > 0, A23, 0)).astype(np.complex64)
    np.testing.assert_array_equal(npy(ops.isreal(t(z))),
                                  torch.isreal(torch.tensor(z)).numpy())
    pts = rng.random((20, 2)).astype(np.float32)
    h = ops.histogramdd(t(pts), bins=4)
    href = np.histogramdd(pts, bins=4)
    np.testing.assert_array_equal(npy(h[0]), href[0])
    np.testing.assert_allclose(npy(h[1]), href[1][0], atol=1e-6)
    # cholesky_inverse: A^-1 from its factor
    S = (A46[:3, :3] @ A46[:3, :3].T + 3 * np.eye(3)).astype(np.float32)
    L = np.linalg.cholesky(S)
    np.testing.assert_allclose(npy(ops.cholesky_inverse(t(L))),
                               np.linalg.inv(S), atol=1e-3)
    # geqrf/orgqr: Q orthonormal and QR == A
    Amn = A46[:4, :3].copy()
    a, tau = ops.geqrf(t(Amn))
    Q = npy(ops.orgqr(a, tau))
    np.testing.assert_allclose(Q.T @ Q, np.eye(3), atol=1e-4)
    R = np.triu(npy(a))[:3, :]
    np.testing.assert_allclose(Q @ R, Amn, atol=1e-4)


def test_sequence_mask():
    from paddle_tpu.nn.functional import sequence_mask
    got = npy(sequence_mask(t(np.array([1, 3, 2], np.int64)), maxlen=4))
    want = np.array([[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
    np.testing.assert_array_equal(got, want)


def test_roi_align_linear_ramp():
    # bilinear interpolation reproduces a linear ramp EXACTLY at any
    # sample point, so 1x1 roi_align of a ramp == ramp value at the box
    # center — an analytic oracle with no torchvision dependency
    from paddle_tpu.vision.ops import roi_align
    ii, jj = np.meshgrid(np.arange(8.), np.arange(8.), indexing="ij")
    ramp = (2.0 * ii + 3.0 * jj + 1.0).astype(np.float32)
    x = np.stack([ramp, -ramp])[None]                  # [1, 2, 8, 8]
    boxes = np.array([[1.0, 1.0, 5.0, 7.0]], np.float32)
    out = npy(roi_align(t(x), t(boxes),
                        boxes_num=t(np.array([1], np.int32)),
                        output_size=1, spatial_scale=1.0,
                        sampling_ratio=2, aligned=True))
    cy, cx = (1.0 + 7.0) / 2 - 0.5, (1.0 + 5.0) / 2 - 0.5
    want = 2.0 * cy + 3.0 * cx + 1.0
    np.testing.assert_allclose(out.reshape(2), [want, -want], atol=1e-3)


def test_box_coder_roundtrip():
    # decode(encode(gt, prior), prior) == gt (self-consistency oracle,
    # ref: phi box_coder encode/decode_center_size)
    from paddle_tpu.vision.ops import box_coder
    prior = np.array([[0., 0., 10., 10.], [5., 5., 20., 25.]], np.float32)
    var = np.ones_like(prior)
    gt = np.array([[1., 1., 8., 9.], [6., 7., 18., 22.]], np.float32)
    enc = box_coder(t(prior), t(var), t(gt),
                    code_type="encode_center_size")
    dec = npy(box_coder(t(prior), t(var), enc,
                        code_type="decode_center_size"))
    np.testing.assert_allclose(dec.reshape(2, 4), gt, atol=1e-3)


def test_npair_loss_formula():
    # ref formula (phi npair_loss): CE of anchor-positive similarities
    # against the diagonal + l2 regularization of both embeddings
    a = rng.standard_normal((4, 8)).astype(np.float32)
    p = rng.standard_normal((4, 8)).astype(np.float32)
    got = float(npy(ops.npair_loss(t(a), t(p), t(np.arange(4)),
                                   l2_reg=0.002)))
    sim = torch.tensor(a) @ torch.tensor(p).T
    ce = TF.cross_entropy(sim, torch.arange(4))
    l2 = 0.002 * (np.sum(a * a) + np.sum(p * p)) / (2.0 * 4)
    np.testing.assert_allclose(got, float(ce) + l2, rtol=1e-5)


def test_sparse_conv_trainable_and_subm_default_padding():
    """code-review r3: subm conv must work with ANY user padding (output
    shape == input shape, ref ResetSubmKernelSizeAndStrides) and sparse
    conv layers must be trainable (grads reach the weights)."""
    import paddle_tpu.sparse as sp
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    for s in [(0, 0, 1, 1), (0, 2, 2, 3)]:
        dense[s] = rng.standard_normal(2)
    idx = np.array([(0, 0, 1, 1), (0, 2, 2, 3)]).T
    vals = np.stack([dense[(0, 0, 1, 1)], dense[(0, 2, 2, 3)]])
    x = sp.sparse_coo_tensor(idx, vals, shape=dense.shape)
    conv = sp.nn.SubmConv3D(2, 3, 3)        # default padding=0
    out = conv(x)
    assert list(out.shape) == [1, 4, 4, 4, 3]
    conv2 = sp.nn.SubmConv3D(3, 2, 3)
    loss = (conv2(out).to_dense() ** 2).sum()
    loss.backward()
    assert conv.weight.grad is not None      # chained sparse layers train
    assert conv2.weight.grad is not None


def test_geqrf_batched():
    A = rng.standard_normal((2, 4, 3)).astype(np.float32)
    a, tau = ops.geqrf(t(A))
    assert list(a.shape) == [2, 4, 3] and list(tau.shape) == [2, 3]
    for b in range(2):
        Q = npy(ops.orgqr(t(npy(a)[b]), t(npy(tau)[b])))
        np.testing.assert_allclose(Q.T @ Q, np.eye(3), atol=1e-4)


class TestWeightOnlyQuant:
    """paddle.nn.quant parity (ref: nn/quant/quantized_linear.py:39).
    Oracle: explicit numpy per-channel absmax quantization."""

    def _w(self):
        return rng.standard_normal((32, 16)).astype(np.float32)  # [in,out]

    def test_weight_quantize_int8_roundtrip(self):
        from paddle_tpu.nn.quant import weight_quantize, weight_dequantize
        w = self._w()
        q, scale = weight_quantize(t(w), algo="weight_only_int8")
        assert list(q.shape) == [16, 32] and str(q.dtype).endswith("int8")
        assert list(scale.shape) == [16]
        np.testing.assert_allclose(npy(scale),
                                   np.abs(w).max(0) / 127.0, rtol=1e-6)
        wd = weight_dequantize(q, scale, out_dtype="float32")
        # dequantized weight within one quantization step per channel
        step = npy(scale)[None, :]
        assert np.all(np.abs(npy(wd) - w) <= step * 0.5 + 1e-6)

    def test_weight_quantize_int4_roundtrip(self):
        from paddle_tpu.nn.quant import weight_quantize, weight_dequantize
        w = self._w()
        q, scale = weight_quantize(t(w), algo="weight_only_int4")
        assert list(q.shape) == [16, 16]  # packed nibble pairs
        wd = npy(weight_dequantize(q, scale, algo="weight_only_int4",
                                   out_dtype="float32"))
        step = npy(scale)[None, :]
        assert np.all(np.abs(wd - w) <= step * 0.5 + 1e-5)

    def test_weight_only_linear_matches_dequant_matmul(self):
        from paddle_tpu.nn.quant import (weight_quantize,
                                         weight_only_linear)
        w = self._w()
        x = rng.standard_normal((4, 32)).astype(np.float32)
        b = rng.standard_normal(16).astype(np.float32)
        q, scale = weight_quantize(t(w))
        out = npy(weight_only_linear(t(x), q, bias=t(b),
                                     weight_scale=scale))
        wd = npy(q).astype(np.float32) * npy(scale)[:, None]
        ref = x @ wd.T + b
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_llm_int8_linear_outlier_decomposition(self):
        from paddle_tpu.nn.quant import weight_quantize, llm_int8_linear
        w = self._w()
        x = rng.standard_normal((4, 32)).astype(np.float32)
        x[:, 3] *= 50.0  # an outlier activation column
        q, scale = weight_quantize(t(w), algo="llm.int8")
        out = npy(llm_int8_linear(t(x), q, weight_scale=scale,
                                  threshold=6.0))
        wd = npy(q).astype(np.float32) * npy(scale)[:, None]
        ref = x @ wd.T
        # int8 path quantizes the non-outlier part: allow quant error
        np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.2)


def test_misc_yaml_batch2():
    np.testing.assert_allclose(float(npy(ops.mean_all(t(A46)))),
                               A46.mean(), rtol=1e-6)
    assert int(npy(ops.numel(t(A46)))) == 24
    np.testing.assert_array_equal(npy(ops.shape_op(t(A345))), [3, 4, 5])
    np.testing.assert_array_equal(npy(ops.fill(t(A23), 2.5)),
                                  np.full((2, 3), 2.5, np.float32))
    got = npy(ops.fill_diagonal_tensor(t(np.zeros((3, 4), np.float32)),
                                       t(np.ones(3, np.float32))))
    np.testing.assert_array_equal(got, np.eye(3, 4, dtype=np.float32))
    v = npy(ops.view_dtype(t(np.zeros(4, np.float32)), "int32"))
    assert v.dtype == np.int32 and v.shape == (4,)
    acc = float(npy(ops.accuracy_op(
        t(np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]], np.float32)),
        t(np.array([0, 1, 1], np.int64)))))
    np.testing.assert_allclose(acc, 2.0 / 3.0, rtol=1e-6)
    # AUC vs sklearn-equivalent rank computation
    score = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
    y = np.array([0, 0, 1, 1], np.float32)
    auc = float(npy(ops.auc_op(t(score), t(y))))
    np.testing.assert_allclose(auc, 0.75, rtol=1e-6)  # known value


def test_rnnt_loss_matches_numpy_dp():
    """RNN-T loss vs an independent numpy log-semiring DP (warprnnt
    parity, ref nn/functional/loss.py:1953), incl. per-sample lengths
    and gradient flow."""
    import paddle_tpu.nn.functional as F2

    def np_rnnt(logits, labels, T, U, blank=0):
        e = np.exp(logits - np.max(logits, -1, keepdims=True))
        lp = np.log(e.astype(np.float64) / e.sum(-1, keepdims=True))
        alpha = np.full((T, U + 1), -np.inf)
        alpha[0, 0] = 0.0
        for ti in range(T):
            for u in range(U + 1):
                if ti == 0 and u == 0:
                    continue
                cands = []
                if ti > 0:
                    cands.append(alpha[ti - 1, u] + lp[ti - 1, u, blank])
                if u > 0:
                    cands.append(alpha[ti, u - 1]
                                 + lp[ti, u - 1, labels[u - 1]])
                alpha[ti, u] = np.logaddexp.reduce(cands)
        return -(alpha[T - 1, U] + lp[T - 1, U, blank])

    rng2 = np.random.default_rng(23)
    B, T, U, V = 2, 5, 3, 6
    logits = rng2.standard_normal((B, T, U + 1, V)).astype(np.float32)
    labels = rng2.integers(1, V, (B, U)).astype(np.int32)
    Ts = np.array([5, 4], np.int32)
    Us = np.array([3, 2], np.int32)
    got = npy(ops.rnnt_loss_op(t(logits), t(labels), t(Ts), t(Us)))
    ref = np.array([np_rnnt(logits[b], labels[b], Ts[b], Us[b])
                    for b in range(B)])
    np.testing.assert_allclose(got, ref, atol=1e-4)
    # reduction + gradient flow through the DP
    xt = pt.to_tensor(logits, stop_gradient=False)
    loss = F2.rnnt_loss(xt, t(labels), t(Ts), t(Us), reduction="mean")
    np.testing.assert_allclose(float(npy(loss)), ref.mean(), rtol=1e-5)
    loss.backward()
    assert xt.grad is not None
    g = np.asarray(xt.grad.numpy())
    assert np.all(np.isfinite(g)) and np.abs(g).sum() > 0
    with pytest.raises(NotImplementedError):
        F2.rnnt_loss(t(logits), t(labels), t(Ts), t(Us),
                     fastemit_lambda=0.001)


def test_fused_softmax_mask_family():
    from paddle_tpu.incubate.nn.functional import (
        fused_softmax_mask, fused_softmax_mask_upper_triangle)
    x = rng.standard_normal((2, 2, 4, 4)).astype(np.float32)
    m = np.where(rng.random((2, 1, 4, 4)) < 0.3, -10000.0, 0.0).astype(
        np.float32)
    got = npy(fused_softmax_mask(t(x), t(m)))
    ref = TF.softmax(torch.tensor(x) + torch.tensor(m), dim=-1).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)
    got = npy(fused_softmax_mask_upper_triangle(t(x)))
    mask = np.triu(np.ones((4, 4), bool), k=1)
    z = np.where(mask, -1e30, x)
    ref = TF.softmax(torch.tensor(z), dim=-1).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)
    # rows are normalized and causal (no mass above the diagonal)
    assert np.allclose(got.sum(-1), 1.0, atol=1e-5)
    assert np.all(got[..., mask] < 1e-6)


def test_max_pool3d_with_index_unpool3d():
    """3-D pool-with-index vs torch, and unpool3d round-trip."""
    x = rng.standard_normal((1, 2, 4, 6, 6)).astype(np.float32)
    out, idx = ops.max_pool3d_with_index(t(x), 2, 2)
    tout, tidx = TF.max_pool3d(torch.tensor(x), 2, 2, return_indices=True)
    np.testing.assert_allclose(npy(out), tout.numpy(), atol=1e-6)
    np.testing.assert_array_equal(npy(idx), tidx.numpy())
    up = ops.unpool3d(out, idx, 2, 2)
    tup = TF.max_unpool3d(tout, tidx, 2, 2)
    np.testing.assert_allclose(npy(up), tup.numpy(), atol=1e-6)


def test_shim_ops_batch3():
    got = npy(ops.assign_value([2, 2], "float32", [1.0, 2.0, 3.0, 4.0]))
    np.testing.assert_array_equal(got, [[1, 2], [3, 4]])
    # check_numerics: pass-through on finite, raises on NaN (eager)
    np.testing.assert_array_equal(npy(ops.check_numerics(t(A23))), A23)
    with pytest.raises(FloatingPointError):
        ops.check_numerics(t(np.array([1.0, np.nan], np.float32)))
    got = npy(ops.full_batch_size_like(t(A46), [7, 5], 2.5))
    assert got.shape == (4, 5) and np.all(got == 2.5)
    np.testing.assert_array_equal(
        npy(ops.index_select_strided(t(A46), t(np.array([2, 0])), 0)),
        A46[[2, 0]])
    np.testing.assert_array_equal(
        npy(ops.trans_layout(t(A345), [2, 0, 1])),
        A345.transpose(2, 0, 1))


class TestProposalOps:
    """RPN pipeline completion (ref generate_proposals /
    distribute_fpn_proposals kernels) — numpy reference oracles."""

    def test_generate_proposals_semantics(self):
        h = w = 4
        a = 2
        anchors = np.zeros((h, w, a, 4), np.float32)
        for i in range(h):
            for j in range(w):
                anchors[i, j, 0] = [j * 8, i * 8, j * 8 + 15, i * 8 + 15]
                anchors[i, j, 1] = [j * 8, i * 8, j * 8 + 31, i * 8 + 31]
        var = np.ones((h, w, a, 4), np.float32)
        scores = rng.random((1, a, h, w)).astype(np.float32)
        deltas = np.zeros((1, 4 * a, h, w), np.float32)  # identity decode
        img = np.array([[32.0, 32.0]], np.float32)
        rois, probs, nums = ops.generate_proposals(
            t(scores), t(deltas), t(img), t(anchors), t(var),
            pre_nms_top_n=32, post_nms_top_n=8, nms_thresh=0.7,
            min_size=4.0)
        n_live = int(npy(nums)[0])
        assert 1 <= n_live <= 8
        rois_np = npy(rois)[0][:n_live]
        probs_np = npy(probs)[0][:n_live]
        # scores come back sorted descending; every roi inside the image
        assert np.all(np.diff(probs_np) <= 1e-6)
        assert np.all(rois_np >= 0) and np.all(rois_np <= 32.0)
        # with zero deltas, every roi is exactly a clipped anchor
        clipped = np.clip(anchors.reshape(-1, 4), 0, 32.0)
        for rrow in rois_np:
            assert np.any(np.all(np.isclose(clipped, rrow, atol=1e-4),
                                 axis=1))
        # nms actually suppressed: overlapping shifted boxes collapse
        def iou(b1, b2):
            lt = np.maximum(b1[:2], b2[:2])
            rb = np.minimum(b1[2:], b2[2:])
            inter = np.prod(np.clip(rb - lt, 0, None))
            a1 = np.prod(b1[2:] - b1[:2])
            a2 = np.prod(b2[2:] - b2[:2])
            return inter / (a1 + a2 - inter)
        for i in range(n_live):
            for j in range(i + 1, n_live):
                assert iou(rois_np[i], rois_np[j]) <= 0.7 + 1e-5

    def test_distribute_fpn_proposals(self):
        rois = np.array([
            [0, 0, 20, 20],      # sqrt(400)=20 -> low level
            [0, 0, 200, 200],    # 200 -> refer level
            [0, 0, 800, 800],    # 800 -> high level
            [0, 0, 210, 190],    # ~refer level
        ], np.float32)
        outs = ops.distribute_fpn_proposals(
            t(rois), min_level=2, max_level=5, refer_level=4,
            refer_scale=224)
        *levels, nums, restore = outs
        nums = npy(nums)
        assert nums.sum() == 4
        lv = {2 + i: npy(l) for i, l in enumerate(levels)}
        # 20-scale roi sits at the bottom level, 800 at the top
        assert nums[0] >= 1 and np.allclose(lv[2][0], rois[0])
        assert np.allclose(lv[5][0], rois[2])
        # restore index is a permutation of the concatenated order
        ri = npy(restore).reshape(-1)
        assert sorted(ri.tolist()) == [0, 1, 2, 3]
        concat = np.concatenate([lv[L][:nums[L - 2]] for L in (2, 3, 4, 5)])
        np.testing.assert_allclose(concat[ri], rois, atol=1e-5)

    def test_distribute_with_rois_num_padding(self):
        rois = np.zeros((6, 4), np.float32)
        rois[:3] = [[0, 0, 30, 30], [0, 0, 300, 300], [0, 0, 700, 700]]
        outs = ops.distribute_fpn_proposals(
            t(rois), 2, 5, 4, 224,
            rois_num=t(np.array([3], np.int32)))
        *levels, nums, restore = outs
        assert npy(nums).sum() == 3  # padding rows assigned to no level

    def test_generate_proposals_conformance_details(self):
        """Reference details: min_size floors at 1.0, exp clip at
        log(1000/16), eta<1 rejected, pre_nms_top_n<=0 = all anchors."""
        h = w = 2
        a = 1
        anchors = np.zeros((h, w, a, 4), np.float32)
        for i in range(h):
            for j in range(w):
                anchors[i, j, 0] = [j * 8, i * 8, j * 8 + 7, i * 8 + 7]
        var = np.ones((h, w, a, 4), np.float32)
        scores = rng.random((1, a, h, w)).astype(np.float32)
        deltas = np.zeros((1, 4, h, w), np.float32)
        deltas[0, 2:, :, :] = 6.0   # huge dw/dh: must clip at log(1000/16)
        img = np.array([[4000.0, 4000.0]], np.float32)
        rois, probs, nums = ops.generate_proposals(
            t(scores), t(deltas), t(img), t(anchors), t(var),
            pre_nms_top_n=-1, post_nms_top_n=4, nms_thresh=0.99,
            min_size=0.0)
        rois_np = npy(rois)[0][: int(npy(nums)[0])]
        wmax = (rois_np[:, 2] - rois_np[:, 0]).max()
        assert wmax <= 8 * 1000.0 / 16.0 + 1e-3   # clipped decode
        with pytest.raises(ValueError, match="adaptive"):
            ops.generate_proposals(t(scores), t(deltas), t(img),
                                   t(anchors), t(var), eta=0.9)
