"""Machine check of the op-parity audit (VERDICT r3 missing #2): every
forward op in the reference's five PHI YAML files must map to a registry
op, a resolvable API path, or a documented exclusion — and the doc
generator must agree with the live classification."""
import paddle_tpu  # noqa: F401  (populate the registry)
from paddle_tpu.ops.parity import (ALIASES, EXCLUDED, YAML_OPS, classify,
                                   resolve_api)


def test_every_yaml_op_is_mapped():
    table, unmapped = classify()
    assert len(unmapped) == 0, f"unmapped YAML ops: {unmapped}"
    assert len(table) == len({n for v in YAML_OPS.values() for n in v})


def test_alias_paths_resolve():
    dead = sorted(p for p in set(ALIASES.values()) if not resolve_api(p))
    assert not dead, f"alias paths that no longer import: {dead}"


def test_no_overlapping_or_stale_entries():
    from paddle_tpu.ops.registry import OPS
    # an alias or exclusion for a name the registry now provides is
    # stale bookkeeping — the registry entry must win and the row go
    stale_alias = sorted(n for n in ALIASES if n in OPS)
    stale_excl = sorted(n for n in EXCLUDED if n in OPS)
    both = sorted(set(ALIASES) & set(EXCLUDED))
    assert not stale_alias, f"aliases shadowed by registry: {stale_alias}"
    assert not stale_excl, f"exclusions shadowed by registry: {stale_excl}"
    assert not both, f"names in both ALIASES and EXCLUDED: {both}"


def test_snapshot_covers_all_five_yamls():
    assert set(YAML_OPS) == {"ops.yaml", "legacy_ops.yaml",
                             "static_ops.yaml", "fused_ops.yaml",
                             "sparse_ops.yaml"}
    assert sum(len(v) for v in YAML_OPS.values()) >= 560


def test_doc_is_in_sync():
    import os
    md = os.path.join(os.path.dirname(__file__), "..", "OPS_PARITY.md")
    assert os.path.exists(md), "run tools/gen_ops_parity.py"
    text = open(md).read()
    table, unmapped = classify()
    assert "UNMAPPED" not in text
    assert f"**{len(table)} YAML forward ops**" in text
