"""Torch-oracle conformance for the op long tail (C32).

Each case drives a registered op against torch's CPU implementation —
the same comparison style as the reference's OpTest-vs-framework checks
(test/legacy_test/op_test.py) but vectorized over a case table instead
of per-op classes.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
from paddle_tpu import ops
import paddle_tpu.nn.functional as F

rng = np.random.default_rng(0)


def t(x):
    return pt.to_tensor(np.asarray(x))


def npy(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


A23 = rng.standard_normal((2, 3)).astype(np.float32)
A345 = rng.standard_normal((3, 4, 5)).astype(np.float32)
V8 = rng.standard_normal(8).astype(np.float32)
POS33 = (rng.random((3, 3)) + 0.5).astype(np.float32)
SPD = (lambda m: (m @ m.T + 3 * np.eye(4)).astype(np.float32))(
    rng.standard_normal((4, 4)))

# (name, our_fn, torch_fn, rtol)
CASES = [
    ("lerp", lambda: ops.lerp(t(A23), t(A23 * 2), 0.3),
     lambda: torch.lerp(torch.tensor(A23), torch.tensor(A23 * 2), 0.3),
     1e-5),
    ("ldexp", lambda: ops.ldexp(t(A23), t(np.array([1, 2, 3], np.int32))),
     lambda: torch.ldexp(torch.tensor(A23), torch.tensor([1, 2, 3])),
     1e-5),
    ("histogram", lambda: ops.histogram(t(V8), bins=4, min=-2, max=2),
     lambda: torch.histc(torch.tensor(V8), bins=4, min=-2, max=2), 0),
    ("bincount",
     lambda: ops.bincount(t(np.array([0, 1, 1, 3], np.int32)), minlength=5),
     lambda: torch.bincount(torch.tensor([0, 1, 1, 3]), minlength=5), 0),
    ("kthvalue", lambda: ops.kthvalue(t(A345), 2, axis=-1)[0],
     lambda: torch.kthvalue(torch.tensor(A345), 2, dim=-1)[0], 1e-6),
    ("mode", lambda: ops.mode(t(np.array([[1., 2., 2.], [3., 3., 1.]])))[0],
     lambda: torch.mode(torch.tensor([[1., 2., 2.], [3., 3., 1.]]))[0], 0),
    ("quantile", lambda: ops.quantile(t(A345), 0.25, axis=-1),
     lambda: torch.quantile(torch.tensor(A345), 0.25, dim=-1), 1e-5),
    ("nanquantile",
     lambda: ops.nanquantile(t(np.array([1., np.nan, 3., 4.])), 0.5),
     lambda: torch.nanquantile(torch.tensor([1., np.nan, 3., 4.]), 0.5),
     1e-6),
    # paddle's nanmedian averages the two middles (np semantics), torch
    # takes the lower one — numpy is the right oracle
    ("nanmedian", lambda: ops.nanmedian(t(np.array([1., np.nan, 3., 7.]))),
     lambda: np.nanmedian(np.array([1., np.nan, 3., 7.])), 1e-6),
    ("polygamma", lambda: ops.polygamma(t(POS33), 1),
     lambda: torch.polygamma(1, torch.tensor(POS33)), 1e-4),
    ("searchsorted",
     lambda: ops.searchsorted(t(np.sort(V8)), t(A23)),
     lambda: torch.searchsorted(torch.tensor(np.sort(V8)),
                                torch.tensor(A23)), 0),
    ("put_along_axis",
     lambda: ops.put_along_axis(t(A23), t(np.array([[0], [1]])),
                                9.0, 1),
     lambda: torch.tensor(A23).scatter(
         1, torch.tensor([[0], [1]]), 9.0), 0),
    ("take_along_axis",
     lambda: ops.take_along_axis(t(A23), t(np.array([[0, 1], [1, 2]])), 1),
     lambda: torch.gather(torch.tensor(A23),
                          1, torch.tensor([[0, 1], [1, 2]])), 0),
    ("index_select",
     lambda: ops.index_select(t(A345), t(np.array([0, 2], np.int32)), 1),
     lambda: torch.index_select(torch.tensor(A345), 1,
                                torch.tensor([0, 2])), 0),
    ("index_add",
     lambda: ops.index_add(t(A23), t(np.array([0, 1], np.int32)), 0,
                           t(np.ones((2, 3), np.float32))),
     lambda: torch.tensor(A23).index_add(
         0, torch.tensor([0, 1]), torch.ones(2, 3)), 1e-6),
    ("masked_fill",
     lambda: ops.masked_fill(t(A23), t(A23 > 0), -1.0),
     lambda: torch.tensor(A23).masked_fill(torch.tensor(A23 > 0), -1.0),
     0),
    ("masked_select",
     lambda: ops.masked_select(t(A23), t(A23 > 0)),
     lambda: torch.masked_select(torch.tensor(A23), torch.tensor(A23 > 0)),
     0),
    ("cholesky_solve",
     lambda: ops.cholesky_solve(t(rng.standard_normal((4, 2))
                                  .astype(np.float32)),
                                t(np.linalg.cholesky(SPD)), upper=False),
     None, None),  # checked against numpy below
    ("matrix_power", lambda: ops.matrix_power(t(SPD), 3),
     lambda: torch.linalg.matrix_power(torch.tensor(SPD), 3), 1e-3),
    ("svdvals", lambda: ops.svdvals(t(A23)),
     lambda: torch.linalg.svdvals(torch.tensor(A23)), 1e-4),
    ("pinv", lambda: ops.pinv(t(A23)),
     lambda: torch.linalg.pinv(torch.tensor(A23)), 1e-4),
    ("householder_product",
     lambda: ops.householder_product(
         t(rng.standard_normal((4, 3)).astype(np.float32)),
         t(rng.standard_normal((3,)).astype(np.float32))),
     None, None),  # orthogonality checked below
    ("dist", lambda: ops.dist(t(A23), t(A23 * 0.5), 2.0),
     lambda: torch.dist(torch.tensor(A23), torch.tensor(A23 * 0.5), 2),
     1e-5),
    ("cov", lambda: ops.cov(t(A23)),
     lambda: torch.cov(torch.tensor(A23)), 1e-4),
    ("corrcoef", lambda: ops.corrcoef(t(A23)),
     lambda: torch.corrcoef(torch.tensor(A23)), 1e-4),
    ("glu", lambda: ops.glu(t(rng.standard_normal((2, 6))
                              .astype(np.float32))),
     None, None),
    ("prelu", lambda: ops.prelu(t(A23), t(np.array([0.25], np.float32))),
     lambda: TF.prelu(torch.tensor(A23), torch.tensor([0.25])), 1e-6),
    ("cosine_similarity",
     lambda: F.cosine_similarity(t(A23), t(A23 * 2 + 1), axis=1),
     lambda: TF.cosine_similarity(torch.tensor(A23),
                                  torch.tensor(A23 * 2 + 1), dim=1),
     1e-5),
    ("triplet_margin_loss",
     lambda: ops.triplet_margin_loss(t(A23), t(A23 + 1), t(A23 - 2)),
     lambda: TF.triplet_margin_loss(torch.tensor(A23),
                                    torch.tensor(A23 + 1),
                                    torch.tensor(A23 - 2)), 1e-5),
    ("hinge_embedding_loss",
     lambda: ops.hinge_embedding_loss(
         t(A23), t(np.sign(A23) + (A23 == 0))),
     lambda: TF.hinge_embedding_loss(
         torch.tensor(A23),
         torch.tensor(np.sign(A23) + (A23 == 0))), 1e-5),
    ("cosine_embedding_loss",
     lambda: ops.cosine_embedding_loss(
         t(A23), t(A23 * 0.5 + 0.1), t(np.array([1., -1.], np.float32))),
     lambda: TF.cosine_embedding_loss(
         torch.tensor(A23), torch.tensor(A23 * 0.5 + 0.1),
         torch.tensor([1., -1.])), 1e-5),
    ("margin_ranking_loss",
     lambda: ops.margin_ranking_loss(
         t(V8), t(V8[::-1].copy()), t(np.sign(V8))),
     lambda: TF.margin_ranking_loss(
         torch.tensor(V8), torch.tensor(V8[::-1].copy()),
         torch.tensor(np.sign(V8))), 1e-5),
    ("sigmoid_cross_entropy_with_logits",
     lambda: ops.sigmoid_cross_entropy_with_logits(
         t(A23), t((A23 > 0).astype(np.float32))),
     lambda: TF.binary_cross_entropy_with_logits(
         torch.tensor(A23), torch.tensor((A23 > 0).astype(np.float32)),
         reduction="none"), 1e-5),
    ("log_loss",
     lambda: ops.log_loss(t(np.clip(POS33[0] / 2, 0.05, 0.95)),
                          t(np.array([1., 0., 1.], np.float32))),
     None, None),
    ("isclose", lambda: ops.isclose(t(A23), t(A23 + 1e-9)),
     lambda: torch.isclose(torch.tensor(A23), torch.tensor(A23 + 1e-9)),
     0),
    ("equal_all", lambda: ops.equal_all(t(A23), t(A23.copy())),
     lambda: torch.equal(torch.tensor(A23), torch.tensor(A23.copy())), 0),
    ("allclose", lambda: ops.allclose(t(A23), t(A23 + 1e-9)),
     lambda: torch.allclose(torch.tensor(A23), torch.tensor(A23 + 1e-9)),
     0),
    ("diag_embed", lambda: ops.diag_embed(t(A23)),
     lambda: torch.diag_embed(torch.tensor(A23)), 0),
    ("diagflat", lambda: ops.diagflat(t(V8)),
     lambda: torch.diagflat(torch.tensor(V8)), 0),
    ("trapezoid", lambda: ops.trapezoid(t(V8), dx=0.5),
     lambda: torch.trapezoid(torch.tensor(V8), dx=0.5), 1e-5),
    ("cumulative_trapezoid",
     lambda: ops.cumulative_trapezoid(t(V8), dx=0.5),
     lambda: torch.cumulative_trapezoid(torch.tensor(V8), dx=0.5), 1e-5),
    ("unfold",
     lambda: ops.unfold(t(V8), 0, 3, 2),
     lambda: torch.tensor(V8).unfold(0, 3, 2), 0),
    ("repeat_interleave",
     lambda: ops.repeat_interleave(t(A23), 2, axis=1),
     lambda: torch.repeat_interleave(torch.tensor(A23), 2, dim=1), 0),
    ("nonzero", lambda: ops.nonzero(t(np.array([0., 1., 0., 2.]))),
     lambda: torch.nonzero(torch.tensor([0., 1., 0., 2.])), 0),
    ("increment", lambda: ops.increment(t(np.array([1.0], np.float32))),
     lambda: torch.tensor([2.0]), 0),
    ("gather_nd",
     lambda: ops.gather_nd(t(A345), t(np.array([[0, 1], [2, 3]],
                                               np.int32))),
     lambda: torch.tensor(A345)[[0, 2], [1, 3]], 0),
    ("strided_slice",
     lambda: ops.strided_slice(t(A345), [1], [0], [4], [2]),
     lambda: torch.tensor(A345)[:, 0:4:2], 0),
    ("expand_as", lambda: ops.expand_as(t(V8[:1]), t(V8)),
     lambda: torch.tensor(V8[:1]).expand_as(torch.tensor(V8)), 0),
    ("angle", lambda: ops.angle(t(A23)),
     lambda: torch.angle(torch.tensor(A23)), 1e-6),
    ("conj", lambda: ops.conj(t(A23)),
     lambda: torch.conj(torch.tensor(A23)), 0),
]


@pytest.mark.parametrize("name,ours,ref,rtol",
                         CASES, ids=[c[0] for c in CASES])
def test_matches_torch(name, ours, ref, rtol):
    got = ours()
    if ref is None:
        pytest.skip("custom check below")
    want = ref()
    g = npy(got)
    w = want.numpy() if hasattr(want, "numpy") else np.asarray(want)
    if rtol == 0:
        np.testing.assert_array_equal(np.asarray(g, w.dtype), w)
    else:
        np.testing.assert_allclose(np.asarray(g, np.float64),
                                   np.asarray(w, np.float64),
                                   rtol=rtol, atol=rtol)


def test_cholesky_solve_numpy():
    L = np.linalg.cholesky(SPD)
    b = rng.standard_normal((4, 2)).astype(np.float32)
    got = npy(ops.cholesky_solve(t(b), t(L), upper=False))
    np.testing.assert_allclose(SPD @ got, b, rtol=1e-3, atol=1e-3)


def test_householder_product_orthogonal():
    # drive with LAPACK geqrf output (valid (v, tau) pairs)
    from scipy.linalg import lapack
    a = rng.standard_normal((4, 3)).astype(np.float32)
    qr, tau, _, _ = lapack.sgeqrf(a)
    got = npy(ops.householder_product(t(qr), t(tau)))
    np.testing.assert_allclose(got.T @ got, np.eye(3), atol=1e-4)


def test_glu_matches_torch():
    x = rng.standard_normal((2, 6)).astype(np.float32)
    np.testing.assert_allclose(npy(ops.glu(t(x))),
                               TF.glu(torch.tensor(x)).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_log_loss_formula():
    p = np.clip(rng.random(4).astype(np.float32), 0.05, 0.95)
    y = np.array([1., 0., 1., 0.], np.float32)
    eps = 1e-4  # the op's reference default (phi log_loss epsilon)
    ref = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
    np.testing.assert_allclose(npy(ops.log_loss(t(p), t(y))).reshape(-1),
                               ref, rtol=1e-4)
