"""Conformance tests for the vision / sequence / graph op long tail.

torch (CPU) is the oracle where it implements the op (mirroring the
reference's OpTest-vs-framework comparisons in test/legacy_test/); pure
numpy/python references cover the rest.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

import paddle_tpu as pt
from paddle_tpu import ops
import paddle_tpu.nn.functional as F


def t(x):
    return pt.to_tensor(np.asarray(x))


def npy(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestConvVariants:
    def test_depthwise_conv2d_matches_torch(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 6, 10, 10)).astype(np.float32)
        w = rng.standard_normal((6, 1, 3, 3)).astype(np.float32)
        got = npy(F.depthwise_conv2d(t(x), t(w), padding=1))
        ref = TF.conv2d(torch.tensor(x), torch.tensor(w), padding=1,
                        groups=6).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_conv3d_transpose_matches_torch(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 5, 6, 7)).astype(np.float32)
        w = rng.standard_normal((4, 3, 3, 3, 3)).astype(np.float32)
        got = npy(F.conv3d_transpose(t(x), t(w), stride=2, padding=1))
        ref = TF.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                                  stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)

    def test_deformable_conv_zero_offset_is_conv(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((1, 4, 8, 8)).astype(np.float32)
        w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32)
        off = np.zeros((1, 2 * 9, 8, 8), np.float32)
        got = npy(F.deformable_conv(t(x), t(off), t(w), padding=1))
        ref = TF.conv2d(torch.tensor(x), torch.tensor(w), padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


class TestFoldUnpool:
    def test_fold_matches_torch(self):
        rng = np.random.default_rng(3)
        cols = rng.standard_normal((2, 4 * 9, 36)).astype(np.float32)
        got = npy(F.fold(t(cols), output_sizes=(6, 6), kernel_sizes=3,
                         strides=1, paddings=1))
        ref = TF.fold(torch.tensor(cols), output_size=(6, 6), kernel_size=3,
                      stride=1, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_max_pool_with_index_and_unpool_roundtrip(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
        out, idx = F.max_pool2d_with_index(t(x), kernel_size=2, stride=2)
        tref, tidx = TF.max_pool2d(torch.tensor(x), 2, 2,
                                   return_indices=True)
        np.testing.assert_allclose(npy(out), tref.numpy(), rtol=1e-6,
                                   atol=1e-6)
        np.testing.assert_array_equal(npy(idx), tidx.numpy())
        up = F.unpool(out, idx, kernel_size=2, stride=2)
        tup = TF.max_unpool2d(tref, tidx, 2, 2)
        np.testing.assert_allclose(npy(up), tup.numpy(), rtol=1e-6,
                                   atol=1e-6)


class TestRoiPooling:
    def test_roi_pool_matches_torchvision_or_naive(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((1, 2, 16, 16)).astype(np.float32)
        boxes = np.array([[0.0, 0.0, 7.0, 7.0], [4.0, 4.0, 15.0, 11.0]],
                         np.float32)
        got = npy(ops.roi_pool(t(x), t(boxes), output_size=2,
                               spatial_scale=1.0))
        # naive quantized-bin reference (matches the CUDA kernel's spec)
        def naive(img, box, oh, ow):
            x1, y1, x2, y2 = [int(round(v)) for v in box]
            rh = max(y2 - y1 + 1, 1)
            rw = max(x2 - x1 + 1, 1)
            out = np.zeros((img.shape[0], oh, ow), np.float32)
            for i in range(oh):
                for j in range(ow):
                    hs = y1 + int(np.floor(i * rh / oh))
                    he = y1 + int(np.ceil((i + 1) * rh / oh))
                    ws = x1 + int(np.floor(j * rw / ow))
                    we = x1 + int(np.ceil((j + 1) * rw / ow))
                    hs, he = max(hs, 0), min(he, img.shape[1])
                    ws, we = max(ws, 0), min(we, img.shape[2])
                    patch = img[:, hs:he, ws:we]
                    out[:, i, j] = (patch.max(axis=(1, 2))
                                    if patch.size else 0.0)
            return out
        for r in range(2):
            np.testing.assert_allclose(got[r], naive(x[0], boxes[r], 2, 2),
                                       rtol=1e-6, atol=1e-6)

    def test_psroi_pool_shapes_and_mean(self):
        x = np.arange(1 * 8 * 4 * 4, dtype=np.float32).reshape(1, 8, 4, 4)
        boxes = np.array([[0.0, 0.0, 4.0, 4.0]], np.float32)
        got = npy(ops.psroi_pool(t(x), t(boxes), output_size=2,
                                 spatial_scale=1.0))
        assert got.shape == (1, 2, 2, 2)
        # bin (0,0) of out-channel 0 averages channel 0 over rows 0-1, cols 0-1
        np.testing.assert_allclose(got[0, 0, 0, 0],
                                   x[0, 0, :2, :2].mean(), rtol=1e-6)


class TestDetection:
    def test_prior_box_shapes_and_range(self):
        feat = np.zeros((1, 3, 4, 4), np.float32)
        img = np.zeros((1, 3, 32, 32), np.float32)
        boxes, var = ops.prior_box(t(feat), t(img), min_sizes=[8.0],
                                   max_sizes=[16.0],
                                   aspect_ratios=[2.0], clip=True)
        b = npy(boxes)
        assert b.shape[:2] == (4, 4) and b.shape[-1] == 4
        assert (b >= 0).all() and (b <= 1).all()
        assert npy(var).shape == b.shape

    def test_yolo_box_decodes_center(self):
        # zero logits -> sigmoid 0.5 -> box centered in each cell
        x = np.zeros((1, 2 * 7, 2, 2), np.float32)
        img_size = np.array([[64, 64]], np.int32)
        boxes, scores = ops.yolo_box(t(x), t(img_size),
                                     anchors=[10, 10, 20, 20], class_num=2,
                                     conf_thresh=0.3, downsample_ratio=32)
        b = npy(boxes).reshape(2, 2, 2, 4)
        # first cell center: (0.5+0)/2 * 64 = 16
        cx = (b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2
        np.testing.assert_allclose(cx, 16.0, atol=1e-3)

    def test_multiclass_nms_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                         np.float32)[None]
        scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # [1, C=1, M=3]
        out, cnt = ops.multiclass_nms(t(boxes), t(scores),
                                      score_threshold=0.1,
                                      nms_threshold=0.5, keep_top_k=3)
        o = npy(out)[0]
        assert int(npy(cnt)[0]) == 2          # one of the overlapping pair dies
        kept_scores = sorted(o[o[:, 1] > 0][:, 1].tolist(), reverse=True)
        np.testing.assert_allclose(kept_scores, [0.9, 0.7], atol=1e-6)

    def test_matrix_nms_decays_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10], [20, 20, 30, 30]],
                         np.float32)[None]
        scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)
        out, cnt = ops.matrix_nms(t(boxes), t(scores), score_threshold=0.1,
                                  post_threshold=0.0, keep_top_k=3)
        o = npy(out)[0]
        s = o[:, 1]
        # identical boxes: second score decays to ~0 (linear decay 1-iou=0)
        assert s.max() <= 0.9 + 1e-6
        assert (s[(s > 0)] >= 0.69).sum() >= 2


class TestSequenceOps:
    def test_ctc_loss_matches_torch(self):
        rng = np.random.default_rng(6)
        T_, B, C, L = 12, 3, 5, 4
        logits = rng.standard_normal((T_, B, C)).astype(np.float32)
        labels = rng.integers(1, C, (B, L)).astype(np.int32)
        in_len = np.array([12, 10, 8], np.int32)
        lab_len = np.array([4, 3, 2], np.int32)
        got = npy(ops.ctc_loss(t(logits), t(labels), t(in_len), t(lab_len),
                               blank=0, reduction="none"))
        ref = TF.ctc_loss(torch.tensor(logits).log_softmax(-1),
                          torch.tensor(labels.astype(np.int64)),
                          torch.tensor(in_len.astype(np.int64)),
                          torch.tensor(lab_len.astype(np.int64)),
                          blank=0, reduction="none").numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_ctc_loss_grad_flows(self):
        rng = np.random.default_rng(7)
        logits = pt.to_tensor(
            rng.standard_normal((6, 2, 4)).astype(np.float32))
        logits.stop_gradient = False
        loss = ops.ctc_loss(logits, t(np.array([[1, 2], [2, 1]], np.int32)),
                            t(np.array([6, 6], np.int32)),
                            t(np.array([2, 2], np.int32)))
        loss.backward()
        g = npy(logits.grad)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_viterbi_decode_matches_bruteforce(self):
        rng = np.random.default_rng(8)
        B, T_, N = 2, 5, 4
        pots = rng.standard_normal((B, T_, N)).astype(np.float32)
        trans = rng.standard_normal((N, N)).astype(np.float32)
        lens = np.array([5, 3], np.int32)
        score, path = ops.viterbi_decode(t(pots), t(trans), t(lens),
                                         include_bos_eos_tag=False)
        score, path = npy(score), npy(path)
        import itertools
        for b in range(B):
            best, bestp = -1e30, None
            for p in itertools.product(range(N), repeat=int(lens[b])):
                s = pots[b, 0, p[0]]
                for i in range(1, len(p)):
                    s += trans[p[i - 1], p[i]] + pots[b, i, p[i]]
                if s > best:
                    best, bestp = s, p
            np.testing.assert_allclose(score[b], best, rtol=1e-5)
            np.testing.assert_array_equal(path[b, :lens[b]], bestp)

    def test_gather_tree_matches_python(self):
        ids = np.array([[[2, 5]], [[3, 6]], [[4, 7]]], np.int64)  # [T,B=1,W=2]
        parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
        got = npy(ops.gather_tree(t(ids), t(parents)))
        # beam 0 at t=2: parent chain 0 -> parents[2][0]=0 -> beam0@t1
        #   whose parent... standard python backtrace:
        T_, B, W = ids.shape
        ref = np.zeros_like(ids)
        for b in range(B):
            for w in range(W):
                beam = w
                for tt in range(T_ - 1, -1, -1):
                    ref[tt, b, w] = ids[tt, b, beam]
                    beam = parents[tt, b, beam]
        np.testing.assert_array_equal(got, ref)

    def test_top_p_sampling_stays_in_nucleus(self):
        probs = np.array([[0.5, 0.3, 0.15, 0.05],
                          [0.97, 0.01, 0.01, 0.01]], np.float32)
        pv, ids = ops.top_p_sampling(t(probs), t(np.array([0.7, 0.5],
                                                          np.float32)),
                                     seed=7)
        ids = npy(ids)
        assert ids[0, 0] in (0, 1)   # nucleus of row 0 at p=0.7
        assert ids[1, 0] == 0        # row 1's nucleus is just token 0

    def test_edit_distance_matches_python(self):
        hyp = np.array([[1, 2, 3, 0]], np.int64)
        ref = np.array([[1, 3, 3, 4]], np.int64)
        d, n = ops.edit_distance(t(hyp), t(ref),
                                 t(np.array([3], np.int64)),
                                 t(np.array([4], np.int64)),
                                 normalized=False)
        # "123" vs "1334": sub 2->3, keep 3, ins 3/4... classic DP = 2
        np.testing.assert_allclose(npy(d)[0, 0], 2.0)
        assert int(npy(n)[0]) == 1

    def test_class_center_sample(self):
        label = np.array([3, 7, 3, 1], np.int64)
        remapped, sampled = ops.class_center_sample(t(label), 10, 6, seed=3)
        remapped, sampled = npy(remapped), npy(sampled)
        for orig, rm in zip(label, remapped):
            assert sampled[rm] == orig      # positives correctly remapped
        assert len(set(sampled.tolist())) == 6


class TestLosses:
    def test_huber_matches_torch(self):
        rng = np.random.default_rng(9)
        x = rng.standard_normal(50).astype(np.float32)
        y = rng.standard_normal(50).astype(np.float32)
        got = npy(F.huber_loss(t(x), t(y), delta=0.7))
        ref = TF.huber_loss(torch.tensor(x), torch.tensor(y),
                            delta=0.7).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_hsigmoid_loss_runs_and_matches_naive(self):
        rng = np.random.default_rng(10)
        B, Fd, NC = 3, 6, 8
        x = rng.standard_normal((B, Fd)).astype(np.float32)
        label = np.array([0, 3, 7], np.int64)
        w = rng.standard_normal((NC - 1, Fd)).astype(np.float32)
        bias = rng.standard_normal((NC - 1,)).astype(np.float32)
        got = npy(F.hsigmoid_loss(t(x), t(label), NC, t(w), t(bias)))

        def naive(xb, c):
            code = c + NC
            length = int(np.floor(np.log2(code)))
            total = 0.0
            for d in range(length):
                node = (code >> (length - d)) - 1
                bit = (code >> (length - d - 1)) & 1
                z = xb @ w[node] + bias[node]
                total += max(z, 0) - z * bit + np.log1p(np.exp(-abs(z)))
            return total
        for b in range(B):
            np.testing.assert_allclose(got[b, 0], naive(x[b], label[b]),
                                       rtol=1e-4, atol=1e-4)

    def test_margin_cross_entropy_reduces_to_ce(self):
        rng = np.random.default_rng(11)
        logits = (rng.standard_normal((4, 6)) * 0.5).clip(-1, 1).astype(
            np.float32)
        label = np.array([0, 2, 4, 5], np.int64)
        # no margins, scale 1 -> plain softmax CE on cosine logits
        got = npy(ops.margin_cross_entropy(t(logits), t(label), margin1=1.0,
                                           margin2=0.0, margin3=0.0,
                                           scale=1.0))
        ref = TF.cross_entropy(torch.tensor(logits),
                               torch.tensor(label),
                               reduction="none").numpy()
        np.testing.assert_allclose(got[:, 0], ref, rtol=1e-5, atol=1e-5)

    def test_bce_loss_alias(self):
        p = np.array([0.3, 0.8], np.float32)
        y = np.array([0.0, 1.0], np.float32)
        np.testing.assert_allclose(npy(F.bce_loss(t(p), t(y))),
                                   npy(F.binary_cross_entropy(t(p), t(y))))


class TestMathAdditions:
    def test_logcumsumexp_matches_torch(self):
        rng = np.random.default_rng(12)
        x = rng.standard_normal((3, 20)).astype(np.float32)
        got = npy(ops.logcumsumexp(t(x), axis=1))
        ref = torch.logcumsumexp(torch.tensor(x), dim=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_renorm_matches_torch(self):
        rng = np.random.default_rng(13)
        x = rng.standard_normal((4, 5, 6)).astype(np.float32)
        got = npy(ops.renorm(t(x), p=2.0, axis=1, max_norm=1.5))
        ref = torch.renorm(torch.tensor(x), p=2, dim=1,
                           maxnorm=1.5).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_clip_by_norm(self):
        x = np.array([3.0, 4.0], np.float32)       # norm 5
        np.testing.assert_allclose(npy(ops.clip_by_norm(t(x), 1.0)),
                                   x / 5.0, rtol=1e-6)

    def test_p_norm(self):
        x = np.array([[1.0, -2.0, 2.0]], np.float32)
        np.testing.assert_allclose(npy(ops.p_norm(t(x), porder=1.0, axis=1)),
                                   [5.0])
        np.testing.assert_allclose(
            npy(ops.p_norm(t(x), porder=float("inf"), axis=1)), [2.0])

    def test_add_n_and_unstack_and_fill_diagonal(self):
        a, b = t([1.0, 2.0]), t([3.0, 4.0])
        np.testing.assert_allclose(npy(ops.add_n([a, b])), [4.0, 6.0])
        parts = ops.unstack(t(np.arange(6).reshape(2, 3)), axis=0)
        assert len(parts) == 2 and npy(parts[1]).tolist() == [3, 4, 5]
        filled = ops.fill_diagonal(t(np.zeros((3, 3), np.float32)), 7.0)
        np.testing.assert_allclose(np.diag(npy(filled)), [7.0] * 3)

    def test_lu_unpack_reconstructs(self):
        rng = np.random.default_rng(14)
        a = rng.standard_normal((5, 5)).astype(np.float32)
        lu_, piv = ops.lu(t(a))
        P, L, U = ops.lu_unpack(lu_, piv)
        rec = npy(P) @ npy(L) @ npy(U)
        np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)

    def test_spectral_norm_unit_sigma(self):
        rng = np.random.default_rng(15)
        w = rng.standard_normal((8, 6)).astype(np.float32)
        wn = npy(ops.spectral_norm(t(w), power_iters=50))
        s = np.linalg.svd(wn, compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, atol=1e-3)


class TestRandomAdditions:
    def test_binomial_dirichlet_truncated(self):
        b = npy(ops.binomial(t(np.full((2000,), 10.0, np.float32)),
                             t(np.full((2000,), 0.5, np.float32))))
        assert 4.5 < b.mean() < 5.5 and b.min() >= 0 and b.max() <= 10
        d = npy(ops.dirichlet(t(np.ones((100, 3), np.float32))))
        np.testing.assert_allclose(d.sum(-1), np.ones(100), rtol=1e-5)
        tn = npy(ops.truncated_normal((5000,), std=2.0))
        assert abs(tn.mean()) < 0.2 and np.abs(tn).max() <= 4.0 + 1e-5

    def test_rrelu_modes(self):
        x = np.array([-2.0, 3.0], np.float32)
        ev = npy(F.rrelu(t(x), training=False))
        np.testing.assert_allclose(ev, [-2.0 * (1 / 8 + 1 / 3) / 2, 3.0],
                                   rtol=1e-6)
        tr = npy(F.rrelu(t(x), training=True))
        assert tr[1] == 3.0 and -2.0 / 3 - 1e-6 <= tr[0] <= -2.0 / 8 + 1e-6


class TestGeometric:
    def test_send_u_recv_reductions(self):
        x = np.arange(8, dtype=np.float32).reshape(4, 2)
        src = np.array([0, 1, 2, 3], np.int64)
        dst = np.array([1, 1, 0, 0], np.int64)
        import paddle_tpu.geometric as G
        s = npy(G.send_u_recv(t(x), t(src), t(dst), "sum"))
        np.testing.assert_allclose(s[0], x[2] + x[3])
        np.testing.assert_allclose(s[1], x[0] + x[1])
        m = npy(G.send_u_recv(t(x), t(src), t(dst), "max"))
        np.testing.assert_allclose(m[0], np.maximum(x[2], x[3]))

    def test_send_ue_recv_and_send_uv(self):
        import paddle_tpu.geometric as G
        x = np.ones((3, 2), np.float32)
        e = np.array([1.0, 2.0, 3.0], np.float32)
        src = np.array([0, 1, 2], np.int64)
        dst = np.array([0, 0, 1], np.int64)
        out = npy(G.send_ue_recv(t(x), t(e), t(src), t(dst), "mul", "sum"))
        np.testing.assert_allclose(out[0], [3.0, 3.0])   # 1*1 + 1*2
        uv = npy(G.send_uv(t(x * 2), t(x * 3), t(src), t(dst), "add"))
        np.testing.assert_allclose(uv, np.full((3, 2), 5.0))

    def test_segment_pool(self):
        import paddle_tpu.geometric as G
        x = np.array([[1.0], [2.0], [30.0]], np.float32)
        ids = np.array([0, 0, 1], np.int64)
        np.testing.assert_allclose(npy(G.segment_mean(t(x), t(ids))),
                                   [[1.5], [30.0]])


class TestBilinear:
    def test_bilinear_matches_torch(self):
        rng = np.random.default_rng(16)
        x1 = rng.standard_normal((4, 3)).astype(np.float32)
        x2 = rng.standard_normal((4, 5)).astype(np.float32)
        w = rng.standard_normal((2, 3, 5)).astype(np.float32)
        b = rng.standard_normal((2,)).astype(np.float32)
        got = npy(F.bilinear(t(x1), t(x2), t(w), t(b.reshape(1, 2))))
        ref = TF.bilinear(torch.tensor(x1), torch.tensor(x2),
                          torch.tensor(w), torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
