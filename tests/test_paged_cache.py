"""Paged KV-cache serving runtime: native C++ block allocator +
PagedKVCache manager + end-to-end use with
block_multihead_attention (ref: the reference's inference runtime
around block_multihead_attention.py:19)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference import BlockAllocator, PagedKVCache


class TestBlockAllocator:
    def test_alloc_free_reuse(self):
        a = BlockAllocator(8)
        assert a.num_free == 8
        first = a.alloc(5)
        assert len(set(first)) == 5 and a.num_free == 3
        assert a.free(first[:2]) == 2
        assert a.num_free == 5
        again = a.alloc(5)
        assert a.num_free == 0
        # the two freed blocks were reused
        assert set(first[:2]) <= set(again)

    def test_oom_is_all_or_nothing(self):
        a = BlockAllocator(4)
        a.alloc(3)
        with pytest.raises(MemoryError):
            a.alloc(2)
        assert a.num_free == 1          # nothing leaked by the failure
        a.alloc(1)

    def test_double_free_rejected(self):
        a = BlockAllocator(4)
        blks = a.alloc(2)
        assert a.free(blks) == 2
        with pytest.raises(ValueError, match="invalid free"):
            a.free(blks)                # double free raises...
        with pytest.raises(ValueError, match="invalid free"):
            a.free([99])                # ...as does out-of-range...
        with pytest.raises(ValueError, match="invalid free"):
            a.free([-1])
        assert a.num_free == 4          # ...with the free list intact

    def test_concurrent_alloc_free(self):
        import threading
        a = BlockAllocator(64)
        errors = []

        def worker():
            try:
                for _ in range(200):
                    blks = a.alloc(4)
                    a.free(blks)
            except Exception as e:      # pragma: no cover
                errors.append(e)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errors
        assert a.num_free == 64


class TestPagedKVCache:
    def test_page_accounting(self):
        c = PagedKVCache(num_layers=2, num_blocks=16, kv_heads=2,
                         block_size=4, head_dim=8)
        c.add_sequence("a", num_tokens=6)     # 2 pages
        c.add_sequence("b", num_tokens=4)     # 1 page
        assert c.allocator.num_free == 13
        c.extend("a", 3)                      # 6+3=9 -> 3 pages
        assert c.allocator.num_free == 12
        tbl = np.asarray(c.block_table(["a", "b"]))
        assert tbl.shape == (2, 3)
        assert (tbl[0] >= 0).all()
        assert (tbl[1, 1:] == -1).all()
        c.free_sequence("a")
        assert c.allocator.num_free == 15
        with pytest.raises(KeyError):
            c.block_table(["a"])

    def test_end_to_end_with_block_attention(self):
        """Prefill one sequence, decode one step through the paged op
        using manager-produced operands; oracle = dense SDPA."""
        import paddle_tpu.incubate.nn.functional as F
        import math
        kvH = H = 2
        D, bs = 8, 4
        S = 6
        cache = PagedKVCache(num_layers=1, num_blocks=8, kv_heads=kvH,
                             block_size=bs, head_dim=D,
                             dtype=np.float32)
        cache.add_sequence(0, num_tokens=S)
        rng = np.random.default_rng(0)
        qkv = rng.standard_normal((S, 3 * H * D)).astype(np.float32)
        cu = np.asarray([0, S], np.int32)

        def run(qkv_step, dec_len, stt):
            out, _, kc, vc = F.block_multihead_attention(
                pt.to_tensor(qkv_step), cache.key_cache(0),
                cache.value_cache(0),
                pt.to_tensor(np.asarray([[S]], np.int32)),
                pt.to_tensor(np.asarray([[dec_len]], np.int32)),
                pt.to_tensor(np.asarray([[stt]], np.int32)),
                None, None, pt.to_tensor(np.asarray([0, stt], np.int32)),
                pt.to_tensor(np.asarray([0, stt], np.int32)),
                cache.block_table([0]), max_seq_len=stt, block_size=bs)
            cache.update(0, kc._data, vc._data)
            return out

        out_prefill = run(qkv, 0, S)
        cache.extend(0, 1)
        step = rng.standard_normal((1, 3 * H * D)).astype(np.float32)
        out_step = run(step, S, 1)

        # oracle over the concatenated 7 tokens
        allq = np.concatenate([qkv, step])[:, :H * D].reshape(-1, H, D)
        allk = np.concatenate([qkv, step])[:, H * D:2 * H * D].reshape(
            -1, H, D)
        allv = np.concatenate([qkv, step])[:, 2 * H * D:].reshape(
            -1, H, D)
        q7 = allq[-1]                   # the decode token
        s = np.einsum("hd,khd->hk", q7, allk) / math.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("hk,khd->hd", p, allv).reshape(-1)
        np.testing.assert_allclose(np.asarray(out_step._data)[0], want,
                                   rtol=1e-4, atol=1e-5)
