"""Pallas block-size autotune cache (VERDICT r3 missing #5 / next-6):
pick/persist/reload logic, kill-switch, and reentrancy — the machinery
is exercised with mocked timings (the real kernel measurement needs the
TPU; its wiring is validated by the bench, see BENCH_EXTRA.md)."""
import numpy as np
import pytest

from paddle_tpu.kernels.pallas import autotune


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CACHE_DIR", str(tmp_path))
    autotune.clear()
    yield
    autotune.clear()


def test_picks_fastest_and_persists(tmp_path):
    times = {(128, 512): 0.03, (256, 1024): 0.01, (512, 512): 0.02}
    calls = []

    def run(c):
        calls.append(c)
        return times[c]

    key = ("fwd", 4, 256, 256, 8, 8, 64, 1, 0)
    win = autotune.tune(key, list(times), run)
    assert win == (256, 1024)
    # every candidate measured at least once
    assert set(calls) == set(times)
    # memoized: no more measurement
    calls.clear()
    assert autotune.tune(key, list(times), run) == (256, 1024)
    assert calls == []
    # survives a fresh in-process state (disk reload)
    autotune.clear()
    assert autotune.lookup(key) == (256, 1024)
    assert autotune.tune(key, list(times), run) == (256, 1024)
    assert calls == []


def test_failed_candidates_are_skipped():
    def run(c):
        if c == (512, 512):
            raise RuntimeError("vmem oom")
        return {(128, 512): 0.02, (256, 1024): 0.05}[c]

    win = autotune.tune(("bwd", 1, 128, 128, 2, 2, 64, 0, 0),
                        [(512, 512), (128, 512), (256, 1024)], run)
    assert win == (128, 512)


def test_all_failed_falls_back_to_first():
    def run(c):
        raise RuntimeError("nope")

    key = ("fwd", 1, 128, 128, 2, 2, 64, 0, 1)
    win = autotune.tune(key, [(256, 1024), (128, 512)], run)
    assert win == (256, 1024)
    # a transient all-fail must NOT freeze into the cache
    assert autotune.lookup(key) is None


def test_kill_switch(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PALLAS_AUTOTUNE", "0")
    assert not autotune.enabled()
    monkeypatch.setenv("PADDLE_TPU_PALLAS_AUTOTUNE", "1")
    assert autotune.enabled()


def test_reentrancy_guard():
    """A measurement that re-enters tune() (the kernel under test calls
    the autotuned entrypoint) must not recurse into another search."""
    inner_calls = []

    def run_outer(c):
        w = autotune.tune(("fwd", 9, 9, 9, 9, 9, 9, 9, 9),
                          [(1, 1), (2, 2)],
                          lambda c2: inner_calls.append(c2) or 0.01)
        assert w == (1, 1)          # first candidate, no search
        return {(128, 512): 0.02, (256, 1024): 0.01}[c]

    win = autotune.tune(("fwd", 2, 256, 256, 4, 4, 64, 1, 0),
                        [(128, 512), (256, 1024)], run_outer)
    assert win == (256, 1024)
    assert inner_calls == []        # inner search never measured


def test_distinct_keys_distinct_entries():
    k1 = ("fwd", 4, 256, 256, 8, 8, 64, 1, 0)
    k2 = ("fwd", 4, 512, 512, 8, 8, 64, 1, 0)
    autotune.tune(k1, [(1, 1), (2, 2)], lambda c: {(1, 1): 0.1,
                                                   (2, 2): 0.2}[c])
    autotune.tune(k2, [(1, 1), (2, 2)], lambda c: {(1, 1): 0.2,
                                                   (2, 2): 0.1}[c])
    assert autotune.lookup(k1) == (1, 1)
    assert autotune.lookup(k2) == (2, 2)
