"""Pallas fused-norm kernel conformance via interpret mode (the same CI
strategy as test_flash_attention.py; VERDICT r1 weak item 3 asked that
every Pallas kernel be exercised off-TPU)."""
import importlib

import numpy as np
import pytest
import jax.numpy as jnp

norms = importlib.import_module("paddle_tpu.kernels.pallas.norms")


def _x(n=64, h=256, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((n, h)), jnp.float32)


@pytest.mark.parametrize("with_affine", [False, True])
def test_layer_norm_interpret_matches_xla(with_affine):
    x = _x()
    w = jnp.asarray(np.random.default_rng(1).standard_normal(256),
                    jnp.float32) if with_affine else None
    b = jnp.asarray(np.random.default_rng(2).standard_normal(256),
                    jnp.float32) if with_affine else None
    got = norms._ln_pallas(x, w, b, 1e-5, interpret=True)
    ref = norms._ln_xla(x, w, b, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("with_w", [False, True])
def test_rms_norm_interpret_matches_xla(with_w):
    x = _x(seed=3)
    w = jnp.asarray(np.random.default_rng(4).standard_normal(256),
                    jnp.float32) if with_w else None
    got = norms._rms_pallas(x, w, 1e-6, interpret=True)
    ref = norms._rms_xla(x, w, 1e-6)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bf16_rows_blocking():
    # bf16 path picks its own row blocking; just conformance-check it
    x = _x(n=128, h=512, seed=5).astype(jnp.bfloat16)
    got = norms._rms_pallas(x, None, 1e-6, interpret=True)
    ref = norms._rms_xla(x, None, 1e-6)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)
