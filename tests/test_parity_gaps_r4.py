"""Round-4 op-parity gap closures (VERDICT r3 missing #2): LBFGS,
decode_jpeg/read_file, squared_l2_norm, frexp, yolo_loss, deform_conv2d,
graph sampling, sparse conversion methods, ModelAverage/LookAhead."""
import numpy as np
import pytest

import paddle_tpu as pt


def test_squared_l2_norm():
    x = pt.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    out = pt.ops.squared_l2_norm(x)
    np.testing.assert_allclose(out.numpy(), [30.0], rtol=1e-6)


def test_frexp_matches_numpy():
    # full normal range incl. the exponent extremes that overflow a
    # naive exp2(e); subnormals are excluded (TPU hardware flushes them
    # to zero — documented in the op)
    x = np.array([0.0, 1.0, -3.5, 0.25, 1024.0, -1e-8, 2e38, -3e38,
                  1e-37], np.float32)
    m, e = pt.ops.frexp(pt.to_tensor(x))
    wm, we = np.frexp(x)
    np.testing.assert_allclose(m.numpy(), wm, rtol=2e-6, atol=1e-9)
    np.testing.assert_array_equal(e.numpy(), we)


def test_read_file_and_decode_jpeg(tmp_path):
    from PIL import Image
    # smooth gradient: JPEG is near-lossless on it (noise is its worst
    # case and would fail any closeness check)
    gy, gx = np.mgrid[0:16, 0:20]
    arr = np.stack([gy * 12, gx * 10, (gy + gx) * 6], -1).astype(
        np.uint8)
    p = tmp_path / "img.jpg"
    Image.fromarray(arr).save(p, quality=95)
    raw = pt.vision.ops.read_file(str(p))
    assert raw.numpy().dtype == np.uint8 and raw.numpy().ndim == 1
    img = pt.vision.ops.decode_jpeg(raw)
    assert img.numpy().shape == (3, 16, 20)
    # lossy codec: just require closeness
    assert np.abs(img.numpy().astype(int).transpose(1, 2, 0)
                  - arr.astype(int)).mean() < 12
    gray = pt.vision.ops.decode_jpeg(raw, mode="gray")
    assert gray.numpy().shape == (1, 16, 20)


class TestDeformConv2d:
    def test_zero_offset_equals_conv(self):
        import jax
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 9, 9)).astype(np.float32)
        w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32)
        off = np.zeros((2, 2 * 9, 7, 7), np.float32)
        out = pt.vision.ops.deform_conv2d(
            pt.to_tensor(x), pt.to_tensor(off), pt.to_tensor(w))
        want = jax.lax.conv_general_dilated(
            x, w, (1, 1), "VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(out.numpy(), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_integer_offset_shifts_sampling(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((1, 1, 8, 8)).astype(np.float32)
        w = np.ones((1, 1, 1, 1), np.float32)
        # 1x1 kernel, offset (0, +1): out[i,j] = x[i, j+1]
        off = np.zeros((1, 2, 8, 8), np.float32)
        off[:, 1] = 1.0
        out = pt.vision.ops.deform_conv2d(
            pt.to_tensor(x), pt.to_tensor(off), pt.to_tensor(w))
        np.testing.assert_allclose(out.numpy()[0, 0, :, :-1],
                                   x[0, 0, :, 1:], rtol=1e-5)
        # out-of-image taps contribute zero
        np.testing.assert_allclose(out.numpy()[0, 0, :, -1], 0.0)

    def test_mask_modulates(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
        w = rng.standard_normal((3, 2, 3, 3)).astype(np.float32)
        off = np.zeros((1, 2 * 9, 4, 4), np.float32)
        mask = np.full((1, 9, 4, 4), 0.5, np.float32)
        full = pt.vision.ops.deform_conv2d(
            pt.to_tensor(x), pt.to_tensor(off), pt.to_tensor(w))
        half = pt.vision.ops.deform_conv2d(
            pt.to_tensor(x), pt.to_tensor(off), pt.to_tensor(w),
            mask=pt.to_tensor(mask))
        np.testing.assert_allclose(half.numpy(), full.numpy() * 0.5,
                                   rtol=1e-4, atol=1e-5)


class TestYoloLoss:
    def _inputs(self, seed=0):
        rng = np.random.default_rng(seed)
        N, S, C, H = 2, 3, 4, 4
        x = rng.standard_normal((N, S * (5 + C), H, H)).astype(
            np.float32) * 0.1
        gt_box = np.zeros((N, 5, 4), np.float32)
        gt_box[0, 0] = [0.3, 0.4, 0.2, 0.3]
        gt_box[1, 0] = [0.7, 0.2, 0.4, 0.4]
        gt_label = np.zeros((N, 5), np.int32)
        gt_label[0, 0] = 2
        anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
        return x, gt_box, gt_label, anchors

    def test_finite_and_positive(self):
        x, gb, gl, anchors = self._inputs()
        loss = pt.vision.ops.yolo_loss(
            pt.to_tensor(x), pt.to_tensor(gb), pt.to_tensor(gl),
            anchors=anchors, anchor_mask=[0, 1, 2], class_num=4,
            ignore_thresh=0.7, downsample_ratio=32)
        v = loss.numpy()
        assert v.shape == (2,) and np.isfinite(v).all() and (v > 0).all()

    def test_matching_prediction_lowers_loss(self):
        x, gb, gl, anchors = self._inputs()
        base = pt.vision.ops.yolo_loss(
            pt.to_tensor(x), pt.to_tensor(gb), pt.to_tensor(gl),
            anchors=anchors, anchor_mask=[0, 1, 2], class_num=4,
            ignore_thresh=0.7, downsample_ratio=32).numpy().sum()
        # push all objectness logits very negative except where gt sits:
        # loss must DROP vs the random init (objectness dominates)
        x2 = x.copy().reshape(2, 3, 9, 4, 4)
        x2[:, :, 4] = -8.0
        x2 = x2.reshape(x.shape)
        better = pt.vision.ops.yolo_loss(
            pt.to_tensor(x2), pt.to_tensor(gb), pt.to_tensor(gl),
            anchors=anchors, anchor_mask=[0, 1, 2], class_num=4,
            ignore_thresh=0.7, downsample_ratio=32).numpy().sum()
        assert better < base

    def test_no_gt_only_objectness(self):
        x, _, _, anchors = self._inputs()
        gb = np.zeros((2, 5, 4), np.float32)
        gl = np.zeros((2, 5), np.int32)
        loss = pt.vision.ops.yolo_loss(
            pt.to_tensor(x), pt.to_tensor(gb), pt.to_tensor(gl),
            anchors=anchors, anchor_mask=[0, 1, 2], class_num=4,
            ignore_thresh=0.7, downsample_ratio=32).numpy()
        # pure background: loss == sum of bce(obj_logit, 0)
        xr = x.reshape(2, 3, 9, 4, 4)
        lo = xr[:, :, 4]
        want = (np.maximum(lo, 0) + np.log1p(np.exp(-np.abs(lo)))).sum(
            axis=(1, 2, 3))
        np.testing.assert_allclose(loss, want, rtol=1e-4)


def test_lbfgs_quadratic_converges():
    from paddle_tpu.optimizer import LBFGS
    w = pt.to_tensor(np.array([5.0, -3.0], np.float32))
    w.stop_gradient = False
    opt = LBFGS(learning_rate=1.0, max_iter=30,
                line_search_fn="strong_wolfe", parameters=[w])
    target = np.array([1.0, 2.0], np.float32)

    def closure():
        opt.clear_grad()
        d = w - pt.to_tensor(target)
        loss = (d * d).sum()
        loss.backward()
        return loss

    opt.step(closure)
    np.testing.assert_allclose(w.numpy(), target, atol=1e-4)


def test_lbfgs_rosenbrock_descends():
    from paddle_tpu.optimizer import LBFGS
    w = pt.to_tensor(np.array([-1.2, 1.0], np.float32))
    w.stop_gradient = False
    opt = LBFGS(learning_rate=1.0, max_iter=15,
                line_search_fn="strong_wolfe", parameters=[w])

    def rosen():
        a = w[1] - w[0] * w[0]
        b = 1.0 - w[0]
        return 100.0 * (a * a) + b * b

    def closure():
        opt.clear_grad()
        loss = rosen()
        loss.backward()
        return loss

    f0 = float(rosen().numpy())
    for _ in range(3):
        opt.step(closure)
    f1 = float(rosen().numpy())
    assert f1 < f0 * 0.05, (f0, f1)


def test_model_average_and_lookahead():
    from paddle_tpu.incubate import LookAhead, ModelAverage
    lin = pt.nn.Linear(2, 2)
    w0 = lin.weight.numpy().copy()
    inner = pt.optimizer.SGD(learning_rate=0.1,
                             parameters=lin.parameters())
    la = LookAhead(inner, alpha=0.5, k=2)
    x = pt.to_tensor(np.ones((4, 2), np.float32))
    for _ in range(4):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
    assert not np.allclose(lin.weight.numpy(), w0)

    ma = ModelAverage(0.5, parameters=lin.parameters(),
                      min_average_window=10, max_average_window=100)
    snapshots = []
    for _ in range(3):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        inner.step()
        inner.clear_grad()
        ma.step()
        snapshots.append(lin.weight.numpy().copy())
    cur = lin.weight.numpy().copy()
    with ma.apply():
        avg = lin.weight.numpy().copy()
    np.testing.assert_allclose(lin.weight.numpy(), cur)  # restored
    np.testing.assert_allclose(avg, np.mean(snapshots, axis=0),
                               rtol=1e-5, atol=1e-6)


def test_dense_to_sparse_methods():
    d = pt.to_tensor(np.array([[0.0, 1.0], [2.0, 0.0]], np.float32))
    coo = d.to_sparse_coo()
    np.testing.assert_allclose(np.asarray(coo.to_dense()._data
                                          if hasattr(coo.to_dense(),
                                                     "_data")
                                          else coo.to_dense().numpy()),
                               d.numpy())
    csr = d.to_sparse_csr()
    dn = csr.to_dense()
    dn = dn.numpy() if hasattr(dn, "numpy") else np.asarray(dn._data)
    np.testing.assert_allclose(dn, d.numpy())


def test_fused_bias_act():
    import jax
    import paddle_tpu.incubate.nn.functional as F
    rng = np.random.default_rng(0)
    x = rng.standard_normal((3, 8)).astype(np.float32)
    b = rng.standard_normal((8,)).astype(np.float32)
    out = F.fused_bias_act(pt.to_tensor(x), pt.to_tensor(b),
                           act_method="gelu")
    np.testing.assert_allclose(out.numpy(),
                               np.asarray(jax.nn.gelu(x + b)),
                               rtol=1e-5, atol=1e-6)
    # swiglu gating halves the width
    out = F.fused_bias_act(pt.to_tensor(x), act_method="swiglu")
    assert out.numpy().shape == (3, 4)
    a, g = x[:, :4], x[:, 4:]
    np.testing.assert_allclose(
        out.numpy(), np.asarray(jax.nn.silu(a)) * g, rtol=1e-5,
        atol=1e-6)
    with pytest.raises(NotImplementedError):
        F.fused_bias_act(pt.to_tensor(x), quant_scale=1.0)
