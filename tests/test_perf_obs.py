"""Performance observability (ISSUE 8): the one cost-model reader,
executable flops/bytes gauges per compile family, roofline accounting
against device peaks (honest no-series on unknown devices), the eager
backward dispatch-gap profiler, the perf ledger, and the disabled-mode
zero-overhead guard extended over all of it."""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.observability import metrics, perf, tracing


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts and ends disabled with empty series/ring and
    no device-peak override (the registry and override are
    process-global)."""
    obs.disable()
    obs.reset()
    perf.set_device_peaks()
    yield
    obs.disable()
    obs.reset()
    perf.set_device_peaks()


def _series(name):
    return obs.snapshot()[name]["series"]


@pytest.fixture(scope="module")
def tiny_gpt():
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import gpt_tiny
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


def _tiny_compiled():
    import jax
    import jax.numpy as jnp

    def f(a, b):
        return jnp.tanh(a @ b).sum()

    a = jnp.ones((8, 8), jnp.float32)
    return jax.jit(f).lower(a, a).compile(), (a, a)


# ---------------------------------------------------------------------------
# the one cost-model reader
# ---------------------------------------------------------------------------
class TestCostModelReader:
    def test_reads_flops_and_bytes(self):
        compiled, _ = _tiny_compiled()
        cm = perf.read_cost_model(compiled)
        assert cm is not None
        assert cm.flops > 0                  # 8x8x8 matmul at least
        assert cm.bytes_accessed > 0
        assert cm.bytes_argument > 0
        d = cm.as_dict()
        assert set(d) == {"flops", "bytes_accessed", "bytes_output",
                          "bytes_argument", "bytes_temp"}
        assert json.dumps(d)                 # ledger-serializable

    def test_unreadable_executable_is_none_not_zero(self):
        assert perf.read_cost_model(object()) is None


# ---------------------------------------------------------------------------
# CompileTimed: compile telemetry + cost model + degradation contract
# ---------------------------------------------------------------------------
class TestCompileTimed:
    def test_first_call_records_family_once(self):
        import jax
        import jax.numpy as jnp
        obs.enable()
        fn = perf.CompileTimed(jax.jit(lambda a: (a * 2).sum()),
                               "t_fam_ct")
        x = jnp.ones((4,), jnp.float32)
        out1 = fn(x)
        out2 = fn(x)
        assert float(out1) == float(out2) == 8.0
        comp = _series("paddle_tpu_compile_total")
        assert comp[("t_fam_ct", "compile")] == 1      # once, not per call
        assert fn.expected is not None and fn.expected.flops > 0
        fl = _series("paddle_tpu_executable_flops")
        assert fl[("t_fam_ct",)] == fn.expected.flops
        by = _series("paddle_tpu_executable_bytes")
        for kind in ("accessed", "output", "temp", "argument"):
            assert ("t_fam_ct", kind) in by
        assert by[("t_fam_ct", "accessed")] > 0

    def test_new_signature_falls_back_to_jit(self):
        import jax
        import jax.numpy as jnp
        obs.enable()
        fn = perf.CompileTimed(jax.jit(lambda a: a.sum()), "t_fam_sig")
        assert float(fn(jnp.ones((4,), jnp.float32))) == 4.0
        # AOT executables are monomorphic: a new shape must revert the
        # shim to the polymorphic jit function, not raise
        assert fn.expected is not None
        assert float(fn(jnp.ones((6,), jnp.float32))) == 6.0
        assert fn.fn is fn.jit_fn
        # the recorded cost model described the FIRST signature only —
        # after the revert, roofline reads must go silent, not stale
        assert fn.expected is None
        assert float(fn(jnp.ones((4,), jnp.float32))) == 4.0

    def test_expected_readable_even_when_disabled(self):
        import jax
        import jax.numpy as jnp
        fn = perf.CompileTimed(jax.jit(lambda a: a * 3), "t_fam_off")
        fn(jnp.ones((4,), jnp.float32))
        # tools (profile_engine columns) read .expected regardless of
        # metric recording; the registry saw nothing
        assert fn.expected is not None and fn.expected.flops > 0
        assert _series("paddle_tpu_compile_total").get(
            ("t_fam_off", "compile"), 0) == 0


# ---------------------------------------------------------------------------
# roofline accounting
# ---------------------------------------------------------------------------
class TestRoofline:
    def test_unknown_device_publishes_no_series(self):
        obs.enable()
        assert perf.device_peaks() is None   # the CPU test box
        perf.observe_roofline("t_fam_cpu", 0.01,
                              perf.CostModel(flops=1e6,
                                             bytes_accessed=1e6))
        roof = _series("paddle_tpu_roofline_utilization")
        assert not any(v for k, v in roof.items()
                       if k[0] == "t_fam_cpu")
        # the achieved record still accumulates (the ledger does not
        # need a peak to report absolute rates)
        rec = perf.family_records()["t_fam_cpu"]
        assert rec["achieved_bytes_per_s"] == pytest.approx(1e8)
        assert rec["utilization_hbm"] is None

    def test_pinned_peaks_give_exact_utilization(self):
        obs.enable()
        perf.set_device_peaks(1e12, 1e11)
        perf.observe_roofline(
            "t_fam_pin", 0.01,
            perf.CostModel(flops=5e9, bytes_accessed=2e8))
        roof = _series("paddle_tpu_roofline_utilization")
        assert roof[("t_fam_pin", "flops")] == pytest.approx(5e11 / 1e12)
        assert roof[("t_fam_pin", "hbm")] == pytest.approx(2e10 / 1e11)
        rec = perf.family_records()["t_fam_pin"]
        assert rec["utilization_flops"] == pytest.approx(0.5)
        assert rec["utilization_hbm"] == pytest.approx(0.2)

    def test_disabled_records_nothing(self):
        perf.observe_roofline("t_fam_dis", 0.01,
                              perf.CostModel(flops=1e6,
                                             bytes_accessed=1e6))
        assert "t_fam_dis" not in perf.family_records()

    def test_window_resets_with_obs_reset(self):
        obs.enable()
        perf.observe_roofline("t_fam_win", 0.01,
                              perf.CostModel(flops=1.0,
                                             bytes_accessed=1.0))
        assert "t_fam_win" in perf.family_records()
        obs.reset()
        assert perf.family_records() == {}


# ---------------------------------------------------------------------------
# the wired paths: engine launches, fused optimizer, TrainStep,
# eager backward
# ---------------------------------------------------------------------------
def _one_train_and_eager_step():
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import SGD, AdamW
    lin = pt.nn.Linear(8, 8)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=lin.parameters())
    x = pt.to_tensor(np.ones((2, 8), np.float32))
    (lin(x) ** 2).mean().backward()          # eager backward: gaps
    opt.step()                               # fused family
    opt.clear_grad()
    lin2 = pt.nn.Linear(8, 8)
    step = TrainStep(lin2, SGD(learning_rate=1e-3,
                               parameters=lin2.parameters()),
                     lambda m, a: (m(a) ** 2).mean())
    xa = np.ones((4, 8), np.float32)
    for _ in range(4):                       # >=1 steady-state sample
        step(xa)


class TestWiredFamilies:
    def test_engine_train_and_optimizer_families_report(self, tiny_gpt):
        from paddle_tpu.inference import LLMEngine
        obs.enable()
        perf.set_device_peaks(1e12, 1e11)    # CPU box: pin peaks
        rng = np.random.default_rng(3)
        eng = LLMEngine(tiny_gpt, max_batch=2, block_size=16,
                        decode_chunk=4, prompt_quantum=16,
                        max_model_len=64)
        res = eng.generate(
            [rng.integers(0, 1024, (n,)).astype(np.int32)
             for n in (5, 9, 13)], max_new_tokens=8)
        assert all(r.ok for r in res)
        _one_train_and_eager_step()

        live = {fam for (fam, _out), v in
                _series("paddle_tpu_compile_total").items() if v}
        assert {"engine_ragged", "engine_decode", "optimizer_fused",
                "train_step"} <= live
        fl = _series("paddle_tpu_executable_flops")
        fl_fams = {fam for (fam,), v in fl.items() if v}
        # one gauge row per live family, no orphan families
        assert fl_fams == live
        by = _series("paddle_tpu_executable_bytes")
        for fam in live:
            assert by[(fam, "accessed")] > 0
            for kind in ("output", "temp", "argument"):
                assert (fam, kind) in by
        # roofline: engine launches are blocking-timed, the train loop
        # samples steady-state inter-step periods; the async-dispatched
        # fused optimizer honestly publishes none
        roof = _series("paddle_tpu_roofline_utilization")
        roof_fams = {fam for (fam, _b), v in roof.items() if v}
        assert {"engine_ragged", "engine_decode",
                "train_step"} <= roof_fams
        assert "optimizer_fused" not in roof_fams
        for fam in ("engine_ragged", "engine_decode", "train_step"):
            assert roof[(fam, "hbm")] > 0
            assert roof[(fam, "flops")] > 0
        recs = perf.family_records()
        assert recs["optimizer_fused"]["achieved_bytes_per_s"] is None
        assert recs["engine_decode"]["achieved_bytes_per_s"] > 0
        assert json.dumps(recs)              # ledger-serializable

    def test_eager_backward_records_dispatch_gaps(self):
        from paddle_tpu.autograd import dispatch_queue as dq
        obs.enable()
        lin1, lin2 = pt.nn.Linear(8, 8), pt.nn.Linear(8, 8)
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        # per_node mode: one gap per inter-node hop (the batched engine
        # collapses the whole chain into one dispatch — see below)
        with dq.backward_dispatch_mode("per_node"):
            for _ in range(3):
                (lin2(pt.ops.tanh(lin1(x))) ** 2).mean().backward()
        gap = _series("paddle_tpu_dispatch_gap_seconds")[()]
        # >= 2 inter-node gaps per backward over the 4-op chain
        assert gap["count"] >= 6
        assert gap["sum"] > 0
        ops = _series("paddle_tpu_dispatch_gap_op_seconds_total")
        assert ops                           # attributed by op type
        assert any(v > 0 for v in ops.values())
        assert pytest.approx(gap["sum"]) == sum(ops.values())

    def test_batched_backward_pins_batch_size_histogram(self):
        # ISSUE 10: the batched engine's run lengths are a pinned
        # series — a 5-node single-consumer chain is ONE fused
        # dispatch (batch size 5, zero inter-dispatch gaps)
        from paddle_tpu.autograd import dispatch_queue as dq
        obs.enable()
        lin1, lin2 = pt.nn.Linear(8, 8), pt.nn.Linear(8, 8)
        x = pt.to_tensor(np.ones((4, 8), np.float32))
        with dq.backward_dispatch_mode("batched"):
            for _ in range(3):
                (lin2(pt.ops.tanh(lin1(x))) ** 2).mean().backward()
        batch = _series("paddle_tpu_dispatch_batch_size")[()]
        assert batch["count"] == 3           # one dispatch per backward
        assert batch["max"] == 5
        assert batch["sum"] == 15            # every node dispatched
        gap = _series("paddle_tpu_dispatch_gap_seconds")[()]
        assert gap["count"] == 0

    def test_disabled_backward_records_nothing(self):
        lin = pt.nn.Linear(4, 4)
        x = pt.to_tensor(np.ones((2, 4), np.float32))
        (lin(x) ** 2).mean().backward()
        assert _series(
            "paddle_tpu_dispatch_gap_seconds")[()]["count"] == 0
        assert _series(
            "paddle_tpu_dispatch_batch_size")[()]["count"] == 0


# ---------------------------------------------------------------------------
# disabled-mode zero-overhead guard, extended over the perf paths
# ---------------------------------------------------------------------------
class TestDisabledOverhead:
    def test_no_allocation_growth_when_disabled(self):
        import tracemalloc
        assert not obs.enabled()
        cm = perf.CostModel(flops=1e6, bytes_accessed=1e6)
        for _ in range(16):                  # warm lazy state
            perf.observe_roofline("t_ov_perf", 0.01, cm)
        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        for _ in range(5000):
            # the roofline recorder and the tape's per-node guard are
            # both a single module-flag check when off
            perf.observe_roofline("t_ov_perf", 0.01, cm)
            if metrics._ENABLED:
                pytest.fail("enabled")
        grown = tracemalloc.get_traced_memory()[0] - base
        tracemalloc.stop()
        assert grown < 2048, f"disabled-mode perf ops leaked {grown}B"
        assert perf.family_records() == {}
        assert tracing.events() == []


# ---------------------------------------------------------------------------
# perf ledger: bench appends, tools/perf_ledger.py attributes
# ---------------------------------------------------------------------------
def _ledger_record(rev, config, fams, device="cpu", mode=None,
                   gap_ms_per_step=None):
    rec = {"rev": rev, "config": config, "ts": 1.0,
           "device": device, "metric": "m", "value": 1.0,
           "vs_baseline": 1.0,
           "families": {
               f: {"runs": 3, "compiles": 1, "seconds": 0.01,
                   "expected": None,
                   "achieved_flops_per_s": None,
                   "achieved_bytes_per_s": bps,
                   "utilization_hbm": None,
                   "utilization_flops": None}
               for f, bps in fams.items()}}
    if mode is not None:
        rec["mode"] = mode
    if gap_ms_per_step is not None:
        rec["dispatch_gap"] = {"steps": 20, "count": 80,
                               "total_ms": gap_ms_per_step * 20,
                               "ms_per_step": gap_ms_per_step}
    return rec


def _perf_ledger():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import perf_ledger
    finally:
        sys.path.pop(0)
    return perf_ledger


class TestPerfLedger:
    def _write(self, path, records):
        with open(path, "w", encoding="utf-8") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")

    def test_same_revision_ledger_is_self_consistent(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [
            _ledger_record("rev_a", "decode", {"engine_decode": 1e9}),
            _ledger_record("rev_a", "decode", {"engine_decode": 0.5e9}),
        ])
        assert pl.main(["--ledger", p, "--check"]) == 0
        records, bad = pl.load(p)
        assert bad == 0
        v = pl.check(records, tol=0.2)
        # same-rev delta reported but NOT failed: run-to-run noise is
        # the gate's business, attribution is this tool's
        fam = v["configs"]["decode"]["families"]["engine_decode"]
        assert fam["ratio_vs_history"] == pytest.approx(0.5)
        assert v["pass"]

    def test_cross_revision_regression_names_the_family(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [
            _ledger_record("rev_a", "decode",
                           {"engine_decode": 1e9, "engine_ragged": 2e9}),
            _ledger_record("rev_b", "decode",
                           {"engine_decode": 0.5e9,
                            "engine_ragged": 1.95e9}),
        ])
        assert pl.main(["--ledger", p, "--check"]) == 1
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        fams = v["configs"]["decode"]["families"]
        assert fams["engine_decode"]["regressed"]       # the culprit
        assert not fams["engine_ragged"]["regressed"]   # within tol
        assert fams["engine_decode"]["baseline_rev"] == "rev_a"

    def test_disappeared_family_fails(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [
            _ledger_record("rev_a", "decode",
                           {"engine_decode": 1e9, "engine_ragged": 2e9}),
            _ledger_record("rev_b", "decode", {"engine_decode": 1e9}),
        ])
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        assert not v["pass"]
        assert v["configs"]["decode"]["missing_families"] == \
            ["engine_ragged"]

    def test_other_device_records_are_not_baselines(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        # a v5e record must not make the CPU smoke run of a different
        # revision read as a 100x per-family regression
        self._write(p, [
            _ledger_record("rev_a", "decode", {"engine_decode": 100e9},
                           device="TPU v5 lite"),
            _ledger_record("rev_b", "decode", {"engine_decode": 1e9},
                           device="cpu"),
        ])
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        assert v["pass"]
        fam = v["configs"]["decode"]["families"]["engine_decode"]
        assert fam["ratio_vs_history"] is None    # no same-device prior

    def test_modes_baseline_independently(self, tmp_path):
        # ISSUE 10: batched and per_node dispatch records are separate
        # baseline groups — per_node's (larger) gap must not read as a
        # regression baseline for batched, nor vice versa
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [
            _ledger_record("rev_a", "dispatch", {}, mode="per_node",
                           gap_ms_per_step=0.2),
            _ledger_record("rev_a", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.01),
            _ledger_record("rev_b", "dispatch", {}, mode="per_node",
                           gap_ms_per_step=0.21),
            _ledger_record("rev_b", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.012),
        ])
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        assert v["pass"]
        assert set(v["configs"]) == {"dispatch[per_node]",
                                     "dispatch[batched]"}
        g = v["configs"]["dispatch[batched]"]["dispatch_gap"]
        assert g["baseline_rev"] == "rev_a"
        assert not g["regressed"]

    def test_whole_graph_mode_and_graph_cache_ride_the_ledger(
            self, tmp_path):
        # ISSUE 13: whole_graph records baseline per (config, mode)
        # like the PR 10 modes, and their graph-cache counts are
        # echoed in the verdict and the trajectory (report-only)
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        recs = [
            _ledger_record("rev_a", "dispatch", {}, mode="whole_graph",
                           gap_ms_per_step=0.0),
            _ledger_record("rev_b", "dispatch", {}, mode="whole_graph",
                           gap_ms_per_step=0.004),
        ]
        recs[-1]["graph_cache"] = {"hit": 20, "miss": 1}
        self._write(p, recs)
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        assert v["pass"]            # 0.004 is under the absolute floor
        out = v["configs"]["dispatch[whole_graph]"]
        assert out["graph_cache"] == {"hit": 20, "miss": 1}
        traj = pl.trajectory(records)
        assert "(graph cache)" in traj
        assert "hit=20 miss=1 bypass=0" in traj

    def test_dispatch_gap_regression_fails_per_mode(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [
            _ledger_record("rev_a", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.01),
            _ledger_record("rev_b", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.05),   # 5x the gap
        ])
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        assert not v["pass"]
        g = v["configs"]["dispatch[batched]"]["dispatch_gap"]
        assert g["regressed"]
        assert g["ratio_vs_history"] == pytest.approx(5.0)
        # same-revision gap deltas report, never fail (box noise)
        self._write(p, [
            _ledger_record("rev_a", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.01),
            _ledger_record("rev_a", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.05),
        ])
        records, _ = pl.load(p)
        assert pl.check(records, tol=0.2)["pass"]

    def test_zero_gap_baseline_has_finite_sensitivity(self, tmp_path):
        # the routine batched result is ms_per_step=0.0 (one fused
        # dispatch per backward, zero gaps): timer jitter above it
        # must NOT read as a regression — the absolute floor applies
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [
            _ledger_record("rev_a", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.0),
            _ledger_record("rev_b", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.004),   # < floor
        ])
        records, _ = pl.load(p)
        assert pl.check(records, tol=0.2)["pass"]
        # but a real gap reappearing over a zero baseline still fails
        self._write(p, [
            _ledger_record("rev_a", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.0),
            _ledger_record("rev_b", "dispatch", {}, mode="batched",
                           gap_ms_per_step=0.1),
        ])
        records, _ = pl.load(p)
        v = pl.check(records, tol=0.2)
        assert not v["pass"]
        assert v["configs"]["dispatch[batched]"][
            "dispatch_gap"]["regressed"]

    def test_autotune_sweeps_render_in_trajectory(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        rec = _ledger_record("rev_a", "gpt2s", {"train_step": 1e9})
        rec["autotune_sweeps"] = [{
            "key": ["fwd", 2048], "device": "TPU_v5e",
            "candidates": {"(256, 1024)": 0.002, "(512, 512)": 0.001},
            "winner": [512, 512], "bw_window": [233e9, 314e9],
            "window_validated": True, "persisted": True}]
        self._write(p, [rec])
        records, _ = pl.load(p)
        table = pl.trajectory(records)
        assert "autotune" in table and "fwd|2048" in table
        assert "validated=True" in table
        # sweeps never affect the regression verdict
        assert pl.check(records, tol=0.2)["pass"]

    def test_missing_ledger_is_loud(self, tmp_path):
        pl = _perf_ledger()
        assert pl.main(["--ledger", str(tmp_path / "none.jsonl"),
                        "--check"]) == 2

    def test_trajectory_renders(self, tmp_path):
        pl = _perf_ledger()
        p = str(tmp_path / "ledger.jsonl")
        self._write(p, [_ledger_record("rev_a", "decode",
                                       {"engine_decode": 1e9})])
        records, _ = pl.load(p)
        table = pl.trajectory(records)
        assert "engine_decode" in table and "rev_a" in table


# ---------------------------------------------------------------------------
# obs_top roofline panel (render-tested like the spec-accept line)
# ---------------------------------------------------------------------------
class TestObsTopRooflinePanel:
    def _obs_top(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        try:
            import obs_top
        finally:
            sys.path.pop(0)
        return obs_top

    def test_renders_utilization_and_gap(self):
        obs_top = self._obs_top()
        obs.enable()
        perf.set_device_peaks(1e12, 1e11)
        perf.observe_roofline(
            "engine_decode", 0.01,
            perf.CostModel(flops=5e9, bytes_accessed=2e8))
        perf.note_dispatch_gap(120e-6, "linear")
        perf.note_dispatch_gap(80e-6, "tanh")
        frame = obs_top.render(json.loads(obs.to_json()))
        assert "== roofline ==" in frame
        assert "engine_decode" in frame
        assert "hbm=" in frame and "flops=" in frame
        assert "dispatch gap" in frame and "n=2" in frame

    def test_gap_percentiles_between_frames(self):
        obs_top = self._obs_top()
        obs.enable()
        perf.note_dispatch_gap(100e-6, "linear")
        prev = json.loads(obs.to_json())
        for _ in range(3):
            perf.note_dispatch_gap(200e-6, "linear")
        doc = json.loads(obs.to_json())
        frame = obs_top.render(doc, prev, dt=1.0)
        # the between-frames window holds 3 gaps, not the cumulative 4
        assert "n=3" in frame

    def test_renders_graph_cache_line(self):
        obs_top = self._obs_top()
        obs.enable()
        for _ in range(9):
            perf.note_graph_cache("hit")
        perf.note_graph_cache("miss")
        frame = obs_top.render(json.loads(obs.to_json()))
        assert "graph cache" in frame
        assert "90.0%" in frame
        assert "9 hit / 1 miss / 0 bypass" in frame
