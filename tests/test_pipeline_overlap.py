"""Pipeline overlap measurement (VERDICT r3 weak #2 / next-4).

The design claim (pipeline_parallel.py): ScheduleExecutor dispatches
units from one Python thread and XLA's ASYNC dispatch overlaps stage
s's micro-batch m+1 with stage s+1's m on their distinct devices.

What this box can and cannot measure: it has ONE physical core, and the
XLA CPU client runs every virtual device's computations on the same
single-worker Eigen pool — so two stage executables can never make
simultaneous progress HERE (measured: consecutive device intervals abut
with ~1 ms callback gaps, zero overlap, regardless of dispatch). The
properties that carry the overlap claim to real multi-chip hardware —
where each chip has its own executor — ARE measurable and are asserted
below:

  1. no starvation: the device work queue never waits on Python — gaps
     between consecutive device intervals stay tiny vs unit duration;
  2. the schedule's bubble fraction, computed from the simulator's own
     cycle clock (units sharing a cycle run on disjoint stage meshes),
     matches the analytic 1F1B bound (p-1)/(m+p-1) exactly and beats
     FThenB — i.e. given concurrency the hardware provides, the emitted
     order achieves textbook pipelining.

Recorded in BENCH_EXTRA.md.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist

LOG = []


class _StampedHeavy(pt.nn.Layer):
    """A stage layer whose jitted body records device-schedule-time
    start/end host timestamps around a compute loop heavy enough
    (~15-30 ms) that the device queue builds up behind Python."""

    def __init__(self, dim, tag, iters=800):
        super().__init__()
        self.tag = tag
        self.weight = self.create_parameter((dim, dim))

        def _stamp(phase):
            def cb(_x):
                LOG.append((tag, phase, time.perf_counter()))
                return np.int32(0)
            return cb

        @jax.jit
        def run(x, w):
            t0 = jax.experimental.io_callback(
                _stamp("s"), jax.ShapeDtypeStruct((), jnp.int32), x)
            h = x + 0.0 * t0.astype(x.dtype)

            def body(_, h):
                return jnp.tanh(h @ w)

            h = jax.lax.fori_loop(0, iters, body, h)
            t1 = jax.experimental.io_callback(
                _stamp("e"), jax.ShapeDtypeStruct((), jnp.int32), h)
            return h + 0.0 * t1.astype(x.dtype)

        self._run = run

    def forward(self, x):
        return pt.Tensor._wrap(self._run(x._data, self.weight._data))


def _build(dim=192, m=6):
    from paddle_tpu.distributed.fleet import fleet
    from paddle_tpu.distributed.meta_parallel import (LayerDesc,
                                                      PipelineLayer)
    # pure-pp topology: the measured intervals contain ONLY stage
    # compute (no mp/dp collective rendezvous)
    strategy = dist.fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": m}
    dist.fleet.init(strategy=strategy)
    pt.seed(11)
    model = PipelineLayer(
        layers=[LayerDesc(_StampedHeavy, dim, 0),
                LayerDesc(_StampedHeavy, dim, 1)],
        loss_fn=None)
    return model


def _run_forward(pipe, m, dim, seed=0):
    from paddle_tpu.distributed.meta_parallel.pipeline_schedules import (
        ScheduleExecutor, Unit)
    rng = np.random.default_rng(seed)
    micro = [pt.to_tensor(rng.standard_normal((16, dim))
                          .astype(np.float32)) for _ in range(m)]
    order = []
    for k in range(m):
        order.append(Unit("F", 0, k, 0, k))
        order.append(Unit("F", 1, k, 1, k + 1))
    ScheduleExecutor(pipe, None).run(order, micro, [None] * m,
                                     forward_only=True)
    for d in jax.devices()[:2]:
        jnp.zeros((), device=d).block_until_ready()
    time.sleep(0.1)


def _measure_timeline_once(pipe, m, dim, seed):
    """One measured forward pass -> (sim_bubble, gap_ratio): the
    projected 2-independent-executor bubble from the measured per-unit
    durations, and max inter-unit gap over mean unit duration."""
    LOG.clear()
    _run_forward(pipe, m, dim, seed=seed)
    events = list(LOG)
    assert len(events) == 2 * 2 * m, events

    # per-(part, micro) measured durations from the stamps
    seen = {0: 0, 1: 0}
    dur = {}
    start_t = {}
    for tag, phase, t in sorted(events, key=lambda e: e[2]):
        if phase == "s":
            start_t[(tag, seen[tag])] = t
        else:
            dur[(tag, seen[tag])] = t - start_t[(tag, seen[tag])]
            seen[tag] += 1
    assert len(dur) == 2 * m

    # simulate the same F-only pipeline on TWO independent executors:
    # F(p, k) starts when executor p is free AND F(p-1, k) finished
    free = [0.0, 0.0]
    done = {}
    for k in range(m):
        t0 = free[0]
        done[(0, k)] = t0 + dur[(0, k)]
        free[0] = done[(0, k)]
        t1 = max(free[1], done[(0, k)])
        done[(1, k)] = t1 + dur[(1, k)]
        free[1] = done[(1, k)]
    span = max(done.values())
    busy = sum(dur.values())
    sim_bubble = 1.0 - busy / (2 * span)

    # inter-unit gaps: on this 1-worker CPU client execution is
    # serialized, so consecutive intervals should abut — a starved
    # queue would show dispatch-sized holes
    marks = sorted((t, phase) for _, phase, t in events)
    unit_durs, gaps = [], []
    for (t1, p1), (t2, p2) in zip(marks, marks[1:]):
        if p1 == "s" and p2 == "e":
            unit_durs.append(t2 - t1)
        elif p1 == "e" and p2 == "s":
            gaps.append(t2 - t1)
    assert unit_durs and gaps
    gap_ratio = max(gaps) / (sum(unit_durs) / len(unit_durs))
    return sim_bubble, gap_ratio


def test_executor_timeline_never_starves_the_device():
    """What IS measurable here: the device work queue never waits on
    Python between units (no dispatch-sized holes in the measured
    device timeline), and a timeline SIMULATION that replays the
    measured per-unit durations on p INDEPENDENT executors (what a
    real pod has) against the schedule's data dependencies lands at
    the analytic 1F1B bubble — i.e. the executor's emitted order loses
    nothing beyond the hardware's own serialization.

    Best-of-3 trial windows: a single-core scheduler noise spike can
    blow one inter-unit gap (or one stamped duration) without the
    executor starving anything — noise only ever INFLATES both
    measures, so the best window is the honest timeline and one clean
    window is decisive. Deflaked per ISSUE 7 (was: one window, false
    regression signals under box contention).

    (Direct queue-ahead is NOT observable on this box: the CPU client
    inline-executes each computation on its single worker, measured as
    0/12 units still running when forward_part returns; documented in
    BENCH_EXTRA.md.)"""
    m, dim = 6, 192
    pipe = _build(dim, m)
    LOG.clear()
    _run_forward(pipe, m, dim)          # compile
    analytic = (2 - 1) / (m + 2 - 1)   # F-only 2-stage pipeline
    best_bubble = best_gap = float("inf")
    for attempt in range(3):
        sim_bubble, gap_ratio = _measure_timeline_once(
            pipe, m, dim, seed=1 + attempt)
        best_bubble = min(best_bubble, sim_bubble)
        best_gap = min(best_gap, gap_ratio)
        if best_bubble <= analytic + 0.08 and best_gap < 0.5:
            break                       # one clean window is decisive
    assert best_bubble <= analytic + 0.08, (
        f"projected bubble {best_bubble:.3f} far exceeds the analytic "
        f"1F1B bound {analytic:.3f} in every window — the emitted "
        "order itself wastes pipeline slots")
    assert best_gap < 0.5, (
        f"queue starved in every window: best max-gap/mean-unit ratio "
        f"{best_gap:.3f}")


def _bubble_from_cycles(order, p):
    """Bubble fraction from the simulator's cycle clock: each cycle is
    one unit-time slot per stage; busy slots = len(order)."""
    total_cycles = max(u.cycle for u in order) + 1
    return 1.0 - len(order) / (p * total_cycles)


def test_schedule_bubble_matches_analytic():
    """The emitted 1F1B order's bubble on its own cycle clock (units
    sharing a cycle run on disjoint stage meshes => that IS the
    overlapped timeline) must stay within the textbook bound
    (p-1)/(m+p-1) — the simulator models zero p2p latency, so it may
    land TIGHTER, never looser. FThenB has the SAME makespan/bubble
    (its penalty is peak in-flight memory, asserted by
    test_pipeline_schedules.py max_in_flight, not wall time)."""
    from paddle_tpu.distributed.meta_parallel.pipeline_schedules import (
        build_schedule)
    for p, m in [(2, 4), (2, 8), (4, 8), (4, 16)]:
        order = build_schedule("1F1B", p, m)
        measured = _bubble_from_cycles(order, p)
        analytic = (p - 1) / (m + p - 1)
        assert measured <= analytic + 1e-9, (
            f"p={p} m={m}: 1F1B bubble {measured:.4f} exceeds analytic "
            f"{analytic:.4f}")
        assert measured > 0 or p == 1
        ftb = _bubble_from_cycles(build_schedule("FThenB", p, m), p)
        assert ftb == pytest.approx(measured), (
            f"FThenB bubble {ftb:.4f} != 1F1B {measured:.4f}: with "
            "unbounded memory their makespans should coincide")


def test_interleaved_beats_1f1b_bubble():
    """VPP's point is a smaller bubble: (p-1)/(v*m/…) — assert the
    simulator's cycle clock shows Interleaved1F1B < 1F1B for equal
    work (v chunks of 1/v size each: compare in unit-time slots)."""
    from paddle_tpu.distributed.meta_parallel.pipeline_schedules import (
        build_schedule)
    p, m, v = 4, 8, 2
    b_1f1b = _bubble_from_cycles(build_schedule("1F1B", p, m), p)
    b_vpp = _bubble_from_cycles(
        build_schedule("Interleaved1F1B", p, m, v), p)
    assert b_vpp < b_1f1b, (b_vpp, b_1f1b)
