"""Explicit pipeline schedule tests (VERDICT r1 item 4).

Covers: unit-order generation (warmup/steady/drain), dependency
validity, the 1F1B memory cap vs F-then-B, gradient equivalence of the
scheduled paths against the legacy per-micro loop, and interleaved VPP.
Reference semantics: fleet/meta_parallel/pipeline_parallel.py:431 (1F1B),
:1091 (interleave), :1473 (FThenB).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.meta_parallel.pipeline_schedules import (
    build_schedule, max_in_flight)


@pytest.fixture(autouse=True)
def _reset_groups():
    dist.destroy_process_group()
    from paddle_tpu.distributed.topology import set_hybrid_communicate_group
    set_hybrid_communicate_group(None)
    yield
    dist.destroy_process_group()
    set_hybrid_communicate_group(None)


def _check_dependencies(order, num_parts):
    done_f, done_b = set(), set()
    for u in order:
        if u.kind == "F":
            if u.part > 0:
                assert (u.part - 1, u.micro) in done_f, u
            done_f.add((u.part, u.micro))
        else:
            assert (u.part, u.micro) in done_f, u
            if u.part < num_parts - 1:
                assert (u.part + 1, u.micro) in done_b, u
            done_b.add((u.part, u.micro))
    return done_f, done_b


class TestScheduleGeneration:
    def test_1f1b_warmup_steady_drain(self):
        p, n = 4, 8
        order = build_schedule("1F1B", p, n)
        done_f, done_b = _check_dependencies(order, p)
        assert len(done_f) == len(done_b) == p * n
        # last stage pipelines immediately: its first B directly follows
        # its first F (warmup 0)
        last = [u for u in order if u.stage == p - 1]
        assert [u.kind for u in last[:4]] == ["F", "B", "F", "B"]
        # stage 0 warms up p-1 forwards, then the steady state's leading
        # F — its first backward is unit index p (Megatron 1F1B timeline)
        s0 = [u for u in order if u.stage == 0]
        first_b = next(i for i, u in enumerate(s0) if u.kind == "B")
        assert first_b == p
        # memory cap: stage s keeps at most p - s micro-batches in flight
        peaks = max_in_flight(order, p)
        assert peaks == [p - s for s in range(p)]

    def test_fthenb_holds_everything(self):
        p, n = 4, 8
        order = build_schedule("FThenB", p, n)
        _check_dependencies(order, p)
        peaks = max_in_flight(order, p)
        assert peaks == [n] * p   # every micro-batch's activations live

    def test_1f1b_beats_fthenb_on_memory(self):
        p, n = 4, 16
        f = max_in_flight(build_schedule("FThenB", p, n), p)
        o = max_in_flight(build_schedule("1F1B", p, n), p)
        assert max(o) < max(f)

    def test_interleaved_dependencies_and_warmup(self):
        p, n, v = 2, 4, 2
        order = build_schedule("Interleaved1F1B", p, n, v)
        done_f, done_b = _check_dependencies(order, p * v)
        assert len(done_f) == len(done_b) == p * v * n
        # chunks round-robin: part j on stage j % p
        for u in order:
            assert u.stage == u.part % p
        # interleaving really happens: some B precedes the last F
        kinds = [u.kind for u in order]
        assert "B" in kinds[:kinds[::-1].index("F") * -1 or len(kinds)]

    def test_overlap_cycles_use_disjoint_stages(self):
        order = build_schedule("1F1B", 4, 8)
        by_cycle = {}
        for u in order:
            by_cycle.setdefault(u.cycle, []).append(u.stage)
        # within a simulated cycle every unit is on a different stage
        # sub-mesh -> genuinely overlappable under async dispatch
        for c, stages in by_cycle.items():
            assert len(stages) == len(set(stages)), (c, stages)

    def test_bad_modes_raise(self):
        with pytest.raises(ValueError):
            build_schedule("zigzag", 2, 4)
        with pytest.raises(ValueError):
            build_schedule("Interleaved1F1B", 2, 4, 1)
        with pytest.raises(ValueError):
            build_schedule("1F1B", 2, 4, 2)


def _build_pipe(schedule_mode, accumulate_steps=4, v=1, seed=7):
    from paddle_tpu.distributed.fleet import fleet
    from paddle_tpu.distributed.meta_parallel import (
        PipelineLayer, LayerDesc)

    strategy = dist.fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 2}
    cfg = {"accumulate_steps": accumulate_steps}
    if schedule_mode is not None:
        cfg["schedule_mode"] = schedule_mode
    strategy.pipeline_configs = cfg
    dist.fleet.init(strategy=strategy)
    pt.seed(seed)
    descs = [
        LayerDesc(pt.nn.Linear, 16, 32),
        LayerDesc(pt.nn.Linear, 32, 32),
        LayerDesc(pt.nn.Linear, 32, 16),
        LayerDesc(pt.nn.Linear, 16, 8),
    ]
    model = PipelineLayer(
        layers=descs,
        loss_fn=lambda out, lbl: pt.ops.mean((out - lbl) ** 2),
        num_virtual_pipeline_stages=v if v > 1 else None)
    pipe = fleet.distributed_model(model)
    return pipe, model


def _grads(model):
    return {n: p.grad.numpy().copy() for n, p in model.named_parameters()
            if p.grad is not None}


class TestScheduledExecution:
    def _data(self):
        rng = np.random.default_rng(0)
        x = pt.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
        y = pt.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
        return x, y

    def test_1f1b_matches_legacy_loop(self):
        x, y = self._data()
        pipe, model = _build_pipe(None)
        loss_ref = pipe.forward_backward_pipeline([x, y])
        g_ref = _grads(model)
        assert g_ref

        pipe2, model2 = _build_pipe("1F1B")
        loss = pipe2.forward_backward_pipeline([x, y])
        g = _grads(model2)
        np.testing.assert_allclose(float(loss.numpy()),
                                   float(loss_ref.numpy()), rtol=1e-5)
        assert g.keys() == g_ref.keys()
        for k in g_ref:
            np.testing.assert_allclose(g[k], g_ref[k], rtol=1e-4,
                                       atol=1e-5, err_msg=k)
        # execution log follows the declared schedule order
        assert pipe2.last_executed == [
            (u.kind, u.part, u.micro) for u in pipe2.last_schedule]
        assert any(k == "B" for k, _, _ in pipe2.last_executed[:-5])

    def test_fthenb_matches_legacy_loop(self):
        x, y = self._data()
        pipe, model = _build_pipe(None)
        pipe.forward_backward_pipeline([x, y])
        g_ref = _grads(model)

        pipe2, model2 = _build_pipe("FThenB")
        pipe2.forward_backward_pipeline([x, y])
        g = _grads(model2)
        for k in g_ref:
            np.testing.assert_allclose(g[k], g_ref[k], rtol=1e-4,
                                       atol=1e-5, err_msg=k)
        # all forwards precede all backwards
        kinds = [k for k, _, _ in pipe2.last_executed]
        assert kinds.index("B") == kinds.count("F")

    def test_interleaved_vpp_runs_and_matches(self):
        x, y = self._data()
        pipe, model = _build_pipe(None)
        pipe.forward_backward_pipeline([x, y])
        g_ref = _grads(model)

        pipe2, model2 = _build_pipe("Interleaved1F1B", v=2)
        assert model2.num_parts == 4 and model2.num_chunks == 2
        loss = pipe2.forward_backward_pipeline([x, y])
        assert np.isfinite(float(loss.numpy()))
        g = _grads(model2)
        # same underlying 4 Linear layers, same math
        for (k1, v1), (k2, v2) in zip(sorted(g_ref.items()),
                                      sorted(g.items())):
            np.testing.assert_allclose(v2, v1, rtol=1e-4, atol=1e-5,
                                       err_msg=f"{k1} vs {k2}")

    def test_train_batch_with_optimizer_1f1b(self):
        from paddle_tpu.distributed.fleet import fleet
        x, y = self._data()
        pipe, model = _build_pipe("1F1B")
        opt = fleet.distributed_optimizer(
            pt.optimizer.AdamW(learning_rate=1e-3,
                               parameters=model.parameters()))
        l1 = pipe.train_batch([x, y], opt)
        l2 = pipe.train_batch([x, y], opt)
        assert np.isfinite(float(l2.numpy()))
        assert float(l2.numpy()) < float(l1.numpy())

    def test_eval_batch_forward_only(self):
        x, y = self._data()
        pipe, _ = _build_pipe("1F1B")
        loss = pipe.eval_batch([x, y])
        assert np.isfinite(float(loss.numpy()))
        assert all(k == "F" for k, _, _ in pipe.last_executed)


class _TupleBlock(pt.nn.Layer):
    """Transformer-style stage module threading (hidden, mask) tuples
    across part boundaries."""

    def __init__(self, din, dout):
        super().__init__()
        self.lin = pt.nn.Linear(din, dout)

    def forward(self, inputs):
        if isinstance(inputs, tuple):
            h, mask = inputs
        else:
            h, mask = inputs, None
        h = pt.ops.tanh(self.lin(h))
        if mask is not None:
            h = h * mask
        return (h, mask)


class TestPytreeActivations:
    """ScheduleExecutor carries pytrees of Tensors across stage
    boundaries (VERDICT r2 weak #4; ref p2p tuple negotiation,
    pp_utils/p2p_communication.py:87-157)."""

    def _build(self, schedule_mode):
        from paddle_tpu.distributed.fleet import fleet
        from paddle_tpu.distributed.meta_parallel import (
            PipelineLayer, LayerDesc)

        strategy = dist.fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        cfg = {"accumulate_steps": 4}
        if schedule_mode is not None:
            cfg["schedule_mode"] = schedule_mode
        strategy.pipeline_configs = cfg
        dist.fleet.init(strategy=strategy)
        pt.seed(11)
        descs = [
            LayerDesc(_TupleBlock, 16, 32),
            LayerDesc(_TupleBlock, 32, 32),
            LayerDesc(_TupleBlock, 32, 8),
            LayerDesc(_TupleBlock, 8, 8),
        ]
        model = PipelineLayer(
            layers=descs,
            loss_fn=lambda out, lbl: pt.ops.mean((out[0] - lbl) ** 2))
        pipe = fleet.distributed_model(model)
        return pipe, model

    def _data(self):
        rng = np.random.default_rng(3)
        x = pt.to_tensor(rng.standard_normal((8, 16)).astype(np.float32))
        mask = pt.to_tensor(
            (rng.random((8, 1)) > 0.3).astype(np.float32))
        y = pt.to_tensor(rng.standard_normal((8, 8)).astype(np.float32))
        return (x, mask), y

    def test_tuple_activations_train_under_1f1b(self):
        (x, mask), y = self._data()
        pipe, model = self._build("1F1B")
        loss = pipe.forward_backward_pipeline([(x, mask), y])
        assert np.isfinite(float(loss.numpy()))
        grads = _grads(model)
        assert len(grads) >= 4  # every stage's params got gradients

    def test_tuple_matches_legacy_loop(self):
        (x, mask), y = self._data()
        pipe_ref, model_ref = self._build(None)
        loss_ref = pipe_ref.forward_backward_pipeline([(x, mask), y])
        g_ref = _grads(model_ref)
        assert g_ref

        pipe2, model2 = self._build("1F1B")
        loss = pipe2.forward_backward_pipeline([(x, mask), y])
        g = _grads(model2)
        np.testing.assert_allclose(float(loss.numpy()),
                                   float(loss_ref.numpy()), rtol=1e-5)
        assert g.keys() == g_ref.keys()
        for k in g_ref:
            np.testing.assert_allclose(g[k], g_ref[k], rtol=1e-4,
                                       atol=1e-5, err_msg=k)
