"""Automatic prefix caching for the paged serving engine: refcounted
copy-on-write pages (native allocator), hash-indexed reuse + LRU
retention (PagedKVCache), prefix-resume prefill (LLMEngine) — plus the
satellites that ride along (top_k sampling, Tensor pickle protocol,
metric-name conventions checker).

The load-bearing property is ORACLE EXACTNESS: greedy engine outputs
with prefix caching ON must be bit-identical to caching OFF and to the
dense generate() baseline — including under pool pressure that evicts
cached pages mid-run and under preemption."""
import os
import pickle
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.inference import BlockAllocator, LLMEngine, PagedKVCache
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.generation import generate
from paddle_tpu.models.gpt import gpt_tiny
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny


@pytest.fixture(autouse=True)
def _obs_clean():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


@pytest.fixture(scope="module")
def tiny_gpt():
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


@pytest.fixture(scope="module")
def tiny_llama():
    pt.seed(0)
    return LlamaForCausalLM(llama_tiny())


def _oracle(model, prompt, n_new, **kw):
    out = generate(model, pt.to_tensor(np.asarray(prompt, np.int32)[None]),
                   max_new_tokens=n_new, **kw).numpy()[0]
    return out[len(prompt):]


def _drain(eng):
    done = {}
    while eng.has_unfinished:
        for r in eng.step():
            done[r.request_id] = r
    return done


def _serve_sequentially(eng, prompts, n_new):
    """One request at a time, run to completion before the next — the
    staggered arrival pattern that lets later requests hit the pages
    earlier ones parked."""
    outs = []
    for i, p in enumerate(prompts):
        eng.add_request(i, p, max_new_tokens=n_new)
        outs.append(_drain(eng)[i].output_ids)
    return outs


# ---------------------------------------------------------------------------
# native allocator: refcounts + strict free/ref guards
# ---------------------------------------------------------------------------
class TestAllocatorRefcounts:
    def test_ref_unref_lifecycle(self):
        a = BlockAllocator(8)
        blks = a.alloc(2)
        assert all(a.refcount(b) == 1 for b in blks)
        a.ref(blks)
        assert all(a.refcount(b) == 2 for b in blks)
        assert a.num_free == 6          # refs don't consume blocks
        a.free(blks)                    # 2 -> 1: still leased
        assert a.num_free == 6 and all(a.refcount(b) == 1 for b in blks)
        a.free(blks)                    # 1 -> 0: back on the free list
        assert a.num_free == 8 and all(a.refcount(b) == 0 for b in blks)

    def test_free_of_unallocated_raises_and_preserves_state(self):
        a = BlockAllocator(4)
        blks = a.alloc(2)
        # one valid id + one invalid id in the SAME call: nothing at
        # all may be applied (all-or-nothing guard)
        with pytest.raises(ValueError, match="invalid free"):
            a.free([blks[0], 3])        # 3 was never allocated
        assert a.refcount(blks[0]) == 1 and a.num_free == 2

    def test_over_unref_within_one_call_rejected(self):
        a = BlockAllocator(4)
        (b,) = a.alloc(1)
        with pytest.raises(ValueError, match="invalid free"):
            a.free([b, b])              # refcount 1, two drops planned
        assert a.refcount(b) == 1
        a.ref([b])
        assert a.free([b, b]) == 2      # refcount 2: now legal
        assert a.num_free == 4

    def test_ref_of_free_block_rejected(self):
        a = BlockAllocator(4)
        with pytest.raises(ValueError, match="invalid ref"):
            a.ref([0])
        with pytest.raises(ValueError, match="invalid ref"):
            a.ref([99])
        assert a.refcount(99) == -1     # out of range, not crash


# ---------------------------------------------------------------------------
# PagedKVCache: hash index, LRU parking, eviction, copy-on-write
# ---------------------------------------------------------------------------
def _cache(num_blocks=8, bs=4, layers=1, caching=True):
    return PagedKVCache(num_layers=layers, num_blocks=num_blocks,
                        kv_heads=1, block_size=bs, head_dim=4,
                        dtype=np.float32, layout="token",
                        enable_prefix_caching=caching)


class TestPrefixIndex:
    def test_park_match_lease_roundtrip(self):
        c = _cache()
        toks = np.arange(11, dtype=np.int32)    # 2 full blocks + 3
        assert c.add_sequence("a", 11, tokens=toks) == 0
        c.commit_prefix("a", toks)
        pages_a = c.pages("a")
        c.free_sequence("a")
        # full blocks parked hash-indexed; the partial page went free
        assert c.lru_pages == 2 and c.cached_pages == 2
        assert c.available_blocks == 8
        ncached, pages = c.match_prefix(toks)
        assert ncached == 8 and pages == pages_a[:2]
        # leasing revives the pages out of the LRU at refcount 1
        assert c.add_sequence("b", 11, tokens=toks) == 8
        assert c.pages("b")[:2] == pages_a[:2] and c.lru_pages == 0
        assert c.allocator.refcount(pages_a[0]) == 1
        # a second sharer refs the ACTIVE pages
        assert c.add_sequence("c", 9, tokens=toks[:9]) == 8
        assert c.allocator.refcount(pages_a[0]) == 2

    def test_match_capped_below_full_context(self):
        """At least one token must stay uncached (the engine needs real
        last-token logits), so a fully page-aligned known prompt still
        matches only up to its last block boundary."""
        c = _cache()
        toks = np.arange(8, dtype=np.int32)     # exactly 2 blocks
        c.add_sequence("a", 8, tokens=toks)
        c.commit_prefix("a", toks)
        c.free_sequence("a")
        ncached, _ = c.match_prefix(toks)
        assert ncached == 4                     # (8-1)//4 = 1 block

    def test_eviction_is_lru_and_breaks_chains(self):
        c = _cache(num_blocks=4)
        t = np.arange(16, dtype=np.int32)
        c.add_sequence("a", 16, tokens=t)
        c.commit_prefix("a", t)
        c.free_sequence("a")
        assert c.lru_pages == 4 and c.allocator.num_free == 0
        order = list(c._lru)
        # a 2-block fresh alloc evicts exactly the 2 oldest
        c.add_sequence("b", 8)
        assert c.lru_pages == 2 and list(c._lru) == order[2:]
        # block 0's hash is gone -> the surviving children can never
        # match (chained hashes), and pool accounting stays exact
        assert c.match_prefix(t)[0] == 0
        c.free_sequence("b")
        assert c.available_blocks == 4

    def test_prefix_plan_counts_matched_pages_as_free(self):
        c = _cache(num_blocks=4)
        t = np.arange(16, dtype=np.int32)
        c.add_sequence("a", 16, tokens=t)
        c.commit_prefix("a", t)
        c.free_sequence("a")                    # 4 parked, 0 free
        # full-context re-admission: 4 pages needed, 3 matched +
        # 1 fresh (the fresh one comes from evicting a non-matched
        # parked page) -> feasible
        ncached, feasible, pages = c.prefix_plan(t, 16)
        assert ncached == 12 and feasible and len(pages) == 3
        # a 17-token stranger needs 5 fresh pages > 4 evictable
        stranger = np.arange(100, 117, dtype=np.int32)
        assert not c.prefix_plan(stranger, 17)[1]

    def test_cow_copies_shared_page_content(self):
        c = _cache(num_blocks=8, bs=4)
        toks = np.arange(9, dtype=np.int32)
        c.add_sequence("a", 9, tokens=toks)
        # stamp recognisable content into a's first block's pool rows
        p0 = c.pages("a")[0]
        marked = np.full((4, 1, 4), 7.5, np.float32)
        c.key_caches[0] = c.key_caches[0].at[p0 * 4:(p0 + 1) * 4].set(
            marked)
        c.commit_prefix("a", toks)
        # b matches both of a's full blocks ((9-1)//4 = 2), sharing p0
        assert c.add_sequence("b", 9, tokens=toks) == 8
        assert c.allocator.refcount(p0) == 2
        c.ensure_writable("b", 0)           # force the COW path
        new0 = c.pages("b")[0]
        assert new0 != p0 and c.allocator.refcount(p0) == 1
        np.testing.assert_array_equal(
            np.asarray(c.key_caches[0][new0 * 4:(new0 + 1) * 4]),
            marked)                          # content travelled
        # the copy is private: not hash-indexed
        assert new0 not in c._page_hash
        c.free_sequence("a")
        c.free_sequence("b")
        assert c.available_blocks == 8

    def test_disabled_is_pre_caching_behavior(self):
        c = _cache(caching=False)
        toks = np.arange(11, dtype=np.int32)
        assert c.add_sequence("a", 11, tokens=toks) == 0
        c.commit_prefix("a", toks)              # no-op
        c.free_sequence("a")
        assert c.lru_pages == 0 and c.cached_pages == 0
        assert c.allocator.num_free == 8 == c.available_blocks
        assert c.match_prefix(toks) == (0, [])


# ---------------------------------------------------------------------------
# engine: oracle exactness ON vs OFF vs dense generate()
# ---------------------------------------------------------------------------
def _engine(model, caching=True, **kw):
    args = dict(max_batch=2, block_size=16, decode_chunk=4,
                prompt_quantum=16, max_model_len=64,
                enable_prefix_caching=caching)
    args.update(kw)
    return LLMEngine(model, **args)


def _shared_prefix_prompts(rng, prefix_len, tails, vocab=1024):
    prefix = rng.integers(0, vocab, (prefix_len,)).astype(np.int32)
    return [np.concatenate(
        [prefix, rng.integers(0, vocab, (t,)).astype(np.int32)])
        for t in tails]


class TestEnginePrefixCaching:
    def test_greedy_bit_identical_with_hits(self, tiny_gpt):
        rng = np.random.default_rng(0)
        prompts = _shared_prefix_prompts(rng, 20, (3, 7, 5))
        n_new = 8
        on = _engine(tiny_gpt, True, max_batch=1)
        off = _engine(tiny_gpt, False, max_batch=1)
        outs_on = _serve_sequentially(on, prompts, n_new)
        outs_off = _serve_sequentially(off, prompts, n_new)
        assert on.stats["prefix_cache_hit_tokens"] > 0
        assert off.stats["prefix_cache_hit_tokens"] == 0
        for p, a, b in zip(prompts, outs_on, outs_off):
            want = _oracle(tiny_gpt, p, n_new)
            np.testing.assert_array_equal(a, want)
            np.testing.assert_array_equal(b, want)
        # no pages lost to the cache machinery
        assert on.cache.available_blocks == \
            on.cache.allocator.num_blocks - 1

    def test_multi_turn_reuses_generated_tokens(self, tiny_gpt):
        """Turn 2 = full turn-1 conversation (prompt + generated) plus
        a new user suffix: the cache must serve the generated tokens
        too, not just the original prompt."""
        rng = np.random.default_rng(1)
        p1 = rng.integers(0, 1024, (18,)).astype(np.int32)
        eng = _engine(tiny_gpt, True, max_batch=1)
        eng.add_request("t1", p1, max_new_tokens=8)
        out1 = _drain(eng)["t1"].output_ids
        p2 = np.concatenate([p1, out1,
                             rng.integers(0, 1024, (4,)).astype(np.int32)])
        eng.add_request("t2", p2, max_new_tokens=6)
        out2 = _drain(eng)["t2"].output_ids
        # 18+8 = 26 -> one full 16-block includes generated tokens
        assert eng.stats["prefix_cache_hit_tokens"] >= 16
        np.testing.assert_array_equal(out2, _oracle(tiny_gpt, p2, 6))

    def test_exact_under_lru_eviction_pressure(self, tiny_gpt):
        """Pool sized so the parked pages of earlier requests MUST be
        evicted to serve later ones (4 requests x 3 parked pages >> 7
        usable blocks): outputs stay bit-identical throughout."""
        rng = np.random.default_rng(2)
        shared = _shared_prefix_prompts(rng, 16, (4, 6))
        strangers = [rng.integers(0, 1024, (20,)).astype(np.int32)
                     for _ in range(2)]
        prompts = [shared[0], strangers[0], strangers[1], shared[1]]
        n_new = 12
        on = _engine(tiny_gpt, True, max_batch=1, block_size=8,
                     num_blocks=8)
        off = _engine(tiny_gpt, False, max_batch=1, block_size=8,
                      num_blocks=8)
        outs_on = _serve_sequentially(on, prompts, n_new)
        outs_off = _serve_sequentially(off, prompts, n_new)
        for p, a, b in zip(prompts, outs_on, outs_off):
            want = _oracle(tiny_gpt, p, n_new)
            np.testing.assert_array_equal(a, want)
            np.testing.assert_array_equal(b, want)
        assert on.cache.available_blocks == \
            on.cache.allocator.num_blocks - 1

    def test_exact_under_preemption(self, tiny_gpt):
        """The preemption scenario from test_llm_engine with a SHARED
        prefix: the victim's committed pages park on eviction and serve
        its own resume (and its neighbor), still oracle-exact."""
        rng = np.random.default_rng(3)
        prompts = _shared_prefix_prompts(rng, 16, (1, 2))
        n_new = 20
        eng = _engine(tiny_gpt, True, max_batch=2, block_size=8,
                      num_blocks=9, decode_chunk=4)
        results = eng.generate(prompts, max_new_tokens=n_new)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["prefix_cache_hit_tokens"] > 0
        for p, r in zip(prompts, results):
            np.testing.assert_array_equal(r.output_ids,
                                          _oracle(tiny_gpt, p, n_new))
        assert eng.cache.available_blocks == \
            eng.cache.allocator.num_blocks - 1

    def test_llama_family_prefix_resume(self, tiny_llama):
        """Rotary positions are per-row in the prefix-resume prefill —
        the LLaMA family exercises that path."""
        rng = np.random.default_rng(4)
        prompts = _shared_prefix_prompts(rng, 18, (4, 6))
        eng = _engine(tiny_llama, True, max_batch=1)
        outs = _serve_sequentially(eng, prompts, 6)
        assert eng.stats["prefix_cache_hit_tokens"] > 0
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _oracle(tiny_llama, p, 6))

    def test_metrics_and_gauges(self, tiny_gpt):
        obs.enable()
        rng = np.random.default_rng(5)
        prompts = _shared_prefix_prompts(rng, 20, (3, 5))
        eng = _engine(tiny_gpt, True, max_batch=1)
        _serve_sequentially(eng, prompts, 4)
        snap = obs.snapshot()
        tok = snap["paddle_tpu_engine_prefix_cache_tokens_total"]["series"]
        assert tok[("hit",)] == eng.stats["prefix_cache_hit_tokens"] > 0
        assert tok[("miss",)] == eng.stats["prefix_cache_miss_tokens"] > 0
        pages = snap["paddle_tpu_engine_prefix_cache_pages"]["series"]
        assert pages[("indexed",)] == eng.cache.cached_pages > 0
        assert pages[("lru",)] == eng.cache.lru_pages > 0


# ---------------------------------------------------------------------------
# satellite: top_k sampling (engine + generate parity)
# ---------------------------------------------------------------------------
class TestTopKSampling:
    def test_pick_token_masks_to_top_k(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models.generation import _pick_token
        lf = jnp.asarray(np.array([[0.0, 3.0, 1.0, 2.0, -1.0]] * 2,
                                  np.float32))
        for seed in range(20):
            tok, _ = _pick_token(lf, jax.random.PRNGKey(seed), True,
                                 1.0, 1.0, top_k=2)
            assert set(np.asarray(tok).tolist()) <= {1, 3}
        # top_k=1 collapses sampling to argmax for any key
        tok, _ = _pick_token(lf, jax.random.PRNGKey(7), True, 1.0, 1.0,
                             top_k=1)
        assert np.asarray(tok).tolist() == [1, 1]

    def test_generate_engine_top1_parity(self, tiny_gpt):
        """top_k=1 with do_sample=True must equal greedy on BOTH
        sampling paths — the engine's fused executables and
        generate()'s loop share _pick_token, so a drift in either
        plumbing shows up here."""
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, 1024, (9,)).astype(np.int32)
        greedy = _oracle(tiny_gpt, prompt, 8)
        via_generate = _oracle(tiny_gpt, prompt, 8, do_sample=True,
                               top_k=1, seed=11)
        np.testing.assert_array_equal(via_generate, greedy)
        eng = _engine(tiny_gpt, True, max_batch=1, do_sample=True,
                      top_k=1)
        (r,) = eng.generate([prompt], max_new_tokens=8)
        np.testing.assert_array_equal(r.output_ids, greedy)

    def test_generate_top_k_fused_matches_eager(self, tiny_gpt):
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 1024, (2, 5)).astype(np.int32)
        kw = dict(do_sample=True, top_k=3, temperature=0.8, seed=13)
        fused = generate(tiny_gpt, pt.to_tensor(prompt),
                         max_new_tokens=6, use_fused_step=True, **kw)
        eager = generate(tiny_gpt, pt.to_tensor(prompt),
                         max_new_tokens=6, use_fused_step=False, **kw)
        np.testing.assert_array_equal(np.asarray(fused._data),
                                      np.asarray(eager._data))

    def test_engine_top_k_deterministic_under_seed(self, tiny_gpt):
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, 1024, (7,)).astype(np.int32)

        def run():
            eng = _engine(tiny_gpt, True, max_batch=1, do_sample=True,
                          top_k=4, temperature=0.9, seed=5)
            (r,) = eng.generate([prompt], max_new_tokens=6)
            return r.output_ids

        np.testing.assert_array_equal(run(), run())


# ---------------------------------------------------------------------------
# satellite: Tensor pickle protocol (numpy roundtrip)
# ---------------------------------------------------------------------------
class TestTensorPickle:
    @pytest.mark.parametrize("dtype", ["float32", "int32", "bool",
                                       "bfloat16"])
    def test_roundtrip(self, dtype):
        t = pt.to_tensor(np.arange(6).reshape(2, 3), dtype=dtype)
        u = pickle.loads(pickle.dumps(t))
        assert isinstance(u, Tensor)
        assert u.dtype == t.dtype and u.shape == t.shape
        np.testing.assert_array_equal(np.asarray(u.numpy()),
                                      np.asarray(t.numpy()))

    def test_roundtrip_preserves_flags_drops_autograd(self):
        t = pt.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        (t * 2).sum().backward()
        assert t.grad is not None
        u = pickle.loads(pickle.dumps(t))
        assert u.stop_gradient is False and u.name == t.name
        assert u.grad is None and u._grad_node is None


# ---------------------------------------------------------------------------
# satellite: metric-name conventions checker (tier-1 wired)
# ---------------------------------------------------------------------------
class TestMetricNameChecker:
    def _tool(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(root, "tools"))
        try:
            import check_metric_names
        finally:
            sys.path.pop(0)
        return check_metric_names, root

    def test_repo_is_clean(self):
        tool, root = self._tool()
        assert tool.main(root) == 0

    def test_conventions_enforced(self):
        tool, _ = self._tool()
        # every bad name except the undocumented one IS "documented",
        # isolating one violation per case
        readme = ("paddle_tpu_bad_count paddle_tpu_depth_total "
                  "paddle_tpu_lat paddle_tpu_good_total "
                  "paddle_tpu_lat_seconds paddle_tpu_nohelp_total")
        bad = [
            ("counter", "paddle_tpu_bad_count", "h", "x.py"),  # no _total
            ("gauge", "paddle_tpu_depth_total", "h", "x.py"),  # gauge _total
            ("histogram", "paddle_tpu_lat", "h", "x.py"),      # no unit
            ("counter", "engine_total", "h", "x.py"),          # no prefix
            ("counter", "paddle_tpu_undoc_total", "h", "x.py"),  # not in README
            ("counter", "paddle_tpu_nohelp_total", "", "x.py"),  # empty help
        ]
        problems = tool.check(bad, readme)
        assert len(problems) == 6
        for frag in ("must end _total", "must NOT end _total",
                     "base-unit suffix", "paddle_tpu_ prefix",
                     "not documented", "help"):
            assert any(frag in p for p in problems), frag
        good = [("counter", "paddle_tpu_good_total", "h", "x.py"),
                ("histogram", "paddle_tpu_lat_seconds", "h", "x.py")]
        assert tool.check(good, readme) == []

    def test_collects_real_registrations(self):
        tool, root = self._tool()
        series = tool.collect_series(root)
        names = {n for _, n, _, _ in series}
        assert "paddle_tpu_engine_prefix_cache_tokens_total" in names
        assert "paddle_tpu_request_ttft_seconds" in names
        assert "paddle_tpu_engine_step_seconds" in names
        # the regex sees through line wraps: every registration's
        # FIRST help fragment must be non-empty
        assert all(h.strip() for _, _, h, _ in series)
