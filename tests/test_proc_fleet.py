"""Cross-process serving fleet (ISSUE 18): real-OS-process replicas
speaking the engine contract over HMAC RPC, SIGKILL chaos through the
router's failover machinery with bit-identical rerouted outputs, warm
reintegration of replacement processes from the persistent executable
store, and the obs_top fleet panel's per-process rows."""
import json
import os
import signal
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENGINE_KW = dict(max_batch=4, decode_chunk=4)
N_NEW = 8


def _chaos_model():
    """Module-level so the replica spawn context can pickle it by
    reference (the worker re-imports this test module)."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
    pt.seed(0)
    return GPTForCausalLM(gpt_tiny())


def _prompts(n):
    rng = np.random.default_rng(11)
    return [rng.integers(1, 50, (3 + i,)).astype(np.int32)
            for i in range(n)]


@pytest.fixture(autouse=True)
def _obs_clean():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _reference_outputs(prompts):
    """Greedy outputs from a never-killed in-process engine on the
    SAME tp=2 ("mp",) mesh shape the process replicas use — GSPMD
    reduction order matches, so rerouted fleet outputs must be
    bit-identical to these."""
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models.shard_plans import gpt_tp_rules
    mesh = Mesh(np.array(jax.devices()[:2]),  # graftlint: disable=host-sync
                ("mp",))
    eng = LLMEngine(_chaos_model(), mesh=mesh,
                    shard_param=gpt_tp_rules, **ENGINE_KW)
    for i, p in enumerate(prompts):
        eng.add_request(f"r{i}", p, N_NEW)
    out = {}
    while eng.has_unfinished:
        for r in eng.step():
            assert r.ok, r.error
            out[r.request_id] = tuple(int(t) for t in r.output_ids)
    return out


# ---------------------------------------------------------------------------
# SIGKILL chaos: failover + bit-identical reroute + warm replacement
# ---------------------------------------------------------------------------
class TestProcessFleetChaos:
    def test_kill9_failover_reroute_and_warm_reintegration(
            self, tmp_path):
        from paddle_tpu.inference import Router
        from paddle_tpu.inference.replica_proc import (
            process_engine_factory)
        from paddle_tpu.models.shard_plans import gpt_tp_rules

        prompts = _prompts(6)
        reference = _reference_outputs(prompts)

        obs.enable()
        factory = process_engine_factory(
            _chaos_model, engine_kwargs=ENGINE_KW, tp=2,
            shard_param=gpt_tp_rules, exec_cache_dir=str(tmp_path),
            name_prefix="chaos-engine")
        router = Router(factory, n_replicas=2, affinity=False,
                        cooldown_s=0.05, max_cooldown_s=0.1)
        try:
            for i, p in enumerate(prompts):
                router.submit(f"r{i}", p, max_new_tokens=N_NEW)
            got = {}

            def drain_one_pass():
                for r in router.step():
                    assert r.ok, (r.request_id, r.finish_reason,
                                  r.error)
                    got[r.request_id] = tuple(
                        int(t) for t in r.output_ids)

            # step until the fleet is mid-stream (both replicas hold
            # in-flight work and at least one step ran), then SIGKILL
            # the busier replica — the hard-crash path: no goodbye,
            # no flush, the OS just takes the process
            drain_one_pass()
            victim = max(router.replicas.handles, key=lambda h: h.load)
            survivor = next(h for h in router.replicas.handles
                            if h is not victim)
            assert victim.load > 0
            victim_rids = set(victim.inflight)
            victim_pid = victim.engine.pid
            os.kill(victim_pid, signal.SIGKILL)

            deadline = time.monotonic() + 300
            while router.has_unfinished:
                assert time.monotonic() < deadline, "drain wedged"
                drain_one_pass()

            # every request finished, and every output — including the
            # rerouted victims' — is bit-identical to the never-killed
            # reference engine
            assert set(got) == set(reference)
            assert got == reference
            assert victim_rids, "chaos did not catch in-flight work"
            assert router.stats["failovers"] >= 1
            assert router.stats["reroutes"] >= len(victim_rids)

            # the breaker replaced the dead process via the factory:
            # same stable fleet name, NEW pid, serving again
            assert victim.live and victim.engine is not None
            assert victim.engine.pid != victim_pid
            assert victim.engine.pid != survivor.engine.pid

            # the replacement reintegrates WARM: route it fresh work,
            # then read its own registry — every executable it
            # instantiated came from the persistent store
            # (outcome=disk_hit pinned, zero fresh compiles)
            for i, p in enumerate(prompts):
                router.submit(f"w{i}", p, max_new_tokens=N_NEW)
            got2 = {}
            deadline = time.monotonic() + 300
            while router.has_unfinished:
                assert time.monotonic() < deadline, "drain wedged"
                for r in router.step():
                    assert r.ok, (r.request_id, r.error)
                    got2[r.request_id] = tuple(
                        int(t) for t in r.output_ids)
            assert {k[1:] for k in got2} == {k[1:] for k in reference}
            for rid, toks in got2.items():
                assert toks == reference["r" + rid[1:]]

            outcomes = victim.engine.compile_outcomes()
            assert outcomes, "replacement replica never ran"
            assert all(out == "disk_hit" for _fam, out in outcomes)
            stats = victim.engine.exec_cache_stats()
            assert stats["hits"] > 0
            assert stats["misses"] == 0 and stats["saves"] == 0
        finally:
            for h in router.replicas.handles:
                eng = h.engine
                if eng is not None:
                    try:
                        eng.shutdown(timeout_s=10)
                    except Exception:
                        pass


# ---------------------------------------------------------------------------
# obs_top fleet panel: per-process rows (pid, role, capacity, cache)
# ---------------------------------------------------------------------------
class TestObsTopFleetProcessRows:
    def _obs_top(self):
        tools = os.path.join(REPO, "tools")
        sys.path.insert(0, tools)
        try:
            import obs_top
        finally:
            sys.path.remove(tools)
        return obs_top

    def _engine_delta(self, compiles, disk_hits, requests, tokens):
        """A metrics delta shaped like a serving worker's bundle."""
        from paddle_tpu.observability import fleet, metrics as om
        obs.reset()
        obs.enable()
        c, _ = om.compile_metrics()
        for _ in range(compiles):
            c.labels(family="engine_ragged", outcome="compile").inc()
        for _ in range(disk_hits):
            c.labels(family="engine_ragged", outcome="disk_hit").inc()
        om.registry().counter(
            "paddle_tpu_request_finished_total",
            "requests by terminal reason",
            ("reason",)).labels(reason="length").inc(requests)
        om.registry().counter(
            "paddle_tpu_engine_events_total", "engine events",
            ("event",)).labels(event="decode_tokens").inc(tokens)
        md = fleet.delta_snapshot(om.registry().snapshot(), None)
        obs.reset()
        return md

    def test_renders_process_rows(self):
        obs_top = self._obs_top()
        from paddle_tpu.observability import fleet
        agg = fleet.FleetAggregator()
        try:
            agg.ingest(fleet.make_bundle(
                "engine-0", "engine", 1,
                metrics_delta=self._engine_delta(2, 0, 3, 24),
                heartbeat_extra={"pid": 4242}))
            time.sleep(0.05)    # a real capacity window
            agg.ingest(fleet.make_bundle(
                "engine-0", "engine", 2,
                metrics_delta=self._engine_delta(0, 3, 5, 40),
                heartbeat_extra={"pid": 4242}))
            doc = json.loads(agg.to_json())
            frame = obs_top.render(doc)
            assert "== replicas ==" in frame
            row = [ln for ln in frame.splitlines()
                   if "engine-0" in ln][0]
            assert "pid=4242" in row
            assert "engine" in row
            assert "cache hit=3 compile=2" in row
            assert "req/s=" in row and "req/s=     -" not in row
        finally:
            agg.close()

    def test_compiles_panel_splits_disk_hits(self):
        obs_top = self._obs_top()
        obs.enable()
        from paddle_tpu.observability import metrics as om
        c, _ = om.compile_metrics()
        c.labels(family="engine_ragged", outcome="compile").inc()
        c.labels(family="engine_ragged", outcome="disk_hit").inc(2)
        frame = obs_top.render(json.loads(obs.to_json()))
        line = [ln for ln in frame.splitlines()
                if "engine_ragged" in ln][0]
        assert "(disk_hit=2)" in line
