"""Multiprocess DataLoader workers (VERDICT r4 next-8; ref:
python/paddle/io/reader.py:216 — process workers because transforms
hold the GIL). Spawn + SharedMemory transport; thread tier stays the
fallback for unpicklable datasets.

Note: this sandbox exposes ONE cpu core, so these tests verify the
mechanism (spawn, ordering, shm round-trip, error/worker-info
plumbing), not a parallel speedup — documented in BENCH_EXTRA.md."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.io import DataLoader, Dataset, get_worker_info


class ArrayDs(Dataset):
    """Module-level (spawn-picklable) dataset with a visible transform."""

    def __init__(self, n=16, big=False):
        self.n = n
        self.big = big

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        size = 64 * 1024 if self.big else 8   # big -> SharedMemory path
        x = rng.standard_normal(size).astype(np.float32) * 2.0
        return x, np.int64(i)

    def __len__(self):
        return self.n


class BoomDs(ArrayDs):
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return super().__getitem__(i)


class InfoDs(ArrayDs):
    def __getitem__(self, i):
        info = get_worker_info()
        assert info is not None and info.num_workers == 2
        return np.full((4,), float(info.id), np.float32), np.int64(i)


def _collect(loader):
    out = []
    for x, y in loader:
        out.append((np.asarray(x.numpy()), np.asarray(y.numpy())))
    return out


@pytest.mark.parametrize("big", [False, True])
def test_process_workers_match_serial(big):
    ds = ArrayDs(n=13, big=big)
    serial = _collect(DataLoader(ds, batch_size=4, num_workers=0))
    procs = _collect(DataLoader(ds, batch_size=4, num_workers=2))
    assert len(serial) == len(procs) == 4
    for (sx, sy), (px, py) in zip(serial, procs):
        np.testing.assert_array_equal(sx, px)
        np.testing.assert_array_equal(sy, py)


def test_process_worker_error_propagates():
    ds = BoomDs(n=16)
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        _collect(loader)


def test_worker_info_inside_process():
    ds = InfoDs(n=8)
    out = _collect(DataLoader(ds, batch_size=2, num_workers=2))
    ids = {float(x[0, 0]) for x, _ in out}
    assert ids <= {0.0, 1.0} and len(ids) == 2
    # main process sees no worker context
    assert get_worker_info() is None


def test_unpicklable_falls_back_to_threads():
    class LocalDs(ArrayDs):      # class defined in function: unpicklable
        pass

    ds = LocalDs(n=8)
    loader = DataLoader(ds, batch_size=4, num_workers=2)
    with pytest.warns(UserWarning, match="not picklable"):
        out = _collect(loader)
    assert len(out) == 2


def test_early_break_cleans_up():
    ds = ArrayDs(n=64, big=True)
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        prefetch_factor=2)
    it = iter(loader)
    next(it)
    next(it)
    it.close()      # generator finally: stop, drain, unlink segments
    # a fresh epoch over the same loader still works
    assert len(_collect(loader)) == 16
