"""Per-op profiler spans (VERDICT r3 missing #3 / next-5): dispatch
must report per-op rows while a Profiler records, with zero overhead
when not recording (the live dispatch pointer is swapped, not checked
per call)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import profiler
import paddle_tpu.ops.registry as registry


def _train_steps(n=50):
    lin1, lin2 = pt.nn.Linear(32, 32), pt.nn.Linear(32, 32)
    opt = pt.optimizer.SGD(learning_rate=1e-3,
                           parameters=lin1.parameters()
                           + lin2.parameters())
    x = pt.to_tensor(np.random.default_rng(0).standard_normal(
        (4, 32)).astype(np.float32))
    for _ in range(n):
        h = pt.ops.tanh(lin1(x))
        loss = (lin2(h) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()


def test_summary_lists_per_op_rows():
    p = profiler.Profiler(timer_only=True)
    p.start()
    _train_steps(50)
    p.stop()
    rows = p.op_stats()
    for op in ("linear", "tanh", "mean", "pow"):
        assert op in rows, f"{op} missing from op stats"
        calls, total_ms, max_ms, hits = rows[op]
        assert calls >= 50 and total_ms > 0
    # linear runs twice per fwd + its use in sgd? at least 100 calls
    assert rows["linear"][0] >= 100
    # warm caches -> hit ratio must be high
    assert rows["linear"][3] / rows["linear"][0] > 0.9
    text = p.summary()
    assert "Operator Summary" in text
    assert "linear" in text and "tanh" in text


def test_dispatch_pointer_swaps():
    assert registry.dispatch is registry._dispatch
    p = profiler.Profiler(timer_only=True)
    p.start()
    try:
        assert registry.dispatch is registry._dispatch_profiled
    finally:
        p.stop()
    assert registry.dispatch is registry._dispatch


def test_stats_reset_between_sessions():
    p = profiler.Profiler(timer_only=True)
    p.start()
    _train_steps(2)
    p.stop()
    first = p.op_stats()["linear"][0]
    p2 = profiler.Profiler(timer_only=True)
    p2.start()
    _train_steps(1)
    p2.stop()
    assert p2.op_stats()["linear"][0] < first
