"""PS-capability rendering (SURVEY C23 partial): ShardedEmbedding —
row-sharded tables over the mesh with sharded lookups and sharded
optimizer state (ref: python/paddle/distributed/ps/ table service;
here a GSPMD substitution, scope note in distributed/ps.py)."""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import ProcessMesh
from paddle_tpu.distributed.ps import ShardedEmbedding


@pytest.fixture
def mesh():
    return ProcessMesh(np.arange(8), dim_names=["dp"])


def test_storage_is_row_sharded(mesh):
    emb = ShardedEmbedding(64, 16, mesh=mesh)
    # each device holds 8 of the 64 rows
    shard_shapes = {s.data.shape for s in
                    emb.weight._data.addressable_shards}
    assert shard_shapes == {(8, 16)}
    rows, nbytes = emb.shard_info()
    assert rows == 8 and nbytes == 8 * 16 * 4


def test_lookup_matches_replicated(mesh):
    pt.seed(0)
    emb = ShardedEmbedding(64, 16, mesh=mesh)
    ids = np.random.default_rng(0).integers(0, 64, (4, 7))
    out = emb(pt.to_tensor(ids.astype(np.int32)))
    want = np.asarray(emb.weight._data)[ids]
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-6)


def test_trains_with_sharded_update(mesh):
    """backward + optimizer step work on the sharded table, the update
    stays sharded (no full-table materialization), and training moves
    the looked-up rows only."""
    pt.seed(1)
    emb = ShardedEmbedding(64, 16, mesh=mesh)
    opt = pt.optimizer.SGD(learning_rate=0.5,
                           parameters=emb.parameters())
    w0 = np.asarray(emb.weight._data).copy()
    ids = np.asarray([[1, 9, 33]], np.int32)
    loss = (emb(pt.to_tensor(ids)) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    # still sharded after the update
    shard_shapes = {s.data.shape for s in
                    emb.weight._data.addressable_shards}
    assert shard_shapes == {(8, 16)}
    w1 = np.asarray(emb.weight._data)
    touched = sorted({1, 9, 33})
    untouched = [i for i in range(64) if i not in touched]
    assert not np.allclose(w1[touched], w0[touched])
    np.testing.assert_allclose(w1[untouched], w0[untouched])


def test_row_divisibility_enforced(mesh):
    with pytest.raises(ValueError):
        ShardedEmbedding(63, 16, mesh=mesh)


def test_padding_idx(mesh):
    emb = ShardedEmbedding(16, 8, mesh=mesh, padding_idx=0)
    out = emb(pt.to_tensor(np.asarray([[0, 3]], np.int32))).numpy()
    np.testing.assert_allclose(out[0, 0], 0.0)
    assert np.abs(out[0, 1]).sum() > 0


def test_shard_info_on_2d_mesh():
    m = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    emb = ShardedEmbedding(64, 16, mesh=m, axis="dp")
    rows, nbytes = emb.shard_info()
    # sharded only over dp(2): 32 rows/device, replicated over mp
    assert rows == 32 and nbytes == 32 * 16 * 4
    shard_shapes = {s.data.shape for s in
                    emb.weight._data.addressable_shards}
    assert shard_shapes == {(32, 16)}
