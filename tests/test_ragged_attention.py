"""Ragged paged attention kernel conformance.

The jnp reference path is the engine's CPU tier-1 / oracle
implementation; it is checked here against a from-first-principles
naive construction (per-token python loops over the ownership map),
and the Pallas kernel logic runs on CPU via interpret mode against the
reference — mirroring tests/test_flash_attention.py. A TPU-gated test
covers the compiled path.

Scenario shapes follow the engine's layout contract (module docstring
of kernels/pallas/ragged_paged_attention.py): token-major pools,
off[row, physical_page] = start position (-1 unowned), rows=-1 dead
padding.
"""
import importlib

import numpy as np
import pytest
import jax
import jax.numpy as jnp

ra = importlib.import_module(
    "paddle_tpu.kernels.pallas.ragged_paged_attention")


def _naive(q, k_new, v_new, kpool, vpool, rows, pos, kv_start, off,
           bs, scale, kdq=None, vdq=None, with_pool=True):
    """Per-token loop oracle: pool context strictly below kv_start via
    the ownership map, then own-row causal packed context."""
    q, k_new, v_new = (np.asarray(a, np.float64) for a in
                      (q, k_new, v_new))
    kpool = np.asarray(kpool, np.float64)
    vpool = np.asarray(vpool, np.float64)
    T, H, D = q.shape
    Hk = k_new.shape[1]
    G = H // Hk
    out = np.zeros((T, H, D))
    for t in range(T):
        r = int(rows[t])
        if r < 0:
            continue
        for h in range(H):
            hk = h // G
            ks, vs = [], []
            if with_pool:
                for p in range(off.shape[1]):
                    st = int(off[r, p])
                    if st < 0:
                        continue
                    for s in range(bs):
                        if st + s < kv_start[r]:
                            kk = kpool[p * bs + s, hk]
                            vv = vpool[p * bs + s, hk]
                            if kdq is not None:
                                kk = kk * float(kdq[hk])
                            if vdq is not None:
                                vv = vv * float(vdq[hk])
                            ks.append(kk)
                            vs.append(vv)
            for u in range(T):
                if int(rows[u]) == r and pos[u] <= pos[t]:
                    ks.append(k_new[u, hk])
                    vs.append(v_new[u, hk])
            s_ = np.array([q[t, h] @ kk * scale for kk in ks])
            p_ = np.exp(s_ - s_.max())
            p_ = p_ / p_.sum()
            out[t, h] = sum(pp * vv for pp, vv in zip(p_, vs))
    return out


def _mixed_case(T=64, B=4, NB=8, bs=8, H=4, Hk=2, D=64, int8=False,
                seed=0):
    """One packed launch with every row kind the engine ships:
    row 0 fresh prefill (no pool reads), row 1 single decode token,
    row 2 a verify window, row 3 a prefix-resume suffix; tail dead."""
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((T, H, D)).astype(np.float32) * 0.3
    k_new = rng.standard_normal((T, Hk, D)).astype(np.float32) * 0.3
    v_new = rng.standard_normal((T, Hk, D)).astype(np.float32) * 0.3
    if int8:
        kpool = rng.integers(-127, 128, (NB * bs, Hk, D)).astype(np.int8)
        vpool = rng.integers(-127, 128, (NB * bs, Hk, D)).astype(np.int8)
        kdq = (rng.uniform(0.01, 0.05, (Hk,))).astype(np.float32)
        vdq = (rng.uniform(0.01, 0.05, (Hk,))).astype(np.float32)
    else:
        kpool = rng.standard_normal((NB * bs, Hk, D)).astype(
            np.float32) * 0.3
        vpool = rng.standard_normal((NB * bs, Hk, D)).astype(
            np.float32) * 0.3
        kdq = vdq = None
    rows = np.full((T,), -1, np.int32)
    pos = np.zeros((T,), np.int32)
    kv_start = np.zeros((B,), np.int32)
    off = np.full((B, NB), -1, np.int32)
    c = 0

    def pack(r, start, m):
        nonlocal c
        rows[c:c + m] = r
        pos[c:c + m] = start + np.arange(m)
        kv_start[r] = start
        c += m

    pack(0, 0, 20)               # fresh prefill, 20 tokens
    pack(1, 24, 1)               # decode, 24 cached tokens
    pack(2, 10, 5)               # verify window over 10 cached
    pack(3, 16, 7)               # prefix-resume over 16 cached
    # physical pages: row 1 -> pages 0..2, row 2 -> 3..4, row 3 -> 5..6
    off[1, [0, 1, 2]] = np.arange(3) * bs
    off[2, [3, 4]] = np.arange(2) * bs
    off[3, [5, 6]] = np.arange(2) * bs
    return dict(q=q, k_new=k_new, v_new=v_new, kpool=kpool,
                vpool=vpool, rows=rows, pos=pos, kv_start=kv_start,
                off=off, bs=bs, scale=1.0 / np.sqrt(D), kdq=kdq,
                vdq=vdq)


def _run_ref(c, path="jnp", with_pool=True):
    return np.asarray(ra.ragged_paged_attention(
        jnp.asarray(c["q"]), jnp.asarray(c["k_new"]),
        jnp.asarray(c["v_new"]), jnp.asarray(c["kpool"]),
        jnp.asarray(c["vpool"]), jnp.asarray(c["rows"]),
        jnp.asarray(c["pos"]), jnp.asarray(c["kv_start"]),
        jnp.asarray(c["off"]), block_size=c["bs"], scale=c["scale"],
        kdq=None if c["kdq"] is None else jnp.asarray(c["kdq"]),
        vdq=None if c["vdq"] is None else jnp.asarray(c["vdq"]),
        with_pool=with_pool, path=path))


def test_reference_matches_naive_mixed_rows():
    c = _mixed_case()
    got = _run_ref(c)
    ref = _naive(**c)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_reference_int8_pool_dequant():
    c = _mixed_case(int8=True)
    got = _run_ref(c)
    ref = _naive(**c)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_reference_no_pool_is_packed_causal_self_attention():
    c = _mixed_case()
    got = _run_ref(c, with_pool=False)
    ref = _naive(**{**c, "with_pool": False})
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_dead_rows_emit_zero():
    c = _mixed_case()
    got = _run_ref(c)
    dead = np.asarray(c["rows"]) < 0
    assert dead.any()
    np.testing.assert_array_equal(got[dead], 0.0)
    assert np.isfinite(got).all()


def test_gqa_and_mqa_head_mapping():
    for hk in (1, 2):
        c = _mixed_case(Hk=hk, D=128, seed=3)
        got = _run_ref(c)
        ref = _naive(**c)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("int8", [False, True])
def test_pallas_interpret_matches_reference(int8):
    # D=128 keeps Hk*D lane-aligned so the kernel shape is accepted
    c = _mixed_case(Hk=2, D=128, int8=int8, seed=5)
    assert ra._shape_reject_reason(
        64, c["kpool"].shape[0], 4, 2, 128, c["bs"], True) is None
    got = _run_ref(c, path="pallas_interpret")
    ref = _run_ref(c, path="jnp")
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    dead = np.asarray(c["rows"]) < 0
    np.testing.assert_array_equal(got[dead], 0.0)


def test_pallas_interpret_no_pool():
    c = _mixed_case(Hk=2, D=128, seed=7)
    got = _run_ref(c, path="pallas_interpret", with_pool=False)
    ref = _run_ref(c, path="jnp", with_pool=False)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_path_gating_and_shape_rejects():
    # CPU backend -> jnp with a human-readable reason
    path, why = ra.ragged_attention_path(64, 64, 4, 2, 128, 8)
    if jax.default_backend() != "tpu":
        assert path == "jnp" and "backend" in why
    # token stream must stay sublane/lane-aligned
    assert "multiple of 8" in ra._shape_reject_reason(
        12, 64, 4, 2, 128, 8, True)
    assert "multiple of 128" in ra._shape_reject_reason(
        192, 64, 4, 2, 128, 8, True)
    # head-lane alignment (Hk*D: one 64-wide kv head is 64 lanes)
    assert "lane-aligned" in ra._shape_reject_reason(
        64, 64, 4, 1, 64, 8, True)
    # kv heads must divide q heads
    assert "divide" in ra._shape_reject_reason(
        64, 64, 4, 3, 128, 8, True)
    # pool granularity
    assert "block_size" in ra._shape_reject_reason(
        64, 64, 4, 2, 128, 12, True)
    assert "pool length" in ra._shape_reject_reason(
        64, 60, 4, 2, 128, 8, True)
    # the no-pool variant skips pool-shape checks entirely
    assert ra._shape_reject_reason(
        64, 0, 4, 2, 128, 8, False) is None


def test_pick_div():
    assert ra._pick_div(384, 512, 128) == 384
    assert ra._pick_div(384, 256, 128) == 128
    assert ra._pick_div(64, 256, 8) == 64
    assert ra._pick_div(8, 256, 128) is None


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas path needs TPU")
def test_pallas_compiled_matches_reference_tpu():
    c = _mixed_case(T=256, Hk=2, D=128, seed=11)
    got = _run_ref(c, path="pallas")
    ref = _run_ref(c, path="jnp")
    np.testing.assert_allclose(got, ref, rtol=5e-3, atol=5e-3)
